"""Batched @recurse serving: many concurrent queries, ONE lane kernel.

Reference parity: the reference serves a concurrent query mix with
per-query goroutines (worker/task.go); the TPU-native equivalent packs
structurally-compatible `@recurse` queries into the bit-lanes of
`ops/bfs.py ell_recurse` — one fused multi-hop program answers the whole
batch (the north-star kernel, reached from the SERVING path, not just
the bench). Ineligible queries fall back to the per-query engine.

Three kernel families ride the lanes (PR 7 widened the set):
  * unfiltered single-block @recurse — the dedicated recurse path here;
  * level trees / filtered recurse / var chains — engine/treebatch.py;
  * unweighted `shortest` blocks (LDBC IC13/IC14 shapes) — lane-BFS with
    host walk-back, staged through donated mask buffers (this module).

Batch PLANS are memoized by (schema fingerprint, query texts) riding
utils/jitcache.Memo: a repeated query template skips parsing and
`plan_batch_groups` entirely (plan_cache_{hits,misses}_total), the same
way the ELL build and the compiled kernels already amortize per
snapshot.
"""

from __future__ import annotations

import time

import numpy as np

from dgraph_tpu.engine.execute import Executor, LevelNode
from dgraph_tpu.engine.ir import SubGraph
from dgraph_tpu.engine.outputnode import to_json
from dgraph_tpu.engine.recurse import RecurseData, _bind_recurse_vars
from dgraph_tpu.utils import costprofile, deadline, locks, memgov, tracing
from dgraph_tpu.utils.jitcache import Memo, jit_call
from dgraph_tpu.utils.metrics import METRICS

MIN_BATCH = 4            # below this the per-query engine is cheaper
# cost-packed planning (ISSUE 9): a group SMALLER than MIN_BATCH still
# earns a kernel launch when its predicted cost says the work dwarfs
# the launch overhead — grouping by predicted cost, not query count
# (utils/costprior.py; priors below the sample floor leave the count
# rule in charge)
KERNEL_WORTH_US = 5_000.0
# Depth is a static arg of the jitted kernel: each distinct value is an
# XLA compile, and the scan materializes a [depth, n+1, W] hops buffer
# with no early exit. Depths past any real graph's diameter fall back to
# the per-query engine (whose host loop exits when the frontier empties)
# instead of letting a client-controlled depth size device buffers.
MAX_KERNEL_DEPTH = 64
# shortest lane-BFS: hops per kernel launch. The staged host loop stops
# as soon as every lane resolved (found / exhausted), so a short path
# never pays the full depth cap; mask carries are DONATED between
# stages (ops/bfs.py make_ell_step).
SHORTEST_STAGE = 8


class _BatchPlan:
    def __init__(self, blocks, attr, reverse, depth):
        self.blocks = blocks          # one root SubGraph per query
        self.attr = attr
        self.reverse = reverse
        self.depth = depth


class _ShortestPlan:
    """One shortest-path kernel group: same predicate/direction/depth
    cap/numpaths/weight bounds across the batch; per-query (blocks,
    shortest block index, src uid, dst uid)."""

    def __init__(self, sig, items):
        self.sig = sig
        (_tag, self.attr, self.reverse, self.depth, self.k,
         self.minw, self.maxw, self.first_visit) = sig
        self.queries = [blocks for blocks, _bi, _s, _d in items]
        self.block_idx = [bi for _b, bi, _s, _d in items]
        self.src_uids = [s for _b, _bi, s, _d in items]
        self.dst_uids = [d for _b, _bi, _s, d in items]


def _expands(store, c: SubGraph) -> bool:
    from dgraph_tpu.engine.execute import expands
    return expands(store.schema, c)


def _eligible(store, blocks):
    """(signature, root_sg) when the query fits the lane kernel, else
    None. The signature is what must MATCH across a kernel launch."""
    if len(blocks) != 1:
        return None
    sg = blocks[0]
    r = sg.recurse
    if r is not None and r.depth and r.depth > MAX_KERNEL_DEPTH:
        return None
    if (r is None or r.loop or not r.depth or sg.shortest is not None
            or sg.filters is not None or sg.first or sg.offset
            or sg.after or sg.orders or sg.groupby or sg.cascade
            or sg.normalize or sg.var_name):
        return None
    edge_sgs = [c for c in sg.children if _expands(store, c)]
    if len(edge_sgs) != 1:
        return None
    e = edge_sgs[0]
    if (e.filters is not None or e.facet_filter is not None
            or e.facet_orders or e.facet_keys is not None
            or e.first or e.offset or e.after or e.orders
            or e.var_name):
        return None
    return (e.attr, e.is_reverse, r.depth), sg


def _eligible_shortest(store, blocks):
    """(signature, (blocks, shortest block idx, src uid, dst uid)) when
    the query's `shortest` block fits the lane-BFS kernel, else None.

    Kernel-eligible shapes: UNWEIGHTED shortest over exactly one edge
    predicate, no filters/facets on the edge, and a reverse CSR
    available for the host walk-back (path reconstruction follows
    in-edges of the found levels). numpaths == 1 rides the first-visit
    BFS; numpaths > 1 / weight bounds ride the level-DAG variant.
    Facet-weighted relaxation (the literal IC14 `@facets(weight)`)
    stays on the host path — the batched Bellman-Ford kernel is the
    ROADMAP follow-on."""
    from dgraph_tpu.engine.shortest import MAX_PATH_DEPTH

    sidx = [i for i, b in enumerate(blocks) if b.shortest is not None]
    if len(sidx) != 1:
        return None
    bi = sidx[0]
    sg = blocks[bi]
    a = sg.shortest
    if a.weight_facet:
        return None
    edge_sgs = [c for c in sg.children if _expands(store, c)]
    if len(edge_sgs) != 1:
        return None
    e = edge_sgs[0]
    if (e.filters is not None or e.facet_keys is not None
            or e.facet_filter is not None or e.facet_orders
            or e.children or e.first or e.offset or e.after or e.orders
            or e.var_name or e.lang):
        return None
    # other blocks run per-query on the host AFTER the kernel binds the
    # path var — but only when they don't re-enter shortest themselves
    k = max(1, a.numpaths)
    bounded = a.minweight > float("-inf") or a.maxweight < float("inf")
    max_depth = a.depth or MAX_PATH_DEPTH
    if np.isfinite(a.maxweight):
        max_depth = min(max_depth, max(int(a.maxweight), 0))
    if max_depth < 1 or max_depth > MAX_KERNEL_DEPTH:
        return None
    try:
        if store.rel(e.attr, not e.is_reverse).nnz == 0:
            return None                  # walk-back needs in-edges
        if store.rel(e.attr, e.is_reverse).nnz == 0:
            return None
    except Exception:  # noqa: BLE001 — foreign/routed tablet miss
        return None
    first_visit = k == 1 and not bounded
    sig = ("shortest", e.attr, e.is_reverse, max_depth, k,
           a.minweight, a.maxweight, first_visit)
    return sig, (blocks, bi, a.from_uid, a.to_uid)


def plan_batch(store, queries_blocks):
    """Inspect parsed queries; a plan comes back only when EVERY query
    fits one lane-kernel launch (the homogeneous fast path)."""
    plans, leftover = plan_batch_groups(store, queries_blocks)
    if len(plans) == 1 and not leftover:
        return plans[0][0]
    return None


def plan_batch_groups(store, queries_blocks):
    """Split a MIXED batch into structurally-compatible kernel groups:
    ([(plan, original_indices)], leftover_indices). Groups smaller than
    MIN_BATCH fall back to per-query execution with the leftovers —
    one incompatible query no longer disables the kernel for the rest
    (reference: the per-goroutine mix, served batch-wise here).

    Three kernel families: unfiltered single-block @recurse takes the
    dedicated recurse path (`_BatchPlan`, no permutation translation);
    unweighted `shortest` blocks take the staged lane-BFS
    (`_ShortestPlan`); everything else — filtered recurse, nested level
    trees, multi-block var chains — tries the level-tree planner
    (engine/treebatch.py)."""
    from dgraph_tpu.engine.treebatch import TreePlan, plan_tree

    groups: dict = {}
    sp_groups: dict = {}
    tree_groups: dict = {}
    leftover: list[int] = []
    for i, blocks in enumerate(queries_blocks):
        er = _eligible(store, blocks)
        if er is not None:
            groups.setdefault(er[0], []).append((i, er[1]))
            continue
        es = _eligible_shortest(store, blocks)
        if es is not None:
            sp_groups.setdefault(es[0], []).append((i, es[1]))
            continue
        tp = plan_tree(store, blocks)
        if tp is not None:
            tree_groups.setdefault(tp[0], []).append((i, blocks, tp[1]))
            continue
        leftover.append(i)
    plans = []
    for sig, items in groups.items():
        if not _kernel_worth(f"recurse:{sig[0]}~d{sig[2]}", len(items)):
            leftover.extend(i for i, _ in items)
        else:
            plans.append((_BatchPlan([sg for _, sg in items],
                                     sig[0], sig[1], sig[2]),
                          [i for i, _ in items]))
    for sig, items in sp_groups.items():
        if not _kernel_worth(f"shortest:{sig[1]}~d{sig[3]}",
                             len(items)):
            leftover.extend(i for i, _ in items)
        else:
            plans.append((_ShortestPlan(sig, [it for _, it in items]),
                          [i for i, _ in items]))
    for sig, items in tree_groups.items():
        plan: TreePlan = items[0][2]
        if not _kernel_worth(f"tree:*~d{len(plan.stages)}",
                             len(items)):
            leftover.extend(i for i, _b, _p in items)
        else:
            plan.queries = [b for _i, b, _p in items]
            plans.append((plan, [i for i, _b, _p in items]))
    leftover.sort()
    return plans, leftover


def _kernel_worth(shape: str, n: int) -> bool:
    """Launch gate, by predicted COST rather than query count alone
    (ISSUE 9): `MIN_BATCH` keeps its historical role, but a smaller
    group whose per-shape prior says the work dwarfs the launch
    overhead (`KERNEL_WORTH_US`) still rides the kernel. Without a
    trusted prior (unseen shape, priors off) the count rule decides —
    bit-identical to the pre-prior planner."""
    if n >= MIN_BATCH:
        return True
    if n == 0:
        return False
    from dgraph_tpu.utils import costprior
    if not costprior.enabled():
        return False
    us = costprior.PRIORS.predict_shape(shape)
    return us is not None and us >= KERNEL_WORTH_US


# -- cost-packed launch ordering ---------------------------------------------

def _plan_shape(plan) -> str:
    """The shape-fingerprint component a plan's launch will record
    (matches _note_kernel_features's add_shape) — the prior lookup
    key."""
    from dgraph_tpu.engine.treebatch import TreePlan
    if isinstance(plan, _ShortestPlan):
        return f"shortest:{plan.attr}~d{plan.depth}"
    if isinstance(plan, TreePlan):
        return f"tree:*~d{len(plan.stages)}"
    return f"recurse:{plan.attr}~d{plan.depth}"


def _plan_queries(plan) -> int:
    from dgraph_tpu.engine.treebatch import TreePlan
    if isinstance(plan, (_ShortestPlan, TreePlan)):
        return len(plan.queries)
    return len(plan.blocks)


def plan_cost_us(plan) -> float:
    """Predicted µs for one kernel-group launch: per-shape prior first,
    the feature least-squares fit for unseen shapes (lanes/depth/
    queries are known at plan time — the TpuGraphs-style static
    prediction), query count as the last resort proxy."""
    from dgraph_tpu.engine.treebatch import TreePlan
    from dgraph_tpu.utils import costprior
    n = _plan_queries(plan)
    us = costprior.PRIORS.predict_shape(_plan_shape(plan))
    if us is None:
        depth = (len(plan.stages) if isinstance(plan, TreePlan)
                 else plan.depth)
        us = costprior.PRIORS.predict_features(
            {"lanes": _lane_count(n), "depth": depth, "queries": n})
    if us is None:
        us = 1000.0 * n      # count proxy: every query worth ~1 ms
    return float(us)


def order_plans_by_cost(plans, enabled: bool = True):
    """Order kernel groups for launch by DESCENDING predicted cost
    (longest-processing-time-first: under a shared deadline the
    expensive group starts while the budget is freshest, and total
    makespan shrinks). Gauges the pack imbalance across launches both
    ways — query-count view vs predicted-cost view
    (`plan_pack_imbalance{stage=}`) — so the win of cost packing over
    count packing is visible per batch. Returns a new list; the cached
    plan list is never mutated."""
    plans = list(plans)
    from dgraph_tpu.utils import costprior
    if not enabled or not costprior.enabled() or len(plans) < 2:
        return plans
    counts = [float(_plan_queries(p)) for p, _ in plans]
    costs = [plan_cost_us(p) for p, _ in plans]
    for stage, vals in (("count", counts), ("predicted", costs)):
        mean = sum(vals) / len(vals)
        METRICS.set_gauge("plan_pack_imbalance",
                          max(vals) / mean if mean > 0 else 1.0,
                          stage=stage)
    order = sorted(range(len(plans)), key=lambda i: -costs[i])
    return [plans[i] for i in order]


# -- plan cache --------------------------------------------------------------

# batch plans keyed by (schema fingerprint, query texts): a repeated
# query template (dashboards, benchmark mixes) skips parse + planning
# entirely. Plans carry only parsed SubGraphs — seeds and filters are
# (re)evaluated against the CURRENT snapshot at run time, so reuse
# across stores is sound as long as the schema shape matched.
_plan_memo = Memo("batch.plan", capacity=256, governed="batch.plan")


def _schema_fingerprint(store) -> tuple:
    sch = store.schema
    fp = sch.__dict__.get("_plan_fp")
    if fp is None:
        fp = (tuple(sorted((k, repr(v))
                           for k, v in sch.predicates.items())),
              tuple(sorted((k, repr(v)) for k, v in sch.types.items())))
        sch.__dict__["_plan_fp"] = fp
    return fp


def plan_batch_groups_cached(store, dqls: list):
    """parse + plan_batch_groups with plan memoization. Returns
    ([(plan, original_indices)], leftover_indices); unparseable queries
    land in leftover (the per-query path reproduces their errors)."""
    from dgraph_tpu.dql.parser import parse

    key = (_schema_fingerprint(store), tuple(dqls))
    cached = _plan_memo.get(key)
    if cached is not None:
        METRICS.inc("plan_cache_hits_total", cache="batch")
        costprofile.note("plan_cache_hit", 1)
        return cached
    METRICS.inc("plan_cache_misses_total", cache="batch")
    costprofile.note("plan_cache_hit", 0)
    t_plan = time.perf_counter()
    with tracing.span("batch.plan", queries=len(dqls)):
        parsed = {}
        for i, q in enumerate(dqls):
            try:
                parsed[i] = parse(q)
            except Exception:  # noqa: BLE001 — reproduced per-query
                pass
        order = sorted(parsed)
        plans, group_left = plan_batch_groups(
            store, [parsed[i] for i in order])
        plans = [(p, [order[j] for j in idxs]) for p, idxs in plans]
        leftover = sorted([order[j] for j in group_left]
                          + [i for i in range(len(dqls))
                             if i not in parsed])
    # store under the POST-planning fingerprint: planning may auto-create
    # default schema entries for unknown predicates, which would
    # otherwise shift the lookup key once and miss forever
    costprofile.add("plan_us",
                    int((time.perf_counter() - t_plan) * 1e6))
    sch = store.schema
    sch.__dict__.pop("_plan_fp", None)
    _plan_memo.put((_schema_fingerprint(store), tuple(dqls)),
                   (plans, leftover),
                   rebuild_us=(time.perf_counter() - t_plan) * 1e6)
    memgov.GOVERNOR.maybe_evict("host")
    return plans, leftover


def run_batch(store, plan, device_threshold: int) -> list:
    """Execute the batch as one lane-kernel launch and render each query
    with the standard renderer (full leaf/value support). Dispatches on
    plan family: recurse lane plan here, level-tree plan in treebatch,
    shortest lane-BFS in _run_shortest_batch."""
    import jax

    from dgraph_tpu.engine.treebatch import TreePlan, run_tree_batch

    if isinstance(plan, TreePlan):
        return run_tree_batch(store, plan, device_threshold)
    if isinstance(plan, _ShortestPlan):
        return _run_shortest_batch(store, plan, device_threshold)

    from dgraph_tpu.ops.bfs import pack_seed_masks

    g = _ell_for(store, plan.attr, plan.reverse)
    if g is None:
        return None

    # root seed ranks per query (host index lookups, as run_block does).
    # Lane words round UP to a power of two: padding lanes are zero-seeded
    # and free, and bucketing bounds distinct kernel compiles at O(log B)
    # instead of one multi-second XLA compile per client batch size.
    ex0 = Executor(store, device_threshold=device_threshold)
    seeds = [ex0.root_ranks(sg) for sg in plan.blocks]
    B = _lane_count(len(seeds))
    seed_lists = seeds + [np.zeros(0, np.int32)] * (B - len(seeds))
    mask0 = pack_seed_masks(g, seed_lists)

    # kernel launch gate: past here the fused multi-hop program is one
    # uninterruptible XLA dispatch — the budget check happens before
    # the device is committed, not inside the kernel
    deadline.checkpoint("kernel")
    # kernel-group telemetry: membership, lane-padding waste, compiles
    METRICS.inc("kernel_group_launches_total", family="recurse")
    METRICS.inc("kernel_group_queries_total", float(len(plan.blocks)),
                family="recurse")
    METRICS.inc("kernel_padded_lanes_total", float(B - len(seeds)),
                family="recurse")
    _note_kernel_features(plan.attr, "recurse", B, B - len(seeds),
                          plan.depth, len(plan.blocks))
    costprofile.note_max("bucket_mix", len(g.parts))
    t_exec = time.perf_counter()
    with tracing.span("batch.recurse_kernel", attr=plan.attr,
                      depth=plan.depth, queries=len(plan.blocks),
                      lanes=B, padded_lanes=B - len(seeds)):
        fn = _recurse_for(store, plan.attr, plan.reverse, mask0.shape[1])
        lkey = (plan.attr, plan.reverse, int(mask0.shape[1]),
                plan.depth, g.n)

        def _launch():
            memgov.check_alloc_fault("bfs.ell_recurse")
            with jit_call("bfs.ell_recurse", lkey):
                # the seed mask is donated to the kernel (ops/bfs.py):
                # put a fresh copy per launch (so the OOM retry has an
                # undonated buffer) and let the scan reuse it
                return fn(jax.device_put(mask0), plan.depth, True)

        # allocation failure: evict-to-low + one retry; a second failure
        # sticky-degrades this launch shape and OomDegraded propagates —
        # api.query_batch's per-query fallback serves bit-identically
        _last, _seen, _edges, hops = memgov.oom_retry(
            "bfs.ell_recurse", lkey, _launch)
        hops = np.asarray(hops)      # [depth, n+1, W] fresh masks
    # launch count + dispatch gap are recorded by jit_call itself
    exec_us = (time.perf_counter() - t_exec) * 1e6
    costprofile.add_kernel("recurse", execute_us=exec_us)
    costprofile.add_tablet_cost(plan.attr, exec_us)
    # gather-traffic model per hop (the bench's HBM model): index reads
    # + one mask row per padded slot, times the scan depth
    costprofile.add("bytes_gathered",
                    plan.depth * g.padded_edges
                    * (4 + mask0.shape[1] * 4))
    rel = store.rel(plan.attr, plan.reverse)

    root_nodes = [np.unique(s).astype(np.int32) for s in seeds]
    datas = _rebuild_recurse_batch(store, g, rel, hops, plan.blocks,
                                   root_nodes)
    out = []
    for q, sg in enumerate(plan.blocks):
        ex = Executor(store, device_threshold=device_threshold)
        node = LevelNode(sg=sg, nodes=root_nodes[q],
                         display=root_nodes[q])
        _bind_recurse_vars(ex, node, datas[q], sg)
        node.recurse_data = datas[q]
        out.append(to_json(ex, [node]))
    return out


def _lane_count(nq: int) -> int:
    words = -(-nq // 32)
    return 32 * (1 << (words - 1).bit_length() if words > 1 else 1)


def _note_kernel_features(attr: str, family: str, lanes: int,
                          padded: int, depth: int, queries: int) -> None:
    """Feed one kernel-group launch's plan features into the ambient
    cost recorder (utils/costprofile.py): the shape component joins the
    record to its digest key; lanes/padding/depth are the TpuGraphs-
    style regressors the future cost model trains on."""
    costprofile.add_shape(f"{family}:{attr}~d{depth}")
    costprofile.note_max("lanes", lanes)
    costprofile.note_max("depth", depth)
    costprofile.add("padded_lanes", padded)
    costprofile.note_max("padding_frac",
                         int(1000 * padded / max(lanes, 1)))
    costprofile.add("queries", queries)


def _rebuild_recurse_batch(store, g, rel, hops, blocks,
                           root_nodes) -> list:
    """Per-query first-visit trees from the kernel's per-hop fresh
    masks, ONE batched numpy pass per hop: all queries' parents expand
    through a single shared CSR gather, membership tests are packed-mask
    bit tests (no per-query np.isin / per-query degree slicing), and the
    next frontier falls out of the kept children — exactly the host
    loop's loop=false semantics, B× fewer numpy passes."""
    B = len(blocks)
    depth = hops.shape[0]
    datas = []
    for sg in blocks:
        d = RecurseData(loop=False)
        for c in sg.children:
            (d.edge_sgs if _expands(store, c)
             else d.leaf_sgs).append(c)
        datas.append(d)

    from dgraph_tpu.engine.execute import csr_rows
    qword = np.array([q // 32 for q in range(B)], np.int64)
    qbit = np.array([np.uint32(1 << (q % 32)) for q in range(B)],
                    np.uint32)
    parents = [rn.astype(np.int32) for rn in root_nodes]
    all_nodes = [[rn] for rn in root_nodes]
    p_parts: list[list] = [[] for _ in range(B)]
    c_parts: list[list] = [[] for _ in range(B)]
    for h in range(depth):
        live = [q for q in range(B) if len(parents[q])]
        if not live:
            break
        cat = np.concatenate([parents[q] for q in live])
        counts = np.array([len(parents[q]) for q in live])
        qid = np.repeat(np.arange(len(live)), counts)
        nbrs, seg, _pos = csr_rows(rel, cat)
        if not len(nbrs):
            break
        qe = qid[seg]                      # per-edge live-query index
        rows = g.new_of_old[nbrs]          # permuted mask rows
        lanes = np.asarray(live, np.int64)
        w = qword[lanes[qe]]
        b = qbit[lanes[qe]]
        keep = (hops[h, rows, w] & b) != 0
        kp, kc, kq = cat[seg[keep]], nbrs[keep], qe[keep]
        # edges are query-grouped (cat was), so one split serves all
        bounds = np.searchsorted(kq, np.arange(len(live) + 1))
        for i, q in enumerate(live):
            lo, hi = bounds[i], bounds[i + 1]
            if lo == hi:
                parents[q] = np.zeros(0, np.int32)
                continue
            p_parts[q].append(kp[lo:hi].astype(np.int32))
            c_parts[q].append(kc[lo:hi].astype(np.int32))
            fresh = np.unique(kc[lo:hi]).astype(np.int32)
            parents[q] = fresh
            all_nodes[q].append(fresh)
    edges_total = 0
    for q in range(B):
        if p_parts[q]:
            datas[q].edges[0] = (np.concatenate(p_parts[q]),
                                 np.concatenate(c_parts[q]))
            edges_total += len(datas[q].edges[0][0])
        datas[q].all_nodes = np.unique(
            np.concatenate(all_nodes[q])).astype(np.int32)
    if edges_total:
        costprofile.add("edges_traversed", edges_total)
    return datas


def _rebuild_recurse_data(store, g, rel, hops, q: int, sg: SubGraph,
                          root_nodes: np.ndarray,
                          depth: int) -> RecurseData:
    """Single-query form of the rebuild (kept for direct callers and
    regression tests): extract lane q into a one-word mask stack and
    run the batched pass — membership via packed-mask bit tests instead
    of the old O(edges·log) np.isin against an unsorted fresh set, CSR
    degree slicing shared inside csr_rows."""
    bit = np.uint32(1 << (q % 32))
    lane = ((hops[:depth, :, q // 32] & bit) != 0).astype(np.uint32)
    return _rebuild_recurse_batch(store, g, rel, lane[:, :, None],
                                  [sg], [root_nodes])[0]


# -- shortest lane-BFS -------------------------------------------------------

def _run_shortest_batch(store, plan: _ShortestPlan,
                        device_threshold: int) -> list:
    """Execute one shortest kernel group: seed each lane with its query's
    source, run the staged lane-BFS (first-visit masks for numpaths=1,
    full level-DAG otherwise), then rebuild each query's PathData on the
    host by walking the found levels BACKWARD over the reverse CSR —
    bit-identical to engine/shortest.py's per-query loop, asserted by
    tests/test_batch.py against LDBC IC13/IC14 shapes."""
    import jax

    g = _ell_for(store, plan.attr, plan.reverse)
    if g is None:
        return None
    rrel = store.rel(plan.attr, not plan.reverse)
    if rrel.nnz == 0:
        return None
    n = g.n
    B = len(plan.queries)

    src = store.rank_of(np.asarray(plan.src_uids, np.int64))
    dst = store.rank_of(np.asarray(plan.dst_uids, np.int64))
    lanes = _lane_count(B)
    W = lanes // 32

    # lanes needing a kernel at all: known endpoints, src != dst
    active = [q for q in range(B)
              if src[q] >= 0 and dst[q] >= 0 and src[q] != dst[q]]
    levels: list[np.ndarray] = []      # [n+1, W] per hop, permuted space
    if active:
        mask0 = np.zeros((n + 1, W), np.uint32)
        for q in active:
            r = g.new_of_old[int(src[q])]
            mask0[r, q // 32] |= np.uint32(1 << (q % 32))
        deadline.checkpoint("kernel")
        METRICS.inc("kernel_group_launches_total", family="shortest")
        METRICS.inc("kernel_group_queries_total", float(B),
                    family="shortest")
        METRICS.inc("kernel_padded_lanes_total", float(lanes - B),
                    family="shortest")
        _note_kernel_features(plan.attr, "shortest", lanes, lanes - B,
                              plan.depth, B)
        costprofile.note_max("bucket_mix", len(g.parts))
        t_exec = time.perf_counter()
        step = _step_for(store, plan.attr, plan.reverse, W,
                         plan.first_visit)
        skey = (plan.attr, plan.reverse, W, plan.first_visit, n)
        if memgov.GOVERNOR.is_degraded("bfs.ell_step", skey):
            # sticky OOM degrade: the per-query path serves this shape
            raise memgov.OomDegraded("bfs.ell_step", str(skey))
        unresolved = {q: None for q in active}   # q → found level (bfs)
        dst_rows = {q: int(g.new_of_old[int(dst[q])]) for q in active}
        frontier = jax.device_put(mask0)
        seen = jax.device_put(mask0)
        with tracing.span("batch.shortest_kernel", attr=plan.attr,
                          depth=plan.depth, queries=B, lanes=lanes,
                          padded_lanes=lanes - B,
                          first_visit=plan.first_visit):
            done = 0
            while done < plan.depth and unresolved:
                # budget gate per stage: each launch is one
                # uninterruptible dispatch of SHORTEST_STAGE hops
                deadline.checkpoint("kernel")
                chunk = min(SHORTEST_STAGE, plan.depth - done)
                try:
                    memgov.check_alloc_fault("bfs.ell_step")
                    with jit_call("bfs.ell_step",
                                  (plan.attr, plan.reverse, W, chunk,
                                   plan.first_visit, n)):
                        frontier, seen, hops = step(frontier, seen,
                                                    chunk)
                except Exception as e:
                    if not memgov.is_alloc_failure(e):
                        raise
                    # the carries are DONATED: a failed dispatch leaves
                    # no valid buffers to retry with, so this site
                    # degrades in one step — evict for the next caller,
                    # sticky-mark the shape, per-query path serves
                    memgov.GOVERNOR.note_oom("bfs.ell_step", str(skey))
                    memgov.GOVERNOR.degrade("bfs.ell_step", skey)
                    raise memgov.OomDegraded("bfs.ell_step",
                                             str(skey)) from e
                hops_np = np.asarray(hops)
                # each staged dispatch is one launch: jit_call counts
                # it and bills the host gap between stages
                for h in range(chunk):
                    lvl = hops_np[h]
                    levels.append(lvl)
                    alive = np.bitwise_or.reduce(lvl[:n], axis=0)
                    for q in list(unresolved):
                        wq, bq = q // 32, np.uint32(1 << (q % 32))
                        if plan.first_visit and \
                                (lvl[dst_rows[q], wq] & bq):
                            unresolved.pop(q)   # found: walk back later
                            continue
                        if not (alive[wq] & bq):
                            unresolved.pop(q)   # frontier exhausted
                done += chunk
        exec_us = (time.perf_counter() - t_exec) * 1e6
        costprofile.add_kernel("shortest", execute_us=exec_us)
        costprofile.add_tablet_cost(plan.attr, exec_us)
        costprofile.add("bytes_gathered",
                        done * g.padded_edges * (4 + W * 4))

    out = []
    for q in range(B):
        blocks = plan.queries[q]
        data = _shortest_path_data(store, plan, g, rrel, levels,
                                   int(src[q]), int(dst[q]), q)
        ex = Executor(store, device_threshold=device_threshold)
        from dgraph_tpu.engine.varorder import execution_order
        results: dict[int, LevelNode] = {}
        try:
            order = execution_order(blocks)
        except ValueError:
            return None
        for bi in order:
            sg = blocks[bi]
            if bi == plan.block_idx[q]:
                node = LevelNode(sg=sg, nodes=data.nodes,
                                 path_data=data)
                if sg.var_name:
                    ex.uid_vars[sg.var_name] = data.nodes
                results[bi] = node
            else:
                results[bi] = ex.run_block(sg)
        out.append(to_json(ex, [results[i]
                                for i in range(len(blocks))]))
    return out


def _level_member(g, levels, lvl: int, ranks: np.ndarray, q: int):
    """Bit-test OLD ranks against the level-`lvl` fresh/level mask."""
    m = levels[lvl]
    rows = g.new_of_old[ranks]
    return (m[rows, q // 32] & np.uint32(1 << (q % 32))) != 0


def _shortest_path_data(store, plan, g, rrel, levels, src: int,
                        dst: int, q: int):
    """Rebuild one lane's PathData from the kernel levels — the exact
    paths (and enumeration ORDER) the host loop produces."""
    from dgraph_tpu.engine.shortest import PathData

    blocks = plan.queries[q]
    sg = blocks[plan.block_idx[q]]
    data = PathData(edge_sgs=[c for c in sg.children
                              if _expands(store, c)])
    if src < 0 or dst < 0:
        return data
    k = plan.k

    def parents_of(rank: int, lvl: int) -> list[int]:
        """In-neighbors of `rank` on level `lvl`, ascending — identical
        to the host loop's parent-list order (sorted frontier, one
        pred)."""
        preds = rrel.row(rank).astype(np.int64)
        if not len(preds):
            return []
        if lvl < 0:
            return [int(src)] if (preds == src).any() else []
        keep = _level_member(g, levels, lvl, preds, q)
        return [int(p) for p in preds[keep]]

    paths: list[list[tuple[int, int]]] = []
    if src == dst:
        if plan.minw <= 0 <= plan.maxw:
            paths.append([(src, -1)])
    elif plan.first_visit:
        found = None
        for h in range(len(levels)):
            if _level_member(g, levels, h, np.array([dst]), q)[0]:
                found = h
                break
        if found is not None:
            # walk back choosing each level's FIRST parent — first-visit
            # BFS makes that exactly the host fast path's plist[0]
            rev = [(dst, 0)]
            cur = dst
            for lvl in range(found - 1, -2, -1):
                ps = parents_of(cur, lvl)
                cur = ps[0]
                rev.append((cur, 0) if lvl >= 0 else (cur, -1))
            paths.append(rev[::-1])
    else:
        # level-DAG enumeration in the host's order: per level (length
        # order), DFS over ascending parent lists, simple paths only
        def walk_back(lvl: int, rank: int, on_path: frozenset):
            for p in parents_of(rank, lvl - 1):
                if lvl == 0:
                    if p == src:
                        yield [(src, -1), (rank, 0)]
                elif p not in on_path:
                    for prefix in walk_back(lvl - 1, p, on_path | {p}):
                        yield prefix + [(rank, 0)]

        for lvl in range(len(levels)):
            deadline.checkpoint("bfs")
            hops_count = lvl + 1
            if not (plan.minw <= hops_count <= plan.maxw):
                continue
            if not _level_member(g, levels, lvl, np.array([dst]), q)[0]:
                continue
            for path in walk_back(lvl, dst, frozenset([dst, src])):
                paths.append(path)
                if len(paths) >= k:
                    break
            if len(paths) >= k:
                break
    data.paths = paths[:k]
    if data.paths:
        data.nodes = np.unique(np.array(
            [r for p in data.paths for r, _ in p], np.int32))
    return data


# -- per-snapshot kernel caches ----------------------------------------------

# one lock guards cache init/population on every snapshot: concurrent
# batch requests under ThreadingHTTPServer must not both build/upload the
# same ELL arrays (double HBM) or clobber each other's cache dicts
_cache_lock = locks.make_lock("batch.plan_cache")

# compiled recurse/step kernels are opaque closures; a nominal per-entry
# charge keeps the cache byte-governable with honest relative pressure
_KERNEL_NBYTES_EST = 64 << 10


def _governed_host_cache(host, attr_name: str, gov_name: str, kind: str,
                         sizer, cascade=None) -> None:
    """Register a per-snapshot cache dict (`host.<attr_name>`) with the
    memory governor, once per snapshot. Caller holds `_cache_lock`;
    the callbacks re-take it and close over a weakref so a dropped
    snapshot's caches fall out of the registry with it. Eviction pops
    the oldest-inserted entry (these dicts fill in first-use order, so
    oldest ≈ coldest)."""
    import weakref

    done = getattr(host, "_memgov_registered", None)
    if done is None:
        done = host._memgov_registered = set()
    if attr_name in done:
        return
    done.add(attr_name)
    ref = weakref.ref(host)

    def nbytes():
        h = ref()
        if h is None:
            return 0
        with _cache_lock:
            vals = list((getattr(h, attr_name, None) or {}).values())
        return sum(sizer(v) for v in vals)

    def evict_one():
        h = ref()
        if h is None:
            return 0
        with _cache_lock:
            d = getattr(h, attr_name, None)
            if not d:
                return 0
            k = next(iter(d))
            v = d.pop(k)
            if cascade is not None:
                cascade(h, k)   # drop dependents still pinning bytes
        return sizer(v)

    memgov.GOVERNOR.register(gov_name, kind, nbytes, evict_one,
                             owner=host)


def _drop_dependent_fns(host, dkey) -> None:
    """Evicting a device ELL must also drop the compiled kernels whose
    closures pin its arrays, or the HBM never actually frees. Caller
    holds `_cache_lock`."""
    fns = getattr(host, "_ell_fns", None)
    if not fns:
        return
    attr, reverse = dkey
    for fkey in [k for k in fns if k[1] == attr and k[2] == reverse]:
        del fns[fkey]


def _cache_host(store, attr: str, reverse: bool):
    """Where kernel caches live: the UNDERLYING immutable snapshot when
    the view's predicate data IS the snapshot's (routed/ACL wrappers are
    per-request throwaways — caching on them would rebuild/re-upload per
    batch); the view itself when the data is view-local (e.g. a faulted
    foreign tablet, whose version can change between requests)."""
    base = getattr(store, "_ell_host", store)
    if base is not store:
        pd_view = store.preds.get(attr)
        if pd_view is None or base.preds.get(attr) is not pd_view:
            return store
    return base


def _note_ell_cache(hit: bool) -> None:
    """ell_cache_hit feature bit: 1 only when EVERY ELL lookup of the
    request hit the snapshot cache — one cold build flips it to 0 for
    the whole record (a build dominates the cost)."""
    rec = costprofile.active()
    if rec is None:
        return
    if not hit:
        rec.note("ell_cache_hit", 0)
    elif "ell_cache_hit" not in rec.vals:
        rec.note("ell_cache_hit", 1)


def _ell_for(store, attr: str, reverse: bool):
    """EllGraph per (snapshot, predicate, direction) — built once,
    reused across batches until the snapshot changes (stores are
    immutable; rollup carries untouched predicates' entries forward,
    see carry_kernel_caches)."""
    from dgraph_tpu.ops.bfs import build_ell

    host = _cache_host(store, attr, reverse)
    key = (attr, reverse)
    cache = getattr(host, "_ell_cache", None)
    if cache is not None and key in cache:  # hot path: no lock
        _note_ell_cache(hit=True)
        return cache[key]
    with _cache_lock:
        cache = getattr(host, "_ell_cache", None)
        if cache is None:
            cache = host._ell_cache = {}
            _governed_host_cache(host, "_ell_cache", "batch.ell", "host",
                                 memgov.estimate_nbytes)
        if key in cache:
            _note_ell_cache(hit=True)
        else:
            rel = store.rel(attr, reverse)
            if rel.nnz == 0:
                cache[key] = None
            else:
                _note_ell_cache(hit=False)
                t_build = time.perf_counter()
                with tracing.span("batch.build_ell", pred=attr,
                                  reverse=reverse):
                    g = build_ell(rel.indptr, rel.indices)
                build_us = (time.perf_counter() - t_build) * 1e6
                costprofile.add("build_us", int(build_us))
                costprofile.add_tablet_cost(attr, build_us)
                cache[key] = g
                # segment-CSR padding waste: padded slots / real edges
                METRICS.set_gauge("ell_padding_ratio",
                                  g.padded_edges / max(g.nnz, 1) - 1.0,
                                  pred=attr, reverse=str(reverse))
        out = cache[key]
    memgov.GOVERNOR.maybe_evict("host")
    return out


def _dev_for(store, attr: str, reverse: bool):
    """DeviceEll per (snapshot, pred, dir): the index blocks upload once
    and are shared by every lane width and kernel family."""
    from dgraph_tpu.ops.bfs import device_ell

    host = _cache_host(store, attr, reverse)
    g = _ell_for(store, attr, reverse)  # takes the lock itself
    if g is None:
        return None, None
    with _cache_lock:
        devs = getattr(host, "_ell_devs", None)
        if devs is None:
            devs = host._ell_devs = {}
            _governed_host_cache(host, "_ell_devs", "batch.ell_dev",
                                 "device", memgov.estimate_nbytes,
                                 cascade=_drop_dependent_fns)
        dkey = (attr, reverse)
        if dkey not in devs:
            devs[dkey] = device_ell(g)
        out = g, devs[dkey]
    memgov.GOVERNOR.maybe_evict("device")
    return out


def _recurse_for(store, attr: str, reverse: bool, W: int):
    """Compiled kernel per (snapshot, pred, dir, lane width)."""
    from dgraph_tpu.ops.bfs import make_ell_recurse
    from dgraph_tpu.ops.pallas_hop import pallas_enabled

    host = _cache_host(store, attr, reverse)
    # the hop implementation is baked in at prepare time: the flag is
    # part of the key, so an A/B toggle mid-process can't serve a stale
    # kernel under the other implementation's name
    key = ("recurse", attr, reverse, W, pallas_enabled())
    fns = getattr(host, "_ell_fns", None)
    if fns is not None and key in fns:  # hot path: no lock
        return fns[key]
    g, dev = _dev_for(store, attr, reverse)
    with _cache_lock:
        fns = getattr(host, "_ell_fns", None)
        if fns is None:
            fns = host._ell_fns = {}
            _governed_host_cache(host, "_ell_fns", "batch.kernel",
                                 "host", lambda v: _KERNEL_NBYTES_EST)
        if key not in fns:
            fns[key] = make_ell_recurse(dev, g.outdeg, g.n, W,
                                        count_edges=False)
        return fns[key]


def _step_for(store, attr: str, reverse: bool, W: int,
              first_visit: bool):
    """Compiled resumable hop block per (snapshot, pred, dir, width,
    family) — the staged shortest path's kernel, donated carries."""
    from dgraph_tpu.ops.bfs import make_ell_step
    from dgraph_tpu.ops.pallas_hop import pallas_enabled

    host = _cache_host(store, attr, reverse)
    key = ("step", attr, reverse, W, first_visit, pallas_enabled())
    fns = getattr(host, "_ell_fns", None)
    if fns is not None and key in fns:  # hot path: no lock
        return fns[key]
    g, dev = _dev_for(store, attr, reverse)
    with _cache_lock:
        fns = getattr(host, "_ell_fns", None)
        if fns is None:
            fns = host._ell_fns = {}
            _governed_host_cache(host, "_ell_fns", "batch.kernel",
                                 "host", lambda v: _KERNEL_NBYTES_EST)
        if key not in fns:
            fns[key] = make_ell_step(dev, g.n, W,
                                     first_visit=first_visit)
        return fns[key]


def carry_kernel_caches(old_store, new_store, touched) -> int:
    """Incremental rebuild on snapshot fold: predicates untouched by the
    folded layers rebuilt to IDENTICAL CSR content (same vocabulary ⇒
    same dense rank space), so the old snapshot's ELL blocks, device
    uploads, and compiled kernels stay valid — copy their cache entries
    to the new snapshot instead of rebuilding a 1M-node ELL from
    scratch. Returns how many (pred, direction) entries carried."""
    if old_store is new_store or old_store is None or new_store is None:
        return 0
    if getattr(old_store, "n_nodes", -1) != \
            getattr(new_store, "n_nodes", -2):
        return 0
    if not np.array_equal(old_store.uids, new_store.uids):
        return 0
    carry_mesh_residency(old_store, new_store, touched)
    carried = 0
    with _cache_lock:
        src_cache = getattr(old_store, "_ell_cache", None)
        if not src_cache:
            return 0
        dst_cache = getattr(new_store, "_ell_cache", None)
        if dst_cache is None:
            dst_cache = new_store._ell_cache = {}
            _governed_host_cache(new_store, "_ell_cache", "batch.ell",
                                 "host", memgov.estimate_nbytes)
        src_devs = getattr(old_store, "_ell_devs", {}) or {}
        src_fns = getattr(old_store, "_ell_fns", {}) or {}
        dst_devs = getattr(new_store, "_ell_devs", None)
        if dst_devs is None:
            dst_devs = new_store._ell_devs = {}
            _governed_host_cache(new_store, "_ell_devs", "batch.ell_dev",
                                 "device", memgov.estimate_nbytes,
                                 cascade=_drop_dependent_fns)
        dst_fns = getattr(new_store, "_ell_fns", None)
        if dst_fns is None:
            dst_fns = new_store._ell_fns = {}
            _governed_host_cache(new_store, "_ell_fns", "batch.kernel",
                                 "host", lambda v: _KERNEL_NBYTES_EST)
        for key, gval in src_cache.items():
            attr = key[0]
            if attr in touched or key in dst_cache:
                continue
            dst_cache[key] = gval
            if key in src_devs:
                dst_devs[key] = src_devs[key]
            for fkey, fn in src_fns.items():
                if fkey[1] == attr and fkey[2] == key[1]:
                    dst_fns.setdefault(fkey, fn)
            carried += 1
    if carried:
        METRICS.inc("ell_cache_carried_total", float(carried))
    return carried


def carry_mesh_residency(old_store, new_store, touched) -> int:
    """Sharded mesh tablets (store.sharded_rel cache) carry across a
    fold exactly like ELL/device blocks: a predicate the folded layers
    didn't touch rebuilds to identical CSR content, so the placed shard
    stack stays valid for the same mesh — the serving path never
    re-uploads a resident tablet because of an unrelated fold."""
    src = getattr(old_store, "_sharded", None)
    if not src:
        return 0
    mesh = getattr(old_store, "_sharded_mesh", None)
    with _cache_lock:
        dst = getattr(new_store, "_sharded", None)
        if dst is None or getattr(new_store, "_sharded_mesh",
                                  None) is not mesh:
            dst = new_store._sharded = {}
            new_store._sharded_mesh = mesh
        carried = 0
        for key, srel in src.items():
            if key[0] in touched or key in dst:
                continue
            dst[key] = srel
            carried += 1
    if carried:
        METRICS.inc("mesh_resident_carried_total", float(carried))
    return carried