"""Batched @recurse serving: many concurrent queries, ONE lane kernel.

Reference parity: the reference serves a concurrent query mix with
per-query goroutines (worker/task.go); the TPU-native equivalent packs
structurally-compatible `@recurse` queries into the bit-lanes of
`ops/bfs.py ell_recurse` — one fused multi-hop program answers the whole
batch (the north-star kernel, reached from the SERVING path, not just
the bench). Ineligible queries fall back to the per-query engine.

Eligibility (per query): exactly one root block, `@recurse(depth: d,
loop: false)` with the SAME predicate/direction and depth across the
batch, no filters/facets on the recursed edge, no root pagination/
ordering. Value leaves are unrestricted — rendering reuses the standard
renderer over per-query RecurseData rebuilt from the kernel's per-hop
first-visit masks.
"""

from __future__ import annotations

import numpy as np

from dgraph_tpu.engine.execute import Executor, LevelNode
from dgraph_tpu.engine.ir import SubGraph
from dgraph_tpu.engine.outputnode import to_json
from dgraph_tpu.engine.recurse import RecurseData, _bind_recurse_vars
from dgraph_tpu.utils import deadline, locks, tracing
from dgraph_tpu.utils.jitcache import jit_call
from dgraph_tpu.utils.metrics import METRICS

MIN_BATCH = 4            # below this the per-query engine is cheaper
# Depth is a static arg of the jitted kernel: each distinct value is an
# XLA compile, and the scan materializes a [depth, n+1, W] hops buffer
# with no early exit. Depths past any real graph's diameter fall back to
# the per-query engine (whose host loop exits when the frontier empties)
# instead of letting a client-controlled depth size device buffers.
MAX_KERNEL_DEPTH = 64


class _BatchPlan:
    def __init__(self, blocks, attr, reverse, depth):
        self.blocks = blocks          # one root SubGraph per query
        self.attr = attr
        self.reverse = reverse
        self.depth = depth


def _expands(store, c: SubGraph) -> bool:
    from dgraph_tpu.engine.execute import expands
    return expands(store.schema, c)


def _eligible(store, blocks):
    """(signature, root_sg) when the query fits the lane kernel, else
    None. The signature is what must MATCH across a kernel launch."""
    if len(blocks) != 1:
        return None
    sg = blocks[0]
    r = sg.recurse
    if r is not None and r.depth and r.depth > MAX_KERNEL_DEPTH:
        return None
    if (r is None or r.loop or not r.depth or sg.shortest is not None
            or sg.filters is not None or sg.first or sg.offset
            or sg.after or sg.orders or sg.groupby or sg.cascade
            or sg.normalize or sg.var_name):
        return None
    edge_sgs = [c for c in sg.children if _expands(store, c)]
    if len(edge_sgs) != 1:
        return None
    e = edge_sgs[0]
    if (e.filters is not None or e.facet_filter is not None
            or e.facet_orders or e.facet_keys is not None
            or e.first or e.offset or e.after or e.orders
            or e.var_name):
        return None
    return (e.attr, e.is_reverse, r.depth), sg


def plan_batch(store, queries_blocks) -> _BatchPlan | None:
    """Inspect parsed queries; a plan comes back only when EVERY query
    fits one lane-kernel launch (the homogeneous fast path)."""
    plans, leftover = plan_batch_groups(store, queries_blocks)
    if len(plans) == 1 and not leftover:
        return plans[0][0]
    return None


def plan_batch_groups(store, queries_blocks):
    """Split a MIXED batch into structurally-compatible kernel groups:
    ([(plan, original_indices)], leftover_indices). Groups smaller than
    MIN_BATCH fall back to per-query execution with the leftovers —
    one incompatible query no longer disables the kernel for the rest
    (reference: the per-goroutine mix, served batch-wise here).

    Two kernel families: unfiltered single-block @recurse takes the
    dedicated recurse path (`_BatchPlan`, no permutation translation);
    everything else — filtered recurse, nested level trees, multi-block
    var chains — tries the level-tree planner (engine/treebatch.py)."""
    from dgraph_tpu.engine.treebatch import TreePlan, plan_tree

    groups: dict = {}
    tree_groups: dict = {}
    leftover: list[int] = []
    for i, blocks in enumerate(queries_blocks):
        er = _eligible(store, blocks)
        if er is not None:
            groups.setdefault(er[0], []).append((i, er[1]))
            continue
        tp = plan_tree(store, blocks)
        if tp is not None:
            tree_groups.setdefault(tp[0], []).append((i, blocks, tp[1]))
            continue
        leftover.append(i)
    plans = []
    for sig, items in groups.items():
        if len(items) < MIN_BATCH:
            leftover.extend(i for i, _ in items)
        else:
            plans.append((_BatchPlan([sg for _, sg in items],
                                     sig[0], sig[1], sig[2]),
                          [i for i, _ in items]))
    for sig, items in tree_groups.items():
        if len(items) < MIN_BATCH:
            leftover.extend(i for i, _b, _p in items)
        else:
            plan: TreePlan = items[0][2]
            plan.queries = [b for _i, b, _p in items]
            plans.append((plan, [i for i, _b, _p in items]))
    leftover.sort()
    return plans, leftover


def run_batch(store, plan, device_threshold: int) -> list:
    """Execute the batch as one lane-kernel launch and render each query
    with the standard renderer (full leaf/value support). Dispatches on
    plan family: recurse lane plan here, level-tree plan in treebatch."""
    import jax

    from dgraph_tpu.engine.treebatch import TreePlan, run_tree_batch

    if isinstance(plan, TreePlan):
        return run_tree_batch(store, plan, device_threshold)

    from dgraph_tpu.ops.bfs import pack_seed_masks

    g = _ell_for(store, plan.attr, plan.reverse)
    if g is None:
        return None

    # root seed ranks per query (host index lookups, as run_block does).
    # Lane words round UP to a power of two: padding lanes are zero-seeded
    # and free, and bucketing bounds distinct kernel compiles at O(log B)
    # instead of one multi-second XLA compile per client batch size.
    ex0 = Executor(store, device_threshold=device_threshold)
    seeds = [ex0.root_ranks(sg) for sg in plan.blocks]
    words = -(-len(seeds) // 32)
    B = 32 * (1 << (words - 1).bit_length() if words > 1 else 1)
    seed_lists = seeds + [np.zeros(0, np.int32)] * (B - len(seeds))
    mask0 = pack_seed_masks(g, seed_lists)

    # kernel launch gate: past here the fused multi-hop program is one
    # uninterruptible XLA dispatch — the budget check happens before
    # the device is committed, not inside the kernel
    deadline.checkpoint("kernel")
    # kernel-group telemetry: membership, lane-padding waste, compiles
    METRICS.inc("kernel_group_launches_total", family="recurse")
    METRICS.inc("kernel_group_queries_total", float(len(plan.blocks)),
                family="recurse")
    METRICS.inc("kernel_padded_lanes_total", float(B - len(seeds)),
                family="recurse")
    with tracing.span("batch.recurse_kernel", attr=plan.attr,
                      depth=plan.depth, queries=len(plan.blocks),
                      lanes=B, padded_lanes=B - len(seeds)):
        fn = _recurse_for(store, plan.attr, plan.reverse, mask0.shape[1])
        with jit_call("bfs.ell_recurse",
                      (plan.attr, plan.reverse, int(mask0.shape[1]),
                       plan.depth, g.n)):
            _last, _seen, _edges, hops = fn(jax.device_put(mask0),
                                            plan.depth, True)
        hops = np.asarray(hops)      # [depth, n+1, W] fresh masks
    rel = store.rel(plan.attr, plan.reverse)

    out = []
    for q, sg in enumerate(plan.blocks):
        ex = Executor(store, device_threshold=device_threshold)
        root_nodes = np.unique(seeds[q]).astype(np.int32)
        node = LevelNode(sg=sg, nodes=root_nodes,
                         display=root_nodes)
        data = _rebuild_recurse_data(store, g, rel, hops, q, sg,
                                     root_nodes, plan.depth)
        _bind_recurse_vars(ex, node, data, sg)
        node.recurse_data = data
        out.append(to_json(ex, [node]))
    return out


def _rebuild_recurse_data(store, g, rel, hops, q: int, sg: SubGraph,
                          root_nodes: np.ndarray,
                          depth: int) -> RecurseData:
    """Per-query first-visit tree from the kernel's per-hop fresh masks:
    hop h's parents are hop h-1's first-visit set; a (p, c) edge is kept
    when c is first visited at hop h — exactly the host loop's
    loop=false semantics."""
    data = RecurseData(loop=False)
    for c in sg.children:
        (data.edge_sgs if _expands(store, c)
         else data.leaf_sgs).append(c)

    word, bit = q // 32, np.uint32(1 << (q % 32))
    parents = root_nodes
    all_nodes = [root_nodes]
    p_parts, c_parts = [], []
    for h in range(depth):
        if not len(parents):
            break
        fresh_rows = np.nonzero((hops[h, :g.n, word] & bit) != 0)[0]
        fresh = np.sort(g.perm_order[fresh_rows]).astype(np.int32)
        if not len(fresh):
            break
        # edges parent → (CSR row ∩ fresh)
        deg = rel.indptr[parents + 1] - rel.indptr[parents]
        total = int(deg.sum())
        if total:
            seg = np.repeat(np.arange(len(parents)), deg)
            base = np.repeat(np.cumsum(deg) - deg, deg)
            pos = (np.repeat(rel.indptr[parents].astype(np.int64), deg)
                   + np.arange(total) - base)
            nbrs = rel.indices[pos]
            keep = np.isin(nbrs, fresh)
            p_parts.append(parents[seg[keep]].astype(np.int32))
            c_parts.append(nbrs[keep].astype(np.int32))
        parents = fresh
        all_nodes.append(fresh)
    if p_parts:
        data.edges[0] = (np.concatenate(p_parts), np.concatenate(c_parts))
    data.all_nodes = np.unique(np.concatenate(all_nodes)).astype(np.int32)
    return data


# -- per-snapshot kernel caches ----------------------------------------------

# one lock guards cache init/population on every snapshot: concurrent
# batch requests under ThreadingHTTPServer must not both build/upload the
# same ELL arrays (double HBM) or clobber each other's cache dicts
_cache_lock = locks.make_lock("batch.plan_cache")


def _cache_host(store, attr: str, reverse: bool):
    """Where kernel caches live: the UNDERLYING immutable snapshot when
    the view's predicate data IS the snapshot's (routed/ACL wrappers are
    per-request throwaways — caching on them would rebuild/re-upload per
    batch); the view itself when the data is view-local (e.g. a faulted
    foreign tablet, whose version can change between requests)."""
    base = getattr(store, "_ell_host", store)
    if base is not store:
        pd_view = store.preds.get(attr)
        if pd_view is None or base.preds.get(attr) is not pd_view:
            return store
    return base


def _ell_for(store, attr: str, reverse: bool):
    """EllGraph per (snapshot, predicate, direction) — built once,
    reused across batches until the snapshot changes (stores are
    immutable)."""
    from dgraph_tpu.ops.bfs import build_ell

    host = _cache_host(store, attr, reverse)
    key = (attr, reverse)
    cache = getattr(host, "_ell_cache", None)
    if cache is not None and key in cache:  # hot path: no lock
        return cache[key]
    with _cache_lock:
        cache = getattr(host, "_ell_cache", None)
        if cache is None:
            cache = host._ell_cache = {}
        if key not in cache:
            rel = store.rel(attr, reverse)
            if rel.nnz == 0:
                cache[key] = None
            else:
                with tracing.span("batch.build_ell", pred=attr,
                                  reverse=reverse):
                    g = build_ell(rel.indptr, rel.indices)
                cache[key] = g
                # degree-bucket padding waste: padded slots / real edges
                METRICS.set_gauge("ell_padding_ratio",
                                  g.padded_edges / max(g.nnz, 1),
                                  pred=attr, reverse=str(reverse))
        return cache[key]


def _recurse_for(store, attr: str, reverse: bool, W: int):
    """Compiled kernel per (snapshot, pred, dir, lane width). The device
    arrays upload once per (pred, dir) and are shared across widths."""
    import jax

    from dgraph_tpu.ops.bfs import make_ell_recurse
    from dgraph_tpu.ops.pallas_hop import pallas_enabled

    host = _cache_host(store, attr, reverse)
    # the hop implementation is baked in at prepare time: the flag is
    # part of the key, so an A/B toggle mid-process can't serve a stale
    # kernel under the other implementation's name
    key = (attr, reverse, W, pallas_enabled())
    fns = getattr(host, "_ell_fns", None)
    if fns is not None and key in fns:  # hot path: no lock
        return fns[key]
    g = _ell_for(store, attr, reverse)  # takes the lock itself
    with _cache_lock:
        fns = getattr(host, "_ell_fns", None)
        if fns is None:
            fns = host._ell_fns = {}
        devs = getattr(host, "_ell_devs", None)
        if devs is None:
            devs = host._ell_devs = {}
        if key not in fns:
            dkey = (attr, reverse)
            if dkey not in devs:
                devs[dkey] = [jax.device_put(e) for e in g.ells]
            fns[key] = make_ell_recurse(devs[dkey], None, g.n, W,
                                        count_edges=False)
        return fns[key]
