"""SubGraph execution: ProcessGraph the TPU-native way.

Reference parity: `query/query.go` (SubGraph.ProcessGraph — recursive
per-level expansion, filter application, pagination/order), `worker/task.go`
(processTask) and `query/outputnode.go` (JSON assembly lives in
outputnode.py).

Execution model (SURVEY §7): each level's expansion is ONE batched CSR
gather over the whole frontier — device path through `ops.expand_frontier`
(jitted, static bucket sizes) for large frontiers, numpy path for small
ones; both produce identical (neighbors, seg) pairs. Per-uid goroutines and
per-child RPC fan-out from the reference collapse into array programs.

A level's result is a `LevelNode`:
  nodes        sorted unique ranks at this level (the next frontier)
  matrix_seg   edge → position in parent.nodes   (pb.Result.UidMatrix rows)
  matrix_child edge → child rank (row-ordered: order/pagination applied)
Content is computed once per unique uid (as the reference does), while the
matrix preserves per-parent row structure for nested JSON.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from dgraph_tpu import ops
from dgraph_tpu.engine.funcs import (EMPTY, eval_func,
                                     eval_func_universe)
from dgraph_tpu.engine.ir import FilterNode, FuncNode, Order, SubGraph
from dgraph_tpu.store.store import Store
from dgraph_tpu.store.types import Kind
from dgraph_tpu.utils import costprofile, memgov
from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.utils import tracing
from dgraph_tpu.utils.jitcache import jit_call
from dgraph_tpu.utils.metrics import METRICS


EMPTY64 = np.zeros(0, np.int64)


@dataclass
class LevelNode:
    sg: SubGraph
    nodes: np.ndarray                      # sorted unique int32 ranks
    matrix_seg: np.ndarray = field(default_factory=lambda: EMPTY)
    matrix_child: np.ndarray = field(default_factory=lambda: EMPTY)
    matrix_pos: np.ndarray = field(default_factory=lambda: EMPTY64)
    display: np.ndarray | None = None      # root blocks: ordered rank list
    children: list["LevelNode"] = field(default_factory=list)
    leaf_sgs: list[SubGraph] = field(default_factory=list)
    recurse_data: object | None = None     # engine.recurse.RecurseData
    path_data: object | None = None        # engine.shortest.PathData
    groups: object | None = None           # engine.groupby.GroupResult
    # @msgpass binding (engine/feat.py): rank → f32[d] aggregate; None
    # means the level carries no binding (key "" likewise)
    feat_vals: dict | None = None
    feat_key: str = ""


def _bucket(n: int, lo: int = 64) -> int:
    b = lo
    # graftlint: allow(hot-loop-checkpoint): O(log n) shift arithmetic
    while b < n:
        b <<= 1
    return b


def csr_rows(rel, frontier: np.ndarray):
    """Host CSR row gather for a frontier → (neighbors, seg, edge_pos).
    The one shared implementation of the per-uid posting walk (reference:
    posting.List.Uids per uid; here one vectorized gather) — used by the
    small-frontier expand path and the lane-batch mask rebuild."""
    starts = rel.indptr[frontier]
    deg = rel.indptr[frontier + 1] - starts
    total = int(deg.sum())
    if total == 0:
        return EMPTY, EMPTY, EMPTY64
    seg = np.repeat(np.arange(len(frontier), dtype=np.int32), deg)
    base = np.repeat(np.cumsum(deg) - deg, deg)
    pos = np.repeat(starts.astype(np.int64), deg) + \
        (np.arange(total, dtype=np.int64) - base)
    return rel.indices[pos], seg, pos


class Executor:
    """Executes SubGraph trees against a Store snapshot.

    `device_threshold`: frontiers at least this large expand via the jitted
    TPU kernel; smaller ones via numpy (dispatch overhead dominates tiny
    frontiers). Set to 0 to force the device path (tests do).
    """

    def __init__(self, store: Store, device_threshold: int = 512,
                 mesh=None):
        self.store = store
        self.device_threshold = device_threshold
        self.mesh = mesh  # jax.sharding.Mesh | None: SPMD expansion path
        # variable environments (reference: query var propagation)
        self.uid_vars: dict[str, np.ndarray] = {}
        self.val_vars: dict[str, dict[int, object]] = {}

    # -- frontier expansion (the hot op) ------------------------------------
    def expand(self, pred: str, reverse: bool, frontier: np.ndarray,
               allow_remote: bool = True):
        """Whole-frontier CSR expansion → (neighbors, seg, edge_pos) host
        arrays. `edge_pos` indexes the CSR of the expansion direction;
        facet consumers map reverse positions through facet_positions()
        (forward-aligned) AT USE so facet-free reverse hops — the hot
        distributed-task path — never pay for the rev→fwd table.

        On a routed view, a small-frontier hop over a foreign tablet may
        execute on the OWNER via ServeTask instead of faulting the whole
        tablet in (reference: ProcessTaskOverNetwork); remote results
        carry no edge positions, so callers needing facets pass
        allow_remote=False."""
        with tracing.span("ops.expand", pred=pred, reverse=reverse,
                          frontier=int(len(frontier))) as sp:
            t0 = time.perf_counter()
            out, path = self._expand_routed(pred, reverse, frontier,
                                            allow_remote)
            sp.attrs["path"] = path
            sp.attrs["edges"] = int(len(out[0]))
            if self.mesh is not None:
                # route-selector accounting: which path won while a
                # mesh was configured (the promotion A/B signal)
                METRICS.inc("mesh_route_total", route=path)
            if len(out[0]):
                # learned route costs: µs per 1k edges EMA per path —
                # the prior the selector consults to promote the mesh
                # route below the static threshold
                from dgraph_tpu.utils import costprior
                costprior.PRIORS.learn_route(
                    path, (time.perf_counter() - t0) * 1e6
                    / max(len(out[0]), 1) * 1000.0)
                # the north-star counter, labeled by execution path
                METRICS.inc("edges_traversed_total", float(len(out[0])),
                            path=path)
                costprofile.add("edges_traversed", int(len(out[0])))
                # gather-traffic model: neighbor + seg + position words
                costprofile.add("bytes_gathered", 16 * int(len(out[0])))
                # placement signal: modeled µs charged to this tablet
                # (~16 host edges per µs — the same order the bench's
                # CPU baseline measures)
                costprofile.add_tablet_cost(pred, len(out[0]) // 16 + 1)
            return out

    def _expand_routed(self, pred: str, reverse: bool,
                       frontier: np.ndarray, allow_remote: bool):
        """expand()'s dispatch body → ((nbrs, seg, pos), path) where
        `path` names the execution route (telemetry label)."""
        if allow_remote and len(frontier):
            rem = getattr(self.store, "remote_expand", None)
            if rem is not None:
                out = rem(pred, reverse, frontier)
                if out is not None:
                    return out, "remote"
        rel = self.store.rel(pred, reverse)
        # cost-model regressor: the largest tablet this request touched
        costprofile.note_max("tablet_rows", int(len(rel.indptr)) - 1)
        if len(frontier) == 0 or rel.nnz == 0:
            return (EMPTY, EMPTY, EMPTY64), "empty"
        if len(frontier) >= self.device_threshold:
            try:
                if self.mesh is not None:
                    return (self._expand_mesh(pred, reverse, frontier),
                            "mesh")
                return (self._expand_device(pred, reverse, frontier),
                        "device")
            except memgov.OomDegraded:
                # allocation failure survived its evict-retry (or the
                # shape is sticky-degraded): the host walk produces the
                # identical (nbrs, seg, pos) triple
                pass
        elif self.mesh is not None and self._mesh_promoted(len(frontier)):
            try:
                return self._expand_mesh(pred, reverse, frontier), "mesh"
            except memgov.OomDegraded:
                pass
        return csr_rows(rel, frontier), "numpy"

    # learned-promotion floor: below this many frontier rows, per-launch
    # dispatch overhead dominates any measured per-edge win, so the
    # numpy path keeps them regardless of what the route EMAs say
    mesh_floor = 64

    def _mesh_promoted(self, n: int) -> bool:
        """Cost-prior route promotion: frontiers below device_threshold
        still take the mesh route when the measured per-edge cost EMAs
        (utils/costprior.py, learned from every expansion) say the mesh
        is cheaper than the host walk. Before any data exists — or with
        priors disabled — the classic threshold routing is unchanged."""
        from dgraph_tpu.utils import costprior
        if n < self.mesh_floor or not costprior.enabled():
            return False
        m = costprior.PRIORS.route_cost("mesh")
        h = costprior.PRIORS.route_cost("numpy")
        return m is not None and h is not None and m < h

    def _note_mesh_shards(self, counts) -> None:
        """Shard-keyed accounting for one mesh-routed expansion: the
        shape component + shard-count feature the cost priors key on,
        and modeled per-shard µs into the shard cost sums (the
        scheduler/placement signal /debug/scheduler surfaces)."""
        counts = np.asarray(counts)
        costprofile.add_shape("mesh")
        costprofile.note_max("mesh_shards", int(len(counts)))
        for d, c in enumerate(counts.tolist()):
            if int(c):
                costprofile.add_shard_cost(d, int(c) // 16 + 1)

    def facet_positions(self, sg: SubGraph, pos: np.ndarray) -> np.ndarray:
        """Edge positions in the forward-CSR space facet columns key on
        (reference: facets live on the forward posting but render on
        reverse edges too)."""
        if sg.is_reverse:
            return self.store.rev_to_fwd_pos(sg.attr, pos)
        return pos

    def _shard_edge_cap(self, srel, frontier: np.ndarray,
                        deg: np.ndarray) -> int:
        """Per-shard edge-cap bucket: rows partition over shards, so each
        shard needs only ITS slab's degree sum."""
        shard_of = np.minimum(frontier // srel.rows_per_shard,
                              srel.n_shards - 1)
        per_shard = np.bincount(shard_of, weights=deg,
                                minlength=srel.n_shards)
        return _bucket(max(int(per_shard.max()), 1))

    @staticmethod
    def _stitch_edge_parts(parts):
        """Stitch per-shard edge slices into one global edge matrix:
        each frontier row's edges come from exactly one slice, so a
        stable sort by seg recovers global CSR row order. `parts` yields
        (nbrs, seg, local_pos, pos_lo) — pos offsets into the absolute
        facet position space."""
        parts_n, parts_s, parts_p = [], [], []
        for nbrs, seg, pos, pos_lo in parts:
            if not len(nbrs):
                continue
            parts_n.append(nbrs)
            parts_s.append(seg)
            parts_p.append(pos.astype(np.int64) + int(pos_lo))
        if not parts_n:
            return EMPTY, EMPTY, EMPTY64
        nbrs = np.concatenate(parts_n)
        seg = np.concatenate(parts_s)
        pos = np.concatenate(parts_p)
        order = np.argsort(seg, kind="stable")
        return nbrs[order], seg[order], pos[order]

    @classmethod
    def _reassemble_shards(cls, srel, nbrs_s, seg_s, pos_s, counts):
        from dgraph_tpu.parallel.mesh import host_np
        nbrs_s, seg_s, pos_s = (host_np(nbrs_s), host_np(seg_s),
                                host_np(pos_s))
        counts = host_np(counts)
        return cls._stitch_edge_parts(
            (nbrs_s[d, :int(counts[d])], seg_s[d, :int(counts[d])],
             pos_s[d, :int(counts[d])], srel.pos_lo[d])
            for d in range(srel.n_shards))

    # frontiers above this replicate poorly: shard them and ring-rotate
    # over ICI instead (the long-context analog, SURVEY §5). Tests lower
    # it to force the ring path on small fixtures.
    ring_threshold = 1 << 17

    def _expand_mesh(self, pred: str, reverse: bool, frontier: np.ndarray):
        """SPMD expansion over the device mesh: every device expands the
        row slab it owns, outputs stay sharded, the host reassembles the
        edge matrix (reference: ProcessTaskOverNetwork scatter/gather —
        SURVEY §3.1 — with gRPC replaced by residency + one shard_map).
        Frontiers past ring_threshold ride the sharded ring path."""
        from dgraph_tpu.parallel.dhop import matrix_hop

        if len(frontier) > self.ring_threshold:
            return self._expand_mesh_ring(pred, reverse, frontier)
        srel = self.store.sharded_rel(pred, reverse, self.mesh)
        fr = ops.pad_to(frontier, _bucket(len(frontier)))
        deg = self.store.rel(pred, reverse).degree(frontier)
        edge_cap = self._shard_edge_cap(srel, frontier, deg)
        from dgraph_tpu.parallel.mesh import host_np

        def _launch():
            memgov.check_alloc_fault("mesh.matrix_hop")
            return matrix_hop(self.mesh, srel, fr, edge_cap)

        nbrs_s, seg_s, pos_s, totals, max_shard = memgov.oom_retry(
            "mesh.matrix_hop", (pred, reverse), _launch)
        max_shard = int(host_np(max_shard))
        assert max_shard <= edge_cap, (max_shard, edge_cap)
        totals = host_np(totals)
        self._note_mesh_shards(totals)
        return self._reassemble_shards(srel, nbrs_s, seg_s, pos_s, totals)

    def _expand_mesh_ring(self, pred: str, reverse: bool,
                          frontier: np.ndarray):
        """Sharded-frontier expansion: chunks rotate ring-wise (ppermute)
        while each device expands against its resident row slab — the
        engine route for frontiers too large to replicate (SURVEY §5
        long-context analog; structural cousin of ring attention)."""
        from dgraph_tpu.parallel.dhop import ring_matrix_hop
        from dgraph_tpu.parallel.pshard import shard_frontier

        srel = self.store.sharded_rel(pred, reverse, self.mesh)
        d = srel.n_shards
        per = -(-len(frontier) // d)
        f_cap = _bucket(max(per, 1))
        chunks = shard_frontier(frontier, d, f_cap)
        # per (origin chunk × shard) edge cap: a chunk meets every slab
        deg = self.store.rel(pred, reverse).degree(frontier)
        rows_per = srel.rows_per_shard
        shard_of = np.minimum(frontier // rows_per, d - 1)
        chunk_of = np.minimum(np.arange(len(frontier)) // per, d - 1)
        per_pair = np.zeros((d, d))
        np.add.at(per_pair, (chunk_of, shard_of), deg)
        edge_cap = _bucket(max(int(per_pair.max()), 1))
        from dgraph_tpu.parallel.mesh import host_np

        def _launch():
            memgov.check_alloc_fault("mesh.ring_matrix_hop")
            return ring_matrix_hop(self.mesh, srel, chunks, edge_cap)

        nbrs_a, seg_a, pos_a, totals, max_e = memgov.oom_retry(
            "mesh.ring_matrix_hop", (pred, reverse), _launch)
        assert int(host_np(max_e)) <= edge_cap, edge_cap
        nbrs_a, seg_a, pos_a = (host_np(nbrs_a), host_np(seg_a),
                                host_np(pos_a))
        totals = host_np(totals)
        self._note_mesh_shards(totals.sum(axis=1))
        nbrs, seg, pos = self._stitch_edge_parts(
            (nbrs_a[dev, i, :int(totals[dev, i])],
             seg_a[dev, i, :int(totals[dev, i])] + ((dev - i) % d) * per,
             pos_a[dev, i, :int(totals[dev, i])], srel.pos_lo[dev])
            for dev in range(d) for i in range(d))
        keep = seg < len(frontier)  # drop chunk padding rows
        return nbrs[keep], seg[keep], pos[keep]

    def _expand_device(self, pred: str, reverse: bool, frontier: np.ndarray):
        indptr, indices = self.store.device_rel(pred, reverse)
        fcap = _bucket(len(frontier))
        fr = ops.pad_to(frontier, fcap)
        deg = self.store.rel(pred, reverse).degree(frontier)
        ecap = _bucket(max(int(deg.sum()), 1))
        from dgraph_tpu.ops.hop import launch_key

        def _launch():
            memgov.check_alloc_fault("hop.gather_edges")
            with jit_call("hop.gather_edges",
                          launch_key(indptr, fr, ecap)):
                return ops.gather_edges(indptr, indices, fr, ecap)

        # OOM lifecycle: evict-to-low + one retry, then sticky degrade
        # of this predicate's device route (OomDegraded → numpy walk)
        nbrs, seg, pos, valid, total = memgov.oom_retry(
            "hop.gather_edges", (pred, reverse), _launch)
        valid = np.asarray(valid)
        return (np.asarray(nbrs)[valid], np.asarray(seg)[valid],
                np.asarray(pos)[valid].astype(np.int64))

    # -- filters ------------------------------------------------------------
    def apply_filter(self, tree: FilterNode | None, universe: np.ndarray) -> np.ndarray:
        """Evaluate a filter tree restricted to `universe` (sorted ranks).
        Reference: filter SubGraphs + algo.IntersectSorted/Difference.
        Comparison/has leaves evaluate AGAINST the universe (cost tracks
        the frontier); other funcs materialize their set and intersect."""
        if tree is None:
            return universe
        if tree.op == "leaf":
            f = tree.func
            if f.name != "uid" and not f.is_val_var and not f.is_count:
                sub = eval_func_universe(self.store, f, universe)
                if sub is not None:
                    return sub
            return np.intersect1d(universe, self._leaf_set(tree.func, universe))
        if tree.op == "not":
            return np.setdiff1d(universe, self.apply_filter(tree.children[0], universe))
        parts = [self.apply_filter(c, universe) for c in tree.children]
        out = parts[0]
        for p in parts[1:]:
            out = np.intersect1d(out, p) if tree.op == "and" else np.union1d(out, p)
        return out.astype(np.int32)

    def filter_set(self, tree: FilterNode | None) -> np.ndarray | None:
        """Evaluate a filter tree to its allowed set WITHOUT a universe —
        index lookups only, so host cost scales with the result, never with
        n_nodes (reference: index-backed filter SubGraphs). Returns None
        when the tree needs a complement (`not`), which only a universe can
        answer; callers then filter against gathered neighbors instead."""
        if tree is None:
            return None
        if tree.op == "leaf":
            return self._leaf_set(tree.func, EMPTY).astype(np.int32)
        if tree.op == "not":
            return None
        parts = [self.filter_set(c) for c in tree.children]
        if any(p is None for p in parts):
            return None
        out = parts[0]
        for p in parts[1:]:
            out = (np.intersect1d(out, p) if tree.op == "and"
                   else np.union1d(out, p))
        return out.astype(np.int32)

    def _var_ranks(self, name: str) -> np.ndarray:
        """uid(x): a uid var's ranks, or a val var's uid domain."""
        if name in self.uid_vars:
            return self.uid_vars[name]
        if name in self.val_vars:
            return np.array(sorted(self.val_vars[name]), np.int32)
        # reference: referencing an undefined variable is a request error,
        # not an empty result (gql validateResult var checks)
        raise ValueError(f"variable {name!r} is used but not defined")

    def filter_edges(self, filters: FilterNode | None, nbrs: np.ndarray,
                     seg: np.ndarray, pos: np.ndarray | None = None):
        """Apply a filter tree to a flattened edge list, re-masking rows.
        Shared by plain expansion, @recurse, and shortest-path hops."""
        if pos is None:
            pos = EMPTY64
        if filters is None or not len(nbrs):
            return nbrs, seg, pos
        allowed = self.apply_filter(filters, np.unique(nbrs).astype(np.int32))
        keep = np.isin(nbrs, allowed)
        return nbrs[keep], seg[keep], (pos[keep] if len(pos) else pos)

    def _bind_facet_vars(self, sg: SubGraph, nbrs, pos) -> None:
        """@facets(v as key): value var keyed by CHILD rank. A child
        reached over several edges sums numeric facet values (reference:
        facet-variable aggregation)."""
        cols = self.store.edge_facets(
            sg.attr, self.facet_positions(sg, pos),
            [k for _, k in sg.facet_vars])
        for var, key in sg.facet_vars:
            vals = cols.get(key)
            m: dict = {}
            if vals is not None:
                for c, v in zip(nbrs.tolist(), vals):
                    if v is None:
                        continue
                    prev = m.get(c)
                    if (prev is not None and not isinstance(v, bool)
                            and isinstance(v, (int, float))
                            and isinstance(prev, (int, float))):
                        m[int(c)] = prev + v
                    else:
                        m[int(c)] = v
            self.val_vars[var] = m

    def facet_filter_edges(self, sg: SubGraph, pred: str,
                           nbrs: np.ndarray, seg: np.ndarray,
                           pos: np.ndarray):
        """@facets(eq(k, v) ...) — drop edges whose facets fail the tree
        (reference: facets filtering in worker facetsFilter)."""
        if sg.facet_filter is None or not len(nbrs):
            return nbrs, seg, pos
        keep = self._eval_facet_tree(sg.facet_filter, pred,
                                     self.facet_positions(sg, pos))
        return nbrs[keep], seg[keep], pos[keep]

    def _eval_facet_tree(self, tree: FilterNode, pred: str,
                         pos: np.ndarray) -> np.ndarray:
        if tree.op == "leaf":
            f = tree.func
            fvals = self.store.edge_facets(pred, pos, [f.attr]).get(
                f.attr, [None] * len(pos))
            want0 = f.args[0] if f.args else None
            out = np.zeros(len(pos), bool)
            for i, v in enumerate(fvals):
                if v is None:
                    continue
                want = _coerce_to(want0, v)
                try:
                    if f.name == "eq":
                        out[i] = v == want or str(v) == str(want)
                    elif f.name == "le":
                        out[i] = v <= want
                    elif f.name == "lt":
                        out[i] = v < want
                    elif f.name == "ge":
                        out[i] = v >= want
                    elif f.name == "gt":
                        out[i] = v > want
                except TypeError:
                    pass
            return out
        if tree.op == "not":
            return ~self._eval_facet_tree(tree.children[0], pred, pos)
        parts = [self._eval_facet_tree(c, pred, pos) for c in tree.children]
        out = parts[0]
        for p in parts[1:]:
            out = (out & p) if tree.op == "and" else (out | p)
        return out

    def _leaf_set(self, f: FuncNode, universe: np.ndarray) -> np.ndarray:
        if f.name == "uid" and (f.args or not f.uids):
            # mixed literals and variables: union both
            parts = [self._var_ranks(a) for a in f.args]
            if f.uids:
                r = self.store.rank_of(np.array(f.uids, np.int64))
                parts.append(r[r >= 0].astype(np.int32))
            return (np.unique(np.concatenate(parts)).astype(np.int32)
                    if parts else EMPTY)
        if f.name == "similar_to":
            # routed k-NN seed: device/mesh brute-force top-k with host
            # fallback — bit-identical to funcs.host_similar on every
            # route (store/vec.py)
            from dgraph_tpu.store.vec import similar_ranks
            return similar_ranks(self.store, f, mesh=self.mesh,
                                 device_threshold=self.device_threshold)
        return eval_func(self.store, f, self.val_vars)

    # -- root evaluation ----------------------------------------------------
    def root_ranks(self, sg: SubGraph) -> np.ndarray:
        f = sg.func
        if f is None:
            return EMPTY
        return self._leaf_set(f, EMPTY)

    # -- ordering / pagination ----------------------------------------------
    def _value_keys(self, ranks: np.ndarray, order: Order):
        """Sort keys for ranks by a value predicate or val-var. Missing
        values get a placeholder key (they sort last via the has-key)."""
        if order.is_val_var:
            var = self.val_vars.get(order.attr, {})
            vals = [var.get(int(r)) for r in ranks]
        elif not order.lang and (col := self.store.value_col(order.attr)) is not None:
            # vectorised first-value lookup on the sorted columnar pair
            ranks_arr = np.asarray(ranks, np.int32)
            idx = np.searchsorted(col.subj, ranks_arr)
            idx_c = np.minimum(idx, max(len(col.subj) - 1, 0))
            hit = (len(col.subj) > 0) & (col.subj[idx_c] == ranks_arr)
            vals = [col.vals[i] if h else None
                    for i, h in zip(idx_c.tolist(), np.atleast_1d(hit).tolist())]
        else:
            vals = []
            for r in ranks:
                vs = self.store.values_for(order.attr, int(r), order.lang)
                vals.append(vs[0] if vs else None)
        has = np.array([v is not None for v in vals], bool)
        present = [_orderable(v) for v in vals if v is not None]
        placeholder = present[0] if present else 0
        keys = np.array([_orderable(v) if v is not None else placeholder
                         for v in vals])
        return keys, has

    def order_ranks(self, ranks: np.ndarray, orders: list[Order],
                    seg: np.ndarray | None = None):
        """Stable multi-key ordering, optionally within segments (rows).
        lexsort priority: seg (row) > first order > ... > uid tiebreak."""
        if not orders:
            return np.arange(len(ranks))
        keys = [np.asarray(ranks)]  # lowest priority: uid tiebreak
        for o in reversed(orders):
            k, has = self._value_keys(ranks, o)
            if o.desc:
                k = _negate_key(k)
            keys.append(k)
            keys.append(~has)  # missing values last, asc or desc
        if seg is not None:
            keys.append(seg)
        return np.lexsort(tuple(keys))

    def _facet_order(self, sg: SubGraph, nbrs: np.ndarray, seg: np.ndarray,
                     pos: np.ndarray) -> np.ndarray:
        """Row-internal ordering by facet values (@facets(orderasc: k));
        edges without the facet sort last."""
        keys = [np.asarray(nbrs)]
        fpos = self.facet_positions(sg, pos)
        for o in reversed(sg.facet_orders):
            fvals = self.store.edge_facets(sg.attr, fpos, [o.attr]).get(
                o.attr, [None] * len(pos))
            has = np.array([v is not None for v in fvals], bool)
            present = [_orderable(v) for v in fvals if v is not None]
            placeholder = present[0] if present else 0
            k = np.array([_orderable(v) if v is not None else placeholder
                          for v in fvals])
            if o.desc:
                k = _negate_key(k)
            keys.append(k)
            keys.append(~has)
        keys.append(seg)
        return np.lexsort(tuple(keys))

    def paginate(self, arr_len: int, sg: SubGraph, ranks: np.ndarray) -> np.ndarray:
        """Row slice per first/offset/after → index array into the row."""
        idx = np.arange(arr_len)
        if sg.after:
            after_rank = self.store.rank_of(np.array([sg.after], np.int64))[0]
            idx = idx[ranks > after_rank] if after_rank >= 0 else idx
        if sg.offset:
            idx = idx[sg.offset:]
        if sg.first > 0:
            idx = idx[:sg.first]
        elif sg.first < 0:
            idx = idx[sg.first:]
        return idx

    # -- block execution ----------------------------------------------------
    def run_block(self, sg: SubGraph) -> LevelNode:
        """Execute one root block (reference: Request.ProcessQuery per block)."""
        dl.checkpoint("block")
        with tracing.span("engine.block", block=sg.attr) as sp:
            is_knn = sg.func is not None and sg.func.name == "similar_to"
            t0 = time.perf_counter() if is_knn else 0.0
            node = self._run_block(sg)
            if is_knn:
                # the graphrag_read_p99 SLO watches this histogram: the
                # retrieval workload's per-block latency under whatever
                # route (fused/staged, host/device/mesh) actually served
                METRICS.observe("graphrag_latency_us",
                                (time.perf_counter() - t0) * 1e6)
            sp.attrs["nodes"] = int(len(node.nodes))
            return node

    def _run_block(self, sg: SubGraph) -> LevelNode:
        if sg.shortest is not None:
            from dgraph_tpu.engine.shortest import shortest_path
            data = shortest_path(self, sg)
            node = LevelNode(sg=sg, nodes=data.nodes, path_data=data)
            if sg.var_name:
                self.uid_vars[sg.var_name] = data.nodes
            return node
        # whole-query fusion (engine/fused.py): an eligible block tree
        # compiles into ONE jitted program — zero host round-trips
        # between levels. None → the staged path below, bit-identical.
        from dgraph_tpu.engine.fused import try_fused
        fused_node = try_fused(self, sg)
        if fused_node is not None:
            from dgraph_tpu.engine import feat
            if feat.needs_msgpass(sg):
                # the fused featprop stage binds recurse levels
                # in-trace; anything it didn't claim aggregates here
                feat.annotate_tree(self, fused_node)
            return fused_node
        display = self.root_display(sg)
        nodes = np.unique(display).astype(np.int32)
        node = LevelNode(sg=sg, nodes=nodes, display=display.astype(np.int32))
        if sg.var_name:
            self.uid_vars[sg.var_name] = nodes
        if sg.groupby:
            from dgraph_tpu.engine.groupby import process_groupby
            node.groups = process_groupby(self, node)
            return node
        self._descend(node)
        from dgraph_tpu.engine import feat
        if feat.needs_msgpass(sg):
            feat.annotate_tree(self, node)
        return node

    def root_display(self, sg: SubGraph) -> np.ndarray:
        """Root evaluation through ordering + pagination → the block's
        ordered display list (run_block's root half; also the seed set
        the lane-batch planner packs into kernel lanes)."""
        ranks = self.root_ranks(sg)
        ranks = self.apply_filter(sg.filters, ranks)
        display = self._mesh_order_topk(sg, ranks)
        if display is None:
            order_idx = (self.order_ranks(ranks, sg.orders)
                         if sg.orders else np.arange(len(ranks)))
            display = ranks[order_idx]
        page = self.paginate(len(display), sg, display)
        return display[page].astype(np.int32)

    def _descend(self, parent: LevelNode) -> None:
        from dgraph_tpu.engine.recurse import expand_recurse
        if parent.sg.recurse is not None:
            expand_recurse(self, parent)
            return
        for child_sg in self._concrete_children(parent):
            if self._expands(child_sg):
                parent.children.append(self.run_child(child_sg, parent.nodes))
            else:
                parent.leaf_sgs.append(child_sg)
                self._record_leaf_vars(child_sg, parent)

    def run_child(self, sg: SubGraph, frontier: np.ndarray) -> LevelNode:
        """Expand one uid-predicate child level below `frontier`."""
        nbrs, seg, pos, processed = self._level_edges(sg, frontier)
        return self._finish_child(sg, nbrs, seg, pos, processed)

    def _level_edges(self, sg: SubGraph, frontier: np.ndarray):
        """One child level's filtered edge list → (nbrs, seg, pos,
        processed). `processed` means ordering/pagination were already
        applied (the fused device path, which is only eligible when no
        ordering exists). The lane-batch executor overrides this with
        mask-constrained CSR intersection (engine/treebatch.py)."""
        # per-level cancellation point — the acceptance granularity: a
        # deep tree stops within ONE level of its budget expiring
        dl.checkpoint("level")
        with tracing.span("engine.level", pred=sg.attr,
                          frontier=int(len(frontier))) as sp:
            fused = self._fused_level(sg, frontier)
            if fused is not None:
                sp.attrs["path"] = "fused"
                sp.attrs["edges"] = int(len(fused[0]))
                if len(fused[0]):
                    METRICS.inc("edges_traversed_total",
                                float(len(fused[0])), path="fused")
                    costprofile.add("edges_traversed",
                                    int(len(fused[0])))
                    costprofile.add("bytes_gathered",
                                    16 * int(len(fused[0])))
                return (*fused, True)
            nbrs, seg, pos = self.expand(
                sg.attr, sg.is_reverse, frontier,
                allow_remote=not _needs_facets(sg))
            nbrs, seg, pos = self.filter_edges(sg.filters, nbrs, seg, pos)
            nbrs, seg, pos = self.facet_filter_edges(sg, sg.attr, nbrs,
                                                     seg, pos)
            sp.attrs["edges"] = int(len(nbrs))
            return nbrs, seg, pos, False

    def _finish_child(self, sg: SubGraph, nbrs, seg, pos,
                      processed: bool) -> LevelNode:
        """Ordering, per-row pagination, node building, var binding and
        descent below one expanded level (run_child's second half)."""
        if not processed:
            # row-internal ordering (default: uid order from the CSR)
            if sg.orders or sg.facet_orders:
                if sg.facet_orders:
                    order_idx = self._facet_order(sg, nbrs, seg, pos)
                else:
                    order_idx = self._mesh_row_order(sg, nbrs, seg)
                    if order_idx is None:
                        order_idx = self.order_ranks(nbrs, sg.orders,
                                                     seg=seg)
                nbrs, seg = nbrs[order_idx], seg[order_idx]
                pos = pos[order_idx] if len(pos) else pos
            # per-row pagination (seg is nondecreasing: CSR construction
            # order, preserved by masking; lexsort keys on seg first)
            if sg.first or sg.offset or sg.after:
                rows = np.unique(seg)
                starts = np.searchsorted(seg, rows)
                ends = np.searchsorted(seg, rows, "right")
                keep_idx = []
                for s, e in zip(starts.tolist(), ends.tolist()):
                    row_idx = np.arange(s, e)
                    keep_idx.append(
                        row_idx[self.paginate(e - s, sg, nbrs[row_idx])])
                if keep_idx:
                    keep_idx = np.sort(np.concatenate(keep_idx))
                    nbrs, seg = nbrs[keep_idx], seg[keep_idx]
                    pos = pos[keep_idx] if len(pos) else pos
        nodes = np.unique(nbrs).astype(np.int32)
        node = LevelNode(sg=sg, nodes=nodes,
                         matrix_seg=seg.astype(np.int32),
                         matrix_child=nbrs.astype(np.int32),
                         matrix_pos=pos)
        if sg.var_name:
            self.uid_vars[sg.var_name] = nodes
        if sg.facet_vars:
            self._bind_facet_vars(sg, nbrs, pos)
        if sg.groupby:
            from dgraph_tpu.engine.groupby import process_groupby_rows
            node.groups = process_groupby_rows(self, node)
            return node
        self._descend(node)
        return node

    def _mesh_order_topk(self, sg: SubGraph, ranks: np.ndarray):
        """Order-by pushdown on the mesh (reference: SortOverNetwork):
        single-key `orderasc/orderdesc` runs as per-shard top-k + on-mesh
        merge — capped when `first` bounds the result, full-length
        otherwise (orderdesc+offset, no-first). String keys ride a
        rank-dictionary float column. Returns the ordered display list,
        or None → host ordering path."""
        if (self.mesh is None or len(sg.orders) != 1
                or sg.first < 0 or sg.after
                or len(ranks) < self.device_threshold):
            return None
        o = sg.orders[0]
        if o.is_val_var:
            return None
        from dgraph_tpu.parallel.dsort import mesh_topk
        k = (sg.first + max(sg.offset, 0)) if sg.first else len(ranks)
        return mesh_topk(self.mesh, self.store, o.attr, o.lang,
                         ranks, k, desc=o.desc)

    def _mesh_row_order(self, sg: SubGraph, nbrs: np.ndarray,
                        seg: np.ndarray):
        """Child-level (per-row) order-by on the mesh: the whole edge list
        sorts by (row, key, uid) in one SPMD program (reference:
        worker/sort.go per-group sort + coordinator merge). None → host
        lexsort path."""
        if (self.mesh is None or len(sg.orders) != 1 or sg.facet_orders
                or len(nbrs) < self.device_threshold):
            return None
        o = sg.orders[0]
        if o.is_val_var:
            return None
        from dgraph_tpu.parallel.dsort import mesh_row_sort
        return mesh_row_sort(self.mesh, self.store, o.attr, o.lang,
                             nbrs, seg, desc=o.desc)

    def _fused_level(self, sg: SubGraph, frontier: np.ndarray):
        """Large-frontier fast path: expand → filter → paginate → dedupe
        fused into ONE jitted program (ops.level.expand_level); the only
        host work is evaluating the filter tree to a sorted allowed set.
        Returns (nbrs, seg, pos) or None when ineligible (ordering, facet
        filters and `after` cursors need per-edge host logic)."""
        if (len(frontier) < self.device_threshold
                or sg.orders or sg.facet_orders or sg.after
                or sg.facet_filter is not None):
            return None
        rel = self.store.rel(sg.attr, sg.is_reverse)
        if len(frontier) == 0 or rel.nnz == 0:
            return None if rel.nnz else (EMPTY, EMPTY, EMPTY64)
        from dgraph_tpu.ops.level import NO_LIMIT, expand_level

        use_allowed = sg.filters is not None
        if use_allowed:
            # universe-free allowed set: index lookups only, so host cost
            # tracks the filter's selectivity, not n_nodes. Complement-
            # shaped trees (`not`) fall back to the gathered-neighbor path.
            allowed = self.filter_set(sg.filters)
            if allowed is None:
                return None
            allowed_d = ops.pad_to(allowed, _bucket(max(len(allowed), 1)))
        else:
            allowed_d = ops.pad_to(EMPTY, 1)
        first = sg.first if sg.first else NO_LIMIT
        fr = ops.pad_to(frontier, _bucket(len(frontier)))
        deg = rel.degree(frontier)
        if self.mesh is not None:
            return self._fused_level_mesh(sg, frontier, fr, deg, allowed_d,
                                          first, use_allowed)
        indptr, indices = self.store.device_rel(sg.attr, sg.is_reverse)
        ecap = _bucket(max(int(deg.sum()), 1))
        with jit_call("level.expand_level",
                      (int(indptr.shape[0]), int(fr.shape[0]),
                       int(allowed_d.shape[0]), ecap, use_allowed)):
            c_nbrs, c_seg, c_pos, n_kept, _nxt, _nu, total = expand_level(
                indptr, indices, fr, allowed_d,
                np.int32(sg.offset), np.int32(first),
                edge_cap=ecap, out_cap=ecap, use_allowed=use_allowed)
        n = int(n_kept)
        assert int(total) <= ecap, (int(total), ecap)
        return (np.asarray(c_nbrs)[:n], np.asarray(c_seg)[:n],
                np.asarray(c_pos)[:n].astype(np.int64))

    def _fused_level_mesh(self, sg: SubGraph, frontier, fr, deg, allowed_d,
                          first, use_allowed: bool):
        """Fused level on the mesh: expand+filter+paginate per shard in one
        SPMD program, host only reassembles row order (the served-mesh
        seam; reference: pushdown into each group's processTask)."""
        from dgraph_tpu.parallel.dhop import matrix_level

        srel = self.store.sharded_rel(sg.attr, sg.is_reverse, self.mesh)
        edge_cap = self._shard_edge_cap(srel, frontier, deg)
        from dgraph_tpu.parallel.mesh import host_np
        nbrs_s, seg_s, pos_s, kept, totals, max_shard = matrix_level(
            self.mesh, srel, fr, allowed_d, sg.offset, first,
            edge_cap, use_allowed)
        assert int(host_np(max_shard)) <= edge_cap, edge_cap
        self._note_mesh_shards(host_np(totals))
        METRICS.inc("mesh_route_total", route="fused")
        return self._reassemble_shards(srel, nbrs_s, seg_s, pos_s, kept)

    # -- leaves, vars, expand(_all_) ----------------------------------------
    def _concrete_children(self, parent: LevelNode) -> list[SubGraph]:
        """Resolve expand(_all_)/expand(Type) into concrete child blocks.
        Reference: query/expand.go semantics via type system."""
        out: list[SubGraph] = []
        for c in parent.sg.children:
            if not c.is_expand_all:
                out.append(c)
                continue
            if c.expand_arg and c.expand_arg != "_all_":
                preds = self.store.predicates_of_types([c.expand_arg])
            else:
                type_names: set[str] = set()
                for r in parent.nodes:
                    type_names.update(
                        self.store.values_for("dgraph.type", int(r)))
                preds = self.store.predicates_of_types(sorted(type_names))
            for p in preds:
                ps = self.store.schema.peek(p)
                if ps and ps.kind == Kind.UID:
                    out.append(SubGraph(attr=p, children=list(c.children)))
                else:
                    out.append(SubGraph(attr=p))
        return out

    def _expands(self, sg: SubGraph) -> bool:
        return expands(self.store.schema, sg)

    def _record_leaf_vars(self, sg: SubGraph, parent: LevelNode) -> None:
        """Bind value/count vars declared on leaves (a as age, c as count(p))."""
        if not sg.var_name:
            return
        if sg.is_uid_leaf and not sg.is_count:
            # `v as uid` binds the enclosing block's uid set (reference:
            # gql uid var on the uid field — the upsert-block idiom);
            # `c as count(uid)` stays a value var (the count branch below)
            self.uid_vars[sg.var_name] = parent.nodes
            return
        if sg.is_count:
            rel = self.store.rel(sg.attr, sg.is_reverse)
            deg = rel.degree(parent.nodes)
            self.val_vars[sg.var_name] = {
                int(r): int(d) for r, d in zip(parent.nodes, deg)}
        elif sg.math_expr is not None:
            from dgraph_tpu.engine.mathexpr import eval_math
            self.val_vars[sg.var_name] = eval_math(
                sg.math_expr, parent.nodes, self.val_vars)
        elif sg.is_val_leaf:
            src = self.val_vars.get(sg.attr, {})
            self.val_vars[sg.var_name] = {
                int(r): src[int(r)] for r in parent.nodes if int(r) in src}
        else:
            env: dict[int, object] = {}
            for r in parent.nodes:
                vs = self.store.values_for(sg.attr, int(r), sg.lang)
                if vs:
                    env[int(r)] = vs[0]
            self.val_vars[sg.var_name] = env


def _needs_facets(sg) -> bool:
    """Whether a block consumes edge positions (facet render/filter/order)
    — remote per-hop results carry none."""
    return (sg.facet_keys is not None or sg.facet_filter is not None
            or sg.facet_vars is not None or bool(sg.facet_orders))


def expands(schema, sg: SubGraph) -> bool:
    """Whether a child block triggers uid expansion (vs a value leaf).
    Schema-driven, as the reference routes by tablet type. Shared by the
    executor and the batch planner — the routing rule must never fork."""
    if (sg.is_count or sg.is_uid_leaf or sg.is_agg or sg.is_val_leaf
            or sg.math_expr is not None):
        return False
    if sg.is_reverse or sg.children or sg.recurse or sg.shortest:
        return True
    ps = schema.peek(sg.attr)
    return bool(ps and ps.kind == Kind.UID)


def _coerce_to(want, v):
    """Coerce a parsed (string) comparison arg to the facet value's type
    (reference: facets are typed per-posting; filter args convert to them)."""
    if not isinstance(want, str):
        return want
    try:
        if isinstance(v, (bool, np.bool_)):
            return want.strip().lower() in ("true", "1")
        if isinstance(v, (int, np.integer)):
            return int(want)
        if isinstance(v, (float, np.floating)):
            return float(want)
    except ValueError:
        pass
    return want


def _orderable(v):
    import numpy as _np
    if isinstance(v, _np.datetime64):
        return v.astype("datetime64[us]").astype("int64")
    if isinstance(v, (bool, _np.bool_)):
        return int(v)
    return v


def _negate_key(k: np.ndarray) -> np.ndarray:
    if k.dtype.kind in "if":
        return -k
    # strings: lexsort can't negate; invert via rank mapping
    uniq, inv = np.unique(k, return_inverse=True)
    return (len(uniq) - 1 - inv).astype(np.int64)
