"""Native JSON emission: lower executed LevelNode trees to columnar specs.

Reference parity: `query/outputnode.go` (`fastJsonNode`, `ToJson`) — the
reference renders responses with a purpose-built byte encoder instead of
generic marshalling; this module plays that role for the serving path.
A block whose feature set fits the columnar form (plain value / uid /
count / val leaves plus uid edges) lowers to flat arrays — per-leaf
pre-encoded JSON fragments aligned to the level's rank domain, per-child
CSR row maps in domain-position space — and native/emit.cpp walks them,
so no per-object Python dict/list assembly happens while serving.
Feature-rich blocks (@normalize, @cascade, @groupby, @recurse, facets,
shortest) fall back to the dict renderer per block.
"""

from __future__ import annotations

import ctypes
import json
from json.encoder import encode_basestring_ascii as _esc

import numpy as np

from dgraph_tpu import native
from dgraph_tpu.engine.execute import LevelNode
from dgraph_tpu.engine.outputnode import _Renderer, _json_val, to_json
from dgraph_tpu.store.types import Kind
from dgraph_tpu.utils import deadline

_SEP = (",", ":")


def to_json_bytes(ex, roots: list[LevelNode]) -> bytes:
    """Serialized `to_json` result; byte-identical semantics (parsed JSON
    equality) with the dict path, native-emitted where eligible."""
    if not native.HAVE_EMIT:
        return json.dumps(to_json(ex, roots), separators=_SEP).encode()
    r: _Renderer | None = None
    parts: dict[str, bytes] = {}
    path_objs: list | None = None
    for node in roots:
        if node.sg.is_internal:
            continue
        if node.sg.shortest is not None:
            if r is None:
                r = _Renderer(ex)
            if path_objs is None:
                path_objs = []
                parts["_path_"] = b"[]"  # pins insertion order
            path_objs.extend(r.render_paths(node))
            continue
        name = node.sg.alias or node.sg.attr or "q"
        payload = _emit_native(ex, node) if _eligible(node) else None
        if payload is None:
            if r is None:
                r = _Renderer(ex)
            payload = json.dumps(r.render_block(node),
                                 separators=_SEP).encode()
        parts[name] = payload
    if path_objs is not None:
        parts["_path_"] = json.dumps(path_objs, separators=_SEP).encode()
    return b"{" + b",".join(
        _esc(k).encode() + b":" + v for k, v in parts.items()) + b"}"


def _eligible(node: LevelNode) -> bool:
    sg = node.sg
    if sg.msgpass is not None:
        # @msgpass bindings (vector-valued entries) stay on the dict
        # renderer — the native emitter has no float-list row kind
        return False
    if node.recurse_data is not None:
        return _recurse_eligible(node)
    if (node.groups is not None
            or node.path_data is not None or sg.normalize or sg.cascade
            or sg.facet_keys is not None):
        return False
    if not _leaves_eligible(node.leaf_sgs):
        return False
    return all(_eligible(child) for child in node.children)


def _leaves_eligible(leaf_sgs) -> bool:
    for leaf in leaf_sgs:
        if (leaf.is_agg or leaf.math_expr is not None
                or leaf.checkpwd_val is not None or leaf.lang == "*"
                or leaf.facet_keys is not None
                or (leaf.is_count and leaf.is_uid_leaf)):
            return False
    return True


def _recurse_eligible(node: LevelNode) -> bool:
    """loop=false @recurse lowers to a chain of per-depth levels (the
    first-visit forest IS a level tree — outputnode's loop=false
    semantics render each rank's global-matrix subtree wherever it
    appears, and ranks partition by first-visit depth). loop=true and
    facet/paginated edges keep the dict renderer."""
    sg = node.sg
    data = node.recurse_data
    if (data.loop or sg.normalize or sg.cascade
            or sg.facet_keys is not None):
        return False
    for e in data.edge_sgs:
        if (e.facet_keys is not None or e.facet_orders
                or e.facet_filter is not None or e.orders
                or e.first or e.offset or e.after or e.children):
            return False
    return _leaves_eligible(data.leaf_sgs)


def _emit_native(ex, node: LevelNode) -> bytes | None:
    """One eligible root block → JSON array bytes (None = lower failed,
    caller falls back to the dict renderer)."""
    keep: list = []     # pins every buffer the C side reads
    levels: list = []   # DgLevel structs in child-first order
    spec = _lower_level(ex, node, keep, levels)
    if spec is None:
        return None
    dom = node.nodes
    display = node.display if node.display is not None else dom
    pos = _positions(dom, np.asarray(display))
    if pos is None:
        return None
    return native.emit_block(spec, pos, len(levels))


def _positions(dom: np.ndarray, ranks: np.ndarray) -> np.ndarray | None:
    """Ranks → positions in the sorted domain; None if any rank is absent
    (renderer semantics would need per-rank store fallbacks — punt)."""
    if not len(ranks):
        return np.zeros(0, np.int32)
    if not len(dom):
        return None
    pos = np.minimum(np.searchsorted(dom, ranks), len(dom) - 1)
    if not np.array_equal(dom[pos], ranks):
        return None
    return pos.astype(np.int32)


def _edges_for(ps: np.ndarray, cs: np.ndarray, dom: np.ndarray):
    """Edges whose (parent-sorted) parents fall in sorted `dom` →
    (row_counts per dom position, child ranks grouped by dom position,
    stored order preserved within each parent)."""
    lo = np.searchsorted(ps, dom, "left")
    hi = np.searchsorted(ps, dom, "right")
    counts = (hi - lo).astype(np.int64)
    total = int(counts.sum())
    if not total:
        return counts, np.zeros(0, cs.dtype)
    base = np.repeat(np.cumsum(counts) - counts, counts)
    rows = np.repeat(lo.astype(np.int64), counts) + np.arange(total) - base
    return counts, cs[rows]


def _lower_recurse(ex, node: LevelNode, keep: list, levels: list):
    """loop=false RecurseData → chained DgLevels, one per first-visit
    depth. Each rank's children in the global first-visit matrix link
    only to next-depth ranks (freshness), so the chain reproduces the
    dict renderer's memoized subtree semantics exactly. Every pred's
    edge matrix is parent-sorted ONCE; each level then selects its slice
    by searchsorted ranges (no per-depth full-matrix scans)."""
    data = node.recurse_data
    grouped = {}
    for i in data.edges:
        parents, childs = data.edges[i]
        order = np.argsort(parents, kind="stable")  # keeps stored order
        grouped[i] = (parents[order], childs[order])

    # depth assignment: roots at 0; a fresh child's depth = parent + 1
    seen: set[int] = {int(r) for r in node.nodes}
    level_doms = [np.asarray(node.nodes, np.int32)]
    while True:
        deadline.checkpoint("emit")
        parts = [_edges_for(ps, cs, level_doms[-1])[1]
                 for ps, cs in grouped.values()]
        parts = [p for p in parts if len(p)]
        if not parts:
            break
        nxt = np.unique(np.concatenate(parts))
        nxt = np.array([c for c in nxt.tolist() if c not in seen],
                       np.int32)
        if not len(nxt):
            break
        seen.update(nxt.tolist())
        level_doms.append(nxt)

    # build bottom-up so each level can point at the next
    next_lvl = None
    for h in range(len(level_doms) - 1, -1, -1):
        dom = level_doms[h]
        leaves = []
        for leaf in data.leaf_sgs:
            lowered = _lower_leaf(ex, leaf, dom, keep)
            if lowered is not None:
                leaves.append(lowered)
        children = []
        if next_lvl is not None:
            ndom = level_doms[h + 1]
            for i, esg in enumerate(data.edge_sgs):
                if i not in grouped:
                    continue
                counts, c_h = _edges_for(*grouped[i], dom)
                if not len(c_h):
                    continue
                indptr = np.concatenate(
                    [[0], np.cumsum(counts)]).astype(np.int64)
                pos = _positions(ndom, c_h)
                if pos is None:
                    return None
                name = esg.alias or (
                    f"~{esg.attr}" if esg.is_reverse else esg.attr)
                key = _key(name, keep)
                keep += [pos, indptr]
                children.append(native.DgChild(
                    key=_bp(key), key_len=len(key),
                    level=ctypes.pointer(next_lvl),
                    row_indptr=_vp(indptr), row_child=_vp(pos)))
        next_lvl = _build_level(len(dom), leaves, children, keep, levels)
    return next_lvl


def _build_level(dom_len: int, leaves: list, children: list, keep: list,
                 levels: list):
    """Assemble one DgLevel from lowered leaves/children — the single
    ctypes layout site shared by the plain and recurse lowerings."""
    leaf_arr = (native.DgLeaf * len(leaves))(*leaves) if leaves else None
    child_arr = (native.DgChild * len(children))(*children) if children \
        else None
    keep += [leaf_arr, child_arr]
    lvl = native.DgLevel(
        n=dom_len,
        n_leaves=len(leaves),
        leaves=ctypes.cast(leaf_arr, ctypes.POINTER(native.DgLeaf))
        if leaf_arr else None,
        n_children=len(children),
        children=ctypes.cast(child_arr, ctypes.POINTER(native.DgChild))
        if child_arr else None,
        level_id=len(levels))
    levels.append(lvl)
    return lvl


def _lower_level(ex, node: LevelNode, keep: list, levels: list):
    if node.recurse_data is not None:
        return _lower_recurse(ex, node, keep, levels)
    dom = node.nodes
    leaves = []
    for leaf in node.leaf_sgs:
        lowered = _lower_leaf(ex, leaf, dom, keep)
        if lowered is not None:
            leaves.append(lowered)
    children = []
    for child in node.children:
        clevel = _lower_level(ex, child, keep, levels)
        if clevel is None:
            return None
        row_child, indptr = _row_map(child, len(dom))
        if row_child is None:
            return None
        name = child.sg.alias or (
            f"~{child.sg.attr}" if child.sg.is_reverse else child.sg.attr)
        key = _key(name, keep)
        keep += [row_child, indptr]
        children.append(native.DgChild(
            key=_bp(key), key_len=len(key), level=ctypes.pointer(clevel),
            row_indptr=_vp(indptr), row_child=_vp(row_child)))
    return _build_level(len(dom), leaves, children, keep, levels)


def _row_map(child: LevelNode, n_parent: int):
    """(row_child positions, row_indptr): the child's matrix grouped by
    parent position, stable matrix order preserved (same grouping the
    dict renderer's _rows performs)."""
    seg = np.asarray(child.matrix_seg)
    order = np.argsort(seg, kind="stable")
    indptr = np.searchsorted(seg[order],
                             np.arange(n_parent + 1)).astype(np.int64)
    ranks = np.asarray(child.matrix_child)[order]
    pos = _positions(child.nodes, ranks)
    return pos, indptr


def _lower_leaf(ex, leaf, dom: np.ndarray, keep: list):
    """One leaf SubGraph → DgLeaf column; None = leaf renders nothing
    (password predicates)."""
    store = ex.store
    n = len(dom)
    if leaf.is_uid_leaf:
        key = _key(leaf.alias or "uid", keep)
        uids = np.ascontiguousarray(
            store.uid_of(dom) if n else np.zeros(0), np.int64)
        keep.append(uids)
        return native.DgLeaf(key=_bp(key), key_len=len(key), kind=1,
                             nums=_vp(uids))
    if leaf.is_count:
        rel = store.rel(leaf.attr, leaf.is_reverse)
        counts = np.ascontiguousarray(
            rel.degree(dom) if n else np.zeros(0), np.int64)
        keep.append(counts)
        name = leaf.alias or \
            f"count({'~' if leaf.is_reverse else ''}{leaf.attr})"
        key = _key(name, keep)
        return native.DgLeaf(key=_bp(key), key_len=len(key), kind=2,
                             nums=_vp(counts))
    if leaf.is_val_leaf:
        var = ex.val_vars.get(leaf.attr, {})
        frags = ["" if int(rk) not in var else _enc(_json_val(var[int(rk)]))
                 for rk in dom.tolist()]
        return _frag_leaf(leaf.alias or f"val({leaf.attr})", frags, keep)
    # plain value predicate
    ps = store.schema.peek(leaf.attr)
    if ps and ps.kind == Kind.PASSWORD:
        return None  # hashes never render (reference semantics)
    is_list = bool(ps and ps.is_list)
    name0 = leaf.alias or (
        f"{leaf.attr}@{leaf.lang}" if leaf.lang else leaf.attr)
    if not leaf.lang and not is_list:
        fast = _int_col_frags(store, leaf.attr, dom)
        if fast is not None:
            return _frag_leaf(name0, fast, keep)
    vmap = store.values_for_many(leaf.attr, dom, leaf.lang)
    frags = [""] * n
    for i, rk in enumerate(dom.tolist()):
        vs = vmap.get(rk)
        if not vs:
            continue
        if is_list or len(vs) > 1:
            frags[i] = "[" + ",".join(_enc(_json_val(v)) for v in vs) + "]"
        else:
            frags[i] = _enc(_json_val(vs[0]))
    return _frag_leaf(name0, frags, keep)


def _int_col_frags(store, attr: str, dom: np.ndarray):
    """Vectorized fragment fast path for a single-valued untagged int
    column (creation_ts, birthday_year — the hot render leaves of the
    LDBC mix): one searchsorted pair + one numpy int→str conversion
    replaces the per-node dict build and per-value json.dumps. Returns
    None when the column shape needs the generic path."""
    pd = store.preds.get(attr)
    if pd is None:
        return [""] * len(dom)
    if list(pd.vals) != [""]:
        return None
    col = pd.vals[""]
    if col.vals.dtype.kind != "i":
        return None
    if not len(dom):
        return []
    lo = np.searchsorted(col.subj, dom, "left")
    hi = np.searchsorted(col.subj, dom, "right")
    if len(col.subj) and int((hi - lo).max()) > 1:
        return None  # multi-valued rows despite non-list schema
    hit = hi > lo
    frags = [""] * len(dom)
    if hit.any():
        strs = col.vals[lo[hit]].astype(np.str_).tolist()
        for i, s in zip(np.nonzero(hit)[0].tolist(), strs):
            frags[i] = s
    return frags


def _frag_leaf(name: str, frags: list[str], keep: list):
    blob = "".join(frags).encode("ascii")
    off = np.zeros(len(frags) + 1, np.int64)
    if frags:
        np.cumsum(np.fromiter((len(f) for f in frags), np.int64,
                              len(frags)), out=off[1:])
    key = _key(name, keep)
    keep += [blob, off]
    return native.DgLeaf(key=_bp(key), key_len=len(key), kind=0,
                         frag_off=_vp(off), frag_blob=_bp(blob))


def _enc(v) -> str:
    """One post-_json_val scalar → its JSON fragment (always ASCII)."""
    t = type(v)
    if t is str:
        return _esc(v)
    if t is bool:
        return "true" if v else "false"
    if t is int:
        return repr(v)
    return json.dumps(v, separators=_SEP)


def _key(name: str, keep: list) -> bytes:
    key = (_esc(name) + ":").encode("ascii")
    keep.append(key)
    return key


def _vp(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def _bp(b: bytes):
    return ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p)
