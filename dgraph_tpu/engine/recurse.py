"""@recurse: iterative whole-frontier re-expansion until fixpoint/depth.

Reference parity: `query/recurse.go` (expandRecurse) — THE north-star
workload. The reference re-seeds the SubGraph with each hop's result and
re-runs ProcessGraph; here each depth is one batched expansion per followed
predicate over the union frontier, with the seen-set subtraction
(`loop: false`) done with sorted-set difference.

Semantics (documented, since the reference tree is unavailable to consult —
SURVEY provenance warning): with `loop: false` a node is expanded at most
once (its first visit); later appearances render without children. With
`loop: true`, expansion repeats up to `depth` regardless of revisits
(depth is required in that case to terminate).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from dgraph_tpu.engine.execute import _needs_facets
from dgraph_tpu.engine.ir import SubGraph
from dgraph_tpu.utils import deadline

MAX_RECURSE_DEPTH = 64  # guard when depth: 0 (fixpoint mode)

# Mesh @recurse route: chained hops (ONE compiled hop program reused at
# every depth, frontier/seen device-resident between launches — the
# reshard-free serving path) vs the monolithic lax.scan program
# (recurse_fused_matrix, which retraces per depth). Chain is the
# serving default; the scan variant stays for A/B and tests.
MESH_CHAIN_HOPS = True


@dataclass
class RecurseData:
    """Per-predicate edge lists accumulated over all depths.

    `edges[pred_key]` = (parents, children) rank arrays; every parent rank
    appears in at most one depth (loop=false), so rows are unambiguous.
    For loop=true, per-depth matrices are kept separate.
    """

    edge_sgs: list[SubGraph] = field(default_factory=list)
    leaf_sgs: list[SubGraph] = field(default_factory=list)
    # loop=false: one global matrix per predicate
    edges: dict[int, tuple[np.ndarray, np.ndarray]] = field(default_factory=dict)
    # loop=true: per-depth list of matrices keyed by (depth, pred index)
    by_depth: list[dict[int, tuple[np.ndarray, np.ndarray]]] = field(default_factory=list)
    loop: bool = False
    all_nodes: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))
    # @msgpass binding (engine/feat.py): rank → f32[d] aggregate over
    # the visit-once edge set; None = unbound (the fused featprop
    # stage binds in-trace, the staged post-pass binds host-side)
    feat_vals: dict | None = None
    feat_key: str = ""


def split_children(ex, sg: SubGraph, data: RecurseData) -> RecurseData:
    """Partition a recurse block's children into edge predicates vs
    leaves — ONE rule shared by the host loop, the mesh paths, and the
    whole-query fused program (engine/fused.py), so the routing can
    never fork."""
    for c in sg.children:
        (data.edge_sgs if ex._expands(c) else data.leaf_sgs).append(c)
    return data


def expand_recurse(ex, root) -> None:
    """Run the recurse loop below an already-evaluated root LevelNode."""
    from dgraph_tpu.engine.execute import LevelNode  # noqa: F401 (doc)

    sg = root.sg
    args = sg.recurse
    depth = args.depth or MAX_RECURSE_DEPTH
    if args.loop and not args.depth:
        raise ValueError("@recurse(loop: true) requires depth")

    data = split_children(ex, root.sg, RecurseData(loop=args.loop))

    # Single-predicate depth-bounded visit-once recursions run as ONE
    # compiled SPMD program on the mesh (all hops inside one lax.scan over
    # shard_map — the north-star fusion). Filters/facet-filters/loop need
    # per-hop host logic and fall back to the loop below.
    if (ex.mesh is not None and not args.loop and args.depth
            and len(data.edge_sgs) == 1 and not data.edge_sgs[0].filters
            and not data.edge_sgs[0].facet_filter
            and len(root.nodes) > 0):
        if MESH_CHAIN_HOPS:
            _chain_recurse(ex, root, data, args.depth)
        else:
            _fused_recurse(ex, root, data, args.depth)
        _bind_recurse_vars(ex, root, data, sg)
        root.recurse_data = data
        return

    frontier = root.nodes
    seen = root.nodes.copy()
    for _d in range(depth):
        if len(frontier) == 0:
            break
        # per-hop cancellation point: a pathological @recurse stops
        # within one hop of its budget (utils/deadline.py)
        deadline.checkpoint("recurse")
        level: dict[int, tuple[np.ndarray, np.ndarray]] = {}
        new_parts = []
        for i, esg in enumerate(data.edge_sgs):
            nbrs, seg, pos = ex.expand(
                esg.attr, esg.is_reverse, frontier,
                allow_remote=not _needs_facets(esg))
            nbrs, seg, pos = ex.filter_edges(esg.filters, nbrs, seg, pos)
            nbrs, seg, pos = ex.facet_filter_edges(esg, esg.attr, nbrs,
                                                   seg, pos)
            if not args.loop and len(nbrs):
                # visit-once: drop edges to already-seen nodes so the result
                # graph is a DAG by depth (first-visit tree semantics)
                keep = ~np.isin(nbrs, seen)
                nbrs, seg = nbrs[keep], seg[keep]
            if not len(nbrs):
                continue
            parents = frontier[seg]
            if data.loop:
                level[i] = (parents, nbrs)
            else:
                if i in data.edges:
                    p0, c0 = data.edges[i]
                    data.edges[i] = (np.concatenate([p0, parents]),
                                     np.concatenate([c0, nbrs]))
                else:
                    data.edges[i] = (parents, nbrs)
            new_parts.append(nbrs)
        if data.loop:
            data.by_depth.append(level)
        if not new_parts:
            break
        nxt = np.unique(np.concatenate(new_parts)).astype(np.int32)
        if not args.loop:
            nxt = np.setdiff1d(nxt, seen).astype(np.int32)
            seen = np.union1d(seen, nxt).astype(np.int32)
        frontier = nxt

    data.all_nodes = seen if not args.loop else np.unique(np.concatenate(
        [root.nodes] + [c for lv in data.by_depth for (_p, c) in lv.values()]
    )).astype(np.int32)
    _bind_recurse_vars(ex, root, data, sg)
    root.recurse_data = data


def _bind_recurse_vars(ex, root, data: RecurseData, sg: SubGraph) -> None:
    """Leaf value vars bind over every visited node; the block's uid var
    is the whole reachable set."""
    for leaf in data.leaf_sgs:
        if leaf.var_name:
            saved_nodes = root.nodes
            root.nodes = data.all_nodes
            ex._record_leaf_vars(leaf, root)
            root.nodes = saved_nodes
    if sg.var_name:
        ex.uid_vars[sg.var_name] = data.all_nodes


def _chain_recurse(ex, root, data: RecurseData, depth: int) -> None:
    """Depth-bounded mesh @recurse as `depth` launches of ONE compiled
    hop program (parallel.dhop.chain_hop). The hop's replicated
    out_specs are exactly the next launch's in_specs, so the frontier
    and seen set stay device-resident between hops — zero cross-device
    reshards on the steady path (mesh.reshard_guard armed around the
    loop; the pjit pitfall SNIPPETS calls out) — and the compile is
    depth-independent, where the lax.scan program retraces per depth.
    The host only READS each hop's outputs (edge matrices + the input
    frontier's values, for rendering) and feeds the same device arrays
    back in. Semantics are identical to _fused_recurse (visit-once,
    first-visit-tree), pinned by tests against it and the host loop."""
    from dgraph_tpu.engine.execute import _bucket
    from dgraph_tpu.ops.uidalgebra import SENTINEL32
    from dgraph_tpu.parallel.dhop import chain_hop
    from dgraph_tpu.parallel.mesh import host_np, reshard_guard
    from dgraph_tpu.utils import costprofile, tracing

    def pad_host(a: np.ndarray, size: int) -> np.ndarray:
        # host-side sentinel pad: the chain's SEED is an expected
        # upload; a device-side pad would read as a reshard to the
        # guard (ops.pad_to lands on the default device)
        out = np.full(size, SENTINEL32, np.int32)
        out[:len(a)] = a
        return out

    from dgraph_tpu.utils.metrics import METRICS
    METRICS.inc("mesh_route_total", route="chain")
    esg = data.edge_sgs[0]
    srel = ex.store.sharded_rel(esg.attr, esg.is_reverse, ex.mesh)
    seeds = np.sort(root.nodes).astype(np.int32)
    out_cap = _bucket(max(len(seeds), 1))
    seen_cap = _bucket(4 * out_cap, lo=256)
    edge_cap = _bucket(1, lo=1024)
    parts_p: list[np.ndarray] = []
    parts_c: list[np.ndarray] = []
    seen = None
    for _attempt in range(12):  # geometric cap growth, bounded
        fr = pad_host(seeds, out_cap)
        seen = pad_host(seeds, seen_cap)
        parts_p, parts_c = [], []
        overflowed = False
        with reshard_guard():
            for h in range(depth):
                deadline.checkpoint("recurse")
                with tracing.span("mesh.hop", pred=esg.attr, hop=h,
                                  shards=srel.n_shards) as sp:
                    (fr_next, seen_next, _edges, needs, nbrs_s, seg_s,
                     shard_edges, kept) = chain_hop(
                        ex.mesh, srel, fr, seen,
                        edge_cap, out_cap, seen_cap)
                    need_out, need_seen, need_edge = (
                        int(x) for x in host_np(needs))
                    if (need_out > out_cap or need_seen > seen_cap
                            or need_edge > edge_cap):
                        out_cap = _bucket(max(need_out, out_cap))
                        seen_cap = _bucket(max(need_seen, seen_cap),
                                           lo=256)
                        edge_cap = _bucket(max(need_edge, edge_cap),
                                           lo=1024)
                        overflowed = True
                        break
                    # render reads: the hop's INPUT frontier values map
                    # seg → parent ranks; the device fr/seen arrays feed
                    # the next launch unmoved
                    fr_h = host_np(fr)
                    nbrs_h = host_np(nbrs_s)
                    seg_h = host_np(seg_s)
                    per_shard = host_np(shard_edges)
                    sp.attrs["edges"] = int(host_np(kept))
                    for d in range(srel.n_shards):
                        row = nbrs_h[d]
                        m = row != SENTINEL32
                        if m.any():
                            parts_p.append(fr_h[seg_h[d][m]])
                            parts_c.append(row[m])
                        # modeled per-shard µs (the ~16 edges/µs host
                        # scale expand() charges tablets with) — the
                        # scheduler/placement signal for mesh work
                        if int(per_shard[d]):
                            costprofile.add_shard_cost(
                                d, int(per_shard[d]) // 16 + 1)
                    fr, seen = fr_next, seen_next
                    if need_out == 0:  # frontier emptied: fixpoint
                        break
        if not overflowed:
            break
    else:
        raise RuntimeError("recurse caps failed to converge")

    if parts_p:
        data.edges[0] = (np.concatenate(parts_p).astype(np.int32),
                         np.concatenate(parts_c).astype(np.int32))
    seen_h = host_np(seen)
    data.all_nodes = seen_h[seen_h != SENTINEL32].astype(np.int32)


def _fused_recurse(ex, root, data: RecurseData, depth: int) -> None:
    """Drive parallel.dhop.recurse_fused_matrix: the whole hop loop is one
    jitted shard_map program (reference: query/recurse.go expandRecurse,
    with the per-level ProcessTaskOverNetwork fan-out collapsed into
    on-mesh collectives). Host work is only cap policy + matrix unpack."""
    from dgraph_tpu import ops
    from dgraph_tpu.engine.execute import _bucket
    from dgraph_tpu.ops.uidalgebra import SENTINEL32
    from dgraph_tpu.parallel.dhop import recurse_fused_matrix

    esg = data.edge_sgs[0]
    srel = ex.store.sharded_rel(esg.attr, esg.is_reverse, ex.mesh)
    out_cap = _bucket(max(len(root.nodes), 1))
    seen_cap = _bucket(4 * out_cap, lo=256)
    edge_cap = _bucket(1, lo=1024)
    for _attempt in range(12):  # geometric cap growth, bounded
        fr = ops.pad_to(np.sort(root.nodes).astype(np.int32), out_cap)
        (last, seen, edges, needs, nbrs_s, seg_s, _pos_s,
         frontiers) = recurse_fused_matrix(
            ex.mesh, srel, fr, edge_cap=edge_cap, out_cap=out_cap,
            seen_cap=seen_cap, depth=depth)
        from dgraph_tpu.parallel.mesh import host_np
        need_out, need_seen, need_edge = (int(x) for x in host_np(needs))
        if (need_out <= out_cap and need_seen <= seen_cap
                and need_edge <= edge_cap):
            break
        out_cap = _bucket(max(need_out, out_cap))
        seen_cap = _bucket(max(need_seen, seen_cap), lo=256)
        edge_cap = _bucket(max(need_edge, edge_cap), lo=1024)
    else:
        raise RuntimeError("recurse caps failed to converge")

    nbrs_s = host_np(nbrs_s)         # [D, depth, edge_cap]
    seg_s = host_np(seg_s)
    frontiers = host_np(frontiers)   # [depth, out_cap]
    parts_p, parts_c = [], []
    for h in range(depth):
        fr_h = frontiers[h]
        for d in range(nbrs_s.shape[0]):
            row = nbrs_s[d, h]
            m = row != SENTINEL32
            if not m.any():
                continue
            parts_p.append(fr_h[seg_s[d, h][m]])
            parts_c.append(row[m])
    if parts_p:
        data.edges[0] = (np.concatenate(parts_p).astype(np.int32),
                         np.concatenate(parts_c).astype(np.int32))
    seen = host_np(seen)
    data.all_nodes = seen[seen != SENTINEL32].astype(np.int32)
