"""Query IR: the SubGraph tree and filter/function nodes.

Reference parity: `query/query.go` (SubGraph, params), `gql/parser.go`
(GraphQuery, FilterTree, Function). The DQL parser (dql/) produces this IR
directly; the executor (engine/execute.py) walks it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class FuncNode:
    """A root/filter function: eq, le, ge, lt, gt, between, uid, uid_in,
    has, type, anyofterms, allofterms, anyoftext, alloftext, regexp, match.
    Reference: gql.Function."""

    name: str
    attr: str = ""                 # predicate the func applies to
    args: list = field(default_factory=list)   # literal arguments
    uids: list = field(default_factory=list)   # uid args (uid(), uid_in())
    is_count: bool = False         # eq(count(pred), N)
    is_val_var: bool = False       # eq(val(x), N)
    lang: str = ""                 # name@en


@dataclass
class FilterNode:
    """Boolean filter tree. op ∈ {and, or, not, leaf}.
    Reference: gql.FilterTree."""

    op: str
    children: list["FilterNode"] = field(default_factory=list)
    func: Optional[FuncNode] = None


@dataclass
class Order:
    attr: str           # predicate or val-var name
    desc: bool = False
    is_val_var: bool = False
    lang: str = ""


@dataclass
class RecurseArgs:
    depth: int = 0      # 0 = unbounded (until fixpoint)
    loop: bool = False  # allow revisiting (requires depth)


@dataclass
class MsgPassArgs:
    """@msgpass(pred: emb, agg: mean) — neighbour-feature aggregation
    bound per traversal level (engine/feat.py)."""
    pred: str = ""
    agg: str = "mean"   # sum | mean | max


@dataclass
class ShortestArgs:
    from_uid: int = 0
    to_uid: int = 0
    numpaths: int = 1
    depth: int = 0
    # weight facet name (optional; uniform cost when empty)
    weight_facet: str = ""
    minweight: float = float("-inf")
    maxweight: float = float("inf")


@dataclass
class SubGraph:
    """One block level of the query tree. Reference: query.SubGraph."""

    attr: str = ""                    # predicate expanded at this level
    alias: str = ""
    is_reverse: bool = False          # ~pred
    lang: str = ""                    # pred@en for value leaves
    func: Optional[FuncNode] = None   # root function (root blocks only)
    filters: Optional[FilterNode] = None
    children: list["SubGraph"] = field(default_factory=list)

    # pagination / ordering (reference: params first/offset/after/order)
    first: int = 0
    offset: int = 0
    after: int = 0                    # uid cursor
    orders: list[Order] = field(default_factory=list)

    # node-type flags
    is_count: bool = False            # count(pred) leaf
    is_uid_leaf: bool = False         # the literal `uid` field
    checkpwd_val: Optional[str] = None  # checkpwd(pred, "pw") leaf
    is_agg: bool = False              # min/max/sum/avg(val(x)) leaf
    agg_func: str = ""
    is_val_leaf: bool = False         # val(x) leaf
    is_expand_all: bool = False       # expand(_all_) / expand(Type)
    expand_arg: str = ""

    # variable bindings (reference: var propagation between blocks)
    var_name: str = ""                # `x as friend { ... }`
    is_internal: bool = False         # var-only block: not emitted to JSON

    # directives
    recurse: Optional[RecurseArgs] = None
    msgpass: Optional[MsgPassArgs] = None
    shortest: Optional[ShortestArgs] = None
    cascade: list[str] = field(default_factory=list)  # ["__all__"] or fields
    normalize: bool = False
    groupby: list[str] = field(default_factory=list)

    # facets (reference: @facets on edges/value leaves)
    # None = not requested; [] = all keys; else [(alias, key), ...]
    facet_keys: Optional[list] = None
    facet_vars: Optional[list] = None  # [(var, key)]: @facets(v as k)
    facet_filter: Optional[FilterNode] = None  # leaf FuncNode.attr = key
    facet_orders: list[Order] = field(default_factory=list)

    # math/val computation on leaves
    math_expr: Optional[object] = None  # engine.math.MathTree

    def is_leaf(self) -> bool:
        return not self.children
