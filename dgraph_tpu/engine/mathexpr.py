"""math() expression trees over value variables.

Reference parity: `query/math.go` — arithmetic/conditional expressions over
val-vars, evaluated per uid. The dql parser builds `MathTree`s; evaluation
is vectorised per-rank over the val-var maps.
"""

from __future__ import annotations

import math as _m
from dataclasses import dataclass, field

BINOPS = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: a / b,
    "%": lambda a, b: a % b,
    "min": min,
    "max": max,
    "logbase": lambda a, b: _m.log(a, b),
    "pow": lambda a, b: a ** b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    ">": lambda a, b: a > b,
    "<=": lambda a, b: a <= b,
    ">=": lambda a, b: a >= b,
    "&&": lambda a, b: bool(a) and bool(b),
    "||": lambda a, b: bool(a) or bool(b),
}

UNOPS = {
    "u-": lambda a: -a,
    "ln": _m.log,
    "exp": _m.exp,
    "sqrt": _m.sqrt,
    "floor": _m.floor,
    "ceil": lambda a: _m.ceil(a),
    "abs": abs,
    "not": lambda a: not a,
}


@dataclass
class MathTree:
    """op ∈ BINOPS|UNOPS|{'const','var','cond'}."""

    op: str
    const: object = None
    var: str = ""
    children: list["MathTree"] = field(default_factory=list)


def eval_math(tree: MathTree, ranks, val_vars: dict) -> dict[int, object]:
    """Evaluate per rank; ranks missing any referenced var are skipped
    (reference behavior: missing values drop the uid from the result)."""
    out: dict[int, object] = {}
    for r in ranks:
        r = int(r)
        try:
            v = _eval_one(tree, r, val_vars)
        except _Missing:
            continue
        out[r] = v
    return out


class _Missing(Exception):
    pass


def _eval_one(t: MathTree, rank: int, env: dict):
    if t.op == "const":
        return t.const
    if t.op == "var":
        var = env.get(t.var)
        if var is None or rank not in var:
            raise _Missing()
        return var[rank]
    if t.op == "cond":
        c, a, b = t.children
        return _eval_one(a if _eval_one(c, rank, env) else b, rank, env)
    if t.op in UNOPS:
        return UNOPS[t.op](_eval_one(t.children[0], rank, env))
    if t.op in BINOPS:
        return BINOPS[t.op](_eval_one(t.children[0], rank, env),
                            _eval_one(t.children[1], rank, env))
    raise ValueError(f"unknown math op {t.op!r}")
