"""Feature-bearing traversal: `@msgpass` neighbour aggregation.

PR 18 made embeddings query-native but vectors could only *select*
seeds (`similar_to`); nothing flowed along the expansion. This module
is the propagation half — GNN-style message passing as a query
primitive: `@msgpass(pred: emb, agg: mean)` on a block binds, for each
node the level expands, the sum/mean/max of its traversal children's
feature rows (a `store/vec.py` VecTablet). Composed with `@recurse`
the features re-aggregate each hop — embedding propagation /
personalized-PageRank-style scoring / the GraphRAG propagated-
similarity scorer as ONE kernel family (ops/feat.py).

Three routes, one contract — bit-identical `[k, d]` f32 bindings:

* **host** — numpy `add.at`/`maximum.at` over the kept-edge lists.
  This IS the reference the other routes are pinned against.
* **device** — `ops.feat.combine_edges` under jax.jit, launched
  through the memgov OOM lifecycle at site `feat.agg` (alloc failure
  → evict-retry → sticky degrade to the host route).
* **mesh** — the row-sharded stacks of `Store.vec_sharded` through the
  `mesh.hop_input` zero-reshard guard, per-shard partial combine +
  `psum`/`pmax` merge (each tablet row lives on exactly one shard, so
  partial sums/maxima merge exactly).

Route selection rides the PR-10 costprior route EMAs
(`feat_host`/`feat_device`/`feat_mesh`); the fused `featprop` stage
(engine/fused.py) claims the whole pipeline when the plan is eligible
and reports itself as route `fused`.

Aggregation is per-EDGE over each level's kept-edge lists (duplicates
count; exactly the lists the renderer emits), so the staged host loop,
the routed kernels, and the fused in-trace stage see identical index
pairs — the digest-equality discipline.
"""

from __future__ import annotations

import functools
import time

import numpy as np

from dgraph_tpu.utils import memgov
from dgraph_tpu.utils.metrics import METRICS

__all__ = ["AGGS", "host_combine", "aggregate", "annotate_tree",
           "needs_msgpass", "feat_key"]

AGGS = ("sum", "mean", "max")

EMPTY = np.zeros(0, np.int32)


def feat_key(args) -> str:
    """JSON key of the bound value — the count-leaf naming discipline:
    `mean(emb)` next to `count(friend)`."""
    return f"{args.agg}({args.pred})"


def _bucket(n: int, lo: int = 64) -> int:
    b = lo
    # graftlint: allow(hot-loop-checkpoint): O(log n) shift arithmetic
    while b < n:
        b <<= 1
    return b


# ---------------------------------------------------------------------------
# host route: the bit-identity reference

def host_combine(subj: np.ndarray, vecs: np.ndarray, nbrs: np.ndarray,
                 seg: np.ndarray, n_seg: int, agg: str):
    """Numpy reference combine. Same contract as
    `ops.feat.segment_combine`: returns (out[n_seg, d] f32,
    cnt[n_seg] i32, ecnt[n_seg] i32)."""
    nbrs = np.asarray(nbrs, np.int32)
    seg = np.asarray(seg, np.int64)
    rows, d = int(subj.shape[0]), int(vecs.shape[1])
    if rows:
        idx = np.minimum(np.searchsorted(subj, nbrs), rows - 1)
        has = subj[idx] == nbrs
    else:
        idx = np.zeros(len(nbrs), np.int64)
        has = np.zeros(len(nbrs), bool)
    cnt = np.bincount(seg[has], minlength=n_seg).astype(np.int32)
    ecnt = np.bincount(seg, minlength=n_seg).astype(np.int32)
    if agg == "max":
        out = np.full((n_seg, d), -np.inf, np.float32)
        np.maximum.at(out, seg[has], vecs[idx[has]])
        out = np.where((cnt > 0)[:, None], out, np.float32(0))
    else:
        out = np.zeros((n_seg, d), np.float32)
        np.add.at(out, seg[has], vecs[idx[has]])
        if agg == "mean":
            out = np.where(
                (cnt > 0)[:, None],
                out / np.maximum(cnt, 1)[:, None].astype(np.float32),
                np.float32(0))
    return out.astype(np.float32, copy=False), cnt, ecnt


# ---------------------------------------------------------------------------
# device route: one jitted kernel through the OOM lifecycle

def _device_combine(store, pred: str, nbrs, seg, n_seg: int, agg: str,
                    shape_key):
    from dgraph_tpu.ops import feat as ops_feat
    from dgraph_tpu.ops.uidalgebra import SENTINEL32
    from dgraph_tpu.utils.jitcache import jit_call

    subj_d, vecs_d = store.vec_device(pred)
    rows, d = int(vecs_d.shape[0]), int(vecs_d.shape[1])
    e_cap = _bucket(max(len(nbrs), 1))
    n_cap = _bucket(max(n_seg, 1))
    nb = np.full(e_cap, SENTINEL32, np.int32)
    nb[:len(nbrs)] = nbrs
    sg = np.zeros(e_cap, np.int32)
    sg[:len(seg)] = seg
    key = ops_feat.combine_key(rows, d, e_cap, n_cap, agg)

    def _launch():
        memgov.check_alloc_fault("feat.agg")
        with jit_call("feat.agg", key):
            out, cnt, ecnt = ops_feat.combine_edges(
                subj_d, vecs_d, nb, sg, np.int32(len(nbrs)), n_cap, agg)
        return (np.asarray(out, np.float32)[:n_seg],
                np.asarray(cnt, np.int32)[:n_seg],
                np.asarray(ecnt, np.int32)[:n_seg])

    return memgov.oom_retry("feat.agg", shape_key, _launch)


# ---------------------------------------------------------------------------
# mesh route: per-shard partial combine + psum/pmax merge

def _mesh_combine(store, pred: str, nbrs, seg, n_seg: int, agg: str,
                  mesh, shape_key):
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.ops.uidalgebra import SENTINEL32
    from dgraph_tpu.parallel.mesh import SHARD_AXIS, hop_input

    subj_s, vecs_s, rows = store.vec_sharded(pred, mesh)
    d = int(vecs_s.shape[-1])
    e_cap = _bucket(max(len(nbrs), 1))
    n_cap = _bucket(max(n_seg, 1))
    nb = np.full(e_cap, SENTINEL32, np.int32)
    nb[:len(nbrs)] = nbrs
    sg = np.zeros(e_cap, np.int32)
    sg[:len(seg)] = seg
    fn = _build_mesh_combine(mesh, rows, d, e_cap, n_cap, agg)

    def _launch():
        memgov.check_alloc_fault("feat.agg")
        out, cnt, ecnt = fn(
            hop_input(subj_s, mesh, P(SHARD_AXIS)),
            hop_input(vecs_s, mesh, P(SHARD_AXIS)),
            nb, sg, np.int32(len(nbrs)))
        return (np.asarray(out, np.float32)[:n_seg],
                np.asarray(cnt, np.int32)[:n_seg],
                np.asarray(ecnt, np.int32)[:n_seg])

    return memgov.oom_retry("feat.agg", shape_key, _launch)


@functools.lru_cache(maxsize=32)
def _build_mesh_combine(mesh, rows: int, d: int, e_cap: int, n_cap: int,
                        agg: str):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.ops.feat import segment_combine
    from dgraph_tpu.parallel.mesh import SHARD_AXIS
    from dgraph_tpu.utils.jaxcompat import shard_map

    def per_device(subj_b, vecs_b, nbrs, seg, n_edges):
        subj, vecs = subj_b[0], vecs_b[0]   # [rows], [rows, d]
        valid = jnp.arange(e_cap, dtype=jnp.int32) < n_edges
        # raw partials (mask_empty=False): each tablet row lives on
        # exactly one shard, so psum of partial sums / pmax of partial
        # maxima is the exact single-device result; the one global
        # mask/division happens after the merge
        out, cnt, ecnt = segment_combine(subj, vecs, nbrs, seg, valid,
                                         n_cap, agg, mask_empty=False)
        cnt = lax.psum(cnt, SHARD_AXIS)
        if agg == "max":
            out = lax.pmax(out, SHARD_AXIS)
            out = jnp.where((cnt > 0)[:, None], out, jnp.float32(0))
        else:
            out = lax.psum(out, SHARD_AXIS)
            if agg == "mean":
                out = jnp.where(
                    (cnt > 0)[:, None],
                    out / jnp.maximum(cnt, 1)[:, None].astype(
                        jnp.float32),
                    jnp.float32(0))
        # seg/valid are replicated, so the structural count already is
        return out, cnt, ecnt

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P(),
                             P()),
                   out_specs=(P(), P(), P()), check_vma=False)
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# the routed entry point

def _promoted(route: str, baseline: str) -> bool:
    """Cost-prior promotion below the static threshold (the
    store/vec.py knn-lane discipline, feat lanes)."""
    from dgraph_tpu.utils import costprior
    if not costprior.enabled():
        return False
    r = costprior.PRIORS.route_cost(route)
    b = costprior.PRIORS.route_cost(baseline)
    return r is not None and b is not None and r < b


def aggregate(store, pred: str, agg: str, nbrs, seg, n_seg: int,
              mesh=None, device_threshold: int = 512):
    """Combine one level's kept-edge feature rows with route selection
    + accounting: mesh when one is configured and the work clears the
    threshold (or the feat route EMAs promote it), device likewise on
    a single device, host otherwise — and host ALWAYS on OOM
    degradation, bit-identically. Returns (out[n_seg, d] f32,
    cnt[n_seg] i32, ecnt[n_seg] i32)."""
    t = store.vec_tablet(pred)
    if t is None:
        raise ValueError(
            f"@msgpass(pred: {pred}): not a float32vector predicate")
    work = len(nbrs)
    big = work >= device_threshold or t.rows >= device_threshold
    shape_key = (pred, t.dim, agg)
    t0 = time.perf_counter()
    route = "host"
    try:
        if mesh is not None and t.rows and (
                big or _promoted("feat_mesh", "feat_host")):
            route = "mesh"
            out = _mesh_combine(store, pred, nbrs, seg, n_seg, agg,
                                mesh, shape_key)
        elif t.rows and (big or _promoted("feat_device", "feat_host")):
            route = "device"
            out = _device_combine(store, pred, nbrs, seg, n_seg, agg,
                                  shape_key)
        else:
            out = host_combine(t.subj, t.vecs, nbrs, seg, n_seg, agg)
    except memgov.OomDegraded:
        # allocation failure survived its evict-retry (or the shape is
        # sticky-degraded): the host combine is the identical binding
        route = "host"
        out = host_combine(t.subj, t.vecs, nbrs, seg, n_seg, agg)
    us = (time.perf_counter() - t0) * 1e6
    METRICS.inc("feat_route_total", route=route)
    part = int(out[1].sum())
    if part:
        METRICS.inc("feat_bytes_total", float(part * t.dim * 4))
    METRICS.observe("featprop_latency_us", us)
    if work:
        from dgraph_tpu.utils import costprior
        costprior.PRIORS.learn_route("feat_" + route,
                                     us / work * 1000.0)
    return out


# ---------------------------------------------------------------------------
# the executor post-pass: bind features onto a finished level tree

def needs_msgpass(sg) -> bool:
    """True when any block in the subtree carries `@msgpass` — the
    Executor's cheap gate before walking the level tree."""
    if sg.msgpass is not None:
        return True
    return any(needs_msgpass(c) for c in sg.children)


def annotate_tree(ex, node) -> None:
    """Walk a finished LevelNode tree and bind `feat_vals` (rank →
    f32[d]) wherever the block carries `@msgpass`. Levels the fused
    `featprop` stage already bound are left untouched — the in-trace
    aggregation and this pass see identical kept-edge lists, so either
    binding renders identically."""
    args = node.sg.msgpass
    if args is not None:
        if node.sg.recurse is not None and node.sg.recurse.loop:
            raise ValueError(
                "@msgpass composes with @recurse(loop: false) only: "
                "visit-once expansion gives each node exactly one "
                "aggregation hop")
        if node.recurse_data is not None:
            if getattr(node.recurse_data, "feat_vals", None) is None:
                _annotate_recurse(ex, node, args)
        elif node.feat_vals is None:
            _annotate_level(ex, node, args)
    for ch in node.children:
        annotate_tree(ex, ch)


def _annotate_level(ex, node, args) -> None:
    """Plain (non-recurse) level: aggregate over the concatenated
    kept-edge matrices of every child predicate."""
    node.feat_key = feat_key(args)
    n = len(node.nodes)
    if not n:
        node.feat_vals = {}
        return
    segs = [ch.matrix_seg for ch in node.children
            if len(ch.matrix_seg)]
    childs = [ch.matrix_child for ch in node.children
              if len(ch.matrix_seg)]
    nbrs = np.concatenate(childs) if childs else EMPTY
    seg = np.concatenate(segs) if segs else EMPTY
    vals, _cnt, ecnt = aggregate(
        ex.store, args.pred, args.agg, nbrs, seg, n,
        mesh=ex.mesh, device_threshold=ex.device_threshold)
    nodes = np.asarray(node.nodes)
    node.feat_vals = {
        int(nodes[i]): np.asarray(vals[i], np.float32)
        for i in np.nonzero(ecnt > 0)[0].tolist()}


def _annotate_recurse(ex, node, args) -> None:
    """@recurse level: aggregate over the full visit-once edge set
    (every parent expands at exactly one hop, so the global combine
    equals the fused stage's per-hop combine)."""
    data = node.recurse_data
    data.feat_key = feat_key(args)
    parts_p, parts_c = [], []
    for i in sorted(data.edges):
        p, c = data.edges[i]
        if len(p):
            parts_p.append(np.asarray(p, np.int32))
            parts_c.append(np.asarray(c, np.int32))
    if not parts_p:
        data.feat_vals = {}
        return
    parents = np.concatenate(parts_p)
    childs = np.concatenate(parts_c)
    uniq, seg = np.unique(parents, return_inverse=True)
    vals, _cnt, _ecnt = aggregate(
        ex.store, args.pred, args.agg, childs,
        seg.astype(np.int32), len(uniq),
        mesh=ex.mesh, device_threshold=ex.device_threshold)
    # every unique parent has ≥ 1 kept edge by construction
    data.feat_vals = {
        int(r): np.asarray(vals[i], np.float32)
        for i, r in enumerate(uniq.tolist())}
