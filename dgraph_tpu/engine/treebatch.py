"""Lane-batched LEVEL-TREE serving: whole nested queries as one kernel.

Reference parity: the reference serves the LDBC IC mix with per-query
goroutines descending SubGraph trees (query/query.go ProcessGraph,
worker/task.go fan-out). The TPU-native equivalent packs B structurally
compatible queries into the bit-lanes of ops/bfs.py make_ell_tree: every
uid-expansion level of every query is ONE stage of one fused XLA program
(ELL pull-gathers + bitmask-AND filters), launched once per batch.

What widens eligibility past engine/batch.py's recurse-only path
(round-4 verdict item 2):
  * multi-level expansion trees (IC2-IC12 shapes), each tree edge a stage
  * @filter on expansion levels — evaluated once per distinct constant
    per batch to a node set, packed per-lane, ANDed on device
  * filtered @recurse blocks (config-3 shape) as in-kernel scans
  * multi-block queries: `var` blocks chain stage-to-stage inside the
    kernel (uid(v) roots), host-processed blocks consume the bound vars
  * per-level ordering / pagination / facet keys — render-side, applied
    during host rebuild exactly as the per-query engine applies them

Division of labor: the device computes every level's NODE SET (the
expansion + filter work, amortised across all lanes); the host rebuilds
each query's per-parent edge rows by intersecting parents' CSR rows with
the level masks (bit tests, no set algebra), then the standard renderer
emits JSON — so batch results are bit-identical to the per-query engine,
asserted by tests/test_treebatch.py against the LDBC IC goldens.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from dgraph_tpu.engine.execute import EMPTY64, Executor, LevelNode, expands
from dgraph_tpu.engine.ir import FilterNode, SubGraph
from dgraph_tpu.engine.varorder import execution_order

EMPTY = np.zeros(0, np.int32)

MAX_KERNEL_DEPTH = 64      # recurse stages: device buffers scale with it
MAX_STAGES = 12            # one [n+1, W] mask per stage stays resident


# ---------------------------------------------------------------------------
# plan structures

@dataclass
class StageSpec:
    attr: str
    reverse: bool
    kind: str                  # "hop" | "recurse"
    parent: tuple              # ("seed", slot) | ("stage", idx)
    filt_slot: int | None
    depth: int = 0             # recurse only
    keep_hops: bool = False    # recurse only: rendered block
    path: tuple = ()           # (block_idx,) recurse / (block_idx, i, ...) hop
    filt_shape: tuple | None = None   # structure-only filter canonical


@dataclass
class TreePlan:
    """One kernel group: homogeneous stage structure, per-query params."""

    sig: tuple
    stages: list[StageSpec]
    n_seeds: int
    seed_blocks: list[int]                 # slot s ← block seed_blocks[s]
    filt_paths: list[tuple]                # filt slot → owning stage path
    queries: list = field(default_factory=list)   # per-query parsed blocks


# ---------------------------------------------------------------------------
# planning

_FILTER_FUNCS_BLOCKED = {"uid", "uid_in"}


def _filter_ok(tree: FilterNode | None) -> bool:
    """Filter trees the kernel can take: evaluable to a node set before
    launch (index lookups only — Executor.filter_set), no complement
    (needs a universe), no var/uid references (bind after launch)."""
    if tree is None:
        return True
    if tree.op == "not":
        return False
    if tree.op == "leaf":
        f = tree.func
        return not (f.name in _FILTER_FUNCS_BLOCKED or f.is_val_var
                    or f.is_count)
    return all(_filter_ok(c) for c in tree.children)


def _filter_shape(tree: FilterNode | None):
    """Structure-only canonical form (constants excluded — they vary per
    query and ride per-lane filter masks)."""
    if tree is None:
        return None
    if tree.op == "leaf":
        f = tree.func
        return ("leaf", f.name, f.attr, f.lang)
    return (tree.op, tuple(_filter_shape(c) for c in tree.children))


def _root_uses_vars(sg: SubGraph) -> bool:
    from dgraph_tpu.engine.varorder import _filter_uses, _func_uses
    uses = set()
    if sg.func is not None:
        uses |= _func_uses(sg.func)
    if sg.filters is not None:
        uses |= _filter_uses(sg.filters)
    uses |= {o.attr for o in sg.orders if o.is_val_var}
    return bool(uses)


def _pure_chain_root(sg: SubGraph):
    """uid(v) root with no other root-level processing → the var name,
    else None. Such a block's level sets chain straight off the stage
    that defines v, inside the kernel."""
    f = sg.func
    if (f is None or f.name != "uid" or f.uids or len(f.args) != 1
            or not isinstance(f.args[0], str)):
        return None
    if (sg.filters is not None or sg.orders or sg.first or sg.offset
            or sg.after):
        return None
    return f.args[0]


def _bad_directives(sg: SubGraph) -> bool:
    return bool(sg.groupby or sg.cascade or sg.normalize
                or sg.is_expand_all or sg.shortest is not None)


class _Ineligible(Exception):
    pass


def plan_tree(store, blocks) -> tuple[tuple, TreePlan] | None:
    """(signature, plan skeleton) when the whole query fits the level-tree
    kernel, else None. Signature captures everything that must match for
    two queries to share a launch: the stage DAG (kinds, predicates,
    directions, parentage, filter shapes, recurse depths)."""
    try:
        return _plan_tree(store, blocks)
    except _Ineligible:
        return None


def _plan_tree(store, blocks):
    schema = store.schema
    stages: list[StageSpec] = []
    seed_blocks: list[int] = []
    filt_paths: list[tuple] = []
    var_stage: dict[str, int] = {}
    try:
        order = execution_order(blocks)   # also rejects circular deps
    except ValueError:
        raise _Ineligible from None

    def add_filter(sg: SubGraph, path) -> tuple[int | None, tuple | None]:
        if sg.filters is None:
            return None, None
        if not _filter_ok(sg.filters):
            raise _Ineligible
        filt_paths.append(path)
        return len(filt_paths) - 1, _filter_shape(sg.filters)

    def walk_children(sg: SubGraph, parent_ref, path) -> None:
        child_i = 0
        for c in sg.children:
            if not expands(schema, c):
                continue
            if (_bad_directives(c) or c.recurse is not None
                    or c.lang):
                raise _Ineligible
            cpath = (*path, child_i)
            child_i += 1
            slot, fshape = add_filter(c, cpath)
            if len(stages) >= MAX_STAGES:
                raise _Ineligible
            stages.append(StageSpec(
                attr=c.attr, reverse=c.is_reverse, kind="hop",
                parent=parent_ref, filt_slot=slot, path=cpath,
                filt_shape=fshape))
            idx = len(stages) - 1
            if c.var_name:
                var_stage[c.var_name] = idx
            walk_children(c, ("stage", idx), cpath)

    any_stage_block = False
    for bi in order:
        sg = blocks[bi]
        if _bad_directives(sg):
            raise _Ineligible
        edge_children = [c for c in sg.children if expands(schema, c)]
        if sg.recurse is not None:
            r = sg.recurse
            if (r.loop or not r.depth or r.depth > MAX_KERNEL_DEPTH
                    or len(edge_children) != 1):
                raise _Ineligible
            e = edge_children[0]
            if (e.facet_filter is not None or e.facet_keys is not None
                    or e.facet_vars is not None or e.facet_orders
                    or e.first or e.offset or e.after or e.orders
                    or e.children or e.lang):
                raise _Ineligible
            if _root_uses_vars(sg):
                raise _Ineligible
            slot, fshape = add_filter(e, (bi,))
            seed_blocks.append(bi)
            if len(stages) >= MAX_STAGES:
                raise _Ineligible
            # keep_hops always: internal (var) blocks also rebuild their
            # reachable set from the per-hop masks via candidate walks —
            # O(visited edges), never O(n) per lane
            stages.append(StageSpec(
                attr=e.attr, reverse=e.is_reverse, kind="recurse",
                parent=("seed", len(seed_blocks) - 1), filt_slot=slot,
                depth=r.depth, keep_hops=True, path=(bi,),
                filt_shape=fshape))
            if e.var_name or sg.var_name:
                # block var = reachable set = the stage's seen mask;
                # an edge-child var inside @recurse binds the same set
                for name in filter(None, (e.var_name, sg.var_name)):
                    var_stage[name] = len(stages) - 1
            any_stage_block = True
            continue
        if not edge_children:
            # host-only block (value leaves / aggregations); vars it
            # defines are bound during the per-query run
            continue
        chain_var = _pure_chain_root(sg)
        if chain_var is not None and chain_var in var_stage:
            parent_ref = ("stage", var_stage[chain_var])
        else:
            if _root_uses_vars(sg):
                raise _Ineligible
            seed_blocks.append(bi)
            parent_ref = ("seed", len(seed_blocks) - 1)
        walk_children(sg, parent_ref, (bi,))
        any_stage_block = True

    if not any_stage_block or not stages:
        raise _Ineligible
    sig = (len(seed_blocks), tuple(
        (s.kind, s.attr, s.reverse, s.parent, s.depth, s.keep_hops,
         s.path, s.filt_shape) for s in stages))
    plan = TreePlan(sig=sig, stages=stages, n_seeds=len(seed_blocks),
                    seed_blocks=seed_blocks, filt_paths=filt_paths)
    return sig, plan


class _StageIndex:
    """Maps (path) → per-query SubGraph + stage idx, resolved with the
    schema like the executor resolves children."""

    def __init__(self, store, plan: TreePlan, blocks):
        self.by_path: dict[tuple, int] = {
            s.path: i for i, s in enumerate(plan.stages)}
        self.sg_by_path: dict[tuple, SubGraph] = {}
        schema = store.schema
        for bi, sg in enumerate(blocks):
            if sg.recurse is not None:
                ecs = [c for c in sg.children if expands(schema, c)]
                if len(ecs) == 1 and (bi,) in self.by_path:
                    self.sg_by_path[(bi,)] = ecs[0]
                continue
            self._walk(schema, sg, (bi,))

    def _walk(self, schema, sg, path):
        child_i = 0
        for c in sg.children:
            if not expands(schema, c):
                continue
            cpath = (*path, child_i)
            child_i += 1
            if cpath in self.by_path:
                self.sg_by_path[cpath] = c
                self._walk(schema, c, cpath)


# ---------------------------------------------------------------------------
# execution

def run_tree_batch(store, plan: TreePlan, device_threshold: int) -> list:
    """Execute one homogeneous group as a single make_ell_tree launch and
    render each query with the standard engine over mask-constrained
    expansion. Returns one JSON dict per query (None → caller falls back
    to per-query execution)."""
    import jax

    from dgraph_tpu.engine.outputnode import to_json

    n = store.n_nodes
    B = len(plan.queries)
    words = -(-B // 32)
    W = 1 << max(words - 1, 0).bit_length() if words > 1 else 1
    lanes = 32 * W

    # per-(attr, dir) device state, shared with the recurse batch path
    from dgraph_tpu.engine.batch import _ell_for
    rels = {}
    for s in plan.stages:
        key = (s.attr, s.reverse)
        if key not in rels:
            g = _ell_for(store, s.attr, s.reverse)
            if g is None:                 # empty relation: no kernel win
                return None
            if g.n != n:
                return None
            rels[key] = g

    # per-query seeds (host root evaluation) and filter node sets
    seed_lists: list[list[np.ndarray]] = [[] for _ in range(plan.n_seeds)]
    filt_lists: list[list[np.ndarray]] = [[] for _ in plan.filt_paths]
    idx_per_query: list[_StageIndex] = []
    root_displays: list[dict[int, np.ndarray]] = []
    # graftlint: allow(cache-registration): per-call local memo of this one batch's filter sets — it dies with the function, never holds bytes across requests
    filt_cache: dict = {}
    for q, blocks in enumerate(plan.queries):
        ex = Executor(store, device_threshold=device_threshold)
        sidx = _StageIndex(store, plan, blocks)
        idx_per_query.append(sidx)
        displays: dict[int, np.ndarray] = {}
        root_displays.append(displays)
        for slot, bi in enumerate(plan.seed_blocks):
            try:
                display = ex.root_display(blocks[bi])
            except Exception:
                return None
            displays[bi] = display
            seed_lists[slot].append(np.unique(display).astype(np.int32))
        for slot, path in enumerate(plan.filt_paths):
            sg = sidx.sg_by_path.get(path)
            if sg is None or sg.filters is None:
                return None
            ckey = _filter_const_key(sg.filters)
            allowed = filt_cache.get(ckey)
            if allowed is None:
                allowed = ex.filter_set(sg.filters)
                if allowed is None:
                    return None
                filt_cache[ckey] = allowed
            filt_lists[slot].append(allowed)

    seeds_np = [_pack_global(n, lst, lanes) for lst in seed_lists]
    filts_np = [_pack_global(n, lst, lanes) for lst in filt_lists]

    import time as _time

    from dgraph_tpu.engine.batch import _note_kernel_features
    from dgraph_tpu.utils import costprofile, deadline, tracing
    from dgraph_tpu.utils.jitcache import jit_call
    from dgraph_tpu.utils.metrics import METRICS
    # budget gate before the device is committed to the fused program
    deadline.checkpoint("kernel")
    METRICS.inc("kernel_group_launches_total", family="tree")
    METRICS.inc("kernel_group_queries_total", float(B), family="tree")
    METRICS.inc("kernel_padded_lanes_total", float(lanes - B),
                family="tree")
    _note_kernel_features("*", "tree", lanes, lanes - B,
                          len(plan.stages), B)
    fn, stage_descs = _tree_kernel_for(store, plan, rels, n, W)
    t_exec = _time.perf_counter()
    with tracing.span("batch.tree_kernel", stages=len(plan.stages),
                      queries=B, lanes=lanes, padded_lanes=lanes - B):
        with jit_call("treebatch.tree_kernel", (plan.sig, W, n)):
            outs = fn(tuple(jax.device_put(m) for m in seeds_np),
                      tuple(jax.device_put(m) for m in filts_np))
    # launch count + dispatch gap are recorded by jit_call itself
    costprofile.add_kernel(
        "tree", execute_us=(_time.perf_counter() - t_exec) * 1e6)

    # one host transfer per stage output; bit tests against these masks
    # rebuild every query's edge rows
    masks: list = []
    for s, o in zip(plan.stages, outs):
        if s.kind == "recurse" and s.keep_hops:
            seen, hops = o
            masks.append((np.asarray(seen), np.asarray(hops)))
        else:
            masks.append((np.asarray(o), None))

    out_json = []
    for q, blocks in enumerate(plan.queries):
        ex = _MaskedExecutor(store, q, idx_per_query[q], masks,
                             root_displays[q],
                             device_threshold=device_threshold)
        results: dict[int, LevelNode] = {}
        for bi in execution_order(blocks):
            ex._path = (bi,)
            results[bi] = ex.run_block(blocks[bi])
        roots = [results[bi] for bi in range(len(blocks))]
        out_json.append(to_json(ex, roots))
    return out_json


def _filter_const_key(tree: FilterNode):
    """Canonical key INCLUDING constants — identical filters across the
    batch evaluate once."""
    if tree.op == "leaf":
        f = tree.func
        return ("leaf", f.name, f.attr, f.lang, tuple(map(str, f.args)),
                tuple(f.uids))
    return (tree.op, tuple(_filter_const_key(c) for c in tree.children))


def _pack_global(n: int, rank_lists, lanes: int) -> np.ndarray:
    """Per-lane rank sets → [n+1, lanes/32] uint32 mask, global space."""
    m = np.zeros((n + 1, lanes // 32), np.uint32)
    for q, ranks in enumerate(rank_lists):
        if len(ranks):
            m[np.asarray(ranks, np.int64), q // 32] |= np.uint32(
                1 << (q % 32))
    return m


def _tree_kernel_for(store, plan: TreePlan, rels, n: int, W: int):
    """Compiled tree kernel per (snapshot, signature, lane width); device
    ELL blocks (DeviceEll, via the shared batch cache) and permutation
    vectors shared across signatures."""
    import jax

    from dgraph_tpu.engine.batch import _cache_host, _cache_lock, _dev_for
    from dgraph_tpu.ops.bfs import make_ell_tree, prepare_parts
    from dgraph_tpu.ops.pallas_hop import pallas_enabled

    hosts = {_cache_host(store, a, r) for a, r in rels}
    host = hosts.pop() if len(hosts) == 1 else store
    key = (plan.sig, W, pallas_enabled())
    devells = {rkey: _dev_for(store, *rkey)[1] for rkey in rels}
    with _cache_lock:
        fns = getattr(host, "_tree_fns", None)
        if fns is None:
            fns = host._tree_fns = {}
        if key in fns:
            return fns[key]
        devs = getattr(host, "_tree_devs", None)
        if devs is None:
            devs = host._tree_devs = {}
        prep = getattr(host, "_tree_prep", None)
        if prep is None:
            prep = host._tree_prep = {}
        for rkey, g in rels.items():
            if rkey not in devs:
                perm_in = np.concatenate(
                    [g.perm_order, [n]]).astype(np.int32)
                out_idx = np.concatenate(
                    [g.new_of_old, [n]]).astype(np.int32)
                devs[rkey] = (jax.device_put(perm_in),
                              jax.device_put(out_idx))
            # prepare_parts is width-independent on the XLA path and the
            # pallas row padding is too — one prepped copy per flag state
            pkey = (rkey, pallas_enabled())
            if pkey not in prep:
                prep[pkey] = prepare_parts(devells[rkey], W)
        stage_descs = []
        for s in plan.stages:
            rkey_s = (s.attr, s.reverse)
            perm_in, out_idx = devs[rkey_s]
            prepared = prep[(rkey_s, pallas_enabled())]
            stage_descs.append({
                "kind": s.kind, "prepared": prepared, "perm_in": perm_in,
                "out_idx": out_idx, "parent": s.parent,
                "filt": s.filt_slot, "depth": s.depth,
                "keep_hops": s.keep_hops})
        fns[key] = (make_ell_tree(stage_descs, n, W), stage_descs)
        return fns[key]


class _MaskedExecutor(Executor):
    """Per-query engine whose uid expansions are constrained by the
    kernel's level masks: a child level's edge list is parents' CSR rows
    bit-tested against the stage mask (filters already folded in on
    device), then ordering/pagination/vars/rendering run unchanged."""

    def __init__(self, store, lane: int, sidx: _StageIndex, masks,
                 root_displays=None, **kw):
        super().__init__(store, **kw)
        self._lane_word = lane // 32
        self._lane_bit = np.uint32(1 << (lane % 32))
        self._sidx = sidx
        self._masks = masks
        self._root_displays = root_displays or {}
        self._path: tuple = ()

    def root_display(self, sg: SubGraph) -> np.ndarray:
        # seed blocks evaluated their root once pre-launch; reuse it
        if self._path and len(self._path) == 1:
            cached = self._root_displays.get(self._path[0])
            if cached is not None:
                return cached
        return super().root_display(sg)

    def _member(self, stage_idx: int, ranks: np.ndarray) -> np.ndarray:
        m = self._masks[stage_idx][0]
        return (m[ranks, self._lane_word] & self._lane_bit) != 0

    # -- expansion override --------------------------------------------------
    def _level_edges(self, sg: SubGraph, frontier: np.ndarray):
        stage_idx = self._sidx.by_path.get(self._path)
        if stage_idx is None:
            # a level the planner did not stage (host-only block)
            return super()._level_edges(sg, frontier)
        nbrs, seg, pos = self._gather_rows(sg, frontier)
        if len(nbrs):
            keep = self._member(stage_idx, nbrs)
            nbrs, seg, pos = nbrs[keep], seg[keep], pos[keep]
        nbrs, seg, pos = self.facet_filter_edges(sg, sg.attr, nbrs, seg,
                                                 pos)
        return nbrs, seg, pos, False

    def _gather_rows(self, sg: SubGraph, frontier: np.ndarray):
        from dgraph_tpu.engine.execute import csr_rows
        rel = self.store.rel(sg.attr, sg.is_reverse)
        if not len(frontier) or rel.nnz == 0:
            return EMPTY, EMPTY, EMPTY64
        return csr_rows(rel, frontier)

    # -- tree descent with path bookkeeping ----------------------------------
    def _descend(self, parent: LevelNode) -> None:
        sg = parent.sg
        if sg.recurse is not None:
            stage_idx = self._sidx.by_path.get(self._path)
            if stage_idx is not None and \
                    self._masks[stage_idx][1] is not None:
                self._masked_recurse(parent, stage_idx)
                return
            from dgraph_tpu.engine.recurse import expand_recurse
            expand_recurse(self, parent)
            return
        child_i = 0
        base_path = self._path
        for child_sg in self._concrete_children(parent):
            if self._expands(child_sg):
                self._path = (*base_path, child_i)
                child_i += 1
                parent.children.append(
                    self.run_child(child_sg, parent.nodes))
            else:
                parent.leaf_sgs.append(child_sg)
                self._record_leaf_vars(child_sg, parent)
        self._path = base_path

    def _masked_recurse(self, root: LevelNode, stage_idx: int) -> None:
        """RecurseData from the kernel's per-hop first-visit masks: hop
        h's kept edges are (parent CSR row) ∩ hops[h] — the host loop's
        loop=false semantics, filters already folded into the masks."""
        from dgraph_tpu.engine.recurse import (RecurseData,
                                               _bind_recurse_vars)

        sg = root.sg
        data = RecurseData(loop=False)
        for c in sg.children:
            (data.edge_sgs if self._expands(c)
             else data.leaf_sgs).append(c)
        esg = data.edge_sgs[0]
        rel = self.store.rel(esg.attr, esg.is_reverse)
        _seen, hops = self._masks[stage_idx]
        w, bit = self._lane_word, self._lane_bit

        parents = root.nodes
        all_nodes = [root.nodes]
        p_parts, c_parts = [], []
        for h in range(hops.shape[0]):
            if not len(parents):
                break
            nbrs, seg, _pos = self._gather_rows(esg, parents)
            if not len(nbrs):
                break
            keep = (hops[h, nbrs, w] & bit) != 0
            if not keep.any():
                break
            p_parts.append(parents[seg[keep]].astype(np.int32))
            kept = nbrs[keep].astype(np.int32)
            c_parts.append(kept)
            parents = np.unique(kept)
            all_nodes.append(parents)
        if p_parts:
            data.edges[0] = (np.concatenate(p_parts),
                             np.concatenate(c_parts))
        data.all_nodes = np.unique(
            np.concatenate(all_nodes)).astype(np.int32)
        _bind_recurse_vars(self, root, data, sg)
        root.recurse_data = data
