"""@groupby: group a level's nodes by scalar predicate values + aggregate.

Reference parity: `query/groupby.go` (processGroupBy, evalLevelAgg) —
groups the uids of a block by the values of the groupby predicates and
evaluates the block's aggregate children (count(uid), min/max/sum/avg of
val-vars or predicates) per group.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class GroupResult:
    # group key attrs in declaration order
    attrs: list[str] = field(default_factory=list)
    # each group: ({attr: value}, {agg_label: value}, member_ranks)
    groups: list[tuple[dict, dict, np.ndarray]] = field(default_factory=list)


def process_groupby(ex, node) -> GroupResult:
    """Root-level @groupby: one group table over the block's nodes."""
    return _group_population(ex, node.sg, node.nodes)


def process_groupby_rows(ex, node) -> dict[int, GroupResult]:
    """Child-level @groupby: one group table PER PARENT over that parent's
    matrix row (reference: groupby applies within each parent's edge list)."""
    out: dict[int, GroupResult] = {}
    for pos in np.unique(node.matrix_seg).tolist():
        members = np.unique(
            node.matrix_child[node.matrix_seg == pos]).astype(np.int32)
        out[int(pos)] = _group_population(ex, node.sg, members)
    return out


def _group_population(ex, sg, pop: np.ndarray) -> GroupResult:
    res = GroupResult(attrs=list(sg.groupby))
    if not len(pop):
        return res

    # group key(s) per rank: scalar attrs contribute their first value, uid
    # attrs contribute EVERY edge target (a node with two genres joins two
    # groups — the reference's canonical groupby-on-uid-predicate case)
    keys: dict[tuple, list[int]] = {}
    for r in pop:
        per_attr = [_key_values(ex.store, a, int(r)) for a in sg.groupby]
        if any(not vs for vs in per_attr):
            continue  # nodes missing a group key are dropped (ref behavior)
        combos = [()]
        for vs in per_attr:
            combos = [c + (v,) for c in combos for v in vs]
        for key in combos:
            keys.setdefault(key, []).append(int(r))

    for key in sorted(keys, key=lambda k: tuple(str(x) for x in k)):
        members = np.array(sorted(keys[key]), np.int32)
        aggs: dict[str, object] = {}
        for c in sg.children:
            label = c.alias or (f"{c.agg_func}(val({c.attr}))" if c.is_agg
                                else "count")
            if c.is_count and (c.attr == "uid" or c.is_uid_leaf):
                aggs[label if c.alias else "count"] = len(members)
            elif c.is_agg:
                var = ex.val_vars.get(c.attr, {})
                vals = [var[m] for m in members.tolist() if m in var]
                v = _aggregate(c.agg_func, vals)
                if v is not None:  # min/max over no values: omit
                    aggs[label] = v
        res.groups.append(({a: k for a, k in zip(sg.groupby, key)}, aggs,
                           members))
    return res


def _key_values(store, attr: str, rank: int) -> list:
    """Group-key values of `attr` on `rank`: first scalar value, or all uid
    edge targets rendered as hex-uid strings."""
    from dgraph_tpu.store.types import Kind
    ps = store.schema.peek(attr.lstrip("~"))
    if ps is not None and ps.kind == Kind.UID:
        rel = store.rel(attr.lstrip("~"), reverse=attr.startswith("~"))
        return [f"0x{int(store.uid_of(t)):x}" for t in rel.row(rank)]
    vs = store.values_for(attr, rank)
    return vs[:1]


def _aggregate(fn: str, vals: list):
    if not vals:
        # reference: sum/avg over an empty set render 0; min/max omit
        return 0 if fn in ("sum", "avg") else None
    if fn == "min":
        return min(vals)
    if fn == "max":
        return max(vals)
    if fn == "sum":
        return sum(vals)
    if fn == "avg":
        return sum(vals) / len(vals)
    raise ValueError(f"unknown aggregate {fn!r}")
