"""shortest(from, to) path queries.

Reference parity: `query/shortest.go` (shortestPath, expandOut) — iterative
frontier expansion with parent pointers; uniform cost BFS here (facet
weights arrive with facet support). `numpaths > 1` returns up to k shortest
by BFS level-DAG enumeration.

The hop loop is the same batched CSR expansion as everything else; parent
pointers are kept host-side (path reconstruction is inherently sequential
and tiny).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

MAX_PATH_DEPTH = 32


@dataclass
class PathData:
    # each path: list of (rank, pred_sg_index_into_edge_sgs or -1 for start)
    paths: list[list[tuple[int, int]]] = field(default_factory=list)
    edge_sgs: list = field(default_factory=list)
    nodes: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))
    # total path cost per path (weighted mode only; rendered as _weight_)
    weights: list[float] = field(default_factory=list)


def shortest_path(ex, sg) -> PathData:
    """BFS from sg.shortest.from_uid to to_uid over the block's edge preds.
    When an edge block names a facet (`friend @facets(weight)`), edges are
    relaxed by that facet's value instead of uniform cost — reference:
    query/shortest.go facet-weight relaxation."""
    args = sg.shortest
    store = ex.store
    src = store.rank_of(np.array([args.from_uid], np.int64))[0]
    dst = store.rank_of(np.array([args.to_uid], np.int64))[0]
    data = PathData(edge_sgs=[c for c in sg.children if ex._expands(c)])
    if src < 0 or dst < 0:
        return data
    if any(c.facet_keys for c in data.edge_sgs):
        return _weighted_shortest(ex, sg, data, int(src), int(dst))
    max_depth = args.depth or MAX_PATH_DEPTH
    k = max(1, args.numpaths)

    if k == 1:
        # fast path: first-visit BFS, one shortest path
        parents: dict[int, list[tuple[int, int]]] = {int(src): []}
        frontier = np.array([src], np.int32)
        found = src == dst
        for _ in range(max_depth):
            if found or not len(frontier):
                break
            level_new: dict[int, list[tuple[int, int]]] = {}
            for i, esg in enumerate(data.edge_sgs):
                nbrs, seg, pos = ex.expand(esg.attr, esg.is_reverse,
                                           frontier)
                nbrs, seg, pos = ex.filter_edges(esg.filters, nbrs, seg,
                                                 pos)
                for n, s in zip(nbrs.tolist(), seg.tolist()):
                    if n not in parents:  # unseen at earlier levels
                        level_new.setdefault(n, []).append(
                            (int(frontier[s]), i))
            parents.update(level_new)
            if int(dst) in level_new:
                found = True
            frontier = np.array(sorted(level_new), np.int32)

        if int(dst) in parents:
            def walk(rank: int):
                plist = parents[rank]
                if not plist:
                    yield [(rank, -1)]
                    return
                for p, pi in plist:
                    for prefix in walk(p):
                        yield prefix + [(rank, pi)]
            data.paths = [next(walk(int(dst)))]
    else:
        data.paths = _k_shortest(ex, data, int(src), int(dst),
                                 max_depth, k)
    if data.paths:
        data.nodes = np.unique(np.array([r for p in data.paths for r, _ in p],
                                        np.int32))
    return data


def _k_shortest(ex, data: PathData, src: int, dst: int, max_depth: int,
                k: int) -> list:
    """Up to k SIMPLE paths in length order (reference: shortest with
    numpaths returns longer paths once shorter ones are exhausted, not
    just equal-length alternates). Level-expansion keeps EVERY (parent,
    pred) edge per level — the full level DAG — then enumerates paths of
    length 1, 2, ... with an on-path set to stay simple."""
    # levels[l][node] = [(parent, pred_i)] for paths reaching node in
    # exactly l+1 hops; frontier at level l = all nodes reached at l
    levels: list[dict[int, list[tuple[int, int]]]] = []
    frontier = np.array([src], np.int32)
    for _ in range(max_depth):
        if not len(frontier):
            break
        level_new: dict[int, list[tuple[int, int]]] = {}
        for i, esg in enumerate(data.edge_sgs):
            nbrs, seg, pos = ex.expand(esg.attr, esg.is_reverse, frontier)
            nbrs, seg, pos = ex.filter_edges(esg.filters, nbrs, seg, pos)
            for n, s in zip(nbrs.tolist(), seg.tolist()):
                pair = (int(frontier[s]), i)
                plist = level_new.setdefault(n, [])
                if pair not in plist:
                    plist.append(pair)
        levels.append(level_new)
        frontier = np.array(sorted(level_new), np.int32)

    def walk_back(level: int, rank: int, on_path: frozenset):
        """Simple paths of exactly `level+1` hops ending at rank."""
        for p, pi in levels[level].get(rank, ()):
            if level == 0:
                if p == src:
                    yield [(src, -1), (rank, pi)]
            elif p not in on_path:
                for prefix in walk_back(level - 1, p, on_path | {p}):
                    yield prefix + [(rank, pi)]

    out: list = []
    if src == dst:
        out.append([(src, -1)])
    for level in range(len(levels)):
        if len(out) >= k:
            break
        # src rides the on-path set from the start: a simple path may
        # END at src (the level-0 termination checks equality) but can
        # never pass THROUGH it mid-walk
        for path in walk_back(level, dst, frozenset([dst, src])):
            out.append(path)
            if len(out) >= k:
                break
    return out[:k]


def _edge_weights(store, ex, esg, nbrs: np.ndarray, pos: np.ndarray,
                  wkey) -> np.ndarray:
    """Facet weights for a batch of edges; edges without the named facet
    (or with a non-numeric value — strings never parse) relax at
    weight 1, per edge, independent of what else is in the batch."""
    if not wkey or not len(pos):
        return np.ones(len(nbrs))
    fvals = store.edge_facets(esg.attr, ex.facet_positions(esg, pos),
                              [wkey]).get(wkey)
    if fvals is None:
        return np.ones(len(nbrs))
    arr = np.asarray(fvals)
    if arr.dtype.kind in "ifb":  # homogeneous numeric: vector cast
        return arr.astype(np.float64)
    ws = np.ones(len(fvals))
    for j, v in enumerate(fvals):
        if isinstance(v, (int, float, np.integer, np.floating)):
            ws[j] = float(v)
    return ws


def _weighted_shortest(ex, sg, data: PathData, src: int,
                       dst: int) -> PathData:
    """Facet-weight shortest path as BATCHED frontier relaxation.

    The per-node priority-queue Dijkstra of the reference
    (query/shortest.go relaxes one settled node at a time) is the wrong
    shape for this engine: every relaxation round here expands the WHOLE
    improved frontier through the same vectorized CSR expansion (host or
    device) every other hop uses — Bellman-Ford rounds, exact for the
    non-negative weights the reference supports, with O(diameter) rounds
    instead of O(nodes) device round-trips. Distances settle first; the
    equal-cost parent DAG is rebuilt afterwards in one tight-edge pass
    (dist[u] + w == dist[v]) so `numpaths > 1` enumerates the same
    minimal-cost DAG the per-node algorithm maintained incrementally.
    maxweight prunes the search frontier; minweight filters the final
    answer."""
    args = sg.shortest
    store = ex.store
    wkeys = [(c.facet_keys[0][1] if c.facet_keys else None)
             for c in data.edge_sgs]
    EPS = 1e-9
    n = store.n_nodes
    dist = np.full(n, np.inf)
    dist[src] = 0.0
    frontier = np.array([src], np.int32)
    # Bellman-Ford round bound guards a (malformed) negative-weight input
    # from looping forever; non-negative graphs exit when no distance
    # improves, typically after ~diameter rounds.
    for _round in range(max(n, 1)):
        if not len(frontier):
            break
        nbr_parts, nd_parts = [], []
        for i, esg in enumerate(data.edge_sgs):
            nbrs, seg, pos = ex.expand(esg.attr, esg.is_reverse,
                                       frontier,
                                       allow_remote=not wkeys[i])
            nbrs, seg, pos = ex.filter_edges(esg.filters, nbrs, seg, pos)
            if not len(nbrs):
                continue
            ws = _edge_weights(store, ex, esg, nbrs, pos, wkeys[i])
            nd = dist[frontier[seg]] + ws
            # prune relaxations that can neither beat maxweight nor lie
            # on a minimal-cost path to an already-reached dst
            keep = (nd <= args.maxweight) & (nd <= dist[dst] + EPS)
            if keep.any():
                nbr_parts.append(nbrs[keep])
                nd_parts.append(nd[keep])
        if not nbr_parts:
            break
        all_nbrs = np.concatenate(nbr_parts)
        all_nd = np.concatenate(nd_parts)
        u_nbrs, inv = np.unique(all_nbrs, return_inverse=True)
        best = np.full(len(u_nbrs), np.inf)
        np.minimum.at(best, inv, all_nd)
        improved = best < dist[u_nbrs] - EPS
        dist[u_nbrs[improved]] = best[improved]
        frontier = u_nbrs[improved].astype(np.int32)

    parents: dict[int, list[tuple[int, int]]] = {src: []}
    if np.isfinite(dist[dst]):
        # tight-edge pass: expand every node that can sit on a minimal
        # path (dist ≤ dist[dst]) once, keep edges with
        # dist[u] + w == dist[v] — the shortest-path DAG
        cand = np.nonzero(np.isfinite(dist)
                          & (dist <= dist[dst] + EPS))[0].astype(np.int32)
        for i, esg in enumerate(data.edge_sgs):
            nbrs, seg, pos = ex.expand(esg.attr, esg.is_reverse, cand,
                                       allow_remote=not wkeys[i])
            nbrs, seg, pos = ex.filter_edges(esg.filters, nbrs, seg, pos)
            if not len(nbrs):
                continue
            ws = _edge_weights(store, ex, esg, nbrs, pos, wkeys[i])
            du = dist[cand[seg]]
            tight = (np.abs(du + ws - dist[nbrs]) <= EPS) \
                & (dist[nbrs] <= dist[dst] + EPS) & (nbrs != src)
            for u, v in zip(cand[seg[tight]].tolist(),
                            nbrs[tight].tolist()):
                plist = parents.setdefault(int(v), [])
                if (int(u), i) not in plist:
                    plist.append((int(u), i))

    if np.isfinite(dist[dst]) and \
            args.minweight <= dist[dst] <= args.maxweight:
        # zero-weight edges can put CYCLES in the tight-edge graph
        # (u→v and v→u both at w=0); tracking the on-path set keeps the
        # enumeration to SIMPLE paths — shortest paths never need to
        # revisit a node, and the recursion depth stays ≤ |DAG nodes|
        def walk(rank: int, on_path: frozenset):
            plist = parents.get(rank, ())
            if not plist:
                yield [(rank, -1)]
                return
            for p, pi in plist:
                if p in on_path:
                    continue
                for prefix in walk(p, on_path | {p}):
                    yield prefix + [(rank, pi)]

        import itertools
        data.paths = list(itertools.islice(walk(dst, frozenset([dst])),
                                           max(1, args.numpaths)))
        data.weights = [float(dist[dst])] * len(data.paths)
    if data.paths:
        data.nodes = np.unique(np.array(
            [r for p in data.paths for r, _ in p], np.int32))
    return data
