"""shortest(from, to) path queries.

Reference parity: `query/shortest.go` (shortestPath, expandOut) — iterative
frontier expansion with parent pointers; uniform cost BFS here (facet
weights arrive with facet support). `numpaths > 1` returns up to k shortest
by BFS level-DAG enumeration.

The hop loop is the same batched CSR expansion as everything else; parent
pointers are kept host-side (path reconstruction is inherently sequential
and tiny).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

MAX_PATH_DEPTH = 32


@dataclass
class PathData:
    # each path: list of (rank, pred_sg_index_into_edge_sgs or -1 for start)
    paths: list[list[tuple[int, int]]] = field(default_factory=list)
    edge_sgs: list = field(default_factory=list)
    nodes: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))
    # total path cost per path (weighted mode only; rendered as _weight_)
    weights: list[float] = field(default_factory=list)


def shortest_path(ex, sg) -> PathData:
    """BFS from sg.shortest.from_uid to to_uid over the block's edge preds.
    When an edge block names a facet (`friend @facets(weight)`), edges are
    relaxed by that facet's value instead of uniform cost — reference:
    query/shortest.go facet-weight relaxation."""
    args = sg.shortest
    store = ex.store
    src = store.rank_of(np.array([args.from_uid], np.int64))[0]
    dst = store.rank_of(np.array([args.to_uid], np.int64))[0]
    data = PathData(edge_sgs=[c for c in sg.children if ex._expands(c)])
    if src < 0 or dst < 0:
        return data
    if any(c.facet_keys for c in data.edge_sgs):
        return _dijkstra(ex, sg, data, int(src), int(dst))
    max_depth = args.depth or MAX_PATH_DEPTH

    # parents[rank] = all (parent_rank, pred_index) found at rank's first
    # BFS level — the shortest-path DAG, enumerable for numpaths > 1
    parents: dict[int, list[tuple[int, int]]] = {int(src): []}
    frontier = np.array([src], np.int32)
    found = src == dst
    for _ in range(max_depth):
        if found or not len(frontier):
            break
        level_new: dict[int, list[tuple[int, int]]] = {}
        for i, esg in enumerate(data.edge_sgs):
            nbrs, seg, pos = ex.expand(esg.attr, esg.is_reverse, frontier)
            nbrs, seg, pos = ex.filter_edges(esg.filters, nbrs, seg, pos)
            for n, s in zip(nbrs.tolist(), seg.tolist()):
                if n not in parents:  # unseen at earlier levels
                    level_new.setdefault(n, []).append((int(frontier[s]), i))
        parents.update(level_new)
        if int(dst) in level_new:
            found = True
        frontier = np.array(sorted(level_new), np.int32)

    if int(dst) in parents:
        # enumerate up to numpaths equal-length paths through the BFS DAG;
        # each path entry is (rank, pred_index_used_to_arrive), -1 at src
        def walk(rank: int):
            plist = parents[rank]
            if not plist:
                yield [(rank, -1)]
                return
            for p, pi in plist:
                for prefix in walk(p):
                    yield prefix + [(rank, pi)]

        import itertools
        data.paths = list(itertools.islice(walk(int(dst)),
                                           max(1, args.numpaths)))
    if data.paths:
        data.nodes = np.unique(np.array([r for p in data.paths for r, _ in p],
                                        np.int32))
    return data


def _dijkstra(ex, sg, data: PathData, src: int, dst: int) -> PathData:
    """Facet-weight uniform-cost search. Parent lists keep every
    equal-cost predecessor, so numpaths > 1 enumerates the minimal-cost
    path DAG the way the BFS path does. Edges without the named facet
    relax at weight 1 (uniform). maxweight prunes the search frontier;
    minweight filters the final answer."""
    import heapq

    args = sg.shortest
    store = ex.store
    wkeys = [(c.facet_keys[0][1] if c.facet_keys else None)
             for c in data.edge_sgs]
    EPS = 1e-9
    dist: dict[int, float] = {src: 0.0}
    parents: dict[int, list[tuple[int, int]]] = {src: []}
    settled: set[int] = set()
    heap: list[tuple[float, int]] = [(0.0, src)]
    while heap:
        d, u = heapq.heappop(heap)
        if u in settled:
            continue
        settled.add(u)
        if u == dst:
            break
        frontier = np.array([u], np.int32)
        for i, esg in enumerate(data.edge_sgs):
            nbrs, seg, pos = ex.expand(esg.attr, esg.is_reverse,
                                       frontier,
                                       allow_remote=not wkeys[i])
            nbrs, seg, pos = ex.filter_edges(esg.filters, nbrs, seg, pos)
            if not len(nbrs):
                continue
            if wkeys[i] and len(pos):
                fvals = store.edge_facets(
                    esg.attr, ex.facet_positions(esg, pos),
                    [wkeys[i]]).get(wkeys[i], [None] * len(pos))
                ws = [float(v) if isinstance(v, (int, float, np.integer,
                                                 np.floating)) else 1.0
                      for v in fvals]
            else:
                ws = [1.0] * len(nbrs)
            for v, w in zip(nbrs.tolist(), ws):
                nd = d + w
                if nd > args.maxweight:
                    continue
                old = dist.get(v)
                if old is None or nd < old - EPS:
                    dist[v] = nd
                    parents[v] = [(u, i)]
                    heapq.heappush(heap, (nd, v))
                elif abs(nd - old) <= EPS and (u, i) not in parents[v]:
                    parents[v].append((u, i))

    if dst in dist and args.minweight <= dist[dst] <= args.maxweight:
        def walk(rank: int):
            plist = parents[rank]
            if not plist:
                yield [(rank, -1)]
                return
            for p, pi in plist:
                for prefix in walk(p):
                    yield prefix + [(rank, pi)]

        import itertools
        data.paths = list(itertools.islice(walk(dst),
                                           max(1, args.numpaths)))
        data.weights = [dist[dst]] * len(data.paths)
    if data.paths:
        data.nodes = np.unique(np.array(
            [r for p in data.paths for r, _ in p], np.int32))
    return data
