"""shortest(from, to) path queries.

Reference parity: `query/shortest.go` (shortestPath, expandOut) — iterative
frontier expansion with parent pointers; uniform cost BFS here (facet
weights arrive with facet support). `numpaths > 1` returns up to k shortest
by BFS level-DAG enumeration.

The hop loop is the same batched CSR expansion as everything else; parent
pointers are kept host-side (path reconstruction is inherently sequential
and tiny).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

MAX_PATH_DEPTH = 32


@dataclass
class PathData:
    # each path: list of (rank, pred_sg_index_into_edge_sgs or -1 for start)
    paths: list[list[tuple[int, int]]] = field(default_factory=list)
    edge_sgs: list = field(default_factory=list)
    nodes: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))


def shortest_path(ex, sg) -> PathData:
    """BFS from sg.shortest.from_uid to to_uid over the block's edge preds."""
    args = sg.shortest
    store = ex.store
    src = store.rank_of(np.array([args.from_uid], np.int64))[0]
    dst = store.rank_of(np.array([args.to_uid], np.int64))[0]
    data = PathData(edge_sgs=[c for c in sg.children if ex._expands(c)])
    if src < 0 or dst < 0:
        return data
    max_depth = args.depth or MAX_PATH_DEPTH

    # parents[rank] = all (parent_rank, pred_index) found at rank's first
    # BFS level — the shortest-path DAG, enumerable for numpaths > 1
    parents: dict[int, list[tuple[int, int]]] = {int(src): []}
    frontier = np.array([src], np.int32)
    found = src == dst
    for _ in range(max_depth):
        if found or not len(frontier):
            break
        level_new: dict[int, list[tuple[int, int]]] = {}
        for i, esg in enumerate(data.edge_sgs):
            nbrs, seg, pos = ex.expand(esg.attr, esg.is_reverse, frontier)
            nbrs, seg, pos = ex.filter_edges(esg.filters, nbrs, seg, pos)
            for n, s in zip(nbrs.tolist(), seg.tolist()):
                if n not in parents:  # unseen at earlier levels
                    level_new.setdefault(n, []).append((int(frontier[s]), i))
        parents.update(level_new)
        if int(dst) in level_new:
            found = True
        frontier = np.array(sorted(level_new), np.int32)

    if int(dst) in parents:
        # enumerate up to numpaths equal-length paths through the BFS DAG;
        # each path entry is (rank, pred_index_used_to_arrive), -1 at src
        def walk(rank: int):
            plist = parents[rank]
            if not plist:
                yield [(rank, -1)]
                return
            for p, pi in plist:
                for prefix in walk(p):
                    yield prefix + [(rank, pi)]

        import itertools
        data.paths = list(itertools.islice(walk(int(dst)),
                                           max(1, args.numpaths)))
    if data.paths:
        data.nodes = np.unique(np.array([r for p in data.paths for r, _ in p],
                                        np.int32))
    return data
