"""shortest(from, to) path queries.

Reference parity: `query/shortest.go` (shortestPath, expandOut) — iterative
frontier expansion with parent pointers; uniform-cost BFS or facet-weighted
relaxation. `numpaths` returns up to k SIMPLE paths in length order
(unweighted: level-DAG enumeration) or cost order (weighted: Yen's
algorithm over the batched relaxation core), longer/costlier paths once
shorter ones exhaust. minweight/maxweight bound the paths COUNTED toward
numpaths (the reference keeps searching past under-min paths); unweighted
edges weigh 1 for these bounds.

The hop loop is the same batched CSR expansion as everything else; parent
pointers are kept host-side (path reconstruction is inherently sequential
and tiny).

Batch serving: UNWEIGHTED shortest blocks (the IC13/IC14 shapes) also
ride the lane-BFS kernel path — engine/batch.py packs compatible
queries into mask lanes, runs the staged first-visit (or level-DAG, for
numpaths > 1) kernel, and reconstructs each lane's paths by walking the
found levels backward over the reverse CSR. That reconstruction pins
THIS module's semantics bit-for-bit (parent-list order = ascending
frontier rank, level-order path enumeration, simple-path exclusion,
min/maxweight counting) — tests/test_batch.py asserts the two paths
byte-identical, so behavior changes here must update both.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from dgraph_tpu.utils import deadline

MAX_PATH_DEPTH = 32
# Yen's outer loop extracts one path per iteration; when min/maxweight
# discard most of them the search could otherwise grind through an
# exponential path space — bound total extractions.
MAX_YEN_ITERS = 128
_EPS = 1e-9


@dataclass
class PathData:
    # each path: list of (rank, pred_sg_index_into_edge_sgs or -1 for start)
    paths: list[list[tuple[int, int]]] = field(default_factory=list)
    edge_sgs: list = field(default_factory=list)
    nodes: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))
    # total path cost per path (weighted mode only; rendered as _weight_)
    weights: list[float] = field(default_factory=list)


def shortest_path(ex, sg) -> PathData:
    """BFS from sg.shortest.from_uid to to_uid over the block's edge preds.
    When an edge block names a facet (`friend @facets(weight)`), edges are
    relaxed by that facet's value instead of uniform cost — reference:
    query/shortest.go facet-weight relaxation."""
    from dgraph_tpu.utils import tracing
    a = sg.shortest
    with tracing.span("engine.shortest", numpaths=a.numpaths,
                      depth=a.depth) as sp:
        data = _shortest_path(ex, sg)
        sp.attrs["paths"] = len(data.paths)
        sp.attrs["nodes"] = int(len(data.nodes))
        return data


def _shortest_path(ex, sg) -> PathData:
    args = sg.shortest
    store = ex.store
    src = store.rank_of(np.array([args.from_uid], np.int64))[0]
    dst = store.rank_of(np.array([args.to_uid], np.int64))[0]
    data = PathData(edge_sgs=[c for c in sg.children if ex._expands(c)])
    if src < 0 or dst < 0:
        return data
    if any(c.facet_keys for c in data.edge_sgs):
        return _weighted_shortest(ex, sg, data, int(src), int(dst))
    max_depth = args.depth or MAX_PATH_DEPTH
    k = max(1, args.numpaths)
    bounded = args.minweight > float("-inf") or \
        args.maxweight < float("inf")

    if k == 1 and not bounded:
        # fast path: first-visit BFS, one shortest path
        parents: dict[int, list[tuple[int, int]]] = {int(src): []}
        frontier = np.array([src], np.int32)
        found = src == dst
        for _ in range(max_depth):
            if found or not len(frontier):
                break
            # per-BFS-iteration cancellation point (the acceptance
            # granularity for shortest-path budgets)
            deadline.checkpoint("bfs")
            level_new: dict[int, list[tuple[int, int]]] = {}
            for i, esg in enumerate(data.edge_sgs):
                nbrs, seg, pos = ex.expand(esg.attr, esg.is_reverse,
                                           frontier)
                nbrs, seg, pos = ex.filter_edges(esg.filters, nbrs, seg,
                                                 pos)
                for n, s in zip(nbrs.tolist(), seg.tolist()):
                    if n not in parents:  # unseen at earlier levels
                        level_new.setdefault(n, []).append(
                            (int(frontier[s]), i))
            parents.update(level_new)
            if int(dst) in level_new:
                found = True
            frontier = np.array(sorted(level_new), np.int32)

        if int(dst) in parents:
            # iterative walk-back (first-visit BFS: following the first
            # parent at every step IS the first path the recursive
            # enumeration would yield) — a recursive walk blows the
            # interpreter stack on a 1000-hop chain, and a pathological
            # path length must cancel via the deadline checkpoints
            # above, never crash the walk
            rev, cur = [], int(dst)
            # graftlint: allow(hot-loop-checkpoint): walk-back length is
            # bounded by the BFS depth the checkpointed loop above built
            while True:
                plist = parents[cur]
                if not plist:
                    rev.append((cur, -1))
                    break
                p, pi = plist[0]
                rev.append((cur, pi))
                cur = p
            data.paths = [rev[::-1]]
    else:
        data.paths = _k_shortest(ex, data, int(src), int(dst), max_depth,
                                 k, args.minweight, args.maxweight)
    if data.paths:
        data.nodes = np.unique(np.array([r for p in data.paths for r, _ in p],
                                        np.int32))
    return data


def _k_shortest(ex, data: PathData, src: int, dst: int, max_depth: int,
                k: int, minw: float, maxw: float) -> list:
    """Up to k SIMPLE paths in length order (reference: shortest with
    numpaths returns longer paths once shorter ones are exhausted).
    Unweighted edges weigh 1, so a path of h hops costs h; only paths
    with minw ≤ h ≤ maxw count toward k. Level expansion keeps EVERY
    (parent, pred) edge per level — the full level DAG — and path
    enumeration interleaves with level construction so the loop stops as
    soon as k in-range paths exist."""
    out: list = []
    if src == dst:
        # the trivial zero-hop path; cycles back to the source are not
        # simple paths and are never returned (matching the weighted
        # branch's semantics)
        if minw <= 0 <= maxw:
            out.append([(src, -1)])
        return out

    # levels[l][node] = [(parent, pred_i)] for paths reaching node in
    # exactly l+1 hops; frontier at level l = all nodes reached at l
    levels: list[dict[int, list[tuple[int, int]]]] = []

    def walk_back(level: int, rank: int, on_path: frozenset):
        """Simple paths of exactly `level+1` hops ending at rank."""
        for p, pi in levels[level].get(rank, ()):
            if level == 0:
                if p == src:
                    yield [(src, -1), (rank, pi)]
            elif p not in on_path:
                for prefix in walk_back(level - 1, p, on_path | {p}):
                    yield prefix + [(rank, pi)]

    if np.isfinite(maxw):
        max_depth = min(max_depth, max(int(maxw), 0))
    frontier = np.array([src], np.int32)
    for level in range(max_depth):
        if not len(frontier):
            break
        deadline.checkpoint("bfs")
        level_new: dict[int, list[tuple[int, int]]] = {}
        for i, esg in enumerate(data.edge_sgs):
            nbrs, seg, pos = ex.expand(esg.attr, esg.is_reverse, frontier)
            nbrs, seg, pos = ex.filter_edges(esg.filters, nbrs, seg, pos)
            for n, s in zip(nbrs.tolist(), seg.tolist()):
                pair = (int(frontier[s]), i)
                plist = level_new.setdefault(n, [])
                if pair not in plist:
                    plist.append(pair)
        levels.append(level_new)
        frontier = np.array(sorted(level_new), np.int32)
        hops = level + 1
        if minw <= hops <= maxw:
            # src rides the on-path set: a simple path may END at src
            # (level-0 termination checks equality) but never passes
            # THROUGH it
            for path in walk_back(level, dst, frozenset([dst, src])):
                out.append(path)
                if len(out) >= k:
                    return out
    return out[:k]


def _edge_weights(store, ex, esg, nbrs: np.ndarray, pos: np.ndarray,
                  wkey) -> np.ndarray:
    """Facet weights for a batch of edges; edges without the named facet
    (or with a non-numeric value — strings never parse) relax at
    weight 1, per edge, independent of what else is in the batch."""
    if not wkey or not len(pos):
        return np.ones(len(nbrs))
    fpos = ex.facet_positions(esg, pos)
    p = store.preds.get(esg.attr)
    col = p.efacets.get(wkey) if p is not None else None
    if col is not None:
        fast = col.numeric_at(np.asarray(fpos, np.int64))
        if fast is not None:
            vals, hit = fast
            return np.where(hit, vals, 1.0)
    fvals = store.edge_facets(esg.attr, fpos, [wkey]).get(wkey)
    if fvals is None:
        return np.ones(len(nbrs))
    arr = np.asarray(fvals)
    if arr.dtype.kind in "ifb":  # homogeneous numeric: vector cast
        return arr.astype(np.float64)
    ws = np.ones(len(fvals))
    for j, v in enumerate(fvals):
        if isinstance(v, (int, float, np.integer, np.floating)):
            ws[j] = float(v)
    return ws


def _weighted_one(ex, data: PathData, src: int, dst: int, wkeys,
                  maxw: float, banned_nodes: frozenset = frozenset(),
                  banned_edges: frozenset = frozenset()):
    """One minimal-cost SIMPLE path src→dst as BATCHED frontier
    relaxation, honoring banned nodes/edges (the restriction sets Yen's
    spur searches need).

    The per-node priority-queue Dijkstra of the reference
    (query/shortest.go relaxes one settled node at a time) is the wrong
    shape for this engine: every relaxation round here expands the WHOLE
    improved frontier through the same vectorized CSR expansion (host or
    device) every other hop uses — Bellman-Ford rounds, exact for the
    non-negative weights the reference supports, with O(diameter) rounds
    instead of O(nodes) device round-trips. Distances settle first; the
    path is read back over one tight-edge pass (dist[u] + w == dist[v]).

    Returns (cost, path[(rank, pred_i)], pcosts) — pcosts[j] is the
    cumulative cost of path[:j+1] (exact along a tight path) — or
    (inf, None, None)."""
    store = ex.store
    n = store.n_nodes
    banned_arr = (np.array(sorted(banned_nodes), np.int32)
                  if banned_nodes else None)
    banned_us = {u for u, _, _ in banned_edges}

    def relax_edges(frontier, i, esg):
        nbrs, seg, pos = ex.expand(esg.attr, esg.is_reverse, frontier,
                                   allow_remote=not wkeys[i])
        nbrs, seg, pos = ex.filter_edges(esg.filters, nbrs, seg, pos)
        if not len(nbrs):
            return nbrs, seg, np.zeros(0)
        ws = _edge_weights(store, ex, esg, nbrs, pos, wkeys[i])
        keep = np.ones(len(nbrs), bool)
        if banned_arr is not None:
            keep &= ~np.isin(nbrs, banned_arr)
        if banned_edges:
            srcs = frontier[seg]
            for j in np.nonzero(np.isin(srcs,
                                        list(banned_us)))[0].tolist():
                if (int(srcs[j]), int(nbrs[j]), i) in banned_edges:
                    keep[j] = False
        return nbrs[keep], seg[keep], ws[keep]

    dist = np.full(n, np.inf)
    dist[src] = 0.0
    frontier = np.array([src], np.int32)
    # Bellman-Ford round bound guards a (malformed) negative-weight input
    # from looping forever; non-negative graphs exit when no distance
    # improves, typically after ~diameter rounds.
    for _round in range(max(n, 1)):
        if not len(frontier):
            break
        deadline.checkpoint("bfs")  # per relaxation round
        nbr_parts, nd_parts = [], []
        for i, esg in enumerate(data.edge_sgs):
            nbrs, seg, ws = relax_edges(frontier, i, esg)
            if not len(nbrs):
                continue
            nd = dist[frontier[seg]] + ws
            # prune relaxations that can neither beat maxweight nor lie
            # on a minimal-cost path to an already-reached dst
            keep = (nd <= maxw) & (nd <= dist[dst] + _EPS)
            if keep.any():
                nbr_parts.append(nbrs[keep])
                nd_parts.append(nd[keep])
        if not nbr_parts:
            break
        all_nbrs = np.concatenate(nbr_parts)
        all_nd = np.concatenate(nd_parts)
        u_nbrs, inv = np.unique(all_nbrs, return_inverse=True)
        best = np.full(len(u_nbrs), np.inf)
        np.minimum.at(best, inv, all_nd)
        improved = best < dist[u_nbrs] - _EPS
        dist[u_nbrs[improved]] = best[improved]
        frontier = u_nbrs[improved].astype(np.int32)

    if not np.isfinite(dist[dst]):
        return np.inf, None, None
    # tight-edge pass: expand every node that can sit on a minimal path
    # (dist ≤ dist[dst]) once, keep edges with dist[u] + w == dist[v]
    parents: dict[int, list[tuple[int, int]]] = {src: []}
    cand = np.nonzero(np.isfinite(dist)
                      & (dist <= dist[dst] + _EPS))[0].astype(np.int32)
    for i, esg in enumerate(data.edge_sgs):
        nbrs, seg, ws = relax_edges(cand, i, esg)
        if not len(nbrs):
            continue
        du = dist[cand[seg]]
        tight = (np.abs(du + ws - dist[nbrs]) <= _EPS) \
            & (dist[nbrs] <= dist[dst] + _EPS) & (nbrs != src)
        for u, v in zip(cand[seg[tight]].tolist(), nbrs[tight].tolist()):
            plist = parents.setdefault(int(v), [])
            if (int(u), i) not in plist:
                plist.append((int(u), i))

    # first SIMPLE path through the tight DAG (zero-weight edges can put
    # cycles in it; the on-path set keeps the walk simple)
    def walk(rank: int, on_path: frozenset):
        plist = parents.get(rank, ())
        if not plist:
            yield [(rank, -1)]
            return
        for p, pi in plist:
            if p in on_path:
                continue
            for prefix in walk(p, on_path | {p}):
                yield prefix + [(rank, pi)]

    path = next(walk(dst, frozenset([dst])), None)
    if path is None:
        return np.inf, None, None
    # per-node dist is exact along a tight path — the cumulative costs
    # Yen's spur budgeting needs, with no re-expansion
    pcosts = [float(dist[r]) for r, _ in path]
    return float(dist[dst]), path, pcosts


def _weighted_shortest(ex, sg, data: PathData, src: int,
                       dst: int) -> PathData:
    """Facet-weight shortest path(s). `numpaths > 1` (or weight bounds)
    runs Yen's algorithm over the batched single-path core: minimal-cost
    SIMPLE paths in cost order, costlier paths once cheaper ones exhaust
    — each spur search is a full batched relaxation with the root prefix
    banned. Only paths with minweight ≤ cost ≤ maxweight count toward
    numpaths (the reference searches past under-min paths)."""
    import heapq

    args = sg.shortest
    wkeys = [(c.facet_keys[0][1] if c.facet_keys else None)
             for c in data.edge_sgs]
    k = max(1, args.numpaths)

    cost, path, pcosts = _weighted_one(ex, data, src, dst, wkeys,
                                       args.maxweight)
    if path is None:
        return data
    A: list[tuple[float, list, list]] = [(cost, path, pcosts)]
    seen_paths = {tuple(path)}
    B: list[tuple[float, int, list, list]] = []  # (cost, tie, path, pcosts)
    tie = 0

    def in_range(c: float) -> bool:
        return args.minweight <= c <= args.maxweight

    kept = sum(1 for c, _p, _pc in A if in_range(c))
    iters = 0
    while kept < k and iters < MAX_YEN_ITERS:
        deadline.checkpoint("yen")
        iters += 1
        _pc, prev, prev_costs = A[-1]
        for i in range(len(prev) - 1):
            spur = prev[i][0]
            root = prev[:i + 1]
            root_cost = prev_costs[i]
            banned_edges = frozenset(
                (p[i][0], p[i + 1][0], p[i + 1][1])
                for _c, p, _ in A
                if len(p) > i + 1 and p[:i + 1] == root)
            banned_nodes = frozenset(r for r, _ in root[:-1])
            sc, sp, spc = _weighted_one(ex, data, spur, dst, wkeys,
                                        args.maxweight - root_cost,
                                        banned_nodes, banned_edges)
            if sp is None:
                continue
            cand_path = root + sp[1:]
            kk = tuple(cand_path)
            if kk in seen_paths:
                continue
            seen_paths.add(kk)
            cand_pcosts = prev_costs[:i + 1] + \
                [root_cost + c for c in spc[1:]]
            tie += 1
            heapq.heappush(B, (root_cost + sc, tie, cand_path,
                               cand_pcosts))
        if not B:
            break
        c2, _t, p2, pc2 = heapq.heappop(B)
        A.append((c2, p2, pc2))
        if in_range(c2):
            kept += 1

    final = [(c, p) for c, p, _pc in A if in_range(c)][:k]
    data.paths = [p for _c, p in final]
    data.weights = [c for c, _p in final]
    if data.paths:
        data.nodes = np.unique(np.array(
            [r for p in data.paths for r, _ in p], np.int32))
    return data
