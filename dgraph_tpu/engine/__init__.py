"""Query engine: IR, executor, recurse/shortest/groupby/math, JSON output.

Reference parity: `query/` package. `Engine` is the query-side facade the
server layer (edgraph analog) calls.
"""

from __future__ import annotations

from dgraph_tpu.engine.execute import Executor, LevelNode
from dgraph_tpu.engine.ir import (
    FilterNode, FuncNode, Order, RecurseArgs, ShortestArgs, SubGraph,
)
from dgraph_tpu.engine.outputnode import to_json


def shape_of(blocks) -> str:
    """Compact structural fingerprint of a parsed query — the
    cost-profile shape key (utils/costprofile.py). Built from the
    BOUNDED vocabulary that predicts cost (root func name, modifiers,
    tree depth, recurse depth) — never from argument VALUES, so the
    shape space stays within the cardinality guard for any workload
    that reuses query templates."""
    parts = []
    for sg in blocks[:4]:
        p = sg.func.name if sg.func is not None else "uid"
        mods = ""
        if sg.recurse is not None:
            mods += f"~r{sg.recurse.depth or 0}"
        if sg.msgpass is not None:
            mods += "~m"
        if sg.shortest is not None:
            mods += "~sp"
        if sg.filters is not None:
            mods += "~f"
        if sg.var_name:
            mods += "~v"
        d, node = 0, sg
        # graftlint: allow(hot-loop-checkpoint): bounded by the parsed
        # tree's depth (parser-limited), no data-dependent iteration
        while node.children:
            d += 1
            node = node.children[0]
        parts.append(f"{p}{mods}~d{d}")
    if len(blocks) > 4:
        parts.append(f"+{len(blocks) - 4}")
    return "q:" + ",".join(parts)


class Engine:
    """Parse + execute + render DQL queries over a Store snapshot.

    Reference: the read path of edgraph.Server.Query →
    query.Request.ProcessQuery → outputnode (SURVEY §3.1).
    """

    def __init__(self, store, device_threshold: int = 512, mesh=None):
        self.store = store
        self.device_threshold = device_threshold
        self.mesh = mesh  # jax.sharding.Mesh | None → SPMD expansion

    def query(self, q: str, variables: dict | None = None) -> dict:
        out, _ex = self.query_with_vars(q, variables)
        return out

    def query_with_vars(self, q: str, variables: dict | None = None):
        """(json, executor): the executor carries the bound uid/val vars —
        the seam upsert blocks substitute from (reference: edgraph
        doQueryInUpsert returns the query's var map)."""
        res, ex = self._run(q, variables)
        if ex is None:
            return res, None
        return to_json(ex, res), ex

    def query_bytes(self, q: str, variables: dict | None = None) -> bytes:
        """Serialized response bytes — the serving path. Uses the native
        emitter (engine/emit.py) where the block shape allows, skipping
        per-object Python assembly entirely (reference: outputnode.go
        ToJson writes bytes, never a generic map)."""
        from dgraph_tpu.engine.emit import to_json_bytes
        res, ex = self._run(q, variables)
        if ex is None:
            import json
            return json.dumps(res, separators=(",", ":")).encode()
        return to_json_bytes(ex, res)

    def _run(self, q: str, variables: dict | None = None):
        """Parse + execute: (LevelNode roots, executor), or for schema{}
        introspection (dict, None) — callers needing vars (upserts)
        reject schema queries explicitly."""
        from dgraph_tpu.dql.parser import parse, parse_schema_query
        from dgraph_tpu.engine.varorder import execution_order

        sq = parse_schema_query(q)
        if sq is not None:
            return self._schema_query(*sq), None

        from dgraph_tpu.utils import costprofile, tracing
        blocks = parse(q, variables)
        costprofile.add_shape(shape_of(blocks))
        costprofile.add("queries", 1)
        ex = Executor(self.store, device_threshold=self.device_threshold,
                      mesh=self.mesh)
        results: dict[int, LevelNode] = {}
        with tracing.span("engine.query", blocks=len(blocks)):
            for i in execution_order(blocks):
                results[i] = ex.run_block(blocks[i])
        roots = [results[i] for i in range(len(blocks))]  # textual order out
        return roots, ex

    def _schema_query(self, preds, fields) -> dict:
        """schema{} introspection (reference: the schema node list the
        reference returns: predicate/type/index/tokenizer/... plus type
        definitions)."""
        out = []
        schema = self.store.schema
        for name in sorted(schema.predicates):
            if preds is not None and name not in preds:
                continue
            ps = schema.predicates[name]
            d = {"predicate": name, "type": ps.kind.value}
            if ps.is_list:
                d["list"] = True
            if ps.index_tokenizers:
                d["index"] = True
                d["tokenizer"] = list(ps.index_tokenizers)
            for flag in ("reverse", "count", "lang", "upsert", "unique"):
                if getattr(ps, flag):
                    d[flag] = True
            if fields is not None:
                d = {k: v for k, v in d.items()
                     if k in fields or k == "predicate"}
            out.append(d)
        resp = {"schema": out}
        if preds is None:
            types = [{"name": t,
                      "fields": [{"name": f} for f in td.fields]}
                     for t, td in sorted(schema.types.items())]
            if types:
                resp["types"] = types
        return resp


__all__ = [
    "Engine", "Executor", "LevelNode", "SubGraph", "FuncNode", "FilterNode",
    "Order", "RecurseArgs", "ShortestArgs", "to_json",
]
