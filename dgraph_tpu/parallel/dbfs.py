"""Distributed batched bitmap traversal: slab-sharded masks over the mesh.

Reference parity: concurrent queries fanning over predicate groups
(`worker/task.go ProcessTaskOverNetwork` with per-query goroutines). Here B
concurrent traversals ride the lanes of a frontier bitmap `[n_nodes, B]`
(see ops/bfs.py), and the mesh dimension shards *rows* (rank-space slabs):

  - device d owns mask rows [d·R, (d+1)·R) AND the COO edges whose src
    lies in that slab (the tablet model: data and its compute co-located)
  - per hop, the active-lane gather `frontier[src]` is fully LOCAL (src
    ranks are slab-local); the scatter writes a full-width partial
    `[N, B]` which one `lax.psum_scatter` folds and re-slabs — the ONLY
    collective per hop, N·B bytes over ICI, independent of edge count.

Contrast with the reference: gRPC ships frontier uid lists per hop and
per group; here the frontier bitmap IS the wire format and the reduction
is the compiler-scheduled collective.

int8 lane sums bound the mesh at 127 devices per psum_scatter (masks are
0/1; the scatter-sum then clips) — far above any single-pod slice.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dgraph_tpu.utils.jaxcompat import shard_map
from dgraph_tpu.parallel.mesh import SHARD_AXIS


def shard_coo_by_src(indptr: np.ndarray, indices: np.ndarray,
                     n_shards: int):
    """Host-side: CSR → per-shard COO (src slab-LOCAL, dst global), padded
    to a common edge cap. Returns (src_s[D,E], dst_s[D,E], deg_s[D,R],
    rows_per_shard). Padded edge slots point at local row R (a zero row
    the kernel appends), so they gather inactive lanes and scatter into
    a dropped slot."""
    n = indptr.shape[0] - 1
    rows = -(-n // n_shards) if n else 1
    deg_all = (indptr[1:] - indptr[:-1]).astype(np.int32)
    srcs, dsts, degs = [], [], []
    e_cap = 1
    for d in range(n_shards):
        lo = min(d * rows, n)
        hi = min(lo + rows, n)
        base, end = int(indptr[lo]), int(indptr[hi])
        deg = np.zeros(rows, np.int32)
        deg[:hi - lo] = deg_all[lo:hi]
        src_l = np.repeat(np.arange(hi - lo, dtype=np.int32),
                          deg_all[lo:hi])
        dst = indices[base:end].astype(np.int32)
        e_cap = max(e_cap, len(dst))
        srcs.append(src_l)
        dsts.append(dst)
        degs.append(deg)
    src_s = np.full((n_shards, e_cap), rows, np.int32)  # pad → zero row
    dst_s = np.full((n_shards, e_cap), 0, np.int32)
    pad_dst = np.iinfo(np.int32).max  # dropped by scatter mode="drop"
    dst_s[:] = 0
    for d in range(n_shards):
        src_s[d, :len(srcs[d])] = srcs[d]
        dst_s[d, :len(dsts[d])] = dsts[d]
        dst_s[d, len(dsts[d]):] = pad_dst
    return src_s, dst_s, np.stack(degs), rows


def shard_mask(mask: np.ndarray, n_shards: int, rows: int) -> np.ndarray:
    """[N, B] host bitmap → [D, R, B] slab stack (zero-padded rows)."""
    n, b = mask.shape
    out = np.zeros((n_shards, rows, b), np.int8)
    for d in range(n_shards):
        lo = min(d * rows, n)
        hi = min(lo + rows, n)
        out[d, :hi - lo] = mask[lo:hi]
    return out


def unshard_mask(slabs: np.ndarray, n_nodes: int) -> np.ndarray:
    """[D, R, B] → [N, B]."""
    d, r, b = slabs.shape
    from dgraph_tpu.parallel.mesh import host_np
    return host_np(slabs).reshape(d * r, b)[:n_nodes]


@functools.lru_cache(maxsize=32)
def _build(mesh: Mesh, depth: int):
    n_dev = mesh.devices.size

    def per_device(src_b, dst_b, deg_b, mask_b):
        src, dst, deg, mask0 = src_b[0], dst_b[0], deg_b[0], mask_b[0]
        rows, B = mask0.shape
        degf = deg.astype(jnp.float32)
        n_pad = rows * n_dev

        def hop(carry, _):
            frontier, seen, edges = carry           # [R, B] slabs
            hop_edges = degf @ frontier.astype(jnp.float32)
            edges = edges + lax.psum(hop_edges.astype(jnp.int32),
                                     SHARD_AXIS)
            # local gather: src indexes this slab (+1 appended zero row
            # for padded edge slots)
            padded = jnp.concatenate(
                [frontier, jnp.zeros((1, B), jnp.int8)])
            act = jnp.take(padded, src, axis=0)
            partial = jnp.zeros((n_pad, B), jnp.int8).at[dst].max(
                act, mode="drop")
            # fold partials across devices and land this device's slab
            summed = lax.psum_scatter(partial, SHARD_AXIS,
                                      scatter_dimension=0, tiled=True)
            nxt = (summed > 0).astype(jnp.int8)
            fresh = jnp.where(seen > 0, jnp.int8(0), nxt)
            seen = jnp.maximum(seen, fresh)
            return (fresh, seen, edges), None

        B_ = mask0.shape[1]
        (last, seen, edges), _ = lax.scan(
            hop, (mask0, mask0, jnp.zeros((B_,), jnp.int32)),
            None, length=depth)
        return last[None], seen[None], edges

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                  P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def bitmap_recurse_sharded(mesh: Mesh, src_s, dst_s, deg_s, mask_slabs,
                           depth: int):
    """Depth-bounded loop=false recurse for B queries, slab-sharded.

    Inputs from `shard_coo_by_src` / `shard_mask` (placed on the mesh or
    host — jit shards on entry). Returns `(last[D,R,B], seen[D,R,B],
    edges[B])` with edges replicated; un-slab with `unshard_mask`.
    """
    return _build(mesh, depth)(src_s, dst_s, deg_s, mask_slabs)
