"""Distributed hop kernels: one query level as one SPMD program on the mesh.

Reference parity: `worker/task.go ProcessTaskOverNetwork` — scatter the
frontier to the groups owning each tablet over gRPC, each Alpha walks its
posting lists, gather `pb.Result`s and k-way merge (`algo.MergeSorted`).
Here the scatter/gather is XLA collectives over ICI inside a single jitted
`shard_map` program:

  scatter-gather hop  — frontier replicated; each device expands the rows
      it owns; `all_gather` + fused sort-unique produce the merged next
      frontier on every device. One collective per hop.

  ring hop            — frontier *sharded* (too big to replicate, the
      long-context case of SURVEY §5); chunks rotate around the mesh via
      `ppermute` while every device expands the resident chunk against its
      local rows. D steps, each overlapping compute with a neighbour
      exchange — the structural cousin of ring attention.

Edge totals are `psum`-reduced — the north-star edges-traversed/sec counter
falls out of the kernel itself.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dgraph_tpu.ops.hop import gather_edges
from dgraph_tpu.ops.uidalgebra import (
    _member, difference_sorted, sentinel, sort_unique_count, valid_mask)
from dgraph_tpu.utils.jaxcompat import shard_map
from dgraph_tpu.parallel.mesh import SHARD_AXIS, hop_input
from dgraph_tpu.parallel.pshard import ShardedRel


def _local_expand_full(indptr, indices, row_lo, frontier, edge_cap):
    """Expand the slice of a (global-rank) frontier this shard owns.
    Returns the full gather_edges tuple; `seg` indexes the GLOBAL
    frontier (rows not owned by this shard simply contribute no edges)."""
    n_rows = indptr.shape[0] - 1
    mine = (valid_mask(frontier) & (frontier >= row_lo)
            & (frontier < row_lo + n_rows))
    local_f = jnp.where(mine, frontier - row_lo, sentinel(frontier.dtype))
    return gather_edges(indptr, indices, local_f, edge_cap)


def _local_expand(indptr, indices, row_lo, frontier, edge_cap):
    nbrs, _seg, _pos, _valid, total = _local_expand_full(
        indptr, indices, row_lo, frontier, edge_cap)
    return nbrs, total


@functools.lru_cache(maxsize=64)
def _build_sg_hop(mesh: Mesh, edge_cap: int, out_cap: int):
    def per_device(indptr_b, indices_b, row_lo_b, frontier):
        nbrs, total = _local_expand(
            indptr_b[0], indices_b[0], row_lo_b[0], frontier, edge_cap)
        local, local_cnt = sort_unique_count(nbrs, out_cap)
        total_all = lax.psum(total, SHARD_AXIS)
        # Overflow witnesses survive the reductions: if any shard needed
        # more than edge_cap slots or out_cap uniques, the max carries it.
        max_shard_edges = lax.pmax(total, SHARD_AXIS)
        gathered = lax.all_gather(local, SHARD_AXIS)  # [D, out_cap]
        merged, count = sort_unique_count(gathered.reshape(-1), out_cap)
        count = jnp.maximum(count, lax.pmax(local_cnt, SHARD_AXIS))
        return merged, count, total_all, max_shard_edges

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def scatter_gather_hop(mesh: Mesh, rel: ShardedRel, frontier: jax.Array,
                       edge_cap: int, out_cap: int):
    """One hop with a replicated frontier.

    Returns `(next_frontier[out_cap], n_unique, edges_traversed,
    max_shard_edges)` — all replicated. Overflow contract (same as
    ops.hop): results are valid only if `n_unique <= out_cap` AND
    `max_shard_edges <= edge_cap`; otherwise re-run at the next bucket
    size. `n_unique` is inflated to the largest per-shard union size so
    per-shard truncation cannot hide below a merged count of exactly
    out_cap.
    """
    return _build_sg_hop(mesh, edge_cap, out_cap)(
        rel.indptr_s, rel.indices_s, rel.row_lo,
        hop_input(frontier, mesh))


@functools.lru_cache(maxsize=64)
def _build_matrix_hop(mesh: Mesh, edge_cap: int):
    def per_device(indptr_b, indices_b, row_lo_b, frontier):
        nbrs, seg, edge_pos, valid, total = _local_expand_full(
            indptr_b[0], indices_b[0], row_lo_b[0], frontier, edge_cap)
        max_shard = lax.pmax(total, SHARD_AXIS)
        return (nbrs[None], seg[None], edge_pos[None], total[None],
                max_shard)

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def matrix_hop(mesh: Mesh, rel: ShardedRel, frontier: jax.Array,
               edge_cap: int):
    """One hop that RETURNS the edge matrix, not just the merged frontier —
    the seam the query engine needs (reference: pb.Result.UidMatrix from
    ProcessTaskOverNetwork). Frontier is replicated; each device expands
    the rows it owns; outputs stay sharded:

      (nbrs[D, edge_cap], seg[D, edge_cap], edge_pos[D, edge_cap],
       totals[D], max_shard_edges)

    Per shard d, the first totals[d] slots are that shard's edges in CSR
    row order; `seg` indexes the GLOBAL frontier (each row is owned by
    exactly one shard, so a host stable-sort by seg rebuilds global row
    order); `edge_pos` is local — add rel.pos_lo[d] for the absolute
    position facet columns key on. Valid only if max_shard_edges ≤
    edge_cap; otherwise re-run at a bigger bucket."""
    return _build_matrix_hop(mesh, edge_cap)(
        rel.indptr_s, rel.indices_s, rel.row_lo,
        hop_input(frontier, mesh))


@functools.lru_cache(maxsize=64)
def _build_matrix_level(mesh: Mesh, edge_cap: int, use_allowed: bool):
    from dgraph_tpu.ops.level import filter_paginate

    def per_device(indptr_b, indices_b, row_lo_b, frontier, allowed,
                   offset, first):
        nbrs, seg, edge_pos, valid, total = _local_expand_full(
            indptr_b[0], indices_b[0], row_lo_b[0], frontier, edge_cap)
        # rows partition over shards, so per-row filter+pagination is
        # shard-local; `allowed` is replicated (it is an index lookup set,
        # small next to the edge set)
        c_nbrs, c_seg, c_pos, n_kept, _ = filter_paginate(
            nbrs, seg, edge_pos, valid, allowed, offset, first,
            frontier.shape[0], use_allowed)
        max_shard = lax.pmax(total, SHARD_AXIS)
        return (c_nbrs[None], c_seg[None], c_pos[None], n_kept[None],
                total[None], max_shard)

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(), P(),
                  P(), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS), P(SHARD_AXIS), P()),
        check_vma=False,
    )
    return jax.jit(fn, static_argnames=())


def matrix_level(mesh: Mesh, rel: ShardedRel, frontier: jax.Array,
                 allowed: jax.Array, offset, first, edge_cap: int,
                 use_allowed: bool):
    """The fused level (expand → filter → paginate → compact) as ONE SPMD
    program — matrix_hop and ops.level.expand_level combined, so the served
    mesh engine gets the same fused fast path as the single-device one
    (reference: ProcessTaskOverNetwork with the filter/pagination pushed
    into each group's processTask rather than applied at the coordinator).

    Returns (nbrs[D, edge_cap], seg[D, edge_cap], pos[D, edge_cap],
    kept[D], totals[D], max_shard_edges): per shard d the first kept[d]
    slots are its surviving edges in CSR row order; seg indexes the GLOBAL
    frontier; pos is local (add rel.pos_lo[d]). Valid only if
    max_shard_edges ≤ edge_cap."""
    return _build_matrix_level(mesh, edge_cap, use_allowed)(
        rel.indptr_s, rel.indices_s, rel.row_lo, frontier, allowed,
        jnp.int32(offset), jnp.int32(first))


@functools.lru_cache(maxsize=64)
def _build_ring_hop(mesh: Mesh, edge_cap: int, out_cap: int):
    n_dev = mesh.devices.size
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def per_device(indptr_b, indices_b, row_lo_b, chunk_b):
        indptr, indices, row_lo = indptr_b[0], indices_b[0], row_lo_b[0]
        chunk = chunk_b[0]
        acc = jnp.full((out_cap,), sentinel(chunk.dtype), chunk.dtype)

        def step(i, carry):
            chunk, acc, total, need, max_step_edges = carry
            nbrs, t = _local_expand(indptr, indices, row_lo, chunk, edge_cap)
            # Fold this step's neighbours into the running local union,
            # remembering the largest size the union ever *needed*.
            acc, cnt = sort_unique_count(jnp.concatenate([acc, nbrs]), out_cap)
            chunk = lax.ppermute(chunk, SHARD_AXIS, perm)
            return (chunk, acc, total + t, jnp.maximum(need, cnt),
                    jnp.maximum(max_step_edges, t))

        _, acc, total, need, max_step_edges = lax.fori_loop(
            0, n_dev, step,
            (chunk, acc, jnp.int32(0), jnp.int32(0), jnp.int32(0)))
        total_all = lax.psum(total, SHARD_AXIS)
        max_edges = lax.pmax(max_step_edges, SHARD_AXIS)
        gathered = lax.all_gather(acc, SHARD_AXIS)
        merged, count = sort_unique_count(gathered.reshape(-1), out_cap)
        count = jnp.maximum(count, lax.pmax(need, SHARD_AXIS))
        return acc[None], merged, count, total_all, max_edges

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(), P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def ring_hop(mesh: Mesh, rel: ShardedRel, frontier_chunks: jax.Array,
             edge_cap: int, out_cap: int):
    """One hop with a SHARDED frontier rotating ring-wise over the mesh.

    `frontier_chunks` is [D, f_cap] (see pshard.shard_frontier). Returns
    `(local_unions[D, out_cap], merged[out_cap], n_unique, edges,
    max_step_edges)` where `local_unions` stays sharded for pipelined
    multi-hop chains and `merged` is the replicated deduped next frontier.
    Results are valid only if `n_unique <= out_cap` AND
    `max_step_edges <= edge_cap` (n_unique is inflated to the largest size
    any device's running union ever needed, so mid-ring truncation is
    always visible).
    """
    return _build_ring_hop(mesh, edge_cap, out_cap)(
        rel.indptr_s, rel.indices_s, rel.row_lo,
        hop_input(frontier_chunks, mesh, P(SHARD_AXIS)))


@functools.lru_cache(maxsize=64)
def _build_ring_matrix(mesh: Mesh, edge_cap: int, f_cap: int):
    n_dev = mesh.devices.size
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def per_device(indptr_b, indices_b, row_lo_b, chunk_b):
        indptr, indices, row_lo = indptr_b[0], indices_b[0], row_lo_b[0]
        chunk = chunk_b[0]

        def step(i, carry):
            chunk, nbrs_a, seg_a, pos_a, tot_a, max_e = carry
            nbrs, seg, pos, valid, t = _local_expand_full(
                indptr, indices, row_lo, chunk, edge_cap)
            nbrs_a = lax.dynamic_update_index_in_dim(nbrs_a, nbrs, i, 0)
            seg_a = lax.dynamic_update_index_in_dim(seg_a, seg, i, 0)
            pos_a = lax.dynamic_update_index_in_dim(pos_a, pos, i, 0)
            tot_a = lax.dynamic_update_index_in_dim(tot_a, t, i, 0)
            chunk = lax.ppermute(chunk, SHARD_AXIS, perm)
            return (chunk, nbrs_a, seg_a, pos_a, tot_a,
                    jnp.maximum(max_e, t))

        z = jnp.zeros
        _, nbrs_a, seg_a, pos_a, tot_a, max_e = lax.fori_loop(
            0, n_dev, step,
            (chunk, z((n_dev, edge_cap), jnp.int32),
             z((n_dev, edge_cap), jnp.int32),
             z((n_dev, edge_cap), jnp.int32),
             z((n_dev,), jnp.int32), jnp.int32(0)))
        max_all = lax.pmax(max_e, SHARD_AXIS)
        return (nbrs_a[None], seg_a[None], pos_a[None], tot_a[None],
                max_all)

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                  P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def ring_matrix_hop(mesh: Mesh, rel: ShardedRel, frontier_chunks,
                    edge_cap: int):
    """One hop with a SHARDED frontier that RETURNS the edge matrix — the
    long-context analog wired for the query engine (SURVEY §5): the
    frontier is too big to replicate, so chunks rotate ring-wise over ICI
    (ppermute) while every device expands the resident chunk against its
    local rows.

    Returns (nbrs[D, D, edge_cap], seg[D, D, edge_cap],
    pos[D, D, edge_cap], totals[D, D], max_step_edges). For shard d at
    ring step i the expanded chunk ORIGINATED on shard (d - i) mod D;
    `seg` indexes within that chunk; valid only if max_step_edges ≤
    edge_cap."""
    f_cap = frontier_chunks.shape[1]
    return _build_ring_matrix(mesh, edge_cap, f_cap)(
        rel.indptr_s, rel.indices_s, rel.row_lo,
        jax.device_put(frontier_chunks))


@functools.lru_cache(maxsize=64)
def _build_recurse(mesh: Mesh, edge_cap: int, out_cap: int, seen_cap: int,
                   depth: int):
    """Whole multi-hop @recurse as ONE compiled program (frontier loop in
    lax.scan, not Python) — the reference's expandRecurse outer loop
    (query/recurse.go) with zero host round-trips between hops."""

    def per_device(indptr_b, indices_b, row_lo_b, frontier):
        indptr, indices, row_lo = indptr_b[0], indices_b[0], row_lo_b[0]

        def hop(carry, _):
            frontier, seen, edges, need_out, need_seen, need_edge = carry
            nbrs, t = _local_expand(indptr, indices, row_lo, frontier, edge_cap)
            local, local_cnt = sort_unique_count(nbrs, out_cap)
            gathered = lax.all_gather(local, SHARD_AXIS)
            merged, mcnt = sort_unique_count(gathered.reshape(-1), out_cap)
            # loop=false semantics: drop uids already visited (reference
            # keeps a `seen` map; here a sorted-set difference).
            fresh = difference_sorted(merged, seen)
            seen, scnt = sort_unique_count(
                jnp.concatenate([seen, fresh]), seen_cap)
            need_out = jnp.maximum(
                need_out, jnp.maximum(mcnt, lax.pmax(local_cnt, SHARD_AXIS)))
            need_seen = jnp.maximum(need_seen, scnt)
            need_edge = jnp.maximum(need_edge, lax.pmax(t, SHARD_AXIS))
            return (fresh, seen, edges + lax.psum(t, SHARD_AXIS),
                    need_out, need_seen, need_edge), None

        seen0, scnt0 = sort_unique_count(frontier, seen_cap)
        (last, seen, edges, need_out, need_seen, need_edge), _ = lax.scan(
            hop, (frontier, seen0, jnp.int32(0), jnp.int32(0), scnt0,
                  jnp.int32(0)),
            None, length=depth)
        # needs[i] > the corresponding cap ⇒ truncation happened somewhere.
        needs = jnp.stack([need_out, need_seen, need_edge])
        return last, seen, edges, needs

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _build_recurse_matrix(mesh: Mesh, edge_cap: int, out_cap: int,
                          seen_cap: int, depth: int):
    """recurse_fused plus per-hop edge-matrix capture: the variant the DQL
    engine drives, because JSON rendering needs every (parent, child) edge,
    not just the frontier (reference: expandRecurse keeps each level's
    UidMatrix for outputnode)."""

    def per_device(indptr_b, indices_b, row_lo_b, frontier):
        indptr, indices, row_lo = indptr_b[0], indices_b[0], row_lo_b[0]
        n_rows = indptr.shape[0] - 1
        snt = sentinel(frontier.dtype)

        def hop(carry, _):
            frontier, seen, edges, need_out, need_seen, need_edge = carry
            mine = (valid_mask(frontier) & (frontier >= row_lo)
                    & (frontier < row_lo + n_rows))
            local_f = jnp.where(mine, frontier - row_lo, snt)
            nbrs, seg, edge_pos, valid, t = gather_edges(
                indptr, indices, local_f, edge_cap)
            # visit-once: drop edges to nodes seen BEFORE this hop (edges
            # between two nodes first reached in the same hop are kept —
            # matching the host loop's first-visit-tree semantics)
            keep = valid & ~_member(nbrs, seen)
            m_nbrs = jnp.where(keep, nbrs, snt)
            m_seg = jnp.where(keep, seg, jnp.int32(-1))
            local, local_cnt = sort_unique_count(m_nbrs, out_cap)
            gathered = lax.all_gather(local, SHARD_AXIS)
            fresh, mcnt = sort_unique_count(gathered.reshape(-1), out_cap)
            seen2, scnt = sort_unique_count(
                jnp.concatenate([seen, fresh]), seen_cap)
            need_out = jnp.maximum(
                need_out, jnp.maximum(mcnt, lax.pmax(local_cnt, SHARD_AXIS)))
            need_seen = jnp.maximum(need_seen, scnt)
            need_edge = jnp.maximum(need_edge, lax.pmax(t, SHARD_AXIS))
            carry = (fresh, seen2, edges + lax.psum(t, SHARD_AXIS),
                     need_out, need_seen, need_edge)
            return carry, (m_nbrs, m_seg, edge_pos, frontier)

        seen0, scnt0 = sort_unique_count(frontier, seen_cap)
        (last, seen, edges, need_out, need_seen, need_edge), ys = lax.scan(
            hop, (frontier, seen0, jnp.int32(0), jnp.int32(0), scnt0,
                  jnp.int32(0)),
            None, length=depth)
        needs = jnp.stack([need_out, need_seen, need_edge])
        ys_nbrs, ys_seg, ys_pos, ys_frontier = ys
        return (last, seen, edges, needs,
                ys_nbrs[None], ys_seg[None], ys_pos[None], ys_frontier)

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(), P(), P(), P(),
                   P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def recurse_fused_matrix(mesh: Mesh, rel: ShardedRel, frontier: jax.Array,
                         edge_cap: int, out_cap: int, seen_cap: int,
                         depth: int):
    """Depth-bounded @recurse over one predicate as ONE compiled SPMD
    program, returning the per-hop edge matrices the engine renders from:

      (last_frontier[out_cap], seen[seen_cap], edges, needs[3],
       nbrs[D, depth, edge_cap], seg[D, depth, edge_cap],
       pos[D, depth, edge_cap], frontiers[depth, out_cap])

    For hop h on shard d: slots with nbrs != sentinel are surviving edges
    (visit-once filtered); seg indexes frontiers[h] (the hop's replicated
    input frontier); pos + rel.pos_lo[d] is the absolute facet position.
    Same overflow contract as recurse_fused: valid only if
    needs <= [out_cap, seen_cap, edge_cap]."""
    if frontier.shape[0] != out_cap:
        raise ValueError(
            f"frontier buffer {frontier.shape[0]} != out_cap {out_cap}")
    return _build_recurse_matrix(mesh, edge_cap, out_cap, seen_cap, depth)(
        rel.indptr_s, rel.indices_s, rel.row_lo, frontier)


def recurse_fused(mesh: Mesh, rel: ShardedRel, frontier: jax.Array,
                  edge_cap: int, out_cap: int, seen_cap: int, depth: int):
    """Depth-bounded @recurse over one predicate, fully fused on-mesh.

    `frontier` must be sorted, sentinel-padded to exactly `out_cap` (the
    per-hop frontier buffer); `seen_cap` bounds the whole reachable set.
    Returns `(last_frontier, seen[seen_cap], edges_traversed, needs[3])`
    where `needs = [max frontier slots, max seen slots, max per-shard
    edge slots]` any hop required — results are valid only if
    `needs <= [out_cap, seen_cap, edge_cap]` elementwise; otherwise
    re-run with the caps `needs` asks for.
    """
    if frontier.shape[0] != out_cap:
        raise ValueError(f"frontier buffer {frontier.shape[0]} != out_cap {out_cap}")
    return _build_recurse(mesh, edge_cap, out_cap, seen_cap, depth)(
        rel.indptr_s, rel.indices_s, rel.row_lo, frontier)


@functools.lru_cache(maxsize=64)
def _build_chain_hop(mesh: Mesh, edge_cap: int, out_cap: int,
                     seen_cap: int):
    """ONE visit-once hop with edge-matrix capture, compiled so its
    replicated (frontier, seen) outputs are EXACTLY the next launch's
    replicated inputs — the reshard-free multi-hop building block. One
    compiled program serves every depth (the lax.scan variants above
    retrace per depth), and between launches the frontier/seen arrays
    stay device-resident: the host reads their VALUES for rendering but
    feeds the same jax.Arrays back in, so no bytes re-cross the mesh
    (mesh.hop_input counts any violation)."""

    def per_device(indptr_b, indices_b, row_lo_b, frontier, seen):
        indptr, indices, row_lo = indptr_b[0], indices_b[0], row_lo_b[0]
        n_rows = indptr.shape[0] - 1
        snt = sentinel(frontier.dtype)
        mine = (valid_mask(frontier) & (frontier >= row_lo)
                & (frontier < row_lo + n_rows))
        local_f = jnp.where(mine, frontier - row_lo, snt)
        nbrs, seg, _pos, valid, t = gather_edges(
            indptr, indices, local_f, edge_cap)
        # visit-once: drop edges to nodes seen BEFORE this hop (edges
        # between two same-hop discoveries are kept — the host loop's
        # first-visit-tree semantics, identical to recurse_fused_matrix)
        keep = valid & ~_member(nbrs, seen)
        m_nbrs = jnp.where(keep, nbrs, snt)
        m_seg = jnp.where(keep, seg, jnp.int32(-1))
        local, local_cnt = sort_unique_count(m_nbrs, out_cap)
        gathered = lax.all_gather(local, SHARD_AXIS)
        fresh, mcnt = sort_unique_count(gathered.reshape(-1), out_cap)
        seen2, scnt = sort_unique_count(
            jnp.concatenate([seen, fresh]), seen_cap)
        needs = jnp.stack([
            jnp.maximum(mcnt, lax.pmax(local_cnt, SHARD_AXIS)),
            scnt, lax.pmax(t, SHARD_AXIS)])
        totals = lax.psum(
            jnp.where(keep, 1, 0).sum().astype(jnp.int32), SHARD_AXIS)
        return (fresh, seen2, lax.psum(t, SHARD_AXIS), needs,
                m_nbrs[None], m_seg[None], t[None], totals)

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(), P()),
        out_specs=(P(), P(), P(), P(),
                   P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def chain_hop(mesh: Mesh, rel: ShardedRel, frontier, seen,
              edge_cap: int, out_cap: int, seen_cap: int):
    """One launch of the chained visit-once hop (see _build_chain_hop).

    `frontier`/`seen` are sorted sentinel-padded buffers of exactly
    `out_cap`/`seen_cap` slots — host numpy on the first hop (the seed
    upload), then the previous launch's DEVICE outputs unmoved. Returns
    `(fresh[out_cap], seen2[seen_cap], edges, needs[3],
    nbrs[D, edge_cap], seg[D, edge_cap], shard_edges[D], kept)`:
    `fresh`/`seen2` are the next launch's inputs; `seg` indexes this
    hop's input frontier; per shard d the slots with nbrs != sentinel
    are its surviving (visit-once filtered) edges in CSR row order;
    `shard_edges[d]` is the raw edges shard d expanded (the balance /
    per-shard cost signal). Overflow contract of recurse_fused: results
    valid only if needs <= [out_cap, seen_cap, edge_cap]."""
    return _build_chain_hop(mesh, edge_cap, out_cap, seen_cap)(
        rel.indptr_s, rel.indices_s, rel.row_lo,
        hop_input(frontier, mesh), hop_input(seen, mesh))
