"""Device mesh construction for distributed query execution.

Reference parity: `conn/` + `worker/groups.go` establish the cluster
topology (which Alpha serves which tablet, gRPC pools between them). On
TPU the topology is a `jax.sharding.Mesh`: one named axis, ``"shard"``,
over which posting-store rows are partitioned and across which the hop
kernel's collectives (all_gather / psum / ppermute) run on ICI.

Multi-host scaling rides the same mesh: `jax.distributed.initialize()`
extends `jax.devices()` across hosts over DCN and everything below is
unchanged — the moral equivalent of adding Alphas to a Raft group without
touching query code.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the first `n_devices` devices (default: all)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"requested {n_devices} devices, have {len(devices)}")
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (SHARD_AXIS,))


def shard_leading(mesh: Mesh) -> NamedSharding:
    """Sharding that splits an array's leading axis over the mesh."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
