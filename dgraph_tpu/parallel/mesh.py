"""Device mesh construction for distributed query execution.

Reference parity: `conn/` + `worker/groups.go` establish the cluster
topology (which Alpha serves which tablet, gRPC pools between them). On
TPU the topology is a `jax.sharding.Mesh`: one named axis, ``"shard"``,
over which posting-store rows are partitioned and across which the hop
kernel's collectives (all_gather / psum / ppermute) run on ICI.

Multi-host scaling rides the same mesh: `jax.distributed.initialize()`
extends `jax.devices()` across hosts over DCN and everything below is
unchanged — the moral equivalent of adding Alphas to a Raft group without
touching query code.
"""

from __future__ import annotations

import contextlib

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dgraph_tpu.utils.metrics import METRICS

SHARD_AXIS = "shard"


def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> bool:
    """Join a multi-host JAX runtime over DCN (reference: the conn/
    cluster bootstrap — but for devices, not Alphas): after this,
    jax.devices() spans every host and make_mesh() lays the shard axis
    across ICI within hosts and DCN between them. Driven by explicit
    args, the JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID env trio, or — with JAX_DIST_AUTO=1 on a TPU pod
    slice — jax's built-in cluster discovery (no-arg initialize).
    Returns True when a multi-process runtime was joined."""
    import os

    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator is None:
        if os.environ.get("JAX_DIST_AUTO") == "1":
            jax.distributed.initialize()
            return jax.process_count() > 1
        return False
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "0")) or None
    process_id = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "-1"))
    if process_id < 0:
        process_id = None
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return jax.process_count() > 1


def host_np(x) -> np.ndarray:
    """Kernel output → host numpy, multi-process safe (reference: the
    coordinator gathering pb.Result legs). Single-process arrays fetch
    directly; under a multi-host runtime an array spanning non-local
    devices allgathers over DCN first (fully-replicated outputs read the
    local copy without any transfer)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        if x.is_fully_replicated:
            return np.asarray(x.addressable_data(0))
        from jax.experimental import multihost_utils
        x = multihost_utils.process_allgather(x, tiled=True)
    return np.asarray(x)


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the first `n_devices` devices (default: all)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"requested {n_devices} devices, have {len(devices)}")
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (SHARD_AXIS,))


def shard_leading(mesh: Mesh) -> NamedSharding:
    """Sharding that splits an array's leading axis over the mesh."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# -- reshard accounting -------------------------------------------------------
# The steady serving contract (the pjit pitfall SNIPPETS calls out): a
# hop's out_specs ARE the next hop's in_specs, so a chained frontier
# re-enters the next launch with its sharding already right and XLA
# inserts no cross-device copy. `hop_input` is the guard at every hop
# entry point: a committed device array arriving with a DIFFERENT
# sharding than the launch expects counts `mesh_hop_resharded_total`
# (host numpy seeds are first-hop uploads, expected and not counted).

def hop_input(x, mesh: Mesh, spec=P()):
    """Count an unexpected reshard on a hop input; returns `x` unchanged.

    Steady-path inputs are either host arrays (the chain's seed — a
    transfer, not a reshard) or device arrays whose sharding already
    equals `NamedSharding(mesh, spec)` (the previous hop's out_specs).
    Anything else would make XLA re-lay the array across devices before
    the launch — the silent copy this counter exists to catch."""
    if isinstance(x, jax.Array):
        sh = getattr(x, "sharding", None)
        if sh is not None and not _sharding_matches(sh, mesh, spec,
                                                    x.ndim):
            METRICS.inc("mesh_hop_resharded_total")
    return x


def _sharding_matches(sh, mesh: Mesh, spec, ndim: int) -> bool:
    want = NamedSharding(mesh, spec)
    try:
        return sh.is_equivalent_to(want, ndim)
    except (AttributeError, TypeError):
        return sh == want


def reshard_count() -> int:
    return int(METRICS.get("mesh_hop_resharded_total"))


@contextlib.contextmanager
def reshard_guard(strict: bool = True):
    """Assert the steady path stayed reshard-free: zero
    `mesh_hop_resharded_total` increments inside the block (armed
    around hop loops by the engine and by the bit-identity tests)."""
    before = reshard_count()
    yield
    after = reshard_count()
    if strict and after != before:
        raise AssertionError(
            f"{after - before} unexpected cross-device reshard(s) on a "
            f"steady hop path — an out_specs/in_specs mismatch between "
            f"chained hops (see parallel/mesh.py hop_input)")
