"""Device mesh construction for distributed query execution.

Reference parity: `conn/` + `worker/groups.go` establish the cluster
topology (which Alpha serves which tablet, gRPC pools between them). On
TPU the topology is a `jax.sharding.Mesh`: one named axis, ``"shard"``,
over which posting-store rows are partitioned and across which the hop
kernel's collectives (all_gather / psum / ppermute) run on ICI.

Multi-host scaling rides the same mesh: `jax.distributed.initialize()`
extends `jax.devices()` across hosts over DCN and everything below is
unchanged — the moral equivalent of adding Alphas to a Raft group without
touching query code.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"


def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> bool:
    """Join a multi-host JAX runtime over DCN (reference: the conn/
    cluster bootstrap — but for devices, not Alphas): after this,
    jax.devices() spans every host and make_mesh() lays the shard axis
    across ICI within hosts and DCN between them. Driven by explicit
    args, the JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID env trio, or — with JAX_DIST_AUTO=1 on a TPU pod
    slice — jax's built-in cluster discovery (no-arg initialize).
    Returns True when a multi-process runtime was joined."""
    import os

    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator is None:
        if os.environ.get("JAX_DIST_AUTO") == "1":
            jax.distributed.initialize()
            return jax.process_count() > 1
        return False
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "0")) or None
    process_id = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "-1"))
    if process_id < 0:
        process_id = None
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return jax.process_count() > 1


def host_np(x) -> np.ndarray:
    """Kernel output → host numpy, multi-process safe (reference: the
    coordinator gathering pb.Result legs). Single-process arrays fetch
    directly; under a multi-host runtime an array spanning non-local
    devices allgathers over DCN first (fully-replicated outputs read the
    local copy without any transfer)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        if x.is_fully_replicated:
            return np.asarray(x.addressable_data(0))
        from jax.experimental import multihost_utils
        x = multihost_utils.process_allgather(x, tiled=True)
    return np.asarray(x)


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the first `n_devices` devices (default: all)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"requested {n_devices} devices, have {len(devices)}")
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (SHARD_AXIS,))


def shard_leading(mesh: Mesh) -> NamedSharding:
    """Sharding that splits an array's leading axis over the mesh."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
