"""Distributed order-by: per-shard top-k + on-mesh k-way merge.

Reference parity: `worker/sort.go SortOverNetwork` — order-by is pushed to
the group holding the index, each group returns its ordered slice, and
the coordinator k-way merges (`algo.MergeSorted`). On the mesh the same
shape is one SPMD program: every device ranks the candidates living in
its row slab against a dense sort-key column, takes its local top-k, and
an all_gather + second sort produces the merged global top-k on every
device — no host merge loop at all.

Keys are float64 with +inf for missing values (missing sorts last, as the
reference does) and are negated host-side for descending order; ties
break by rank ascending (the uid tiebreak of the host path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dgraph_tpu.ops.uidalgebra import valid_mask
from dgraph_tpu.utils.jaxcompat import shard_map
from dgraph_tpu.parallel.mesh import SHARD_AXIS, shard_leading


@functools.lru_cache(maxsize=64)
def _build_topk(mesh: Mesh, cap: int, k: int, rows: int):
    def per_device(keys_b, row_lo_b, cand):
        from dgraph_tpu.ops.uidalgebra import sentinel
        keys, row_lo = keys_b[0], row_lo_b[0]
        local = cand - row_lo
        mine = valid_mask(cand) & (local >= 0) & (local < rows)
        ck = jnp.where(mine, keys[jnp.clip(local, 0, rows - 1)], jnp.inf)
        # candidates another shard owns must drop out entirely (each rank
        # is "mine" on exactly one shard) — sentinel-cand rows sort after
        # every real row, including real missing-value (+inf-key) rows
        cand_m = jnp.where(mine, cand, sentinel(cand.dtype))
        order = jnp.lexsort((cand_m, ck))    # (key, rank-tiebreak)
        top_r = cand_m[order[:k]]
        top_v = ck[order[:k]]
        gr = lax.all_gather(top_r, SHARD_AXIS).reshape(-1)
        gv = lax.all_gather(top_v, SHARD_AXIS).reshape(-1)
        o2 = jnp.lexsort((gr, gv))           # k-way merge, one sort
        return gr[o2[:k]], gv[o2[:k]]

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def _key_column(store, pred: str, lang: str, mesh: Mesh):
    """Dense float64 sort-key column sharded over the mesh, cached on the
    store. None when the predicate's values are not numerically
    orderable (strings fall back to the host sort)."""
    cache = getattr(store, "_key_cols", None)
    if cache is None or getattr(store, "_key_cols_mesh", None) is not mesh:
        cache = {}
        store._key_cols = cache
        store._key_cols_mesh = mesh
    ck = (pred, lang)
    if ck in cache:
        return cache[ck]
    col = store.value_col(pred, lang)
    result = None
    if col is not None and len(col.subj):
        vals = col.vals
        if vals.dtype == object:
            first = next((v for v in vals if v is not None), None)
            if isinstance(first, (bool, np.bool_, int, np.integer, float,
                                  np.floating, np.datetime64)):
                vals = np.array([_to_key(v) for v in vals], np.float64)
            elif isinstance(first, str):
                vals = _string_codes(np.array([str(v) for v in vals]))
            else:
                vals = None
        elif vals.dtype.kind == "U":
            vals = _string_codes(vals)
        elif np.issubdtype(vals.dtype, np.datetime64):
            vals = vals.astype("datetime64[us]").astype(np.int64
                                                        ).astype(np.float64)
        elif np.issubdtype(vals.dtype, np.number) or vals.dtype == bool:
            vals = vals.astype(np.float64)
        else:
            vals = None
        if vals is not None:
            n = store.n_nodes
            d = mesh.devices.size
            rows = -(-max(n, 1) // d)
            dense = np.full(d * rows, np.inf)     # missing → last
            # first value per subject wins (col.subj sorted; keep first)
            subj, idx = np.unique(col.subj, return_index=True)
            dense[subj] = vals[idx]
            keys_s = jax.device_put(dense.reshape(d, rows),
                                    shard_leading(mesh))
            row_lo = jax.device_put(
                (np.arange(d, dtype=np.int32) * rows), shard_leading(mesh))
            result = (keys_s, row_lo, rows)
    cache[ck] = result
    return result


def _to_key(v) -> float:
    if isinstance(v, np.datetime64):
        return float(v.astype("datetime64[us]").astype("int64"))
    return float(v)


def _string_codes(svals: np.ndarray) -> np.ndarray | None:
    """Rank-dictionary encoding: dense codes of the sorted unique strings
    order exactly like the strings, so string order-by runs on the
    device-friendly float column (reference: worker/sort.go ships value
    bytes; here the dictionary stays host-side, codes go to the device).
    The device column is float32, whose mantissa holds 2^24 distinct
    integers — larger dictionaries fall back to the host sort."""
    uniq, codes = np.unique(svals, return_inverse=True)
    if len(uniq) >= 1 << 24:
        return None
    return codes.astype(np.float64)


def mesh_topk(mesh: Mesh, store, pred: str, lang: str, ranks: np.ndarray,
              k: int, desc: bool = False) -> np.ndarray | None:
    """Global top-k of `ranks` ordered by a value predicate, on-mesh.
    Returns the ordered rank array (missing-valued ranks last), or None
    when the key column is not device-orderable."""
    col = _key_column(store, pred, lang, mesh)
    if col is None:
        return None
    keys_s, row_lo, rows = col
    if desc:
        # negate finite keys only: missing (+inf) must still sort last
        keys_s = jnp.where(jnp.isinf(keys_s), keys_s, -keys_s)
    cap = 64
    while cap < len(ranks):
        cap <<= 1
    from dgraph_tpu import ops
    cand = ops.pad_to(np.asarray(ranks, np.int32), cap)
    # full-length sorts (no `first`) take kk=cap so the jitted program is
    # shared across cardinalities within a bucket, not compiled per count
    kk = cap if k >= len(ranks) else min(k, cap)
    top_r, top_v = _build_topk(mesh, cap, kk, rows)(keys_s, row_lo, cand)
    from dgraph_tpu.parallel.mesh import host_np
    top_r = host_np(top_r)
    out = top_r[np.asarray(valid_mask_np(top_r))]
    return out[:min(k, len(ranks))]


def valid_mask_np(a: np.ndarray) -> np.ndarray:
    from dgraph_tpu.ops.uidalgebra import SENTINEL32
    return a != SENTINEL32


@functools.lru_cache(maxsize=64)
def _build_row_sort(mesh: Mesh, cap: int, rows: int, desc: bool):
    def per_device(keys_b, row_lo_b, nbrs, seg):
        keys, row_lo = keys_b[0], row_lo_b[0]
        local = nbrs - row_lo
        mine = valid_mask(nbrs) & (local >= 0) & (local < rows)
        kv = jnp.where(mine, keys[jnp.clip(local, 0, rows - 1)], 0.0)
        # every valid rank lives on exactly ONE shard: a psum assembles
        # the full per-edge key vector on all devices
        kv = lax.psum(kv, SHARD_AXIS)
        if desc:
            kv = jnp.where(jnp.isinf(kv), kv, -kv)
        # padded slots sort last within their (nonexistent) row
        kv = jnp.where(valid_mask(nbrs), kv, jnp.inf)
        seg_k = jnp.where(valid_mask(nbrs), seg, jnp.int32(2**31 - 1))
        # priority: row, key (missing=+inf last), uid tiebreak — the host
        # lexsort contract of Executor.order_ranks
        return jnp.lexsort((nbrs, kv, seg_k))

    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)


def mesh_row_sort(mesh: Mesh, store, pred: str, lang: str,
                  nbrs: np.ndarray, seg: np.ndarray,
                  desc: bool = False) -> np.ndarray | None:
    """Per-row (child-level) order-by on the mesh: one SPMD program sorts
    the whole edge list by (row, key, uid) against the sharded key column
    (reference: worker/sort.go pushed into each group, merged — here the
    merge is the lexsort itself). Returns the permutation, or None when
    the key column is not device-orderable."""
    col = _key_column(store, pred, lang, mesh)
    if col is None:
        return None
    keys_s, row_lo, rows = col
    from dgraph_tpu import ops
    cap = 64
    while cap < len(nbrs):
        cap <<= 1
    # pad_to sentinel-pads (order-preserving); the device code masks
    # padded seg slots via valid_mask(nbrs), so seg's pad value never
    # matters
    nb = ops.pad_to(np.asarray(nbrs, np.int32), cap)
    sg_ = ops.pad_to(np.asarray(seg, np.int32), cap)
    from dgraph_tpu.parallel.mesh import host_np
    order = host_np(_build_row_sort(mesh, cap, rows, desc)(
        keys_s, row_lo, nb, sg_))
    # padded slots carry a maxint row key, so they sort strictly last:
    # the first len(nbrs) slots are the real permutation
    return order[:len(nbrs)]
