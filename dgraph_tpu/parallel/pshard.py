"""Row-sharded CSR relations: the tablet model recast for a device mesh.

Reference parity: `worker/groups.go` (`BelongsTo`, `Tablet`) and
`zero/tablet.go` assign each *predicate* to one Raft group — a coarse
horizontal partition of the edge set. A TPU mesh wants a finer, balanced
partition: each predicate's CSR block is split by **contiguous subject-rank
ranges** across the mesh's `shard` axis, so every device owns an equal row
slab of every predicate and a hop engages all devices at once (SPMD), not
just the one holding a hot predicate.

Layout per predicate/direction (D = mesh size, R = ceil(N/D)):

    indptr_s [D, R+1] int32   local exclusive offsets (padded rows repeat)
    indices_s [D, E]  int32   object ranks in GLOBAL rank space, sentinel-padded
    row_lo   [D]      int32   first global row of each shard

Object ranks stay global, so neighbour gathers need no cross-shard rank
translation — the rendezvous problem the reference solves with uid fan-out
over gRPC disappears into the all_gather of the next frontier.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

from dgraph_tpu.ops.uidalgebra import SENTINEL32
from dgraph_tpu.parallel.mesh import shard_leading
from dgraph_tpu.store.store import EdgeRel


@dataclass
class ShardedRel:
    """One predicate direction, row-partitioned over the mesh."""

    indptr_s: jax.Array | np.ndarray  # [D, R+1]
    indices_s: jax.Array | np.ndarray  # [D, E]
    row_lo: jax.Array | np.ndarray  # [D]
    n_nodes: int
    # global edge-position base per shard (host-only): local edge_pos +
    # pos_lo[d] = absolute position in the unsharded `indices`, which is
    # what facet columns are keyed by
    pos_lo: np.ndarray | None = None

    @property
    def n_shards(self) -> int:
        return int(self.indptr_s.shape[0])

    @property
    def rows_per_shard(self) -> int:
        return int(self.indptr_s.shape[1]) - 1


def shard_rel(rel: EdgeRel, n_shards: int) -> ShardedRel:
    """Split a host CSR into `n_shards` contiguous row slabs (host-side)."""
    n = rel.indptr.shape[0] - 1
    rows = -(-n // n_shards) if n else 1
    parts_ptr, parts_idx, lows, pos_lows = [], [], [], []
    max_nnz = 0
    for d in range(n_shards):
        lo = min(d * rows, n)
        hi = min(lo + rows, n)
        ptr = rel.indptr[lo:hi + 1].astype(np.int64)
        base = ptr[0] if ptr.size else 0
        pos_lows.append(int(base))
        local = (ptr - base).astype(np.int32)
        # Pad ghost rows (beyond n) with repeated final offset → degree 0.
        if hi - lo < rows:
            local = np.concatenate(
                [local, np.full(rows - (hi - lo), local[-1] if local.size else 0,
                                np.int32)])
        idx = rel.indices[base:base + int(local[-1])]
        max_nnz = max(max_nnz, idx.shape[0])
        parts_ptr.append(local)
        parts_idx.append(idx)
        lows.append(lo)
    cap = max(max_nnz, 1)
    indices_s = np.full((n_shards, cap), SENTINEL32, np.int32)
    for d, idx in enumerate(parts_idx):
        indices_s[d, :idx.shape[0]] = idx
    return ShardedRel(
        indptr_s=np.stack(parts_ptr),
        indices_s=indices_s,
        row_lo=np.asarray(lows, np.int32),
        n_nodes=n,
        pos_lo=np.asarray(pos_lows, np.int64),
    )


def device_put_rel(srel: ShardedRel, mesh: Mesh) -> ShardedRel:
    """Place the shard-stacked arrays on the mesh, leading axis sharded."""
    sh = shard_leading(mesh)
    return ShardedRel(
        indptr_s=jax.device_put(srel.indptr_s, sh),
        indices_s=jax.device_put(srel.indices_s, sh),
        row_lo=jax.device_put(srel.row_lo, sh),
        n_nodes=srel.n_nodes,
        pos_lo=srel.pos_lo,  # host-only: used after the kernel returns
    )


def assemble_sharded_rel(mesh: Mesh, n_nodes: int,
                         local_shards: dict) -> ShardedRel:
    """Build a GLOBAL ShardedRel from per-process LOCAL tablet slabs —
    the multi-host deployment shape (reference: each Alpha holds only
    its group's tablets; SURVEY §2.3 tablet row). Unlike device_put_rel,
    no process ever materializes the whole relation: process p provides
    `local_shards[d] = (indptr_local [R+1] int32, indices [nnz_d] int32)`
    ONLY for the shard ids d whose devices it hosts, and the global
    array is stitched with jax.make_array_from_single_device_arrays.

    Shard shapes must agree across processes, so the edge capacity (max
    shard nnz) and the foreign pos_lo values are exchanged with one
    host-level allgather — the only cross-host metadata traffic; edge
    data itself never moves."""
    devices = list(mesh.devices.reshape(-1))
    D = len(devices)
    rows = -(-n_nodes // D) if n_nodes else 1
    local_ids = [d for d, dev in enumerate(devices)
                 if dev.process_index == jax.process_index()]
    assert set(local_shards) == set(local_ids), (
        sorted(local_shards), local_ids)

    # agree on capacity + absolute edge-position bases across processes:
    # one [D] nnz vector, merged by elementwise max (foreign entries 0).
    # Gated on FOREIGN SHARDS EXISTING, not process_count(): a fully
    # local mesh inside a multi-process runtime must not drag unrelated
    # processes into a collective (host_np's is_fully_addressable rule)
    nnz = np.zeros(D, np.int64)
    for d, (_ptr, idx) in local_shards.items():
        nnz[d] = len(idx)
    if len(local_ids) < D:
        from jax.experimental import multihost_utils
        nnz = np.asarray(multihost_utils.process_allgather(nnz))
        nnz = nnz.reshape(-1, D).max(axis=0)
    cap = max(int(nnz.max()), 1)
    pos_lo = np.concatenate([[0], np.cumsum(nnz[:-1])]).astype(np.int64)
    row_lo = np.minimum(np.arange(D) * rows, n_nodes).astype(np.int32)

    sh = shard_leading(mesh)

    def stitch(shape, dtype, per_shard):
        parts = []
        for d in local_ids:
            arr = np.zeros((1,) + shape[1:], dtype)
            per_shard(d, arr)
            parts.append(jax.device_put(arr, devices[d]))
        return jax.make_array_from_single_device_arrays(
            shape, sh, parts)

    def fill_ptr(d, out):
        out[0, :] = local_shards[d][0]

    def fill_idx(d, out):
        idx = local_shards[d][1]
        out[0, :] = SENTINEL32
        out[0, :len(idx)] = idx

    def fill_lo(d, out):
        out[0] = row_lo[d]

    return ShardedRel(
        indptr_s=stitch((D, rows + 1), np.int32, fill_ptr),
        indices_s=stitch((D, cap), np.int32, fill_idx),
        row_lo=stitch((D,), np.int32, fill_lo),
        n_nodes=n_nodes,
        pos_lo=pos_lo,
    )


def shard_frontier(frontier: np.ndarray, n_shards: int, f_cap: int) -> np.ndarray:
    """Split a frontier into [D, f_cap] sentinel-padded chunks for ring hops.

    Contiguous split — chunk→device assignment is arbitrary because ring
    rotation visits every device with every chunk (SURVEY §5: the
    ring-attention analog for frontiers larger than one device's slice).
    """
    frontier = np.asarray(frontier, np.int32)
    out = np.full((n_shards, f_cap), SENTINEL32, np.int32)
    per = -(-max(len(frontier), 1) // n_shards)
    if per > f_cap:
        raise ValueError(f"frontier chunk {per} exceeds f_cap {f_cap}")
    for d in range(n_shards):
        chunk = frontier[d * per:(d + 1) * per]
        out[d, :len(chunk)] = chunk
    return out
