"""Synthetic graph workload generators (benchmark fixtures).

Reference parity: the reference benchmarks against fixed datasets
(21million movie RDF, LDBC SNB, Twitter-2010 — SURVEY §6) that are not
available in this environment, so benchmarks and tests generate structurally
similar graphs deterministically: heavy-tailed out-degree (social-network
shaped, like the follower/`starring` edges the baseline configs name) over a
configurable node count.
"""

from __future__ import annotations

import numpy as np

from dgraph_tpu.store.store import EdgeRel, _csr_from_pairs


def powerlaw_edges(n_nodes: int, avg_deg: float, seed: int = 0,
                   zipf_a: float = 2.0) -> tuple[np.ndarray, np.ndarray]:
    """Directed edges with Zipf-distributed out-degree and preferential
    (rank-skewed) destinations. Returns (src, dst) int64 arrays with
    self-loops removed; duplicate pairs may remain (CSR construction
    dedupes them)."""
    rng = np.random.default_rng(seed)
    deg = rng.zipf(zipf_a, size=n_nodes)
    # cap the tail, then rescale to hit the requested average degree
    deg = np.minimum(deg, max(int(avg_deg * 64), 8))
    deg = np.maximum((deg * (avg_deg / max(deg.mean(), 1e-9))).astype(np.int64), 0)
    src = np.repeat(np.arange(n_nodes, dtype=np.int64), deg)
    # destinations skewed toward low ranks (hubs), like follower graphs
    dst = (n_nodes * rng.beta(0.6, 1.8, size=src.shape[0])).astype(np.int64)
    dst = np.minimum(dst, n_nodes - 1)
    keep = src != dst
    return src[keep], dst[keep]


def powerlaw_rel(n_nodes: int, avg_deg: float, seed: int = 0) -> EdgeRel:
    """A deduped CSR relation over ranks [0, n_nodes) (uid == rank here)."""
    src, dst = powerlaw_edges(n_nodes, avg_deg, seed)
    return _csr_from_pairs(src.astype(np.int32), dst.astype(np.int32), n_nodes)


def uniform_rel(n_nodes: int, deg: int, seed: int = 0) -> EdgeRel:
    """Uniform-degree random relation (regular fan-out; predictable caps)."""
    rng = np.random.default_rng(seed)
    src = np.repeat(np.arange(n_nodes, dtype=np.int64), deg)
    dst = rng.integers(0, n_nodes, size=src.shape[0])
    return _csr_from_pairs(src.astype(np.int32), dst.astype(np.int32), n_nodes)
