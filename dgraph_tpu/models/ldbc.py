"""LDBC SNB-shaped dataset generator (benchmark + golden-test fixture).

Reference parity: the reference's headline configs (BASELINE.json
`configs[2]`/`configs[4]`) run over LDBC Social Network Benchmark data —
persons linked by `knows`, authoring posts/comments in forums, tagged with
topics. The real SNB datagen (Hadoop/Spark) and its datasets are not
available in this environment (zero egress), so this module generates a
deterministic graph with the same *shape*: SF-scaled entity counts, a
community-clustered heavy-tailed `knows` graph, activity (posts/comments)
with creator/reply/tag edges, and typed scalar properties — enough for the
IC-style query mix in bench_baseline.py to be structurally honest.

Scale factors follow SNB's published SF1 proportions (~10k persons, ~180k
knows half-edges, ~1M messages at SF1), scaled linearly.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

FIRST_NAMES = ["Jan", "Yang", "Arjun", "Maria", "Chen", "Otto", "Abebe",
               "Sofia", "Kenji", "Amara", "Ivan", "Lucia", "Wei", "Noor",
               "Pavel", "Aiko"]
LAST_NAMES = ["Kov", "Li", "Sharma", "Garcia", "Wang", "Muller", "Bekele",
              "Rossi", "Sato", "Okafor", "Petrov", "Silva", "Zhang",
              "Hassan", "Novak", "Tanaka"]
CITIES = ["Beijing", "Mumbai", "Lagos", "Moscow", "Sao_Paulo", "Tokyo",
          "Berlin", "Nairobi", "Lima", "Hanoi", "Tbilisi", "Porto"]
TAG_NAMES = [f"tag_{i}" for i in range(128)]


@dataclass
class SNBGraph:
    """Generated graph in rank-free uid space (uids dense from 1)."""
    n_persons: int
    n_posts: int
    n_comments: int
    n_tags: int
    # entity uid ranges: [lo, hi) half-open
    person_uids: np.ndarray
    post_uids: np.ndarray
    comment_uids: np.ndarray
    tag_uids: np.ndarray
    # edges as (src_uid, dst_uid) int64 pairs
    knows: np.ndarray          # person -> person (symmetric pairs both ways)
    has_creator: np.ndarray    # message -> person
    reply_of: np.ndarray       # comment -> post|comment
    has_tag: np.ndarray        # message -> tag
    # properties
    first_name: list           # per person
    last_name: list
    city: list
    birthday_year: np.ndarray  # per person int
    creation_ts: np.ndarray    # per message int (unix-ish)

    @property
    def n_nodes(self) -> int:
        return self.n_persons + self.n_posts + self.n_comments + self.n_tags

    @property
    def n_edges(self) -> int:
        return (len(self.knows) + len(self.has_creator)
                + len(self.reply_of) + len(self.has_tag))


def generate(sf: float = 0.1, seed: int = 9) -> SNBGraph:
    """SF-scaled SNB-shaped graph. sf=1.0 ≈ 10k persons / ~1M messages
    (the published SF1 proportions); sf=0.1 is the test/CI size."""
    rng = np.random.default_rng(seed)
    n_persons = max(int(9892 * sf), 64)
    n_posts = max(int(400_000 * sf), 256)
    n_comments = max(int(600_000 * sf), 256)
    n_tags = min(len(TAG_NAMES), max(int(16_080 * sf), 16))

    uid = 1
    person_uids = np.arange(uid, uid + n_persons, dtype=np.int64)
    uid += n_persons
    post_uids = np.arange(uid, uid + n_posts, dtype=np.int64)
    uid += n_posts
    comment_uids = np.arange(uid, uid + n_comments, dtype=np.int64)
    uid += n_comments
    tag_uids = np.arange(uid, uid + n_tags, dtype=np.int64)

    # -- knows: community-clustered heavy tail ------------------------------
    # persons sit in sqrt(n)-sized communities; ~80% of friendships are
    # intra-community, the rest global with hub skew — the SNB datagen's
    # "university/city cluster + long-range" structure without its pipeline
    n_comm = max(int(np.sqrt(n_persons)), 4)
    comm = rng.integers(0, n_comm, n_persons)
    deg = np.minimum(rng.zipf(2.2, n_persons), 512)
    deg = np.maximum((deg * (18.0 / max(deg.mean(), 1e-9))).astype(np.int64),
                     1)
    src = np.repeat(np.arange(n_persons), deg)
    local = rng.random(len(src)) < 0.8
    dst = np.empty(len(src), np.int64)
    # intra-community picks: random member of the source's community
    order = np.argsort(comm, kind="stable")
    bounds = np.searchsorted(comm[order], np.arange(n_comm + 1))
    csrc = comm[src[local]]
    lo, hi = bounds[csrc], bounds[csrc + 1]
    dst[local] = order[lo + (rng.random(local.sum())
                             * np.maximum(hi - lo, 1)).astype(np.int64)]
    # long-range picks: hub-skewed
    n_far = int((~local).sum())
    dst[~local] = (n_persons * rng.beta(0.7, 2.0, n_far)).astype(np.int64)
    keep = src != dst
    s, d = src[keep], dst[keep]
    knows = np.stack([np.concatenate([s, d]), np.concatenate([d, s])],
                     axis=1)
    knows = np.unique(knows, axis=0)
    knows = np.stack([person_uids[knows[:, 0]], person_uids[knows[:, 1]]],
                     axis=1)

    # -- activity -----------------------------------------------------------
    # post/comment authorship follows the same heavy tail as friendships
    author_w = deg.astype(np.float64) / deg.sum()
    post_author = rng.choice(n_persons, n_posts, p=author_w)
    comment_author = rng.choice(n_persons, n_comments, p=author_w)
    has_creator = np.stack([
        np.concatenate([post_uids, comment_uids]),
        person_uids[np.concatenate([post_author, comment_author])]], axis=1)

    # comments reply to posts (70%) or earlier comments (30%)
    to_post = rng.random(n_comments) < 0.7
    parent = np.empty(n_comments, np.int64)
    parent[to_post] = post_uids[rng.integers(0, n_posts, to_post.sum())]
    idx = np.arange(n_comments)[~to_post]
    earlier = np.maximum(idx, 1)
    parent[~to_post] = comment_uids[(rng.random(len(idx))
                                     * earlier).astype(np.int64)]
    reply_of = np.stack([comment_uids, parent], axis=1)

    # tags: zipf topic popularity, 0-3 tags per message
    n_msgs = n_posts + n_comments
    tag_cnt = rng.integers(0, 4, n_msgs)
    msg_uids = np.concatenate([post_uids, comment_uids])
    tsrc = np.repeat(msg_uids, tag_cnt)
    tpick = np.minimum(rng.zipf(1.8, len(tsrc)) - 1, n_tags - 1)
    has_tag = np.stack([tsrc, tag_uids[tpick]], axis=1)

    first = [FIRST_NAMES[i % len(FIRST_NAMES)] for i in
             rng.integers(0, len(FIRST_NAMES), n_persons)]
    last = [LAST_NAMES[i % len(LAST_NAMES)] for i in
            rng.integers(0, len(LAST_NAMES), n_persons)]
    city = [CITIES[i % len(CITIES)] for i in
            rng.integers(0, len(CITIES), n_persons)]
    birthday = rng.integers(1950, 2005, n_persons)
    creation = np.sort(rng.integers(1_262_304_000, 1_356_998_400, n_msgs))

    return SNBGraph(
        n_persons=n_persons, n_posts=n_posts, n_comments=n_comments,
        n_tags=n_tags, person_uids=person_uids, post_uids=post_uids,
        comment_uids=comment_uids, tag_uids=tag_uids, knows=knows,
        has_creator=has_creator, reply_of=reply_of, has_tag=has_tag,
        first_name=first, last_name=last, city=city,
        birthday_year=birthday, creation_ts=creation)


SCHEMA = """
first_name: string @index(exact, term) .
last_name: string @index(exact) .
city: string @index(exact) .
birthday_year: int @index(int) .
creation_ts: int @index(int) .
tag_name: string @index(exact) .
knows: [uid] @reverse .
has_creator: [uid] @reverse .
reply_of: [uid] @reverse .
has_tag: [uid] @reverse .
"""


def load_into(alpha, g: SNBGraph, batch: int = 200_000) -> None:
    """Install the graph through the mutation path in committed batches."""
    def commit_edges(pred, pairs):
        for i in range(0, len(pairs), batch):
            txn = alpha.new_txn()
            for s, o in pairs[i:i + batch]:
                txn.mutation.edge_sets.append((int(s), pred, int(o), ()))
            txn.commit()

    alpha.alter(SCHEMA)
    commit_edges("knows", g.knows)
    commit_edges("has_creator", g.has_creator)
    commit_edges("reply_of", g.reply_of)
    commit_edges("has_tag", g.has_tag)
    txn = alpha.new_txn()
    for i, uid in enumerate(g.person_uids):
        u = int(uid)
        txn.mutation.val_sets.append((u, "first_name", g.first_name[i],
                                      "", ()))
        txn.mutation.val_sets.append((u, "last_name", g.last_name[i],
                                      "", ()))
        txn.mutation.val_sets.append((u, "city", g.city[i], "", ()))
        txn.mutation.val_sets.append((u, "birthday_year",
                                      int(g.birthday_year[i]), "", ()))
    txn.commit()
    msg_uids = np.concatenate([g.post_uids, g.comment_uids])
    for i in range(0, len(msg_uids), batch):
        txn = alpha.new_txn()
        for j in range(i, min(i + batch, len(msg_uids))):
            txn.mutation.val_sets.append(
                (int(msg_uids[j]), "creation_ts", int(g.creation_ts[j]),
                 "", ()))
        txn.commit()
    txn = alpha.new_txn()
    for i, uid in enumerate(g.tag_uids):
        txn.mutation.val_sets.append((int(uid), "tag_name", TAG_NAMES[i],
                                      "", ()))
    txn.commit()
