"""LDBC SNB-shaped dataset generator (benchmark + golden-test fixture).

Reference parity: the reference's headline configs (BASELINE.json
`configs[2]`/`configs[4]`) run over LDBC Social Network Benchmark data —
persons linked by `knows`, authoring posts/comments in forums, tagged with
topics. The real SNB datagen (Hadoop/Spark) and its datasets are not
available in this environment (zero egress), so this module generates a
deterministic graph with the same *shape*: SF-scaled entity counts, a
community-clustered heavy-tailed `knows` graph, activity (posts/comments)
with creator/reply/tag edges, and typed scalar properties — enough for the
IC-style query mix in bench_baseline.py to be structurally honest.

Scale factors follow SNB's published SF1 proportions (~10k persons, ~180k
knows half-edges, ~1M messages at SF1), scaled linearly.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

FIRST_NAMES = ["Jan", "Yang", "Arjun", "Maria", "Chen", "Otto", "Abebe",
               "Sofia", "Kenji", "Amara", "Ivan", "Lucia", "Wei", "Noor",
               "Pavel", "Aiko"]
LAST_NAMES = ["Kov", "Li", "Sharma", "Garcia", "Wang", "Muller", "Bekele",
              "Rossi", "Sato", "Okafor", "Petrov", "Silva", "Zhang",
              "Hassan", "Novak", "Tanaka"]
CITIES = ["Beijing", "Mumbai", "Lagos", "Moscow", "Sao_Paulo", "Tokyo",
          "Berlin", "Nairobi", "Lima", "Hanoi", "Tbilisi", "Porto"]
TAG_NAMES = [f"tag_{i}" for i in range(128)]


@dataclass
class SNBGraph:
    """Generated graph in rank-free uid space (uids dense from 1)."""
    n_persons: int
    n_posts: int
    n_comments: int
    n_tags: int
    n_forums: int
    n_orgs: int
    # entity uid ranges: [lo, hi) half-open
    person_uids: np.ndarray
    post_uids: np.ndarray
    comment_uids: np.ndarray
    tag_uids: np.ndarray
    forum_uids: np.ndarray
    org_uids: np.ndarray
    # edges as (src_uid, dst_uid) int64 pairs
    knows: np.ndarray          # person -> person (symmetric pairs both ways)
    knows_weight: np.ndarray   # per knows edge, float (IC14 path weights)
    has_creator: np.ndarray    # message -> person
    reply_of: np.ndarray       # comment -> post|comment
    has_tag: np.ndarray        # message -> tag
    has_member: np.ndarray     # forum -> person
    container_of: np.ndarray   # forum -> post
    likes: np.ndarray          # person -> message
    works_at: np.ndarray       # person -> org
    # properties
    first_name: list           # per person
    last_name: list
    city: list
    birthday_year: np.ndarray  # per person int
    creation_ts: np.ndarray    # per message int (unix-ish)

    @property
    def n_nodes(self) -> int:
        return (self.n_persons + self.n_posts + self.n_comments
                + self.n_tags + self.n_forums + self.n_orgs)

    @property
    def n_edges(self) -> int:
        return (len(self.knows) + len(self.has_creator)
                + len(self.reply_of) + len(self.has_tag)
                + len(self.has_member) + len(self.container_of)
                + len(self.likes) + len(self.works_at))


def generate(sf: float = 0.1, seed: int = 9) -> SNBGraph:
    """SF-scaled SNB-shaped graph. sf=1.0 ≈ 10k persons / ~1M messages
    (the published SF1 proportions); sf=0.1 is the test/CI size."""
    rng = np.random.default_rng(seed)
    n_persons = max(int(9892 * sf), 64)
    n_posts = max(int(400_000 * sf), 256)
    n_comments = max(int(600_000 * sf), 256)
    n_tags = min(len(TAG_NAMES), max(int(16_080 * sf), 16))
    n_forums = max(int(20_000 * sf), 32)
    n_orgs = max(int(1_575 * sf), 8)

    uid = 1
    person_uids = np.arange(uid, uid + n_persons, dtype=np.int64)
    uid += n_persons
    post_uids = np.arange(uid, uid + n_posts, dtype=np.int64)
    uid += n_posts
    comment_uids = np.arange(uid, uid + n_comments, dtype=np.int64)
    uid += n_comments
    tag_uids = np.arange(uid, uid + n_tags, dtype=np.int64)
    uid += n_tags
    forum_uids = np.arange(uid, uid + n_forums, dtype=np.int64)
    uid += n_forums
    org_uids = np.arange(uid, uid + n_orgs, dtype=np.int64)

    # -- knows: community-clustered heavy tail ------------------------------
    # persons sit in sqrt(n)-sized communities; ~80% of friendships are
    # intra-community, the rest global with hub skew — the SNB datagen's
    # "university/city cluster + long-range" structure without its pipeline
    n_comm = max(int(np.sqrt(n_persons)), 4)
    comm = rng.integers(0, n_comm, n_persons)
    deg = np.minimum(rng.zipf(2.2, n_persons), 512)
    deg = np.maximum((deg * (18.0 / max(deg.mean(), 1e-9))).astype(np.int64),
                     1)
    src = np.repeat(np.arange(n_persons), deg)
    local = rng.random(len(src)) < 0.8
    dst = np.empty(len(src), np.int64)
    # intra-community picks: random member of the source's community
    order = np.argsort(comm, kind="stable")
    bounds = np.searchsorted(comm[order], np.arange(n_comm + 1))
    csrc = comm[src[local]]
    lo, hi = bounds[csrc], bounds[csrc + 1]
    dst[local] = order[lo + (rng.random(local.sum())
                             * np.maximum(hi - lo, 1)).astype(np.int64)]
    # long-range picks: hub-skewed
    n_far = int((~local).sum())
    dst[~local] = (n_persons * rng.beta(0.7, 2.0, n_far)).astype(np.int64)
    keep = src != dst
    s, d = src[keep], dst[keep]
    knows = np.stack([np.concatenate([s, d]), np.concatenate([d, s])],
                     axis=1)
    knows = np.unique(knows, axis=0)
    knows = np.stack([person_uids[knows[:, 0]], person_uids[knows[:, 1]]],
                     axis=1)

    # -- activity -----------------------------------------------------------
    # post/comment authorship follows the same heavy tail as friendships
    author_w = deg.astype(np.float64) / deg.sum()
    post_author = rng.choice(n_persons, n_posts, p=author_w)
    comment_author = rng.choice(n_persons, n_comments, p=author_w)
    has_creator = np.stack([
        np.concatenate([post_uids, comment_uids]),
        person_uids[np.concatenate([post_author, comment_author])]], axis=1)

    # comments reply to posts (70%) or earlier comments (30%)
    to_post = rng.random(n_comments) < 0.7
    parent = np.empty(n_comments, np.int64)
    parent[to_post] = post_uids[rng.integers(0, n_posts, to_post.sum())]
    idx = np.arange(n_comments)[~to_post]
    earlier = np.maximum(idx, 1)
    parent[~to_post] = comment_uids[(rng.random(len(idx))
                                     * earlier).astype(np.int64)]
    reply_of = np.stack([comment_uids, parent], axis=1)

    # tags: zipf topic popularity, 0-3 tags per message
    n_msgs = n_posts + n_comments
    tag_cnt = rng.integers(0, 4, n_msgs)
    msg_uids = np.concatenate([post_uids, comment_uids])
    tsrc = np.repeat(msg_uids, tag_cnt)
    tpick = np.minimum(rng.zipf(1.8, len(tsrc)) - 1, n_tags - 1)
    has_tag = np.stack([tsrc, tag_uids[tpick]], axis=1)

    # -- forums, likes, organisations (IC5/7/10/11/14 coverage) -------------
    # forum membership: zipf forum popularity, ~10 members each on average
    m_cnt = np.minimum(rng.zipf(1.9, n_forums) + 4, 256)
    fsrc = np.repeat(np.arange(n_forums), m_cnt)
    fmem = rng.choice(n_persons, len(fsrc), p=author_w)
    has_member = np.unique(np.stack(
        [forum_uids[fsrc], person_uids[fmem]], axis=1), axis=0)
    # every post lives in one forum
    container_of = np.stack(
        [forum_uids[rng.integers(0, n_forums, n_posts)], post_uids],
        axis=1)
    # likes: heavy-tailed fan activity over messages
    n_likes = max(int(600_000 * sf), 512)
    lik_p = rng.choice(n_persons, n_likes, p=author_w)
    lik_m = rng.integers(0, n_msgs, n_likes)
    likes = np.unique(np.stack(
        [person_uids[lik_p], msg_uids[lik_m]], axis=1), axis=0)
    # employment: one org per person, zipf org sizes
    org_of = np.minimum(rng.zipf(1.6, n_persons) - 1, n_orgs - 1)
    works_at = np.stack([person_uids, org_uids[org_of]], axis=1)
    # interaction weight per knows edge (IC14's weighted paths) —
    # symmetric per person-pair: both directed rows of a friendship
    # carry the same weight (SNB defines it per pair)
    pair_lo = np.minimum(knows[:, 0], knows[:, 1])
    pair_hi = np.maximum(knows[:, 0], knows[:, 1])
    pair_key = pair_lo * (knows.max() + 1) + pair_hi
    uniq_pairs, inverse = np.unique(pair_key, return_inverse=True)
    pair_w = np.round(rng.uniform(0.5, 10.0, len(uniq_pairs)), 2)
    knows_weight = pair_w[inverse]

    first = [FIRST_NAMES[i % len(FIRST_NAMES)] for i in
             rng.integers(0, len(FIRST_NAMES), n_persons)]
    last = [LAST_NAMES[i % len(LAST_NAMES)] for i in
            rng.integers(0, len(LAST_NAMES), n_persons)]
    city = [CITIES[i % len(CITIES)] for i in
            rng.integers(0, len(CITIES), n_persons)]
    birthday = rng.integers(1950, 2005, n_persons)
    creation = np.sort(rng.integers(1_262_304_000, 1_356_998_400, n_msgs))

    return SNBGraph(
        n_persons=n_persons, n_posts=n_posts, n_comments=n_comments,
        n_tags=n_tags, n_forums=n_forums, n_orgs=n_orgs,
        person_uids=person_uids, post_uids=post_uids,
        comment_uids=comment_uids, tag_uids=tag_uids,
        forum_uids=forum_uids, org_uids=org_uids, knows=knows,
        knows_weight=knows_weight, has_creator=has_creator,
        reply_of=reply_of, has_tag=has_tag, has_member=has_member,
        container_of=container_of, likes=likes, works_at=works_at,
        first_name=first, last_name=last, city=city,
        birthday_year=birthday, creation_ts=creation)


SCHEMA = """
first_name: string @index(exact, term) .
last_name: string @index(exact) .
city: string @index(exact) .
birthday_year: int @index(int) .
creation_ts: int @index(int) .
tag_name: string @index(exact) .
forum_title: string @index(exact) .
org_name: string @index(exact) .
knows: [uid] @reverse .
has_creator: [uid] @reverse .
reply_of: [uid] @reverse .
has_tag: [uid] @reverse .
has_member: [uid] @reverse .
container_of: [uid] @reverse .
likes: [uid] @reverse .
works_at: [uid] @reverse .
"""


def ic_params(g: SNBGraph) -> dict:
    """Concrete parameter choices for the IC templates — the SINGLE
    source both ic_templates and the golden oracle (tests/test_ldbc_ic)
    read, so they can never diverge silently."""
    return {
        "p": int(g.person_uids[len(g.person_uids) // 2]),
        "p2": int(g.person_uids[7]),
        "fn": g.first_name[3],
        "city": g.city[0], "city2": g.city[1],
        "ts_mid": int(np.median(g.creation_ts)),
    }


def ic_templates(g: SNBGraph) -> dict[str, str]:
    """All 14 LDBC SNB Interactive Complex template shapes as DQL — the
    single source used by both the benchmark (bench_baseline.py config
    5) and its regression test (tests/test_ldbc_ic.py)."""
    pr = ic_params(g)
    p_uid = hex(pr["p"])
    p2_uid = hex(pr["p2"])
    fn = pr["fn"]
    city, city2 = pr["city"], pr["city2"]
    ts_mid = pr["ts_mid"]
    return {
        "IC1": '{ v as var(func: uid(%s)) @recurse(depth: 3, '
               'loop: false) { knows } '
               'q(func: uid(v), orderasc: last_name, first: 20) '
               '@filter(eq(first_name, "%s")) '
               '{ first_name last_name city } }' % (p_uid, fn),
        "IC2": '{ q(func: uid(%s)) { knows { ~has_creator '
               '(orderdesc: creation_ts, first: 20) '
               '{ creation_ts } } } }' % p_uid,
        "IC3": '{ q(func: uid(%s)) { knows { knows '
               '@filter(eq(city, "%s") OR eq(city, "%s")) '
               '{ first_name last_name city } } } }'
               % (p_uid, city, city2),
        "IC4": '{ q(func: uid(%s)) { knows { ~has_creator (first: 20) '
               '@filter(ge(creation_ts, %d)) '
               '{ has_tag { tag_name } } } } }' % (p_uid, ts_mid),
        "IC5": '{ q(func: uid(%s)) { knows { ~has_member '
               '(orderasc: forum_title, first: 20) '
               '{ forum_title } } } }' % p_uid,
        "IC6": '{ t(func: eq(tag_name, "tag_1")) { ~has_tag (first: 50)'
               ' { has_tag { tag_name } } } }',
        "IC7": '{ q(func: uid(%s)) { ~has_creator { ~likes (first: 20) '
               '{ first_name } } } }' % p_uid,
        "IC8": '{ q(func: uid(%s)) { ~has_creator { ~reply_of '
               '(orderdesc: creation_ts, first: 20) { creation_ts '
               'has_creator { first_name } } } } }' % p_uid,
        "IC9": '{ var(func: uid(%s)) { knows { f as knows } } '
               'q(func: uid(f)) { ~has_creator (first: 20) '
               '@filter(le(creation_ts, %d)) '
               '{ creation_ts } } }' % (p_uid, ts_mid),
        "IC10": '{ q(func: uid(%s)) { knows { knows (first: 10) '
                '@filter(ge(birthday_year, 1985)) '
                '{ first_name city } } } }' % p_uid,
        "IC11": '{ q(func: uid(%s)) { knows { works_at '
                '@filter(eq(org_name, "org_0")) { org_name } } } }'
                % p_uid,
        "IC12": '{ q(func: uid(%s)) { knows { ~has_creator (first: 20) '
                '@filter(has(reply_of)) { reply_of '
                '{ has_tag { tag_name } } } } } }' % p_uid,
        "IC13": '{ path as shortest(from: %s, to: %s) { knows } '
                'p(func: uid(path)) { first_name } }' % (p_uid, p2_uid),
        "IC14": '{ path as shortest(from: %s, to: %s, numpaths: 2) '
                '{ knows @facets(weight) } }' % (p_uid, p2_uid),
    }


def load_into(alpha, g: SNBGraph, batch: int = 200_000) -> None:
    """Install the graph through the mutation path in committed batches."""
    def commit_edges(pred, pairs):
        for i in range(0, len(pairs), batch):
            txn = alpha.new_txn()
            for s, o in pairs[i:i + batch]:
                txn.mutation.edge_sets.append((int(s), pred, int(o), ()))
            txn.commit()

    def commit_weighted(pred, pairs, weights):
        for i in range(0, len(pairs), batch):
            txn = alpha.new_txn()
            for (s, o), w in zip(pairs[i:i + batch],
                                 weights[i:i + batch]):
                txn.mutation.edge_sets.append(
                    (int(s), pred, int(o), {"weight": float(w)}))
            txn.commit()

    alpha.alter(SCHEMA)
    commit_weighted("knows", g.knows, g.knows_weight)
    commit_edges("has_creator", g.has_creator)
    commit_edges("reply_of", g.reply_of)
    commit_edges("has_tag", g.has_tag)
    commit_edges("has_member", g.has_member)
    commit_edges("container_of", g.container_of)
    commit_edges("likes", g.likes)
    commit_edges("works_at", g.works_at)
    txn = alpha.new_txn()
    for i, uid in enumerate(g.person_uids):
        u = int(uid)
        txn.mutation.val_sets.append((u, "first_name", g.first_name[i],
                                      "", ()))
        txn.mutation.val_sets.append((u, "last_name", g.last_name[i],
                                      "", ()))
        txn.mutation.val_sets.append((u, "city", g.city[i], "", ()))
        txn.mutation.val_sets.append((u, "birthday_year",
                                      int(g.birthday_year[i]), "", ()))
    txn.commit()
    msg_uids = np.concatenate([g.post_uids, g.comment_uids])
    for i in range(0, len(msg_uids), batch):
        txn = alpha.new_txn()
        for j in range(i, min(i + batch, len(msg_uids))):
            txn.mutation.val_sets.append(
                (int(msg_uids[j]), "creation_ts", int(g.creation_ts[j]),
                 "", ()))
        txn.commit()
    txn = alpha.new_txn()
    for i, uid in enumerate(g.tag_uids):
        txn.mutation.val_sets.append((int(uid), "tag_name", TAG_NAMES[i],
                                      "", ()))
    for i, uid in enumerate(g.forum_uids):
        txn.mutation.val_sets.append((int(uid), "forum_title",
                                      f"forum_{i}", "", ()))
    for i, uid in enumerate(g.org_uids):
        txn.mutation.val_sets.append((int(uid), "org_name",
                                      f"org_{i}", "", ()))
    txn.commit()
