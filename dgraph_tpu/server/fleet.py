"""Fleet observability: one correlated view of every node.

Every observability layer before this PR was node-local: a worker's
spans lived in the worker's ring, cost digests merged only through
checkpoints, and breaker/watchdog state was per-node. This module is
the aggregation half of the fleet story (trace PROPAGATION is in
utils/tracing.attach + server/task.py):

* `node_snapshot(alpha)` — ONE node's fleet fragment: identity (addr,
  node id, group, build, uptime), span/propagation counters, the full
  metrics exposition, the cost-digest state (integer, exactly
  mergeable), breaker states, watchdog status, and the race/lock-gate
  counts. Served over the worker transport by the DebugFleet RPC.

* `fleet_snapshot(alpha)` — the `GET /debug/fleet` document: fan out
  over every known cluster node through the pooled clients (so each
  leg rides the per-peer circuit breaker + retry policy), bounded by
  one overall budget (DebugFleet forwards the remaining budget as its
  gRPC deadline), and merge: cost digests combine EXACTLY (integer
  state, associative — bit-identical to an in-process
  `Aggregator.merge`), metrics expositions concatenate with an
  `instance` label per series. A dark or breaker-open peer degrades to
  an entry in `errors` — the snapshot is partial, never a 500.

* identity metrics — `build_info{version=,jax=,backend=}` and
  `process_uptime_s` (monotonic clock per R3), refreshed on every
  exposition render so scrapes and bundles always carry them.
"""

from __future__ import annotations

from dgraph_tpu import __version__
from dgraph_tpu.utils import costprofile, flightrec, locks, tracing
from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.utils.metrics import METRICS

FLEET_BUDGET_MS = 2000.0  # default whole-fan-out budget

_START_MONO = dl.monotonic_s()
_BUILD: dict | None = None


def build_labels() -> dict:
    """The build_info identity labels, resolved once: package version,
    jax version, and the jax backend platform. Resolution failures
    (no jax, device init refused) degrade to "none" — identity metrics
    must never take a process down."""
    global _BUILD
    if _BUILD is None:
        jax_version = backend = "none"
        try:
            import jax
            jax_version = jax.__version__
            backend = jax.default_backend()
        except Exception:  # noqa: BLE001 — identity is best-effort
            pass
        _BUILD = {"version": __version__, "jax": jax_version,
                  "backend": backend}
    return _BUILD


def refresh_identity_metrics() -> None:
    """Set the build/uptime identity gauges. Called before every
    exposition render (HTTP scrape, fleet fragment, flight bundle) so
    `process_uptime_s` is live, not a boot-time constant."""
    b = build_labels()
    METRICS.set_gauge("build_info", 1.0, version=b["version"],
                      jax=b["jax"], backend=b["backend"])
    METRICS.set_gauge("process_uptime_s",
                      round(dl.monotonic_s() - _START_MONO, 3))


def node_snapshot(alpha) -> dict:
    """One node's fleet fragment (the DebugFleet RPC payload)."""
    refresh_identity_metrics()
    groups = getattr(alpha, "groups", None)
    res = getattr(groups, "resilience", None) if groups is not None \
        else None
    races = locks.RACES.snapshot()
    lock_graph = locks.GRAPH.snapshot()
    fr = flightrec.state(1)  # watchdog/dump status; ring stays local
    return {
        "addr": groups.my_addr if groups is not None else "local",
        "node_id": groups.node_id if groups is not None else 0,
        "group": groups.gid if groups is not None else 0,
        "build": dict(build_labels()),
        "uptime_s": round(dl.monotonic_s() - _START_MONO, 3),
        "spans": tracing.stats(),
        "metrics": METRICS.render(),
        "costs": costprofile.COSTS.to_state(),
        "breakers": res.snapshot() if res is not None else {},
        "watchdog": fr.get("watchdog", {"armed": False}),
        "flight": {"armed": fr["armed"], "inflight": fr["inflight"],
                   "dumps": len(fr["dumps"])},
        "gates": {"races": races.get("races_total", 0),
                  "lock_cycles": len(lock_graph.get("cycles", ()))},
        # retained-history fragment (ISSUE 17): the recent-window
        # digest + SLO states, so the fleet merge can answer "which
        # node is burning budget" without a per-node round of pulls
        "timeseries": _timeseries_fragment(),
        "slo": _slo_fragment(),
    }


def _timeseries_fragment() -> dict | None:
    from dgraph_tpu.utils import timeseries
    s = timeseries.state()
    if s is None:
        return None
    return s.ring.summary(60.0)


def _slo_fragment() -> dict | None:
    from dgraph_tpu.utils import slo
    eng = slo.ENGINE
    if eng is None:
        return None
    st = eng.status()
    return {"states": st["states"],
            "breaches_total": st["breaches_total"]}


def _with_instance(line: str, instance: str) -> str:
    """One exposition sample line with an `instance` label spliced in
    (first position, so escaping of the existing labels is
    untouched)."""
    name, _, val = line.partition(" ")
    esc = instance.replace("\\", "\\\\").replace('"', '\\"')
    if "{" in name:
        head, rest = name.split("{", 1)
        return f'{head}{{instance="{esc}",{rest} {val}'
    return f'{name}{{instance="{esc}"}} {val}'


def merge_exposition(per_node: dict[str, str]) -> str:
    """Per-node expositions → one instance-labeled text block. TYPE
    headers dedupe across nodes; every sample gains
    `instance="<addr>"`. Each node's exposition already rode its own
    cardinality guard, so the merged series count is bounded by
    nodes × the per-node cap."""
    out: list[str] = []
    seen_types: set[str] = set()
    for inst in sorted(per_node):
        for line in per_node[inst].splitlines():
            if not line.strip():
                continue
            if line.startswith("# TYPE"):
                if line not in seen_types:
                    seen_types.add(line)
                    out.append(line)
                continue
            if line.startswith("#"):
                continue
            out.append(_with_instance(line, inst))
    return "\n".join(out) + "\n"


def fleet_snapshot(alpha, budget_ms: float = FLEET_BUDGET_MS) -> dict:
    """The `GET /debug/fleet` document. Degraded-not-failed: a peer
    that refuses (dark, breaker-open, or past the budget) lands in
    `errors` keyed by its address; everything reachable still merges.
    The whole fan-out shares ONE request budget — DebugFleet is
    budget-forwarded, so the remaining time rides each leg's gRPC
    deadline and a wedged peer cannot stall the snapshot."""
    local = node_snapshot(alpha)
    me = local["addr"]
    fragments: dict[str, dict] = {me: local}
    errors: dict[str, str] = {}
    groups = getattr(alpha, "groups", None)
    if groups is not None:
        with dl.activate(dl.RequestContext(budget_ms)):
            for addr in groups.known_addrs():
                if addr == me:
                    continue
                try:
                    fragments[addr] = groups.pool(addr).debug_fleet()
                    METRICS.inc("fleet_fanout_total", outcome="ok")
                except Exception as e:  # noqa: BLE001 — degrade, never 500
                    errors[addr] = f"{type(e).__name__}: {e}"[:300]
                    METRICS.inc("fleet_fanout_total", outcome="error")
    merged = costprofile.Aggregator()
    for frag in fragments.values():
        try:
            merged.merge(costprofile.Aggregator.from_state(
                frag.get("costs") or {}))
        except Exception:  # noqa: BLE001 — a malformed fragment merges as empty
            pass
    # cluster SLO/series roll-up (ISSUE 17): per-node burn rates fold
    # into one worst-burn-per-objective view — "is anyone breaching,
    # and who" in a single read; nodes with no engine armed are
    # simply absent (partial, never a 500)
    slo_merged: dict[str, dict] = {}
    breaches_total = 0
    for addr, frag in fragments.items():
        sl = frag.get("slo") or {}
        breaches_total += sl.get("breaches_total", 0)
        for name, st in (sl.get("states") or {}).items():
            for win, w in (st.get("windows") or {}).items():
                cur = slo_merged.setdefault(name, {}).get(win)
                if cur is None or w.get("burn", 0) > cur["burn"]:
                    slo_merged[name][win] = {
                        "burn": w.get("burn", 0),
                        "breached": w.get("breached", False),
                        "node": addr}
    return {
        "self": me,
        "nodes": {addr: {k: v for k, v in frag.items()
                         if k not in ("metrics", "costs")}
                  for addr, frag in fragments.items()},
        "errors": errors,
        "slo": {"worst_burn": slo_merged,
                "breaches_total": breaches_total},
        # exact merge: integer digest state is associative, so this is
        # bit-identical to merging the same fragments in-process (the
        # tier-1 test pins it against a local Aggregator.merge)
        "costs": merged.to_doc(top_n=10),
        "costs_state": merged.to_state(),
        "metrics": merge_exposition(
            {addr: frag.get("metrics", "")
             for addr, frag in fragments.items()}),
    }
