"""gRPC services: the public Dgraph API and the Worker task seam.

Reference parity: `worker/server.go` (grpc `pb.Worker` service —
`ServeTask` is the boundary the north star names: an Alpha offloads
per-hop expansion to this service) and `edgraph/server.go` exposed as the
public `api.Dgraph` service (Query/Mutate/Alter/CommitOrAbort).

grpc-python service stubs normally come from grpcio-tools, which this
image lacks; services are registered through grpc's generic-handler API
against the protoc-generated messages instead — same wire behavior,
no codegen dependency.
"""

from __future__ import annotations

import time
from concurrent import futures

import grpc
import numpy as np

from dgraph_tpu.engine.execute import Executor
from dgraph_tpu.protos import task_pb2 as pb
from dgraph_tpu.server.admission import ServerOverloaded
from dgraph_tpu.server.api import (Alpha, NoQuorum, ReadUnavailable,
                                   StageRefused, TxnAborted)
from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.utils import flightrec, tracing

SERVICE_DGRAPH = "dgraph_tpu.Dgraph"
SERVICE_WORKER = "dgraph_tpu.Worker"

# gRPC metadata keys the ambient trace context rides on — forwarded by
# Client._attempt exactly the way the remaining deadline budget rides
# the gRPC timeout, re-established by every worker-side handler via
# _inbound_trace so a cross-group hop produces ONE trace whose worker
# spans are genuine children of the coordinator's request trace
TRACE_ID_MD = "x-dgraph-trace-id"
PARENT_SPAN_MD = "x-dgraph-parent-span"


def _inbound_trace(ctx):
    """Re-establish the caller's trace context from gRPC metadata (the
    budget-forwarding pattern applied to trace identity). Returns a
    context manager; no metadata = no-op."""
    if ctx is None:
        return tracing.attach("")
    md = {k.lower(): v for k, v in (ctx.invocation_metadata() or ())}
    tid = md.get(TRACE_ID_MD, "")
    try:
        parent = int(md.get(PARENT_SPAN_MD) or 0)
    except ValueError:
        parent = 0
    return tracing.attach(tid, parent)

# read-shaped worker RPCs whose outbound calls FORWARD the remaining
# request budget as the gRPC timeout (the Go context-propagation
# analog). Mutation-protocol legs (ApplyMutation/ApplyDecision) are
# deliberately absent: once two-phase staging starts the decision
# protocol must run to completion — a budget interrupt between stage
# and decide would leak an undecided pend.
_BUDGET_FORWARDED = {"ServeTask", "FetchLog", "TabletSnapshot",
                     "ChainHead", "Query", "DebugTraces", "DebugFleet",
                     "DebugFlight"}

# worker RPCs the resilience layer may RE-ATTEMPT on a transport
# failure (cluster/resilience.py). Every receive path is idempotent —
# re-staging/re-applying a ts the peer already logged is a no-op — so
# the whole worker surface is safe to retry; the retry policy itself
# refuses non-transport failures (DEADLINE_EXCEEDED, app errors).
_RETRYABLE_RPCS = {"ServeTask", "Ping", "ChainHead", "ApplyMutation",
                   "ApplyDecision", "FetchLog", "DebugTraces",
                   "DebugFleet", "DebugFlight", "PullTablet",
                   "TabletSnapshot"}


def _grpc_deadline_ms(ctx) -> float | None:
    """Re-establish a request budget from the inbound gRPC deadline
    (reference: the server-side context.Context carrying the caller's
    deadline). Tolerates a missing context (tests drive handlers
    directly)."""
    rem = ctx.time_remaining() if ctx is not None else None
    return None if rem is None else max(rem, 0.0) * 1e3


class DgraphService:
    """Public API service (api.Dgraph analog)."""

    def __init__(self, alpha: Alpha):
        self.alpha = alpha

    def _acl_user(self, ctx):
        """Token gate for the public service when ACL is on (reference:
        the accessJwt gRPC metadata every dgo client attaches). The
        WORKER service stays cluster-internal — peers authenticate by
        network placement, as the reference's worker port does."""
        if self.alpha.acl is None:
            return None
        md = {k.lower(): v for k, v in (ctx.invocation_metadata() or ())}
        token = md.get("accessjwt") or md.get("x-dgraph-accesstoken")
        try:
            return self.alpha.acl.verify(token)
        except PermissionError as e:
            ctx.abort(grpc.StatusCode.UNAUTHENTICATED, str(e))

    def Query(self, req: pb.Request, ctx) -> pb.Response:
        t0 = time.perf_counter()
        acl_user = self._acl_user(ctx)
        start_ts = req.start_ts or None
        try:
            raw = self.alpha.query_raw(req.query, dict(req.vars) or None,
                                       read_ts=start_ts,
                                       acl_user=acl_user,
                                       deadline_ms=_grpc_deadline_ms(ctx))
        except ReadUnavailable as e:
            # retryable by contract: the replica cannot verify its
            # snapshot is gap-free (partitioned) — same code the
            # reference maps unreachable-quorum reads onto
            ctx.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        except dl.DeadlineExceeded as e:
            ctx.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
        except dl.Cancelled as e:
            ctx.abort(grpc.StatusCode.CANCELLED, str(e))
        except ServerOverloaded as e:
            # RESOURCE_EXHAUSTED is gRPC's retryable overload code; the
            # retry-after hint rides the message (HTTP carries it as a
            # real Retry-After header)
            ctx.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        return pb.Response(
            json=raw,
            txn=pb.TxnContext(start_ts=start_ts or 0),
            latency_us=int((time.perf_counter() - t0) * 1e6))

    def Mutate(self, req: pb.MutationReq, ctx) -> pb.MutationResp:
        acl_user = self._acl_user(ctx)
        try:
            res = self.alpha.mutate(
                set_nquads=req.set_nquads or None,
                del_nquads=req.del_nquads or None,
                set_json=req.set_json or None,
                del_json=req.del_json or None,
                commit_now=req.commit_now,
                start_ts=req.start_ts or None,
                acl_user=acl_user,
                deadline_ms=_grpc_deadline_ms(ctx))
        except TxnAborted as e:
            ctx.abort(grpc.StatusCode.ABORTED, str(e))
        except NoQuorum as e:
            # UNAVAILABLE, not ABORTED: the txn did not lose a conflict —
            # the replica group cannot commit right now (minority side)
            ctx.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        except dl.DeadlineExceeded as e:
            ctx.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
        except dl.Cancelled as e:
            ctx.abort(grpc.StatusCode.CANCELLED, str(e))
        except ServerOverloaded as e:
            ctx.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except PermissionError as e:
            ctx.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))
        return pb.MutationResp(
            uids=res["uids"],
            txn=pb.TxnContext(start_ts=res["txn"]["start_ts"],
                              commit_ts=res["txn"]["commit_ts"]))

    def CommitOrAbort(self, req: pb.TxnContext, ctx) -> pb.TxnContext:
        try:
            cts = self.alpha.commit_or_abort(
                req.start_ts, abort=req.aborted,
                deadline_ms=_grpc_deadline_ms(ctx))
        except TxnAborted as e:
            ctx.abort(grpc.StatusCode.ABORTED, str(e))
        except NoQuorum as e:
            ctx.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        except dl.DeadlineExceeded as e:
            ctx.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
        except ServerOverloaded as e:
            ctx.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        return pb.TxnContext(start_ts=req.start_ts, commit_ts=cts,
                             aborted=req.aborted)

    def Alter(self, req: pb.Operation, ctx) -> pb.Payload:
        acl_user = self._acl_user(ctx)
        if self.alpha.acl is not None:
            try:
                self.alpha.acl.check_alter(acl_user)
            except PermissionError as e:
                ctx.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))
        if req.drop_all:
            self.alpha.drop_all()
        elif req.drop_attr:
            self.alpha.drop_attr(req.drop_attr)
        elif req.schema:
            self.alpha.alter(req.schema)
        return pb.Payload(data=b"ok")

    def AssignUids(self, req: pb.AssignRequest, ctx) -> pb.AssignedIds:
        r = self.alpha.oracle.assign_uids(int(req.num))
        return pb.AssignedIds(start_id=r.start, end_id=r.stop - 1)


class WorkerService:
    """The task seam: one-hop expansion requests (worker.ServeTask)."""

    def __init__(self, alpha: Alpha):
        self.alpha = alpha

    def ServeTask(self, req: pb.TaskQuery, ctx) -> pb.TaskResult:
        # one-shot read: read_only_ts never registers a pending txn (a
        # leaked read_ts would pin the oracle gc watermark forever), and
        # _reading keeps gc from dropping the snapshot mid-task. The
        # caller's remaining budget (gRPC deadline) becomes THIS node's
        # request context, so a forwarded hop keeps checkpointing —
        # context propagation, as the reference's ctx crosses
        # ProcessTaskOverNetwork. The caller's trace context rides the
        # same metadata (_inbound_trace), so this handler's spans are
        # genuine children of the coordinator's request trace — one
        # trace end to end, with no ?peer= proxying.
        try:
            with dl.activate(dl.RequestContext(_grpc_deadline_ms(ctx))), \
                    _inbound_trace(ctx):
                with tracing.span("worker.serve_task", attr=req.attr,
                                  frontier=len(req.frontier.uids)):
                    with self.alpha._reading(
                            int(req.read_ts) or None) as ts:
                        return self._serve(req, ts)
        except dl.DeadlineExceeded as e:
            ctx.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))

    def _serve(self, req: pb.TaskQuery, ts: int) -> pb.TaskResult:
        store = self.alpha.mvcc.read_view(ts)
        ex = Executor(store,
                      device_threshold=self.alpha.device_threshold,
                      mesh=self.alpha.mesh)
        if req.func_name:
            from dgraph_tpu.engine.ir import FuncNode
            from dgraph_tpu.engine.funcs import eval_func
            ranks = eval_func(store, FuncNode(
                name=req.func_name, attr=req.attr,
                args=list(req.func_args), lang=req.lang))
            flat_uids = store.uid_of(ranks).astype(np.uint64)
            return pb.TaskResult(
                flat=pb.UidList(uids=flat_uids.tolist()))
        frontier_uids = np.array(list(req.frontier.uids), np.int64)
        ranks = store.rank_of(frontier_uids)
        known = ranks >= 0
        nbrs, seg, _pos = ex.expand(req.attr, req.reverse,
                                    ranks[known].astype(np.int32))
        rows = []
        kept_pos = np.nonzero(known)[0]
        for i in range(len(frontier_uids)):
            rows.append(pb.UidList())
        if len(nbrs):
            order = np.argsort(seg, kind="stable")
            nbrs, seg = nbrs[order], seg[order]
            bounds = np.searchsorted(seg, np.arange(len(kept_pos) + 1))
            for local, pos in enumerate(kept_pos):
                lo, hi = bounds[local], bounds[local + 1]
                row = nbrs[lo:hi]
                if req.offset:
                    row = row[req.offset:]
                if req.first:
                    row = row[:req.first]
                rows[pos] = pb.UidList(
                    uids=store.uid_of(row).astype(np.uint64).tolist())
        flat = (np.unique(nbrs) if len(nbrs)
                else np.zeros(0, np.int32))
        return pb.TaskResult(
            matrix=pb.UidMatrix(rows=rows),
            flat=pb.UidList(
                uids=store.uid_of(flat).astype(np.uint64).tolist()),
            edges_traversed=int(len(nbrs)))

    # -- cluster seams (worker/draft.go apply + snapshot shipping) ----------
    def Ping(self, req: pb.Empty, ctx) -> pb.Payload:
        """Liveness probe for commit-quorum pre-flight (raft heartbeat
        analog, pull-shaped)."""
        return pb.Payload(data=b"ok")

    def ChainHead(self, req: pb.Empty, ctx) -> pb.AssignedIds:
        """Chain-head probe for the partition-safe read gate: (node id,
        last ts this node broadcast). The reader compares the head
        against what it last APPLIED from this node and pulls any gap
        via FetchLog before serving (api.Alpha._verify_read_chains).
        Reuses AssignedIds (start_id=node, end_id=head) — no proto
        regen needed for two uint64s."""
        with _inbound_trace(ctx):
            a = self.alpha
            nid = a.groups.node_id if a.groups is not None else 0
            return pb.AssignedIds(start_id=nid, end_id=a._last_sent_ts)

    def ApplyMutation(self, req: pb.MutationMsg, ctx) -> pb.Payload:
        """Receive a broadcast (log shipping) — mutation, Alter, or
        DropAll, all riding one chain. Chained origin/prev_ts trigger gap
        catch-up BEFORE applying (the ack then certifies the receiver
        converged through this record's ts)."""
        from dgraph_tpu.store.wal import mut_from_bytes
        with _inbound_trace(ctx):
            if req.stage:
                # commit-quorum phase 1: durably log as pending, no
                # apply; the ack is the durability certificate (raft
                # AppendEntries)
                try:
                    self.alpha.receive_stage(
                        mut_from_bytes(req.mut_json), int(req.commit_ts),
                        int(req.origin), int(req.prev_ts))
                except StageRefused as e:
                    # no armed WAL: the ack would be a durability lie —
                    # the coordinator must not count this node toward
                    # majority
                    ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                              str(e))
                return pb.Payload(data=b"ok")
            if req.drop_all:
                kind, obj = "drop", None
            elif req.drop_attr:
                kind, obj = "drop_attr", req.drop_attr
            elif req.schema:
                kind, obj = "schema", req.schema
            else:
                kind, obj = "mut", mut_from_bytes(req.mut_json)
            self.alpha.receive_broadcast(kind, obj, int(req.commit_ts),
                                         int(req.origin),
                                         int(req.prev_ts))
            return pb.Payload(data=b"ok")

    def ApplyDecision(self, req: pb.DecisionMsg, ctx) -> pb.Payload:
        """Commit-quorum phase 2: resolve a staged ts (apply on commit,
        drop on abort). Idempotent; unknown ts already resolved by
        catch-up."""
        with _inbound_trace(ctx):
            self.alpha.receive_decision(int(req.commit_ts),
                                        bool(req.commit),
                                        int(req.origin))
            return pb.Payload(data=b"ok")

    def FetchLog(self, req: pb.FetchLogRequest, ctx) -> pb.LogRecords:
        """Serve the local WAL tail above since_ts (reference: raft log
        replay to a lagging follower / Badger Stream). Records are FULL
        mutations (apply_committed logs them unrestricted), so any peer
        can extract its own subset."""
        from dgraph_tpu.store.wal import mut_to_bytes, resolved_replay
        since = int(req.since_ts)
        with _inbound_trace(ctx), \
                tracing.span("worker.fetch_log", since_ts=since) as sp:
            out = pb.LogRecords(complete=since >= self.alpha._wal_floor)
            if self.alpha.wal is None:
                out.complete = False
                return out
            # resolved stream: pend+dec pairs surface as committed muts
            # or abort markers; unresolved pends never leave this node
            for ts, kind, obj in resolved_replay(self.alpha.wal.path):
                if ts <= since:
                    continue
                if kind == "mut":
                    out.records.append(pb.LogRecord(
                        ts=ts, mut_json=mut_to_bytes(obj)))
                elif kind == "abort":
                    out.records.append(pb.LogRecord(ts=ts, abort=True))
                elif kind == "schema":
                    out.records.append(pb.LogRecord(ts=ts, schema=obj))
                elif kind == "drop_attr":
                    out.records.append(pb.LogRecord(ts=ts,
                                                    drop_attr=obj))
                else:
                    out.records.append(pb.LogRecord(ts=ts, drop=True))
            sp.attrs["records"] = len(out.records)
            return out

    def DebugTraces(self, req: pb.Operation, ctx) -> pb.Payload:
        """Serve this node's span registry over the worker transport so
        the HTTP debug surface of ANY node can pull peer-leg spans
        (/debug/traces?peer= — ROADMAP observability follow-on).
        Reuses Operation (schema=trace_id, drop_attr=max-n) the way
        ChainHead reuses AssignedIds — no proto regen for two strings;
        the payload is the span-dict JSON /debug/traces already
        serves."""
        import json as _json
        with _inbound_trace(ctx):
            tid = req.schema
            if tid:
                spans = tracing.trace_spans(tid)
            else:
                spans = tracing.recent(int(req.drop_attr or 256))
            return pb.Payload(data=_json.dumps(
                [s.to_dict() for s in spans]).encode())

    def DebugFleet(self, req: pb.Operation, ctx) -> pb.Payload:
        """Serve this node's fleet fragment (server/fleet.py
        node_snapshot: identity, instance metrics exposition, cost-
        digest state, breaker states, watchdog status, race/lock-gate
        counts) over the worker transport — the per-node leg
        GET /debug/fleet fans out on. Reuses Operation → Payload the
        way DebugTraces does; the caller's remaining budget rides as
        the gRPC deadline, so a fleet fan-out never waits on a slow
        peer past its budget."""
        import json as _json
        from dgraph_tpu.server import fleet
        with dl.activate(dl.RequestContext(_grpc_deadline_ms(ctx))), \
                _inbound_trace(ctx):
            doc = fleet.node_snapshot(self.alpha)
        return pb.Payload(data=_json.dumps(doc, default=str).encode())

    def DebugFlight(self, req: pb.Operation, ctx) -> pb.Payload:
        """Serve this node's flight-recorder snapshot — every in-flight
        op with its stack and trace spans, the flight ring, watchdog
        state (utils/flightrec.flight_snapshot) — so a coordinator's
        watchdog conviction (or an operator's /debug/fleet/flight
        pull) can see what the implicated PEER was doing when a DCN
        hop wedged. Operation.drop_attr carries the ring tail length,
        as DebugTraces does."""
        import json as _json
        with _inbound_trace(ctx):
            doc = flightrec.flight_snapshot(int(req.drop_attr or 256))
        return pb.Payload(data=_json.dumps(doc, default=str).encode())

    def PullTablet(self, req: pb.PullTabletRequest, ctx) -> pb.Payload:
        """Pull a whole tablet from a peer and install it locally — the
        data-ship leg of a tablet move (reference: movePredicate's Badger
        Stream from the old owner to the new). Committed layers above the
        snapshot compose on top, so writes racing the move survive."""
        from dgraph_tpu.cluster.tablet import unpack_tablet
        with _inbound_trace(ctx):
            src = Client(req.src_addr)
            try:
                blob, version = src.tablet_snapshot(
                    req.attr, self.alpha.oracle.read_only_ts())
            finally:
                src.close()
            if blob:
                pd = unpack_tablet(blob, req.attr,
                                   self.alpha.mvcc.schema)
                self.alpha.mvcc.install_tablet(req.attr, pd)
                with self.alpha._state_lock:
                    self.alpha.tablet_versions[req.attr] = max(
                        self.alpha.tablet_versions.get(req.attr, 0),
                        version)
                    self.alpha._stale_preds.discard(req.attr)
            return pb.Payload(data=b"ok")

    def TabletSnapshot(self, req: pb.TabletSnapshotRequest,
                       ctx) -> pb.TabletSnapshot:
        """Serve a whole-tablet snapshot as-of read_ts (reference: Badger
        Stream snapshot / tablet move source)."""
        from dgraph_tpu.cluster.tablet import pack_tablet
        with _inbound_trace(ctx), \
                tracing.span("worker.tablet_snapshot",
                             attr=req.attr) as sp:
            with self.alpha._reading(int(req.read_ts) or None) as ts:
                store = self.alpha.mvcc.read_view(ts)
                pd = store.preds.get(req.attr)
                version = self.alpha.tablet_versions.get(req.attr, 0)
                if pd is None:
                    return pb.TabletSnapshot(blob=b"", version=version)
                blob = pack_tablet(pd)
                sp.attrs["bytes"] = len(blob)
                return pb.TabletSnapshot(blob=blob, version=version)


def _unary(fn, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn, request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString())


def make_server(alpha: Alpha, addr: str = "127.0.0.1:0",
                max_workers: int = 8):
    """Build (grpc server, bound port). Reference: worker/server.go
    grpc setup in alpha run()."""
    d, w = DgraphService(alpha), WorkerService(alpha)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(SERVICE_DGRAPH, {
            "Query": _unary(d.Query, pb.Request),
            "Mutate": _unary(d.Mutate, pb.MutationReq),
            "Alter": _unary(d.Alter, pb.Operation),
            "CommitOrAbort": _unary(d.CommitOrAbort, pb.TxnContext),
            "AssignUids": _unary(d.AssignUids, pb.AssignRequest),
        }),
        grpc.method_handlers_generic_handler(SERVICE_WORKER, {
            "ServeTask": _unary(w.ServeTask, pb.TaskQuery),
            "Ping": _unary(w.Ping, pb.Empty),
            "ChainHead": _unary(w.ChainHead, pb.Empty),
            "ApplyMutation": _unary(w.ApplyMutation, pb.MutationMsg),
            "ApplyDecision": _unary(w.ApplyDecision, pb.DecisionMsg),
            "FetchLog": _unary(w.FetchLog, pb.FetchLogRequest),
            "DebugTraces": _unary(w.DebugTraces, pb.Operation),
            "DebugFleet": _unary(w.DebugFleet, pb.Operation),
            "DebugFlight": _unary(w.DebugFlight, pb.Operation),
            "PullTablet": _unary(w.PullTablet, pb.PullTabletRequest),
            "TabletSnapshot": _unary(w.TabletSnapshot,
                                     pb.TabletSnapshotRequest),
        }),
    ))
    port = server.add_insecure_port(addr)
    return server, port


class Client:
    """Minimal client over the same generic method paths (dgo analog).

    Pooled cluster clients (cluster/groups.py) carry a shared
    `resilience` PeerTable: every call then runs under that node's
    per-peer circuit breaker + budget-aware retry policy
    (cluster/resilience.py). Ad-hoc clients (tests, debug proxies,
    PullTablet's one-shot source dial) keep the historical
    single-attempt behavior. `fault_check` is the fault-injection
    hook (cluster/fault.py) — invoked before EVERY wire attempt so an
    injected LinkDown exercises the same retry/breaker path a real
    connect failure does."""

    def __init__(self, target: str, resilience=None,
                 peer_addr: str | None = None):
        self.channel = grpc.insecure_channel(target)
        self.resilience = resilience
        self.peer_addr = peer_addr or target
        self.fault_check = None

    def _call(self, service: str, method: str, req, resp_cls):
        from dgraph_tpu.utils import costprofile
        costprofile.add("rpc_legs", 1)
        rpc = self.channel.unary_unary(
            f"/{service}/{method}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString)
        if self.resilience is not None:
            return self.resilience.call(
                self.peer_addr, method,
                lambda: self._attempt(rpc, method, req),
                retryable=method in _RETRYABLE_RPCS)
        return self._attempt(rpc, method, req)

    def _attempt(self, rpc, method: str, req):
        """One wire attempt, with fault injection, budget forwarding,
        and trace propagation: a read-shaped leg inside an active
        request context carries the REMAINING budget as its gRPC
        timeout, so a peer never works past what the client will wait
        for, and the ambient trace context (trace id + innermost open
        span id) rides as metadata so the peer's handler spans join
        THIS request's trace. An expired budget refuses before the
        wire; a deadline that fires mid-call surfaces as
        DeadlineExceeded (ours), NOT RpcError — the peer is alive, OUR
        budget died, and callers (and the retry policy) must not
        mistake that for an unreachable replica. The whole attempt is
        marked as an in-flight leg (flightrec.rpc_leg) so a watchdog
        conviction of a request stuck here names this peer."""
        kw = {}
        tid = tracing.current_trace_id()
        if tid and tracing.enabled():
            kw["metadata"] = ((TRACE_ID_MD, tid),
                              (PARENT_SPAN_MD,
                               str(tracing.current_span_id())))
        with flightrec.rpc_leg(self.peer_addr, method):
            if self.fault_check is not None:
                self.fault_check()
            if method in _BUDGET_FORWARDED:
                ctx = dl.current()
                if ctx is not None:
                    rem = ctx.remaining_s()
                    if rem is not None:
                        ctx.check(f"rpc.{method}")
                        try:
                            return rpc(req, timeout=rem, **kw)
                        except grpc.RpcError as e:
                            code = (e.code() if hasattr(e, "code")
                                    else None)
                            if code == \
                                    grpc.StatusCode.DEADLINE_EXCEEDED:
                                ctx.check(f"rpc.{method}")  # raises if dead
                                from dgraph_tpu.utils.metrics import \
                                    METRICS
                                METRICS.inc("deadline_exceeded_total",
                                            stage=f"rpc.{method}")
                                raise dl.DeadlineExceeded(
                                    f"budget expired inside {method} "
                                    f"RPC",
                                    stage=f"rpc.{method}") from e
                            raise
            return rpc(req, **kw)

    def query(self, dql: str, start_ts: int = 0) -> dict:
        import json
        resp = self._call(SERVICE_DGRAPH, "Query",
                          pb.Request(query=dql, start_ts=start_ts),
                          pb.Response)
        return json.loads(resp.json)

    def mutate(self, **kw) -> pb.MutationResp:
        return self._call(SERVICE_DGRAPH, "Mutate",
                          pb.MutationReq(**kw), pb.MutationResp)

    def alter(self, schema: str = "", drop_all: bool = False,
              drop_attr: str = "") -> None:
        self._call(SERVICE_DGRAPH, "Alter",
                   pb.Operation(schema=schema, drop_all=drop_all,
                                drop_attr=drop_attr),
                   pb.Payload)

    def commit_or_abort(self, start_ts: int,
                        abort: bool = False) -> pb.TxnContext:
        return self._call(SERVICE_DGRAPH, "CommitOrAbort",
                          pb.TxnContext(start_ts=start_ts, aborted=abort),
                          pb.TxnContext)

    def serve_task(self, **kw) -> pb.TaskResult:
        return self._call(SERVICE_WORKER, "ServeTask",
                          pb.TaskQuery(**kw), pb.TaskResult)

    def apply_mutation(self, mut_json: bytes, commit_ts: int,
                       origin: int = 0, prev_ts: int = 0,
                       stage: bool = False) -> None:
        self._call(SERVICE_WORKER, "ApplyMutation",
                   pb.MutationMsg(mut_json=mut_json, commit_ts=commit_ts,
                                  origin=origin, prev_ts=prev_ts,
                                  stage=stage),
                   pb.Payload)

    def ping(self) -> None:
        self._call(SERVICE_WORKER, "Ping", pb.Empty(), pb.Payload)

    def chain_head(self) -> tuple[int, int]:
        """(node_id, last broadcast ts) of the peer — read-gate probe."""
        r = self._call(SERVICE_WORKER, "ChainHead", pb.Empty(),
                       pb.AssignedIds)
        return int(r.start_id), int(r.end_id)

    def apply_decision(self, commit_ts: int, commit: bool,
                       origin: int = 0) -> None:
        self._call(SERVICE_WORKER, "ApplyDecision",
                   pb.DecisionMsg(commit_ts=commit_ts, commit=commit,
                                  origin=origin),
                   pb.Payload)

    def debug_traces(self, trace_id: str = "", n: int = 256) -> list:
        """Pull the peer's span registry (DebugTraces RPC): span dicts,
        one trace's spans when trace_id is given, else the recent ring."""
        import json as _json
        r = self._call(SERVICE_WORKER, "DebugTraces",
                       pb.Operation(schema=trace_id, drop_attr=str(n)),
                       pb.Payload)
        return _json.loads(bytes(r.data).decode())

    def debug_fleet(self) -> dict:
        """Pull the peer's fleet fragment (DebugFleet RPC): identity,
        metrics exposition, cost-digest state, breaker states,
        watchdog status, gate counts — one node's slice of
        /debug/fleet."""
        import json as _json
        r = self._call(SERVICE_WORKER, "DebugFleet", pb.Operation(),
                       pb.Payload)
        return _json.loads(bytes(r.data).decode())

    def debug_flight(self, n: int = 256) -> dict:
        """Pull the peer's flight-recorder snapshot (DebugFlight RPC):
        in-flight ops with stacks + spans, flight ring tail, watchdog
        state."""
        import json as _json
        r = self._call(SERVICE_WORKER, "DebugFlight",
                       pb.Operation(drop_attr=str(n)), pb.Payload)
        return _json.loads(bytes(r.data).decode())

    def fetch_log(self, since_ts: int):
        """Returns ([(ts, kind, obj)...], complete) mirroring wal.replay."""
        from dgraph_tpu.store.wal import mut_from_bytes
        r = self._call(SERVICE_WORKER, "FetchLog",
                       pb.FetchLogRequest(since_ts=since_ts), pb.LogRecords)
        out = []
        for rec in r.records:
            if rec.abort:
                out.append((int(rec.ts), "abort", None))
            elif rec.drop:
                out.append((int(rec.ts), "drop", None))
            elif rec.drop_attr:
                out.append((int(rec.ts), "drop_attr", rec.drop_attr))
            elif rec.schema:
                out.append((int(rec.ts), "schema", rec.schema))
            else:
                out.append((int(rec.ts), "mut",
                            mut_from_bytes(rec.mut_json)))
        return out, bool(r.complete)

    def apply_schema(self, schema_text: str, ts: int = 0, origin: int = 0,
                     prev_ts: int = 0) -> None:
        self._call(SERVICE_WORKER, "ApplyMutation",
                   pb.MutationMsg(schema=schema_text, commit_ts=ts,
                                  origin=origin, prev_ts=prev_ts),
                   pb.Payload)

    def apply_drop(self, ts: int = 0, origin: int = 0,
                   prev_ts: int = 0) -> None:
        self._call(SERVICE_WORKER, "ApplyMutation",
                   pb.MutationMsg(drop_all=True, commit_ts=ts,
                                  origin=origin, prev_ts=prev_ts),
                   pb.Payload)

    def apply_drop_attr(self, pred: str, ts: int = 0, origin: int = 0,
                        prev_ts: int = 0) -> None:
        self._call(SERVICE_WORKER, "ApplyMutation",
                   pb.MutationMsg(drop_attr=pred, commit_ts=ts,
                                  origin=origin, prev_ts=prev_ts),
                   pb.Payload)

    def pull_tablet(self, attr: str, src_addr: str) -> None:
        self._call(SERVICE_WORKER, "PullTablet",
                   pb.PullTabletRequest(attr=attr, src_addr=src_addr),
                   pb.Payload)

    def tablet_snapshot(self, attr: str, read_ts: int = 0):
        r = self._call(SERVICE_WORKER, "TabletSnapshot",
                       pb.TabletSnapshotRequest(attr=attr, read_ts=read_ts),
                       pb.TabletSnapshot)
        return bytes(r.blob), int(r.version)

    def close(self):
        self.channel.close()
