"""Alpha: the public API server facade (in-process form).

Reference parity: `edgraph/server.go` — `Server.Query`, `Server.Mutate`,
`Server.Alter`, `Server.CommitOrAbort` implementing the `api.Dgraph`
service — plus the worker-side mutation application those call into
(`worker/mutation.go` MutateOverNetwork → posting layer). Network
transports (HTTP/gRPC) wrap this object in `server/http.py` /
`server/task.py`; the query path itself runs the TPU engine.

Transactions follow the reference's client model: `txn = alpha.new_txn()`,
any number of `txn.query` / `txn.mutate` calls, then `txn.commit()` (Zero
arbitration; raises `TxnAborted` on conflict) or `txn.discard()`.
`commit_now=True` mutations are single-shot transactions; with
`commit_now=False` the server keeps the txn open, continued by start_ts
(reference: pb.TxnContext keys round-tripping + CommitOrAbort).
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field

from dgraph_tpu.cluster.oracle import Oracle, TxnAborted
from dgraph_tpu.utils import locks
from dgraph_tpu.engine import Engine
from dgraph_tpu.loader.chunker import NQuad, parse_json, parse_rdf
from dgraph_tpu.server.admission import ServerOverloaded
from dgraph_tpu.loader.xidmap import XidMap
from dgraph_tpu.store.mvcc import MVCCStore, Mutation
from dgraph_tpu.store.schema import parse_schema
from dgraph_tpu.store.store import Store
from dgraph_tpu.store.types import Kind, hash_password
from dgraph_tpu.utils import costprior, costprofile, flightrec, memgov
from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.utils import tracing
from dgraph_tpu.utils.metrics import METRICS

__all__ = ["Alpha", "Txn", "TxnAborted", "NoQuorum", "ReadUnavailable",
           "StageRefused"]


class NoQuorum(Exception):
    """Commit refused: a majority of the replica group did not durably
    log the record (reference: a raft proposal that cannot commit on the
    minority side of a partition). The write was NOT applied locally and
    the client must not treat it as acknowledged."""


class ReadUnavailable(Exception):
    """Read refused, RETRYABLE: this replica cannot verify that its
    snapshot at the read ts is gap-free (a group peer is unreachable and
    the reachable side is a minority, or a known replication gap could
    not be healed). The reference never hits this state — a raft
    follower only serves what its replicated log proves — so the safe
    answer is an explicit refusal, never a snapshot that never
    existed."""


class StageRefused(Exception):
    """Commit-quorum stage refused: this node has no armed WAL, so its
    ack would certify a durability it cannot provide (the coordinator
    counts stage acks toward the DURABILITY majority — a memory-only ack
    is a lie that loses acknowledged writes on crash). Real deployments
    (Alpha.open / cli) always arm the WAL; tests opt in explicitly via
    `allow_volatile_stage`."""

GC_EVERY = 256  # timestamps between oracle/store gc sweeps


def _register_tablet_cache(alpha) -> None:
    """Join the adapted-tablet cache to the process memory governor:
    previously an unbounded dict, now byte-accounted and evictable
    (oldest-inserted first — an evicted tablet refetches from its
    owner). Callbacks close over a weakref and take the Alpha's own
    state lock; the governor never holds its lock across them."""
    import weakref

    ref = weakref.ref(alpha)

    def nbytes():
        a = ref()
        if a is None:
            return 0
        with a._state_lock:
            vals = list(a._tablet_cache.values())
        return sum(memgov.estimate_nbytes(v) for v in vals)

    def evict_one():
        a = ref()
        if a is None:
            return 0
        with a._state_lock:
            if not a._tablet_cache:
                return 0
            v = a._tablet_cache.pop(next(iter(a._tablet_cache)))
        return memgov.estimate_nbytes(v)

    memgov.GOVERNOR.register("api.tablet", "host", nbytes, evict_one,
                             owner=alpha)


class Alpha:
    """Single-process data server: oracle + MVCC store + query engine."""

    def __init__(self, base: Store | None = None,
                 device_threshold: int = 512, wal=None, base_ts: int = 0,
                 oracle=None, groups=None, mesh=None):
        self.oracle = oracle if oracle is not None else Oracle()
        self.mvcc = MVCCStore(base=base, base_ts=base_ts)
        self.oracle.bump_ts(base_ts)
        self.xidmap = XidMap(self.oracle)
        self.device_threshold = device_threshold
        self.mesh = mesh  # jax.sharding.Mesh | None: served SPMD engine
        self.wal = wal  # store.wal.WAL | None: fsync'd commit log
        self.groups = groups  # cluster.groups.Groups | None
        # tablet freshness learned from the mutation broadcast: pred →
        # latest commit_ts anywhere; _stale_preds = foreign tablets whose
        # latest version this node has NOT applied locally
        self.tablet_versions: dict[str, int] = {}
        self._stale_preds: set[str] = set()
        self._tablet_cache: dict[tuple[str, int], object] = {}
        # broadcast chaining (replica catch-up): what we last APPLIED from
        # each origin node, what we last SENT, and peers that missed one of
        # our broadcasts (excluded from read failover until a later chained
        # broadcast succeeds — the receiver catches up before acking)
        self._last_from: dict[int, int] = {}
        self._last_sent_ts = 0
        self._suspect_peers: dict[str, int] = {}
        # detected-but-unhealed per-origin chain gaps: origin node id →
        # since_ts of the oldest record we may be missing from it. Reads
        # must heal these (FetchLog) or refuse (ReadUnavailable) before
        # serving — an applied record past a failed catch-up otherwise
        # hides the hole from prev_ts detection forever.
        self._origin_gaps: dict[int, int] = {}
        # read gate state: monotonic time of the last full chain
        # verification; read_lease_s > 0 lets reads inside the lease skip
        # re-probing (bounded-staleness trade, raft lease-read analog);
        # 0 = verify every read (strict default)
        self._read_verified_at = 0.0
        self.read_lease_s = 0.0
        # test-only opt-in: accept commit-quorum stages without an armed
        # WAL (the ack is then NOT crash-durable — see StageRefused)
        self.allow_volatile_stage = False
        # commit-quorum staging: ts → (Mutation, origin node id) durably
        # logged but undecided (raft "log entry below commit index")
        self._pending: dict[int, tuple[Mutation, int]] = {}
        # oldest ts the local WAL still covers (records at or below were
        # absorbed by a checkpoint); FetchLog answers "complete" only above
        self._wal_floor = base_ts
        self.remote_hop_max = 4096  # frontier cap for per-hop routing
        # slow-query log threshold in ms (0 = off; --slow_query_ms flag)
        self.slow_query_ms = 0.0
        self.acl = None  # server/acl.AclManager | None (enforcement on)
        # store/maintenance.MaintenanceScheduler | None: background
        # rollup/checkpoint/backup/export jobs (attach_maintenance)
        self.maintenance = None
        # server/admission.AdmissionController | None: bounded
        # concurrency + FIFO queue + shedding (attach_admission);
        # default_deadline_ms applies to requests with no explicit
        # budget (0 = unbounded, the historical behavior)
        self.admission = None
        self.default_deadline_ms = 0.0
        # cost-prior scheduling (utils/costprior.py, --cost_priors):
        # per-shape predicted cost feeds admission shedding/hints, the
        # batch planner's ordering, and Zero's placement heartbeat.
        # False restores the count/EMA-only behavior.
        self.cost_priors = True
        self._apply_lock = locks.make_lock("alpha.apply")
        self._state_lock = locks.make_lock("alpha.state")
        self._open_txns: dict[int, Txn] = {}
        self._active_reads: dict[int, int] = {}
        self._gc_tick = 0
        if base is not None and base.n_nodes:
            self.oracle.bump_uid(int(base.uids[-1]))
        locks.guarded(self, "alpha.state")
        _register_tablet_cache(self)

    @classmethod
    def open(cls, p_dir: str, device_threshold: int = 512,
             sync: bool = True, mesh=None,
             memory_budget: int | None = None) -> "Alpha":
        """Boot from a persistence dir: newest checkpoint + WAL replay
        (reference: Badger open + raft WAL restore on alpha start). Every
        commit that reached the WAL before a crash is recovered.

        `memory_budget` (bytes) opens the checkpoint OUT-OF-CORE:
        predicate tablets fault in from disk on first touch and evict
        LRU under the budget (reference: Badger's LSM — data exceeds
        RAM; SURVEY §5 "HBM is a cache, never the source of truth").
        Rollup, checkpoint, backup, and export stream tablet-at-a-time
        under the same budget (store/stream.py); the remaining
        full-materialization path is a READ above the newest fold
        point (kept shallow by the maintenance scheduler's rollup —
        see store/outofcore.py)."""
        import os

        from dgraph_tpu.store import checkpoint

        base, base_ts = None, 0
        if checkpoint.exists(p_dir):
            if memory_budget is not None:
                from dgraph_tpu.store.outofcore import open_out_of_core
                base, base_ts = open_out_of_core(p_dir, memory_budget)
            else:
                base, base_ts = checkpoint.load(p_dir)
        wal_path = os.path.join(p_dir, "wal.log")
        alpha = cls(base=base, device_threshold=device_threshold,
                    base_ts=base_ts, mesh=mesh)
        if base is not None and hasattr(base.preds, "heal_cb"):
            # out-of-core: a tablet fault that fails its integrity
            # check (StorageCorruption) heals from a group replica once
            # this alpha joins a cluster; single-node it stays a typed
            # refusal naming the file
            base.preds.heal_cb = alpha._heal_corrupt_tablet
        max_ts, max_uid = alpha.attach_wal(wal_path, sync=sync)
        alpha.oracle.bump_ts(max_ts)
        if max_uid:
            alpha.oracle.bump_uid(max_uid)
        # cost-profile continuity: merge the aggregate the previous run
        # persisted next to the checkpoint (digest merge is exact, so
        # restart never resets the cost dataset)
        costprofile.load(os.path.join(p_dir, "costprofiles.json"))
        # cost-prior continuity: merge the persisted prior model, then
        # fill in any shapes the digests know that the model doesn't
        # (overwrite=False keeps the merged incremental refinements)
        costprior.load(os.path.join(p_dir, "costpriors.json"))
        costprior.refit(overwrite=False)
        return alpha

    def attach_wal(self, wal_path: str, sync: bool = True) -> tuple[int, int]:
        """Replay + arm an existing WAL on this Alpha — the boot leg
        shared by Alpha.open and cluster-mode start (a node whose stage
        acks certified durability MUST recover its log on restart).
        Resolves pend/dec staging inline (a pend applies at its dec:1
        position — the commit-index analog), re-arms undecided pends,
        seeds the broadcast chain, then opens the WAL for appends.
        Returns (max_ts, max_uid) seen, for oracle / Zero watermark
        seeding by the caller."""
        from dgraph_tpu.store.schema import parse_schema
        from dgraph_tpu.store.wal import WAL, replay

        base_ts = self.mvcc.base_ts
        max_ts, max_uid = base_ts, 0
        # one decode pass: resolve pend/dec staging inline and remember
        # unresolved pends for re-arming below. Records resolved FROM a
        # pend are flagged: a pend that survived a checkpoint truncate
        # was undecided then, so the checkpoint does NOT contain it —
        # it must apply even when its ts is at or below base_ts
        # (straggler absorption), where a plain record would be skipped.
        pends: dict[int, Mutation] = {}
        resolved = []
        for ts, kind, obj in replay(wal_path):
            if kind == "pend":
                pends[ts] = obj
                continue
            if kind == "dec":
                mut = pends.pop(ts, None)
                if obj and mut is not None:
                    resolved.append((ts, "mut", mut, True))
                continue
            resolved.append((ts, kind, obj, False))
        for ts, kind, obj, from_pend in resolved:
            if ts <= base_ts and not from_pend:
                continue  # checkpoint already absorbed it
            if kind == "schema":
                merged = self.mvcc.schema.clone()
                merged.update(parse_schema(obj))
                self.mvcc.rebuild_base(schema=merged)
            elif kind == "drop":
                self.mvcc = MVCCStore()
                self.xidmap = XidMap(self.oracle)
            elif kind == "drop_attr":
                self.mvcc.drop_predicate(obj, ts)
            elif self.mvcc.has_applied(ts):
                continue  # duplicate record (catch-up raced a broadcast)
            else:
                try:
                    self.mvcc.apply(obj, ts)
                except ValueError:
                    # quorum-committed below the checkpoint fold (staged
                    # before the checkpoint, decided after): fold it in
                    self.mvcc.absorb_straggler(obj, ts)
                for s, _p, o, *_ in obj.edge_sets:
                    max_uid = max(max_uid, s, o)
                for s, _p, *_ in (obj.edge_dels + obj.val_sets
                                  + obj.val_dels):
                    max_uid = max(max_uid, s)
            max_ts = max(max_ts, ts)
        # seed the broadcast chain at the replayed horizon: prev_ts on our
        # first post-restart broadcast must not regress to 0 (a receiver
        # would miss the gap check); a too-HIGH prev only triggers a
        # harmless spurious catch-up on peers
        self._last_sent_ts = max_ts
        # re-arm undecided staged records (still durable, still
        # invisible): a peer's decision marker or catch-up resolves them
        # post-restart; origin 0 = unknown after restart. Under the
        # state lock: attach_wal runs at boot, but a cluster restart
        # can already be receiving chained broadcasts on gRPC threads
        with self._state_lock:
            for ts, mut in pends.items():
                if not self.mvcc.has_applied(ts):
                    self._pending[ts] = (mut, 0)
        self.wal = WAL(wal_path, sync=sync)
        return max_ts, max_uid

    def checkpoint_to(self, p_dir: str, pace=None) -> int:
        """Fold all committed state into an on-disk checkpoint and drop the
        WAL records it absorbed. Returns the checkpoint base_ts.

        On an out-of-core base the fold streams tablet-at-a-time
        (store/stream.py) OUTSIDE the apply lock — applies land above
        the fold's upto_ts and stay as delta layers; a straggler below
        it aborts the install (FoldRaced) and the caller retries. Only
        the WAL truncate serializes with appliers."""
        from dgraph_tpu.store import checkpoint, stream
        lazy = stream.lazy_preds(self.mvcc.base)
        if lazy is not None:
            ts = stream.checkpoint_streaming(
                self.mvcc, p_dir, lazy.budget_bytes, pace=pace,
                job="checkpoint")
            with self._apply_lock:
                if self.wal is not None:
                    self.wal.truncate(ts)
                self._wal_floor = max(self._wal_floor, ts)
            self._save_costprofiles(p_dir)
            return ts
        with self._apply_lock:
            store = self.mvcc.rollup()
            ts = self.mvcc.base_ts
            # versioned write + atomic CURRENT flip: a crash mid-save
            # leaves the previous snapshot intact; the WAL is only
            # truncated after the flip succeeded
            checkpoint.save_versioned(store, p_dir, base_ts=ts)
            if self.wal is not None:
                self.wal.truncate(ts)
            # graftlint: allow(split-critical-section): exclusive branches — the streaming path RETURNED above; the two acquisitions never run in one call
            self._wal_floor = max(self._wal_floor, ts)
        self._save_costprofiles(p_dir)
        return ts

    @staticmethod
    def _save_costprofiles(p_dir: str) -> None:
        """Persist the cost-profile aggregate and the fitted priors
        beside the checkpoint (best effort — cost history is
        telemetry, never worth failing a checkpoint over)."""
        import os
        with contextlib.suppress(OSError):
            costprofile.save(os.path.join(p_dir, "costprofiles.json"))
        with contextlib.suppress(OSError):
            costprior.save(os.path.join(p_dir, "costpriors.json"))

    def maintenance_rollup(self, p_dir: str | None = None,
                           pace=None) -> int:
        """Fold pending delta layers into a new fold point — the
        background rollup job (reference: posting-list Rollup). In-core:
        the existing in-memory fold. Out-of-core: the fold is STREAMED
        to a new ckpt dir under `p_dir` (default: the dir the base was
        opened from) and reopened lazily, so the budget holds — an
        out-of-core fold point has to live on disk, exactly as Badger's
        rollup writes back to the LSM. Returns the new fold ts."""
        from dgraph_tpu.store import stream
        lazy = stream.lazy_preds(self.mvcc.base)
        if lazy is None:
            self.mvcc.rollup()
            return self.mvcc.base_ts
        root = p_dir if p_dir is not None else lazy.root_dir
        return stream.checkpoint_streaming(
            self.mvcc, root, lazy.budget_bytes, pace=pace, job="rollup")

    def export_to(self, out_path: str, format: str = "rdf",
                  pace=None) -> int:
        """Dump committed state as RDF N-Quads or JSON (reference:
        worker/export.go streaming every tablet at a read ts). Streams
        tablet-at-a-time on an out-of-core base; pending delta layers
        are folded first (a read_view above the fold would materialize
        everything at once)."""
        from dgraph_tpu.server.export import export_json, export_rdf
        if self.mvcc.layers:
            self.maintenance_rollup(pace=pace)
        store = self.mvcc.base
        with open(out_path, "w") as f:
            n = (export_json if format == "json" else export_rdf)(
                store, f, pace=pace)
        return n

    def attach_maintenance(self, p_dir: str, *, rollup_after: int = 0,
                           checkpoint_every_s: float = 0.0,
                           pacing_ms: float = 0.0):
        """Start the background maintenance scheduler on this Alpha
        (store/maintenance.py): rollup-when-deep, periodic checkpoint,
        requested backup/export — paced, budget-bounded, pausable."""
        from dgraph_tpu.store.maintenance import MaintenanceScheduler
        self.maintenance = MaintenanceScheduler(
            self, p_dir, rollup_after=rollup_after,
            checkpoint_every_s=checkpoint_every_s,
            pacing_ms=pacing_ms).start()
        return self.maintenance

    def attach_admission(self, max_inflight: int, queue_depth: int,
                         default_deadline_ms: float = 0.0):
        """Arm admission control on this Alpha (server/admission.py):
        per-lane token limits, a bounded FIFO wait queue, and shedding
        with a retryable `ServerOverloaded`. `default_deadline_ms`
        budgets requests that bring none of their own."""
        from dgraph_tpu.server.admission import AdmissionController
        self.admission = AdmissionController(max_inflight, queue_depth)
        self.default_deadline_ms = float(default_deadline_ms)
        return self.admission

    @contextlib.contextmanager
    def _request(self, lane: str, deadline_ms: float | None,
                 query_text: str | None = None):
        """Request-lifecycle shell every public entrypoint runs inside:
        establish the budget (explicit deadline_ms, else the configured
        default), install it as the thread's ambient context
        (utils/deadline.py — hot-loop checkpoints + RPC budget
        forwarding find it there), and hold an admission token for the
        duration. A nested server call (a txn read issued inside an
        already-admitted request) reuses the enclosing context: the
        OUTER budget governs, and no second token is taken — a full
        lane must never deadlock against its own request.

        With cost priors armed (`cost_priors` + utils/costprior.py) and
        a `query_text`, the request's cost is PREDICTED before admission
        (shape memo → per-shape prior, lane EMA fallback) and rides the
        admission decision; completed requests feed the observed cost
        back (text→shape memo + incremental prior refit), and a shed
        records its prediction into the cost profile so shed precision
        is measurable after the fact."""
        outer = dl.current()
        if outer is not None:
            # nested leg on the outer recorder: frame the launch-gap
            # baseline so the leg boundary (parse/apply work, not
            # dispatch overhead) is never billed as a launch gap
            with costprofile.launch_frame():
                yield outer
            return
        if deadline_ms is None and self.default_deadline_ms:
            deadline_ms = self.default_deadline_ms
        ctx = dl.RequestContext(deadline_ms)
        # cost profile opens BEFORE admission so queue wait is part of
        # the record; outcomes (ok/shed/deadline/cancelled/error)
        # classify at close (utils/costprofile.py)
        with dl.activate(ctx), costprofile.profile(lane):
            predicted = source = None
            priors_on = self.cost_priors and costprior.enabled()
            if priors_on and query_text is not None:
                predicted, source = costprior.predict(
                    lane, text=query_text)
            t0 = time.perf_counter()
            completed = False
            try:
                # flight-recorder registration (utils/flightrec.py):
                # the watchdog walks this entry — a request running
                # far past `predicted` (or wedged past its deadline)
                # is convicted and dumped with its stack, with no
                # operator watching
                with flightrec.track_request(ctx, lane,
                                             predicted_us=predicted,
                                             query=query_text):
                    if self.admission is not None:
                        with self.admission.admit(lane, ctx,
                                                  cost_us=predicted):
                            # budget may have died while queued
                            ctx.check("admission")
                            yield ctx
                    else:
                        yield ctx
                completed = True
            except (ServerOverloaded, dl.Cancelled, PermissionError):
                # not error-budget burn: a shed is the shed_rate SLO's
                # event, a cancel is the client's, auth is the caller's
                raise
            except Exception:
                # every other escape is a failed serve, whatever the
                # transport — the error_rate SLO's bad-event count
                # (utils/slo.py) must see gRPC and embedded callers,
                # not just the HTTP handler's 400 path
                METRICS.inc("query_errors_total", lane=lane)
                raise
            finally:
                if predicted is not None:
                    # predicted-vs-actual joins the cost record (a shed
                    # keeps its prediction with outcome="shed")
                    costprofile.note("predicted_us", int(predicted))
                if completed and priors_on and query_text is not None:
                    rec = costprofile.active()
                    costprior.learn(
                        lane, query_text,
                        rec.shape_key() if rec is not None else None,
                        (time.perf_counter() - t0) * 1e6,
                        predicted_us=predicted, source=source)

    def shutdown(self, p_dir: str | None = None) -> None:
        """Drain maintenance (finish the in-flight + requested jobs),
        then take a final checkpoint — the clean-exit path the CLI runs
        on SIGINT."""
        if self.maintenance is not None:
            self.maintenance.stop(drain=True)
        if p_dir is not None:
            self.checkpoint_to(p_dir)

    # -- public api surface (api.Dgraph analog) -----------------------------
    def new_txn(self) -> "Txn":
        txn = Txn(self)
        with self._state_lock:
            self._open_txns[txn.start_ts] = txn
        return txn

    def txn(self, start_ts: int) -> "Txn":
        """Continue a server-held open transaction by start_ts."""
        with self._state_lock:
            t = self._open_txns.get(start_ts)
        if t is None:
            raise TxnAborted(f"no open txn at start_ts {start_ts}")
        return t

    @contextlib.contextmanager
    def _reading(self, ts: int | None = None):
        """Track in-flight reads so gc never drops a snapshot under them.

        The ts is issued OUTSIDE the state lock — in cluster mode that is
        a gRPC round-trip to Zero, and holding the Alpha-wide lock across
        it would serialize every read behind network latency. The gc race
        (a sweep running between issuance and registration) is closed by
        re-checking the mvcc floor after registering: if the snapshot was
        collected under us, unregister and retry with a fresh ts (the new
        ts is ≥ every commit the sweep could have folded)."""
        issued = ts is None
        for attempt in range(8):
            if issued:
                ts = self.oracle.read_only_ts()
            with self._state_lock:
                self._active_reads[ts] = self._active_reads.get(ts, 0) + 1
            # last attempt keeps its registration either way: read_view
            # raises a clear error if the snapshot truly is gone
            if (not issued or attempt == 7
                    or self.mvcc.floor_ts() <= ts):
                break
            with self._state_lock:
                # graftlint: allow(split-critical-section): the register/recheck/unregister retry protocol documented above — each acquisition is an independent refcount step, and the gc race it exists to close is re-checked per attempt
                self._active_reads[ts] -= 1
                if not self._active_reads[ts]:
                    del self._active_reads[ts]
        try:
            yield ts
        finally:
            with self._state_lock:
                # graftlint: allow(split-critical-section): refcount release — the earlier read registered this ts; decrementing in its own acquisition is the protocol, not check-then-act
                self._active_reads[ts] -= 1
                if not self._active_reads[ts]:
                    del self._active_reads[ts]

    def _query_view(self, ts: int, acl_user: str | None):
        """Store view a query at `ts` executes against (MVCC snapshot →
        tablet routing → ACL restriction, in that order)."""
        store = self.mvcc.read_view(ts)
        if self.groups is not None:
            from dgraph_tpu.cluster.routed import routed_view
            store = routed_view(self, store, ts)
        if self.acl is not None and acl_user is not None:
            store = self.acl.readable_view(acl_user, store)
        return store

    def _verify_read_chains(self, ts: int) -> None:
        """Partition-safe read gate (reference: a raft follower never
        serves a log state that did not exist). Before a read at `ts` is
        served, every group peer's broadcast chain must be verifiably
        gap-free: the peer's chain head (last ts it broadcast) is
        compared against the last record this node APPLIED from it, and
        any missed tail is pulled via FetchLog BEFORE the read runs.
        Recorded gaps (`_origin_gaps` — a receive-time catch-up that
        failed) must heal the same way.

        Undecided FOREIGN pends are part of the bar, not an exception:
        a staged record whose DecisionMsg was lost may already be
        client-acked — the decision is durable in the coordinator's WAL
        — and serving the pre-commit state would hand a read-modify-
        write txn a lost update (the seeded partition fuzz catches
        exactly this: the stale read predates the commit's ts, so
        conflict detection cannot). The gate resolves such pends
        through the origin's (or any reachable peer's) resolved log; a
        pend that stays unresolved with its origin REACHABLE is
        genuinely undecided — not acked before this read began — and
        may be invisibly skipped.

        An unreachable peer leaves its chain unverifiable. If the
        reachable part of the group (counting this node) is still a
        MAJORITY, the missed tails are pulled from the reachable peers'
        resolved logs instead — every client-acked commit is resolved
        in its coordinator's WAL, and majority staging puts it on at
        least one reachable node. But a pend whose UNREACHABLE origin
        may hold the only copy of its decision blocks the read
        (ReadUnavailable) — the alternative is the lost update above.
        On the minority side nothing can be verified: the read raises
        ReadUnavailable (retryable) rather than serve a snapshot that
        never existed.

        `read_lease_s` bounds probe cost: a successful verification
        stays valid that long (0 = verify every read, strict; a
        positive lease explicitly trades bounded staleness inside the
        window for fewer probes)."""
        if self.groups is None:
            return
        replicas = [a for a in self.groups.group_addrs(self.groups.gid)
                    if a != self.groups.my_addr]
        if not replicas:
            return
        import time as _time
        with self._state_lock:
            gaps = dict(self._origin_gaps)
            fresh = (self.read_lease_s > 0
                     and _time.monotonic() - self._read_verified_at
                     <= self.read_lease_s)
        if fresh and not gaps:
            return
        import grpc as _grpc
        majority = (len(replicas) + 1) // 2 + 1
        my_node = self.groups.node_id
        with self._state_lock:
            pend_origins = {org for _t, (_m, org) in self._pending.items()
                            if org and org != my_node}
        unreachable: dict[str, int | None] = {}
        reachable: list[str] = []
        for addr in replicas:
            # per-peer probe budget gate: a read whose deadline died
            # mid-verification raises HERE (retryable), with no chain
            # state half-advanced — _last_from/_origin_gaps only move
            # after a completed catch-up
            dl.checkpoint("chain_head")
            t0 = _time.perf_counter()
            try:
                node, head = self.groups.pool(addr).chain_head()
            except _grpc.RpcError:
                unreachable[addr] = self.groups.node_of_addr(addr)
                continue
            METRICS.observe("rpc_latency_us",
                            (_time.perf_counter() - t0) * 1e6,
                            rpc="chain_head")
            reachable.append(addr)
            if not node:
                continue  # peer not in cluster mode: no chain to check
            last = self._last_from.get(node, 0)
            if head <= last and node not in gaps \
                    and node not in pend_origins:
                continue
            since = min(last, gaps.get(node, last))
            if node in pend_origins:
                # a lost-decision pend resolves from the origin's log;
                # pull from below the oldest pend so the decision (or
                # abort marker) is in the stream
                with self._state_lock:
                    pts = [t for t, (_m, org) in self._pending.items()
                           if org == node]
                if pts:
                    since = min(since, min(pts) - 1)
            try:
                _complete, seen = self.catch_up(addr, since_ts=since)
            except _grpc.RpcError:
                unreachable[addr] = node
                reachable.pop()
                continue
            pend_origins.discard(node)  # resolved, or truly undecided
            with self._state_lock:
                # graftlint: allow(split-critical-section): the pop lands only after a COMPLETED catch-up covering everything this gap recorded; a gap recorded concurrently re-arms on the next chained receive or read probe
                self._origin_gaps.pop(node, None)
            gaps.pop(node, None)
            if seen >= head:
                # the probed head itself came back resolved: everything
                # the peer ever broadcast is applied here — advance the
                # chain so the next read (and the next chained receive)
                # doesn't re-pull. A head still pending on the peer
                # (stage leg sent, decision unwritten) must NOT advance:
                # that would hide the record from gap detection.
                self._last_from[node] = max(
                    self._last_from.get(node, 0), head)
        if unreachable:
            if 1 + len(reachable) < majority:
                METRICS.inc("read_unavailable_total", reason="minority")
                raise ReadUnavailable(
                    f"read at ts {ts}: replica(s) "
                    f"{sorted(unreachable)} unreachable and the "
                    f"reachable side is a minority of the group — "
                    f"cannot verify the snapshot is gap-free; retry")
            # majority fallback: pull the unreachable origins' tails
            # from the reachable peers' resolved logs
            floors = [self._last_from.get(n, 0)
                      for n in unreachable.values() if n is not None]
            floors += [gaps[n] for n in list(gaps)
                       if n in set(unreachable.values())]
            # a pend whose unreachable origin may hold the only copy of
            # its decision must ALSO pull from below the pend
            dead_nodes = {n for n in unreachable.values()
                          if n is not None}
            with self._state_lock:
                dead_pts = [t for t, (_m, org) in self._pending.items()
                            if org in dead_nodes]
            if dead_pts:
                floors.append(min(dead_pts) - 1)
            since = min(floors, default=0)
            healed = False
            for addr in reachable:
                try:
                    self.catch_up(addr, since_ts=since)
                    healed = True
                except _grpc.RpcError:
                    continue
            if healed:
                # the unreachable origin's tail was served by a
                # DIFFERENT replica — the fetch_log failover leg
                METRICS.inc("failover_total", rpc="fetch_log")
            if not healed:
                METRICS.inc("read_unavailable_total",
                            reason="heal_failed")
                raise ReadUnavailable(
                    f"read at ts {ts}: could not pull the tail of "
                    f"unreachable replica(s) {sorted(unreachable)} "
                    f"from any reachable peer; retry")
            with self._state_lock:
                still = [t for t, (_m, org) in self._pending.items()
                         if org in dead_nodes]
            if still:
                # the decision for these staged records may exist only
                # in the unreachable coordinator's WAL: serving without
                # them risks a lost update (stale read below the
                # commit's ts — conflict detection cannot catch it)
                METRICS.inc("read_unavailable_total",
                            reason="undecided_pend")
                raise ReadUnavailable(
                    f"read at ts {ts}: staged record(s) {sorted(still)} "
                    f"from unreachable coordinator(s) are undecided "
                    f"here; retry")
        else:
            with self._state_lock:
                # graftlint: allow(split-critical-section): monotonic freshness stamp — whichever verification finishes last wins, and any concurrent write only ADVANCES the lease; no decision was made on the earlier read
                self._read_verified_at = _time.monotonic()

    def query(self, dql: str, variables: dict | None = None,
              read_ts: int | None = None,
              acl_user: str | None = None,
              deadline_ms: float | None = None) -> dict:
        """Read-only query at a snapshot (reference: Server.Query with
        best-effort/read-only txn). With ACL enabled and an acl_user,
        unreadable predicates are invisible (reference: query rewriting
        drops unauthorized predicates). `deadline_ms` bounds the whole
        request — engine hot loops and RPC legs checkpoint against it
        and raise a retryable `DeadlineExceeded` within one level/BFS
        iteration of the budget."""
        with self._request("read", deadline_ms, query_text=dql):
            with self._reading(read_ts) as ts:
                self._verify_read_chains(ts)
                store = self._query_view(ts, acl_user)
                out = Engine(store,
                             device_threshold=self.device_threshold,
                             mesh=self.mesh).query(dql, variables)
        self._maybe_gc()
        return out

    def query_raw(self, dql: str, variables: dict | None = None,
                  read_ts: int | None = None,
                  acl_user: str | None = None,
                  deadline_ms: float | None = None) -> bytes:
        """Serving-path query: response BYTES via the native JSON emitter
        (engine/emit.py), never a Python object tree (reference:
        outputnode.go ToJson writes bytes straight into the response)."""
        with self._request("read", deadline_ms, query_text=dql):
            with self._reading(read_ts) as ts:
                self._verify_read_chains(ts)
                store = self._query_view(ts, acl_user)
                raw = Engine(store,
                             device_threshold=self.device_threshold,
                             mesh=self.mesh).query_bytes(dql, variables)
        self._maybe_gc()
        return raw

    def query_batch(self, dqls: list, read_ts: int | None = None,
                    acl_user: str | None = None,
                    deadline_ms: float | None = None) -> list:
        """Serve MANY queries at once: structurally-compatible @recurse
        batches execute as ONE lane-packed kernel launch (the north-star
        throughput path, engine/batch.py); everything else falls back to
        per-query execution. Returns one JSON dict per query, in order."""
        from dgraph_tpu.engine.batch import (order_plans_by_cost,
                                             plan_batch_groups_cached,
                                             run_batch)

        # the batch's scheduler key is the joined texts (one combined
        # shape; repeated dashboard batches hit the same prior)
        with self._request("read", deadline_ms,
                           query_text="\x1e".join(dqls)), \
                self._reading(read_ts) as ts:
            self._verify_read_chains(ts)
            store = self._query_view(ts, acl_user)
            from dgraph_tpu.utils import logging as xlog
            results: list = [None] * len(dqls)
            leftover = list(range(len(dqls)))
            try:
                # parse isolation + plan memoization live in the cached
                # planner: a syntax error sends THAT query to the
                # per-query path (which reproduces its error object),
                # and a repeated batch of identical texts skips parse +
                # plan_batch_groups entirely (plan_cache_hits_total)
                plans, leftover = plan_batch_groups_cached(store, dqls)
                leftover = list(leftover)   # cached list: never mutate
                # cost-packed launch order: predicted-expensive kernel
                # groups first (LPT — shorter makespan under deadlines);
                # a copy, never the cached list (engine/batch.py)
                plans = order_plans_by_cost(
                    plans, enabled=self.cost_priors)
                # each compatible group is ONE lane-kernel launch; a
                # failing group degrades to per-query, not to a failed
                # batch
                for plan, idxs in plans:
                    try:
                        out = run_batch(store, plan,
                                        self.device_threshold)
                    except (dl.DeadlineExceeded, dl.Cancelled):
                        raise  # the whole request's budget died
                    except Exception:  # noqa: BLE001 — optimization only
                        xlog.get("alpha").debug(
                            "batch group failed; per-query fallback",
                            exc_info=True)
                        out = None
                    if out is None:
                        leftover.extend(idxs)
                        continue
                    for i, o in zip(idxs, out):
                        results[i] = o
                leftover.sort()
            except (dl.DeadlineExceeded, dl.Cancelled):
                raise
            except Exception:  # noqa: BLE001 — batch is an optimization
                xlog.get("alpha").debug("batch plan failed; per-query "
                                        "fallback", exc_info=True)
                leftover = list(range(len(dqls)))
            # per-query fallback with per-query error isolation: one bad
            # query yields an error OBJECT in its slot, never a failed
            # batch (the other results still return) — but a dead
            # REQUEST budget fails the batch: grinding through the
            # remaining queries would defeat the deadline's point
            eng = Engine(store, device_threshold=self.device_threshold,
                         mesh=self.mesh)
            for i in leftover:
                try:
                    results[i] = eng.query(dqls[i])
                except (dl.DeadlineExceeded, dl.Cancelled):
                    raise
                except Exception as e:  # noqa: BLE001
                    results[i] = {"errors": [{"message": str(e)}]}
        self._maybe_gc()
        return results

    def mutate(self, *, set_nquads: str | None = None,
               del_nquads: str | None = None,
               set_json=None, del_json=None,
               commit_now: bool = True,
               start_ts: int | None = None,
               acl_user: str | None = None,
               deadline_ms: float | None = None) -> dict:
        """Mutation RPC. With start_ts: continue that open txn. With
        commit_now=False: leave the txn open and return its start_ts
        (reference: Server.Mutate + CommitNow flag). The deadline stops
        the request only BEFORE the two-phase stage begins; once
        staging starts the decision protocol runs to completion (an
        interrupt between stage and decide would leak an undecided
        pend)."""
        with self._request("mutate", deadline_ms):
            return self._mutate(set_nquads=set_nquads,
                                del_nquads=del_nquads,
                                set_json=set_json, del_json=del_json,
                                commit_now=commit_now,
                                start_ts=start_ts, acl_user=acl_user)

    def _mutate(self, *, set_nquads=None, del_nquads=None, set_json=None,
                del_json=None, commit_now=True, start_ts=None,
                acl_user=None) -> dict:
        created = not start_ts
        txn = self.txn(start_ts) if start_ts else self.new_txn()
        try:
            uids = txn.mutate(set_nquads=set_nquads, del_nquads=del_nquads,
                              set_json=set_json, del_json=del_json)
            if self.acl is not None and acl_user is not None:
                m = txn.mutation
                touched = {e[1] for e in (m.edge_sets + m.edge_dels
                                          + m.val_sets + m.val_dels)}
                self.acl.check_mutation(acl_user, touched)
            if commit_now:
                txn.commit()
            return {"uids": uids,
                    "txn": {"start_ts": txn.start_ts,
                            "commit_ts": txn.commit_ts}}
        except TxnAborted:
            txn.discard()
            raise
        except PermissionError:
            # an ACL denial leaves forbidden edits in the buffer — the
            # whole txn dies, continued or not
            txn.discard()
            raise
        except Exception:
            # a newly-created txn whose start_ts never reached the client
            # can never be discarded by them — it would pin the gc
            # watermark forever; only a continued txn survives an error
            if commit_now or created:
                txn.discard()
            raise

    def _bind_upsert_vars(self, txn: "Txn", query_src: str,
                          acl_user: str | None = None):
        """Run the upsert's query at the txn's read snapshot and convert
        the executor's rank-space var bindings to uid space."""
        import numpy as np

        from dgraph_tpu.dql.parser import parse_schema_query
        if parse_schema_query(query_src) is not None:
            raise ValueError("schema{} queries cannot drive an upsert")
        with self._reading(txn.start_ts) as ts:
            self._verify_read_chains(ts)
            store = self.mvcc.read_view(ts)
            if self.groups is not None:
                from dgraph_tpu.cluster.routed import routed_view
                store = routed_view(self, store, ts)
            if self.acl is not None and acl_user is not None:
                store = self.acl.readable_view(acl_user, store)
            # the upsert's query leg is a nested sub-request on the
            # mutate recorder: its own launch-gap frame
            with costprofile.launch_frame():
                out, ex = Engine(
                    store, device_threshold=self.device_threshold,
                    mesh=self.mesh).query_with_vars(query_src)
        uid_vars = {
            name: store.uid_of(np.asarray(ranks, np.int32)).tolist()
            for name, ranks in ex.uid_vars.items()}
        val_vars = {}
        for name, env in ex.val_vars.items():
            ranks = np.fromiter(env.keys(), np.int32, len(env))
            uids = store.uid_of(ranks)
            val_vars[name] = dict(zip(uids.tolist(), env.values()))
        counts = {n: len(u) for n, u in uid_vars.items()}
        for n, env in val_vars.items():
            counts.setdefault(n, len(env))
        return out, uid_vars, val_vars, counts

    def _check_txn_acl(self, txn: "Txn", acl_user: str | None) -> None:
        """Write-permission check over everything buffered in a txn (the
        upsert paths route here; plain mutations check inline)."""
        if self.acl is None or acl_user is None:
            return
        m = txn.mutation
        touched = {e[1] for e in (m.edge_sets + m.edge_dels
                                  + m.val_sets + m.val_dels)}
        self.acl.check_mutation(acl_user, touched)

    def _run_upsert(self, commit_now: bool, start_ts: int | None,
                    run, deadline_ms: float | None = None) -> dict:
        """Txn bookkeeping shared by the RDF and JSON upsert forms;
        `run(txn)` performs query + substitution + buffered mutates and
        returns (queries_json, uids, applied)."""
        with self._request("mutate", deadline_ms):
            return self._run_upsert_body(commit_now, start_ts, run)

    def _run_upsert_body(self, commit_now: bool, start_ts: int | None,
                         run) -> dict:
        created = not start_ts
        txn = self.txn(start_ts) if start_ts else self.new_txn()
        try:
            out, uids, applied = run(txn)
            if commit_now:
                txn.commit()
            return {"uids": uids, "queries": out, "applied": applied,
                    "txn": {"start_ts": txn.start_ts,
                            "commit_ts": txn.commit_ts}}
        except TxnAborted:
            txn.discard()
            raise
        except Exception:
            if commit_now or created:
                txn.discard()
            raise

    def upsert(self, src: str, commit_now: bool = True,
               start_ts: int | None = None,
               acl_user: str | None = None,
               deadline_ms: float | None = None) -> dict:
        """Upsert block: run the query at the txn's read_ts, bind vars,
        evaluate @if conditions, substitute uid(v)/val(v) into the
        mutations, commit through the normal conflict path (reference:
        edgraph upsert semantics, SURVEY L10)."""
        from dgraph_tpu.dql.upsert import (eval_cond, parse_upsert,
                                           substitute)

        req = parse_upsert(src)

        def run(txn):
            out, uid_vars, val_vars, counts = self._bind_upsert_vars(
                txn, req.query_src, acl_user)
            uids: dict[str, str] = {}
            applied = 0
            for m in req.mutations:
                if not eval_cond(m.cond, counts):
                    continue
                set_rdf = substitute(m.set_rdf, uid_vars, val_vars)
                del_rdf = substitute(m.del_rdf, uid_vars, val_vars)
                if set_rdf or del_rdf:
                    uids.update(txn.mutate(set_nquads=set_rdf or None,
                                           del_nquads=del_rdf or None))
                    applied += 1
            self._check_txn_acl(txn, acl_user)
            return out, uids, applied

        return self._run_upsert(commit_now, start_ts, run,
                                deadline_ms=deadline_ms)

    def upsert_json(self, query: str, cond: str = "",
                    set_json=None, del_json=None, commit_now: bool = True,
                    start_ts: int | None = None,
                    acl_user: str | None = None,
                    deadline_ms: float | None = None) -> dict:
        """The HTTP JSON upsert form: {"query", "cond", "set"/"delete" as
        JSON mutation lists with uid(v)/val(v) references} (reference:
        Dgraph HTTP /mutate JSON upsert)."""
        from dgraph_tpu.dql.upsert import (_parse_cond, eval_cond,
                                           substitute_json)

        cond_tree = None
        if cond:
            inner = cond.strip()
            if inner.startswith("@if"):
                inner = inner[3:].strip()
            cond_tree = _parse_cond(inner)

        def run(txn):
            out, uid_vars, val_vars, counts = self._bind_upsert_vars(
                txn, query, acl_user)
            uids: dict[str, str] = {}
            applied = 0
            if eval_cond(cond_tree, counts):
                set_sub = (substitute_json(set_json, uid_vars, val_vars)
                           if set_json else None)
                del_sub = (substitute_json(del_json, uid_vars, val_vars)
                           if del_json else None)
                if set_sub or del_sub:
                    uids.update(txn.mutate(set_json=set_sub or None,
                                           del_json=del_sub or None))
                    applied += 1
            self._check_txn_acl(txn, acl_user)
            return out, uids, applied

        return self._run_upsert(commit_now, start_ts, run,
                                deadline_ms=deadline_ms)

    def commit_or_abort(self, start_ts: int, abort: bool = False,
                        deadline_ms: float | None = None) -> int:
        """reference: Server.CommitOrAbort. Returns commit_ts (0 on abort)."""
        with self._request("mutate", deadline_ms):
            txn = self.txn(start_ts)
            if abort:
                txn.discard()
                return 0
            return txn.commit()

    def alter(self, schema_text: str) -> None:
        """Schema mutation + index rebuild (reference: Server.Alter →
        schema.Update + posting.RebuildIndex). The new snapshot is built
        under the merged schema and swapped in atomically, so concurrent
        queries see either fully-old or fully-new index state. The
        broadcast rides the same chain as mutations, so a peer that
        misses an Alter pulls it (the schema record is in our WAL) on the
        next chained message instead of diverging forever."""
        ts = self.apply_schema_broadcast(schema_text)
        if self.groups is not None:
            with self._apply_lock:
                self._broadcast_chained(
                    ts, lambda c, origin, prev: c.apply_schema(
                        schema_text, ts=ts, origin=origin, prev_ts=prev))

    def drop_attr(self, pred: str) -> None:
        """reference: api.Operation{DropAttr} — delete one predicate's
        data + schema everywhere. Broadcast like Alter."""
        ts = self.apply_drop_attr_broadcast(pred)
        if self.groups is not None:
            with self._apply_lock:
                self._broadcast_chained(
                    ts, lambda c, origin, prev: c.apply_drop_attr(
                        pred, ts=ts, origin=origin, prev_ts=prev))
            import grpc as _grpc
            try:
                # the tablet assignment dies with the predicate
                # (reference: DropAttr deletes it from Zero's map)
                self.groups.zero.remove_tablet(pred)
            except _grpc.RpcError:
                pass  # membership poll self-heals when zero returns

    def apply_drop_attr_broadcast(self, pred: str, ts: int = 0) -> int:
        """Receive/apply a DropAttr (no re-broadcast). The predicate's
        tablet caches reset so a cached foreign copy can't serve dropped
        data."""
        with self._apply_lock:
            ts = ts or self.oracle.read_only_ts()
            if self.wal is not None:
                self.wal.append_drop_attr(pred, ts)
            self.mvcc.drop_predicate(pred, ts)
            with self._state_lock:
                self.tablet_versions.pop(pred, None)
                self._stale_preds.discard(pred)
                for k in [k for k in self._tablet_cache if k[0] == pred]:
                    del self._tablet_cache[k]
        return ts

    def drop_all(self) -> None:
        """reference: api.Operation{DropAll}. Broadcast like Alter: every
        node must drop or spanning queries diverge against survivors."""
        ts = self.apply_drop_broadcast()
        if self.groups is not None:
            with self._apply_lock:
                self._broadcast_chained(
                    ts, lambda c, origin, prev: c.apply_drop(
                        ts=ts, origin=origin, prev_ts=prev))

    def apply_drop_broadcast(self, ts: int = 0) -> int:
        """Receive/apply a DropAll (no re-broadcast). Tablet caches must
        reset too — a cached foreign tablet would keep serving pre-drop
        data locally. Returns the drop's ts (chained broadcasts key on
        it)."""
        with self._apply_lock:
            ts = ts or self.oracle.read_only_ts()
            if self.wal is not None:
                self.wal.append_drop(ts)
            self.mvcc = MVCCStore()
            self.xidmap = XidMap(self.oracle)
            with self._state_lock:
                self._open_txns.clear()
                self.tablet_versions.clear()
                self._stale_preds.clear()
                self._tablet_cache.clear()
        return ts

    # -- commit path (worker/draft.go applyMutations analog) ----------------
    def _commit(self, txn: "Txn") -> int:
        # LAST cancellation point on the write path: past here the
        # two-phase stage/decide protocol runs to completion —
        # interrupting between stage and decide would leak an
        # undecided pend on every replica that acked
        dl.checkpoint("commit")
        with self._apply_lock:
            if self.groups is not None:
                # pre-flight BEFORE the oracle assigns a commit_ts: a
                # minority-side coordinator refuses up front instead of
                # burning a timestamp + conflict window on a commit the
                # group cannot accept. (A link that dies between this
                # probe and the stage still burns the ts — readers never
                # see it, but its conflict keys can spuriously abort
                # concurrent txns until retention expires; the window is
                # one RPC round.)
                self._preflight_quorum()
            commit_ts = self.oracle.commit(
                txn.start_ts, txn.mutation.conflict_keys(self.mvcc.schema))
            if self.groups is not None:
                self._apply_and_broadcast(txn.mutation, commit_ts)
                return commit_ts
            # write-ahead: on disk before the in-memory apply, so a crash
            # between the two replays the record (reference: raft entry
            # fsync before posting-list apply)
            if self.wal is not None:
                self.wal.append(txn.mutation, commit_ts)
            self.mvcc.apply(txn.mutation, commit_ts)
            return commit_ts

    # -- cluster write/read plumbing (worker/draft.go + task.go analogs) -----
    def _apply_and_broadcast(self, mut: Mutation, commit_ts: int) -> None:
        """Replicated commit with MAJORITY acknowledgment (reference:
        worker/draft.go proposeAndWait over etcd raft, collapsed to a
        two-phase chained broadcast):

        Phase 1 — STAGE: the record is durably logged as pending on this
        node and shipped with `stage=true` to every replica of this
        group; each replica durably logs it (no apply) and acks. Phase 2
        — DECIDE: when ≥ majority of the group (counting this node)
        logged it, the decision marker is written, the record applies
        locally, replicas get DecisionMsg (best-effort: a replica that
        misses it resolves through FetchLog, whose resolved stream serves
        the decision durably), and non-group nodes get the normal full
        broadcast. Under majority loss the decision is ABORT: nothing was
        applied anywhere, the client gets NoQuorum, and the staged pend
        resolves to an abort marker — the minority side of a partition
        refuses writes instead of diverging.

        Each message chains to the sender's previous one (origin +
        prev_ts): a receiver that missed a record detects the gap on the
        next chained message and pulls the tail via FetchLog BEFORE
        applying/acking. A peer that misses a broadcast is marked suspect
        (skipped by read failover); a later successful chained broadcast
        clears it, because the ack implies the peer converged first.
        Single-replica groups skip staging (majority of one is self)."""
        from dgraph_tpu.store.wal import mut_to_bytes
        gid = self.groups.gid
        replicas = [a for a in self.groups.group_addrs(gid)
                    if a != self.groups.my_addr]
        if replicas:
            majority = (len(replicas) + 1) // 2 + 1
            if self.wal is not None:
                self.wal.append_pend(mut, commit_ts)
            with self._state_lock:
                self._pending[commit_ts] = (mut, self.groups.node_id)
            blob = mut_to_bytes(mut)
            acks = 1 + self._broadcast_chained(
                commit_ts,
                lambda c, origin, prev: c.apply_mutation(
                    blob, commit_ts, origin=origin, prev_ts=prev,
                    stage=True),
                addrs=replicas)
            if acks < majority:
                if self.wal is not None:
                    self.wal.append_decision(commit_ts, False)
                with self._state_lock:
                    self._pending.pop(commit_ts, None)
                self._send_decisions(replicas, commit_ts, False)
                METRICS.inc("noquorum_total", phase="stage")
                raise NoQuorum(
                    f"commit {commit_ts}: {acks}/{len(replicas) + 1} "
                    f"replicas durably logged it; majority "
                    f"{majority} required")
            if self.wal is not None:
                self.wal.append_decision(commit_ts, True)
            with self._state_lock:
                self._pending.pop(commit_ts, None)
            self.apply_committed(mut, commit_ts, log_wal=False)
            self._send_decisions(replicas, commit_ts, True)
        else:
            self.apply_committed(mut, commit_ts)
        others = [a for a in self.groups.other_addrs()
                  if a not in replicas]
        # the chain advances exactly once per ts: on the stage leg when
        # replicas exist, else on this cross-group leg (a single-replica
        # group that never advanced would pin prev_ts and kill gap
        # detection on every peer)
        self._broadcast_chained(
            commit_ts, lambda c, origin, prev: c.apply_mutation(
                mut_to_bytes(mut), commit_ts, origin=origin,
                prev_ts=prev),
            addrs=others, advance=not replicas)

    def _preflight_quorum(self) -> None:
        """Cheap reachability probe of the replica group before taking a
        commit timestamp (raft leaders know liveness from heartbeats;
        an any-coordinator design must ask)."""
        import grpc as _grpc
        gid = self.groups.gid
        replicas = [a for a in self.groups.group_addrs(gid)
                    if a != self.groups.my_addr]
        if not replicas:
            return
        majority = (len(replicas) + 1) // 2 + 1
        alive = 1
        for addr in replicas:
            if alive >= majority:
                return
            try:
                self.groups.pool(addr).ping()
                alive += 1
            except _grpc.RpcError:
                continue
        if alive < majority:
            METRICS.inc("noquorum_total", phase="preflight")
            raise NoQuorum(
                f"only {alive}/{len(replicas) + 1} group replicas "
                f"reachable; majority {majority} required")

    def _send_decisions(self, replicas, commit_ts: int,
                        commit: bool) -> None:
        """Phase-2 fan-out; failures leave the replica to resolve via
        FetchLog (its pend is durable, our decision marker is durable)."""
        import grpc as _grpc
        for addr in replicas:
            try:
                self.groups.pool(addr).apply_decision(
                    commit_ts, commit, origin=self.groups.node_id)
            except _grpc.RpcError:
                with self._state_lock:
                    self._suspect_peers.setdefault(addr, commit_ts)
                self.groups.invalidate(addr)

    def _broadcast_chained(self, ts: int, send, addrs=None,
                           advance: bool = True) -> int:
        """Send one chained record to `addrs` (default: every peer);
        track suspects; return the number of successful sends. Callers
        hold _apply_lock, which serializes the prev/_last_sent_ts chain.
        `advance=False` reuses the previous chain position — the second
        leg of a two-leg send for the same ts (stage to the replica
        group, then the full record to other groups)."""
        import grpc as _grpc
        if advance:
            self._prev_sent_ts = self._last_sent_ts
            self._last_sent_ts = ts
        prev = getattr(self, "_prev_sent_ts", 0)
        ok = 0
        for addr in (self.groups.other_addrs() if addrs is None
                     else addrs):
            try:
                send(self.groups.pool(addr), self.groups.node_id, prev)
                ok += 1
                with self._state_lock:
                    self._suspect_peers.pop(addr, None)
            except _grpc.RpcError as e:
                # the peer missed this record: its tablets may serve stale
                # reads — exclude it from failover until it resyncs (the
                # chained gap triggers that on our next broadcast). Drop
                # the pooled channel so the retry isn't stuck in backoff.
                with self._state_lock:
                    self._suspect_peers.setdefault(addr, ts)
                self.groups.invalidate(addr)
                from dgraph_tpu.utils import logging as xlog
                xlog.get("alpha").warning(
                    "broadcast of ts %d to %s failed (%s); peer marked "
                    "suspect until it catches up",
                    ts, addr, e.code() if hasattr(e, "code") else e)
                continue
        return ok

    def _chain_catch_up(self, origin: int, since_ts: int) -> None:
        """Pull the missed (since_ts, …] tail from `origin`. On ANY
        failure (unknown address, gRPC receive error) the gap is
        RECORDED instead of propagated: the enclosing stage/broadcast
        RPC must still succeed — refusing it would make an asymmetric
        partition cascade — but the read gate then refuses or heals the
        hole before any snapshot is served (never silently proceed past
        a known gap)."""
        addr = self.groups.addr_of_node(origin)
        try:
            if addr is None:
                raise LookupError(f"origin node {origin} has no known "
                                  f"address")
            self.catch_up(addr, since_ts=since_ts)
        except Exception as e:  # noqa: BLE001 — gap recorded, not lost
            with self._state_lock:
                known = self._origin_gaps.get(origin)
                self._origin_gaps[origin] = (since_ts if known is None
                                             else min(known, since_ts))
            from dgraph_tpu.utils import logging as xlog
            xlog.get("alpha").warning(
                "catch-up from origin %d above ts %d failed (%s); gap "
                "recorded — reads heal or refuse until it resolves",
                origin, since_ts, e)
        else:
            with self._state_lock:
                # graftlint: allow(split-critical-section): pop only after this call's own catch_up SUCCEEDED; a concurrently recorded gap re-arms on the next chained receive or read probe
                self._origin_gaps.pop(origin, None)

    def receive_stage(self, mut: Mutation, ts: int, origin: int,
                      prev_ts: int) -> None:
        """Commit-quorum phase-1 receive: chain gap-check, then durably
        log the record as PENDING — no apply. The ack this produces is
        the durability certificate the coordinator counts toward
        majority (reference: raft AppendEntries success) — which is why
        a node with no armed WAL must REFUSE (StageRefused →
        FailedPrecondition on the wire) instead of acking a durability
        it cannot provide."""
        if self.wal is None and not self.allow_volatile_stage:
            raise StageRefused(
                f"stage of ts {ts} refused: no WAL armed — this node's "
                f"ack would count toward the coordinator's durability "
                f"majority without being crash-durable")
        if origin:
            last = self._last_from.get(origin, 0)
            if prev_ts > last:
                self._chain_catch_up(origin, since_ts=last)
            self._last_from[origin] = max(
                self._last_from.get(origin, 0), ts)
            self._resolve_stale_pendings(origin, ts)
        with self._apply_lock:
            if self.mvcc.has_applied(ts):
                return  # already resolved via catch-up
            if self.wal is not None:
                self.wal.append_pend(mut, ts)
            elif not getattr(self, "_warned_volatile_stage", False):
                # explicit test-only opt-in (allow_volatile_stage): the
                # ack the coordinator counts toward its durability
                # majority is memory-only here. Real deployments
                # (Alpha.open / cli) always arm the WAL.
                self._warned_volatile_stage = True
                from dgraph_tpu.utils import logging as xlog
                xlog.get("alpha").warning(
                    "commit-quorum stage accepted WITHOUT a WAL: acks "
                    "from this node are not crash-durable")
            with self._state_lock:
                self._pending[ts] = (mut, origin)

    def _resolve_stale_pendings(self, origin: int, before_ts: int) -> None:
        """A record from `origin` at `before_ts` proves every EARLIER ts
        it staged here is decided in its durable log (the chain only
        advances after the decision marker is written) — a lost
        DecisionMsg is recovered by pulling the origin's resolved log.
        The chain position alone can't catch this: staging advanced
        _last_from, so there is no prev_ts gap to detect.

        A stale ts the fetch does NOT resolve is an ORPHAN: the origin
        crashed between stage and decision and restarted (its own replay
        discards undecided pends — the client was never acked). It is
        resolved as ABORT here; should the origin somehow have committed
        it after all, the committed record is in its resolved log and
        ordinary gap catch-up re-applies it (apply is idempotent).

        The orphan verdict REQUIRES a successful fetch of the origin's
        resolved log: with its address unknown or the pull failing
        (gRPC receive error), the pends are RETAINED — aborting a
        record the origin may have committed would drop an acknowledged
        write; a later chained message retries the resolution. The
        failed pull must also never fail the ENCLOSING stage RPC (the
        coordinator would count this node unreachable over a third
        party's link)."""
        with self._state_lock:
            stale = [t for t, (_m, org) in self._pending.items()
                     if org == origin and t < before_ts]
        if not stale:
            return
        addr = self.groups.addr_of_node(origin)
        fetched = False
        if addr is not None:
            try:
                self.catch_up(addr, since_ts=min(stale) - 1)
                fetched = True
            except Exception as e:  # noqa: BLE001 — retain, retry later
                from dgraph_tpu.utils import logging as xlog
                xlog.get("alpha").warning(
                    "stale-pend resolution fetch from origin %d (%s) "
                    "failed (%s); retaining %d staged record(s)",
                    origin, addr, e, len(stale))
        if not fetched:
            return  # cannot distinguish orphan from lost decision yet
        with self._state_lock:
            orphans = [t for t in stale if t in self._pending]
            for t in orphans:
                # graftlint: allow(split-critical-section): re-validated — only ts still in _pending under THIS acquisition are deleted; a decision that raced the fetch already removed its entry
                del self._pending[t]
        if self.wal is not None:
            for t in orphans:
                self.wal.append_decision(t, False)

    def receive_decision(self, ts: int, commit: bool,
                         origin: int) -> None:
        """Commit-quorum phase-2 receive: resolve a pending record. A
        decision for an unknown ts is ignored — catch-up already
        resolved it (the origin's WAL serves decisions durably)."""
        with self._apply_lock:
            with self._state_lock:
                entry = self._pending.pop(ts, None)
            if entry is None:
                return
            mut, _origin = entry
            if self.wal is not None:
                self.wal.append_decision(ts, commit)
            if commit and not self.mvcc.has_applied(ts):
                self.apply_committed(mut, ts, log_wal=False)

    def receive_broadcast(self, kind: str, obj, ts: int,
                          origin: int, prev_ts: int) -> None:
        """Broadcast receive path with gap detection: if the sender's
        chain skips past what we last saw from it, pull the missed WAL
        tail from the origin BEFORE applying this record. Applies are
        idempotent against duplicates (catch-up may have just pulled the
        very record being delivered)."""
        if origin:
            last = self._last_from.get(origin, 0)
            if prev_ts > last:
                # we missed (last, prev_ts] from this origin
                self._chain_catch_up(origin, since_ts=last)
            self._last_from[origin] = max(
                self._last_from.get(origin, 0), ts)
            self._resolve_stale_pendings(origin, ts)
        if kind == "schema":
            self.apply_schema_broadcast(obj, ts=ts)
        elif kind == "drop":
            self.apply_drop_broadcast(ts=ts)
        elif kind == "drop_attr":
            self.apply_drop_attr_broadcast(obj, ts=ts)
        elif not self.mvcc.has_applied(ts):
            self.apply_committed(obj, ts)

    def catch_up(self, addr: str, since_ts: int) -> tuple[bool, int]:
        """Pull and apply the peer's WAL records above since_ts
        (reference: raft log replay for a lagging follower). Returns
        (complete, seen_max): complete=False when the peer's WAL no
        longer covers since_ts — the caller falls back to snapshot
        resync (mark tablets stale / TabletSnapshot) — and seen_max is
        the highest RESOLVED ts in the fetched stream (0 when empty),
        which the read gate compares against the peer's probed chain
        head to decide whether the chain may advance.

        since_ts is clamped to our own fold floor: records at or below it
        are already inside our snapshots, and re-absorbing them would
        duplicate @list values (apply is set-idempotent per layer, not
        against folded history)."""
        from dgraph_tpu.utils import logging as xlog
        log = xlog.get("alpha")
        # budget gate per RPC leg: the remaining budget also rides the
        # wire as the gRPC timeout (server/task.py Client._call)
        dl.checkpoint("fetch_log")
        since_ts = max(since_ts, self.mvcc.base_ts)
        with tracing.span("rpc.fetch_log", peer=addr,
                          since_ts=since_ts) as sp:
            t0 = time.perf_counter()
            records, complete = self.groups.pool(addr).fetch_log(since_ts)
            METRICS.observe("rpc_latency_us",
                            (time.perf_counter() - t0) * 1e6,
                            rpc="fetch_log")
            sp.attrs["records"] = len(records)
        applied = 0
        seen_max = self.mvcc.base_ts if since_ts <= self.mvcc.base_ts \
            else 0
        for ts, kind, obj in records:
            seen_max = max(seen_max, ts)
            if kind == "schema":
                self.apply_schema_broadcast(obj, ts=ts)
                continue
            if kind == "drop":
                self.apply_drop_broadcast(ts=ts)
                continue
            if kind == "drop_attr":
                self.apply_drop_attr_broadcast(obj, ts=ts)
                continue
            if kind == "abort":
                # the origin decided ABORT for a staged ts: drop our
                # pending copy and record the decision durably so OUR
                # resolved log propagates it too
                with self._state_lock:
                    entry = self._pending.pop(ts, None)
                if entry is not None and self.wal is not None:
                    self.wal.append_decision(ts, False)
                continue
            if self.mvcc.has_applied(ts):
                continue
            with self._state_lock:
                was_pending = self._pending.pop(ts, None) is not None
            if was_pending and self.wal is not None:
                # our pend is durable; the fetched record proves the
                # origin committed it — resolve with a marker instead of
                # double-logging the payload
                self.wal.append_decision(ts, True)
                self.apply_committed(obj, ts, log_wal=False)
            else:
                self.apply_committed(obj, ts)
            applied += 1
        if applied:
            METRICS.inc("fetchlog_heals_total")
            METRICS.inc("fetchlog_records_applied_total", float(applied))
            log.info("caught up %d records > ts %d from %s",
                     applied, since_ts, addr)
        if not complete:
            # records older than the peer's WAL floor may be missing from
            # us entirely: snapshot-level resync — foreign tablets go
            # stale (re-validated on next read), owned tablets re-pull
            # from a group replica when one exists
            log.warning("peer %s WAL truncated above since_ts %d; "
                        "snapshot-level resync", addr, since_ts)
            self.mark_all_stale()
            self.resync_owned_tablets()
        return complete, seen_max

    def mark_all_stale(self) -> None:
        """Force freshness checks: every known foreign predicate must
        re-validate against its owner before serving (rejoin / deep-gap
        path)."""
        with self._state_lock:
            preds = set(self.mvcc.base.preds) | set(self.tablet_versions)
            for p in preds:
                if self.groups is None or not self.groups.serves(p):
                    self._stale_preds.add(p)
            self._tablet_cache.clear()

    def resync_owned_tablets(self) -> None:
        """Replace every OWNED tablet with a fresh snapshot from a group
        replica (reference: Badger Stream snapshot from the leader). A
        sole-replica group has nobody to pull from — records truncated
        out of every peer's WAL are lost for it; logged loudly (the
        reference's quorum write would have refused the commit instead)."""
        import grpc as _grpc

        from dgraph_tpu.cluster.tablet import unpack_tablet
        from dgraph_tpu.utils import logging as xlog
        log = xlog.get("alpha")
        replicas = [a for a in self.groups.group_addrs(self.groups.gid)
                    if a != self.groups.my_addr]
        with self._state_lock:
            known_versions = set(self.tablet_versions)
        owned = [p for p in set(self.mvcc.base.preds)
                 | known_versions if self.groups.serves(p)]
        if not replicas:
            if owned:
                log.error(
                    "no group replica to resync owned tablets %s from; "
                    "records truncated from peer WALs are unrecoverable",
                    sorted(owned))
            return
        ts = self.oracle.read_only_ts()
        for pred in owned:
            for addr in replicas:
                try:
                    blob, _v = self.groups.pool(addr).tablet_snapshot(
                        pred, ts)
                except _grpc.RpcError:
                    continue
                if blob:
                    pd = unpack_tablet(blob, pred, self.mvcc.schema)
                    self.mvcc.install_tablet(pred, pd)
                    log.info("owned tablet %s resynced from %s", pred, addr)
                break

    def resync_on_join(self, peer_addrs=None) -> None:
        """Rejoin catch-up (reference: restarted follower replaying the
        leader's log + snapshot): pull WAL tails from peers, then mark
        foreign tablets stale so reads re-validate freshness."""
        addrs = (peer_addrs if peer_addrs is not None
                 else self.groups.other_addrs())
        # fetch from our fold floor, NOT our newest layer: commits by other
        # coordinators interleave with our replayed tail, so anything above
        # the floor could be missing; has_applied() skips what we do have
        since = self.mvcc.base_ts
        for addr in addrs:
            try:
                # a peer without a covering WAL (complete=False, e.g. no
                # WAL armed or truncated past `since`) is not a source —
                # keep trying; any COMPLETE tail ends the search
                if self.catch_up(addr, since_ts=since)[0]:
                    break
            except Exception:  # noqa: BLE001 — any live peer will do
                continue
        self.mark_all_stale()

    def apply_committed(self, mut: Mutation, commit_ts: int,
                        log_wal: bool = True) -> None:
        """Install a committed mutation on THIS node: the subset of
        predicates this group serves plus the vocabulary touches. Also the
        receive path of the broadcast (WorkerService.ApplyMutation).
        `log_wal=False` when the record is already durable as a resolved
        pend+decision pair (the quorum path) — a second full copy would
        double it in FetchLog's resolved stream."""
        if self.groups is None:
            if self.wal is not None and log_wal:
                self.wal.append(mut, commit_ts)
            self.mvcc.apply(mut, commit_ts)
            return
        touched = {e[1] for e in mut.edge_sets + mut.edge_dels} | \
                  {v[1] for v in mut.val_sets + mut.val_dels}
        owned = {p for p in touched if self.groups.serves(p)}
        sub = mut.restrict(owned)
        with self._state_lock:
            for p in touched:
                self.tablet_versions[p] = max(
                    self.tablet_versions.get(p, 0), commit_ts)
                if p not in owned:
                    self._stale_preds.add(p)
        # the WAL stores the FULL record (not the owned subset): it doubles
        # as the replication log FetchLog serves to lagging peers, who need
        # every predicate to extract their own subset
        if self.wal is not None and log_wal:
            self.wal.append(mut, commit_ts)
        try:
            self.mvcc.apply(sub, commit_ts)
        except ValueError:
            # commit below a fold point (another coordinator's commit
            # raced a local rollup/alter, or catch-up recovered an old
            # record): fold it into the affected snapshots in place —
            # no data loss, reads at ts >= commit_ts see it
            from dgraph_tpu.utils import logging as xlog
            xlog.get("alpha").warning(
                "absorbing straggler commit_ts %d below fold point %d",
                commit_ts, self.mvcc.base_ts)
            self.mvcc.absorb_straggler(sub, commit_ts)

    def _needs_fetch(self, pred: str, read_ts: int,
                     present_locally) -> bool:
        """Does a routed view need to pull this tablet from its owner?"""
        if self.groups is None:
            return False
        with self._state_lock:
            stale = pred in self._stale_preds
        if stale:
            return True
        return present_locally is None and not self.groups.serves(pred)

    def _cached_tablet(self, pred: str, read_ts: int, view):
        """Fresh cached copy of a foreign tablet adapted to the current
        vocabulary, or None. Cache entries are keyed (pred, version) and
        record the vocab width + max uid at fetch: uid allocation is
        monotone, so as long as later growth appended ABOVE the fetch-time
        max uid, every rank the blob references is unchanged and the CSR
        just pads to the new width — a commit no longer evicts every
        cached tablet on every node (VERDICT r2 weak #3). Only a
        mid-vocabulary insert (explicit low-uid write) invalidates."""
        import numpy as np
        n = view.n_nodes
        with self._state_lock:
            version = self.tablet_versions.get(pred, 0)
            if read_ts < version:
                return None
            adapted = self._tablet_cache.get((pred, version, n))
            entry = self._tablet_cache.get((pred, version))
        if adapted is not None:
            return adapted
        if entry is None:
            return None
        pd, blob_n, last_uid = entry
        if n == blob_n:
            return pd
        if n < blob_n or int(np.searchsorted(
                view.uids, last_uid, "right")) != blob_n:
            return None  # mid-insert shifted ranks: blob unusable
        adapted = self._pad_tablet(pd, blob_n, n)
        with self._state_lock:
            # adaptations live under per-width keys; the RAW entry stays,
            # so readers at older (narrower) views keep hitting it instead
            # of refetching. Only the latest width is retained.
            for k in [k for k in self._tablet_cache
                      if k[0] == pred and len(k) == 3 and k[2] != n]:
                # graftlint: allow(split-critical-section): idempotent cache fill — concurrent fillers install equivalent adaptations for the same (pred, version, n) key, and stale widths are simply re-deleted
                del self._tablet_cache[k]
            self._tablet_cache[(pred, version, n)] = adapted
        memgov.GOVERNOR.maybe_evict("host")
        return adapted

    @staticmethod
    def _pad_tablet(pd, old_n: int, new_n: int):
        """Extend a rank-indexed tablet to a wider (append-only-grown)
        vocabulary: CSR indptr pads with its last offset; columns and
        indexes reference only ranks < old_n and carry over unchanged."""
        import numpy as np

        from dgraph_tpu.store.store import EdgeRel, PredicateData
        out = PredicateData(schema=pd.schema, vals=pd.vals,
                            index=pd.index, efacets=pd.efacets,
                            vfacets=pd.vfacets,
                            # edge POSITIONS are unchanged by widening, so
                            # the rev→fwd facet map carries over for free
                            rev_pos=pd.rev_pos)
        for side in ("fwd", "rev"):
            rel = getattr(pd, side)
            if rel is not None:
                pad = np.full(new_n - old_n, rel.indptr[-1],
                              rel.indptr.dtype)
                setattr(out, side, EdgeRel(
                    indptr=np.concatenate([rel.indptr, pad]),
                    indices=rel.indices))
        return out

    def _fetch_tablet(self, pred: str, read_ts: int):
        """Pull a foreign tablet snapshot as-of read_ts from its owning
        group (any live replica), caching latest-version pulls
        (reference: Badger Stream tablet snapshot shipping)."""
        gid = self.groups.tablet_owner(pred, claim=False)
        if gid is None or gid == self.groups.gid:
            return None
        view = self.mvcc.read_view(read_ts)
        cached = self._cached_tablet(pred, read_ts, view)
        if cached is not None:
            return cached
        dl.checkpoint("tablet_snapshot")
        from dgraph_tpu.cluster.tablet import unpack_tablet
        with tracing.span("rpc.tablet_snapshot", pred=pred,
                          read_ts=read_ts) as sp:
            t0 = time.perf_counter()
            blob, got_version = self.groups.call_group(
                gid, lambda c: c.tablet_snapshot(pred, read_ts),
                exclude=set(self._suspect_peers),
                rpc="tablet_snapshot")
            METRICS.observe("rpc_latency_us",
                            (time.perf_counter() - t0) * 1e6,
                            rpc="tablet_snapshot")
            sp.attrs["bytes"] = len(blob) if blob else 0
        if not blob:
            return None
        METRICS.inc("tablet_bytes_fetched", len(blob))
        pd = unpack_tablet(blob, pred, self.mvcc.schema)
        with self._state_lock:
            version = self.tablet_versions.get(pred, 0)
            # trust the OWNER's version: a broadcast still in flight (or
            # dropped) may have produced a blob newer than we knew — such
            # a blob must not be cached under the stale local version or
            # an older-ts reader would see future writes
            version = max(version, got_version)
            self.tablet_versions[pred] = max(
                self.tablet_versions.get(pred, 0), got_version)
            if read_ts >= version:
                self._tablet_cache[(pred, version)] = (
                    pd, view.n_nodes, int(view.uids[-1])
                    if view.n_nodes else 0)
                for k in [k for k in self._tablet_cache
                          if k[0] == pred and k[1] != version]:
                    del self._tablet_cache[k]
        memgov.GOVERNOR.maybe_evict("host")
        return pd

    def remote_hop(self, pred: str, reverse: bool, frontier,
                   read_ts: int, view):
        """One-hop expansion executed on the tablet's OWNER via ServeTask
        (frontier uids in, UidMatrix out) — O(frontier + result) bytes on
        the wire instead of the whole tablet (reference: worker/task.go
        ProcessTaskOverNetwork, the per-hop mechanism). Used when no
        fresh local copy exists and the frontier is small; large
        frontiers amortize a whole-tablet pull instead. Returns
        (nbrs_ranks, seg, empty_pos) or None when ineligible."""
        import numpy as np
        if self.groups is None or len(frontier) > self.remote_hop_max:
            return None
        dl.checkpoint("serve_task")
        gid = self.groups.tablet_owner(pred, claim=False)
        if gid is None or gid == self.groups.gid:
            return None
        if self._cached_tablet(pred, read_ts, view) is not None:
            return None  # fresh cached copy: zero transfer beats an RPC
        if dict.__contains__(view.preds, pred) and \
                not self._needs_fetch(pred, read_ts, True):
            # locally present and fresh (e.g. the tablet just moved away
            # from this node): serve from memory, skip the RPC
            return None
        uids = view.uid_of(np.asarray(frontier, np.int32)).astype(
            np.uint64)
        import grpc as _grpc
        with tracing.span("rpc.serve_task", pred=pred,
                          frontier=int(len(uids))):
            t0 = time.perf_counter()
            try:
                res = self.groups.call_group(
                    gid, lambda c: c.serve_task(
                        attr=pred, reverse=reverse,
                        frontier={"uids": uids.tolist()},
                        read_ts=read_ts),
                    exclude=set(self._suspect_peers),
                    rpc="serve_task")
            except _grpc.RpcError:
                # every replica of the owning group refused the per-hop
                # leg: fall back to the whole-tablet pull (its own
                # failover path; exhausted there → ReadUnavailable)
                # instead of failing the query on a routing shortcut
                return None
            METRICS.observe("rpc_latency_us",
                            (time.perf_counter() - t0) * 1e6,
                            rpc="serve_task")
        nbrs_parts, seg_parts = [], []
        total_uids = 0
        for i, row in enumerate(res.matrix.rows):
            if not row.uids:
                continue
            ranks = view.rank_of(np.array(row.uids, np.int64))
            ranks = ranks[ranks >= 0]
            nbrs_parts.append(ranks.astype(np.int32))
            seg_parts.append(np.full(len(ranks), i, np.int32))
            total_uids += len(ranks)
        METRICS.inc("taskhop_bytes_fetched",
                    8 * (len(uids) + total_uids))
        if not nbrs_parts:
            e = np.zeros(0, np.int32)
            return e, e, np.zeros(0, np.int64)
        return (np.concatenate(nbrs_parts), np.concatenate(seg_parts),
                np.zeros(0, np.int64))

    def apply_schema_broadcast(self, schema_text: str,
                               ts: int = 0) -> int:
        """Receive/apply an Alter (no re-broadcast). Returns its ts."""
        new = parse_schema(schema_text)
        with self._apply_lock:
            ts = ts or self.oracle.read_only_ts()
            merged = self.mvcc.schema.clone()
            merged.update(new)
            if self.wal is not None:
                self.wal.append_schema(schema_text, ts)
            self.mvcc.rebuild_base(schema=merged)
        return ts

    def _txn_done(self, txn: "Txn") -> None:
        with self._state_lock:
            self._open_txns.pop(txn.start_ts, None)

    def report_tablet_sizes(self) -> dict[str, int]:
        """Report owned-tablet sizes to Zero (reference: the tablet-size
        heartbeat feeding zero/tablet.go's rebalance loop)."""
        store = self.mvcc.read_view(self.oracle.read_only_ts())
        sizes: dict[str, int] = {}
        hints = getattr(store.preds, "size_hints", None)
        if hints is not None:
            # out-of-core base: manifest byte sizes, no faulting — the
            # heartbeat must never page the whole store in
            sizes = {p: nb for p, nb in hints().items()
                     if self.groups.serves(p)}
            self.groups.zero.report_tablets(self.groups.gid, sizes)
            return sizes
        for pred, pd in store.preds.items():
            if not self.groups.serves(pred):
                continue
            n = 0
            for rel in (pd.fwd, pd.rev):
                if rel is not None:
                    n += rel.indptr.nbytes + rel.indices.nbytes
            for col in pd.vals.values():
                n += col.subj.nbytes
                if col.vals.dtype == object:
                    # sampled estimate: exact byte counts would re-scan
                    # millions of strings every heartbeat
                    k = min(len(col.vals), 256)
                    if k:
                        avg = sum(len(str(v))
                                  for v in col.vals[:k]) / k
                        n += int(avg * len(col.vals))
                else:
                    n += col.vals.nbytes
            sizes[pred] = n
        self.groups.zero.report_tablets(self.groups.gid, sizes)
        return sizes

    def report_health(self) -> dict:
        """Ship this node's peer-health view (/debug/peers data: breaker
        states + EMA latencies, cluster/resilience.py) and its per-tablet
        cost sums (utils/costprofile.py) to Zero — the placement signal
        that lets tablet moves prefer healthy, under-loaded peers and
        never target half-open/dead ones (cluster/zero.py
        report_health / move_tablet)."""
        peers = self.groups.peer_health()
        doc = {"node_id": self.groups.node_id,
               "group": self.groups.gid,
               "addr": self.groups.my_addr,
               "peers": peers,
               "tablet_costs": {
                   p: c for p, c in costprofile.tablet_costs().items()
                   # claim=False: a cost key must never CLAIM a tablet
                   # (the overflow key "other" is not even a predicate)
                   if self.groups.tablet_owner(p, claim=False)
                   == self.groups.gid}}
        self.groups.zero.report_health(doc)
        return doc

    def _heal_corrupt_tablet(self, pred: str):
        """Pull a fresh copy of an OWNED tablet from a group replica
        after its on-disk segments failed an integrity check — the
        disk-side twin of the PR-1 FetchLog heal. Iterates replicas in
        PeerTable order (open breakers fail fast); returns the unpacked
        PredicateData or None when no replica can serve it (the caller
        then raises the original StorageCorruption)."""
        if self.groups is None:
            return None
        import grpc as _grpc

        from dgraph_tpu.cluster.tablet import unpack_tablet
        from dgraph_tpu.utils import logging as xlog
        replicas = [a for a in self.groups.group_addrs(self.groups.gid)
                    if a != self.groups.my_addr]
        for addr in replicas:
            try:
                blob, _v = self.groups.pool(addr).tablet_snapshot(
                    pred, self.mvcc.base_ts)
            except _grpc.RpcError:
                continue
            if blob:
                xlog.get("alpha").warning(
                    "healed corrupt tablet %s from replica %s "
                    "(on-disk copy rewrites at the next checkpoint)",
                    pred, addr)
                flightrec.emit("storage.heal", pred=pred, replica=addr)
                return unpack_tablet(blob, pred, self.mvcc.schema)
        return None

    # -- maintenance --------------------------------------------------------
    def _maybe_gc(self) -> None:
        with self._state_lock:
            self._gc_tick += 1
            if self._gc_tick % GC_EVERY:
                return
            reads_floor = min(self._active_reads, default=None)
        floor = self.oracle.gc()
        if reads_floor is not None:
            floor = min(floor, reads_floor)
        self.mvcc.gc(floor)
        # superseded on-disk ckpt dirs whose last referencing fold the
        # gc above just dropped are reclaimable NOW (PR-3 deferred this
        # to the next checkpoint, which may never come)
        from dgraph_tpu.store import stream
        lazy = stream.lazy_preds(self.mvcc.base)
        if lazy is not None:
            stream.gc_superseded(lazy.root_dir, self.mvcc)


@dataclass
class Txn:
    """Transaction bookkeeping (reference: dgo txn / edgraph txn context):
    buffered mutations, blank-node uid map, commit state."""

    alpha: Alpha
    start_ts: int = 0
    commit_ts: int = 0
    mutation: Mutation = field(default_factory=Mutation)
    _blank: dict[str, int] = field(default_factory=dict)
    _done: bool = False

    def __post_init__(self):
        self.start_ts = self.alpha.oracle.read_ts()

    # -- reads --------------------------------------------------------------
    def query(self, dql: str, variables: dict | None = None) -> dict:
        if self._done:
            raise TxnAborted("txn finished")
        return self.alpha.query(dql, variables, read_ts=self.start_ts)

    # -- writes -------------------------------------------------------------
    def mutate(self, *, set_nquads: str | None = None,
               del_nquads: str | None = None,
               set_json=None, del_json=None) -> dict:
        """Buffer mutations; returns blank-node → uid assignments."""
        if self._done:
            raise TxnAborted("txn finished")
        sets: list[NQuad] = []
        dels: list[NQuad] = []
        if set_nquads:
            sets += parse_rdf(set_nquads)
        if set_json is not None:
            sets += parse_json(set_json)
        if del_nquads:
            dels += parse_rdf(del_nquads)
        if del_json is not None:
            dels += parse_json(del_json)
        for nq in sets:
            self._apply_nquad(nq, delete=False)
        for nq in dels:
            self._apply_nquad(nq, delete=True)
        return {b: f"0x{u:x}" for b, u in self._blank.items()}

    def _resolve(self, ref: str) -> int:
        if ref.startswith("_:"):
            uid = self._blank.get(ref)
            if uid is None:
                uid = self.alpha.xidmap.resolve(ref + f"@{self.start_ts}")
                self._blank[ref] = uid
            return uid
        return self.alpha.xidmap.resolve(ref)

    def _apply_nquad(self, nq: NQuad, delete: bool) -> None:
        s = self._resolve(nq.subject)
        m = self.mutation
        schema = self.alpha.mvcc.schema
        if nq.is_star:
            if not delete:
                raise ValueError('object "*" only valid in delete')
            ps = schema.peek(nq.predicate)
            if ps is not None and ps.kind == Kind.UID:
                m.edge_dels.append((s, nq.predicate, None))
            else:
                m.val_dels.append((s, nq.predicate, None, "*"))
        elif nq.object_id is not None:
            o = self._resolve(nq.object_id)
            if delete:
                m.edge_dels.append((s, nq.predicate, o))
            else:
                m.edge_sets.append((s, nq.predicate, o, nq.facets))
        else:
            if delete:
                m.val_dels.append((s, nq.predicate, None, nq.lang))
            else:
                value = nq.object_value
                ps = self.alpha.mvcc.schema.peek(nq.predicate)
                if ps is not None and ps.kind == Kind.PASSWORD:
                    # hash ONCE at ingestion: the WAL/broadcast carry the
                    # hash, so replay is deterministic and plaintext
                    # never reaches disk (reference: password scalar)
                    value = hash_password(str(value))
                elif ps is not None and ps.kind == Kind.GEO:
                    # validate + canonicalize GeoJSON at ingestion so a
                    # malformed literal fails the mutation, not a later
                    # materialize (reference: geo conversion at mutate)
                    from dgraph_tpu.store.geo import parse_geo
                    value = parse_geo(value)
                m.val_sets.append((s, nq.predicate, value, nq.lang,
                                   nq.facets))

    # -- outcome ------------------------------------------------------------
    def commit(self) -> int:
        if self._done:
            raise TxnAborted("txn finished")
        self._done = True
        self.alpha._txn_done(self)
        if self.mutation.is_empty():
            self.alpha.oracle.abort(self.start_ts)
            return 0
        self.commit_ts = self.alpha._commit(self)
        return self.commit_ts

    def discard(self) -> None:
        if not self._done:
            self._done = True
            self.alpha._txn_done(self)
            self.alpha.oracle.abort(self.start_ts)
