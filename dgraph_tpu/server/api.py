"""Alpha: the public API server facade (in-process form).

Reference parity: `edgraph/server.go` — `Server.Query`, `Server.Mutate`,
`Server.Alter`, `Server.CommitOrAbort` implementing the `api.Dgraph`
service — plus the worker-side mutation application those call into
(`worker/mutation.go` MutateOverNetwork → posting layer). Network
transports (HTTP/gRPC) wrap this object in `server/http.py` /
`server/task.py`; the query path itself runs the TPU engine.

Transactions follow the reference's client model: `txn = alpha.new_txn()`,
any number of `txn.query` / `txn.mutate` calls, then `txn.commit()` (Zero
arbitration; raises `TxnAborted` on conflict) or `txn.discard()`.
`commit_now=True` mutations are single-shot transactions.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from dgraph_tpu.cluster.oracle import Oracle, TxnAborted
from dgraph_tpu.engine import Engine
from dgraph_tpu.loader.chunker import NQuad, parse_json, parse_rdf
from dgraph_tpu.loader.xidmap import XidMap
from dgraph_tpu.store.mvcc import MVCCStore, Mutation
from dgraph_tpu.store.schema import parse_schema
from dgraph_tpu.store.store import Store
from dgraph_tpu.store.types import Kind

__all__ = ["Alpha", "Txn", "TxnAborted"]


class Alpha:
    """Single-process data server: oracle + MVCC store + query engine."""

    def __init__(self, base: Store | None = None,
                 device_threshold: int = 512):
        self.oracle = Oracle()
        self.mvcc = MVCCStore(base=base)
        self.xidmap = XidMap(self.oracle)
        self.device_threshold = device_threshold
        self._apply_lock = threading.Lock()
        if base is not None and base.n_nodes:
            self.oracle.bump_uid(int(base.uids[-1]))

    # -- public api surface (api.Dgraph analog) -----------------------------
    def new_txn(self) -> "Txn":
        return Txn(self)

    def query(self, dql: str, variables: dict | None = None,
              read_ts: int | None = None) -> dict:
        """Read-only query at a snapshot (reference: Server.Query with
        best-effort/read-only txn)."""
        ts = self.oracle.read_ts() if read_ts is None else read_ts
        store = self.mvcc.read_view(ts)
        return Engine(store, device_threshold=self.device_threshold).query(
            dql, variables)

    def mutate(self, *, set_nquads: str | None = None,
               del_nquads: str | None = None,
               set_json=None, del_json=None,
               commit_now: bool = True) -> dict:
        """One-shot mutation transaction (reference: Server.Mutate with
        CommitNow)."""
        txn = self.new_txn()
        try:
            uids = txn.mutate(set_nquads=set_nquads, del_nquads=del_nquads,
                              set_json=set_json, del_json=del_json)
            if commit_now:
                txn.commit()
            return {"uids": uids,
                    "txn": {"start_ts": txn.start_ts,
                            "commit_ts": txn.commit_ts}}
        except Exception:
            txn.discard()
            raise

    def alter(self, schema_text: str) -> None:
        """Schema mutation + index rebuild (reference: Server.Alter →
        schema.Update + posting.RebuildIndex)."""
        new = parse_schema(schema_text)
        with self._apply_lock:
            self.mvcc.schema.update(new)
            # rebuild the base snapshot under the new schema: recreates
            # reverse CSR blocks and inverted indexes
            self.mvcc.rollup()
            self.mvcc._views.clear()

    def drop_all(self) -> None:
        """reference: api.Operation{DropAll}."""
        with self._apply_lock:
            self.mvcc.__init__()

    # -- commit path (worker/draft.go applyMutations analog) ----------------
    def _commit(self, txn: "Txn") -> int:
        with self._apply_lock:
            commit_ts = self.oracle.commit(
                txn.start_ts, txn.mutation.conflict_keys())
            self.mvcc.apply(txn.mutation, commit_ts)
            return commit_ts


@dataclass
class Txn:
    """Client-side transaction bookkeeping (reference: dgo txn / edgraph
    txn context): buffered mutations, blank-node uid map, commit state."""

    alpha: Alpha
    start_ts: int = 0
    commit_ts: int = 0
    mutation: Mutation = field(default_factory=Mutation)
    _blank: dict[str, int] = field(default_factory=dict)
    _done: bool = False

    def __post_init__(self):
        self.start_ts = self.alpha.oracle.read_ts()

    # -- reads --------------------------------------------------------------
    def query(self, dql: str, variables: dict | None = None) -> dict:
        if self._done:
            raise TxnAborted("txn finished")
        return self.alpha.query(dql, variables, read_ts=self.start_ts)

    # -- writes -------------------------------------------------------------
    def mutate(self, *, set_nquads: str | None = None,
               del_nquads: str | None = None,
               set_json=None, del_json=None) -> dict:
        """Buffer mutations; returns blank-node → uid assignments."""
        if self._done:
            raise TxnAborted("txn finished")
        sets: list[NQuad] = []
        dels: list[NQuad] = []
        if set_nquads:
            sets += parse_rdf(set_nquads)
        if set_json is not None:
            sets += parse_json(set_json)
        if del_nquads:
            dels += parse_rdf(del_nquads)
        if del_json is not None:
            dels += parse_json(del_json)
        for nq in sets:
            self._apply_nquad(nq, delete=False)
        for nq in dels:
            self._apply_nquad(nq, delete=True)
        return {b: f"0x{u:x}" for b, u in self._blank.items()}

    def _resolve(self, ref: str) -> int:
        if ref.startswith("_:"):
            uid = self._blank.get(ref)
            if uid is None:
                uid = self.alpha.xidmap.resolve(ref + f"@{self.start_ts}")
                self._blank[ref] = uid
            return uid
        return self.alpha.xidmap.resolve(ref)

    def _apply_nquad(self, nq: NQuad, delete: bool) -> None:
        s = self._resolve(nq.subject)
        m = self.mutation
        schema = self.alpha.mvcc.schema
        if nq.is_star:
            if not delete:
                raise ValueError('object "*" only valid in delete')
            ps = schema.peek(nq.predicate)
            if ps is not None and ps.kind == Kind.UID:
                m.edge_dels.append((s, nq.predicate, None))
            else:
                m.val_dels.append((s, nq.predicate, None, ""))
                if ps is not None and ps.lang:
                    # star delete covers every language column
                    m.val_dels.append((s, nq.predicate, None, "*"))
        elif nq.object_id is not None:
            o = self._resolve(nq.object_id)
            (m.edge_dels if delete else m.edge_sets).append(
                (s, nq.predicate, o))
        else:
            if delete:
                m.val_dels.append((s, nq.predicate, None, nq.lang))
            else:
                m.val_sets.append((s, nq.predicate, nq.object_value, nq.lang))

    # -- outcome ------------------------------------------------------------
    def commit(self) -> int:
        if self._done:
            raise TxnAborted("txn finished")
        self._done = True
        if self.mutation.is_empty():
            self.alpha.oracle.abort(self.start_ts)
            return 0
        self.commit_ts = self.alpha._commit(self)
        return self.commit_ts

    def discard(self) -> None:
        if not self._done:
            self._done = True
            self.alpha.oracle.abort(self.start_ts)
