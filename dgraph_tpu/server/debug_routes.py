"""Debug-endpoint inventory: every `/debug/*` route with a one-liner.

ONE vocabulary, two consumers (the `cost_record_fields` pattern):
`server/http.py` renders `GET /debug` from this dict and keys its
runtime dispatch table (`_DEBUG_GET`/`_DEBUG_POST`) on the same paths;
`analysis/facts.py` re-exports it verbatim as `facts.debug_endpoints`.
tests/test_lint.py pins the inventory and the runtime route table to
each other in BOTH directions — a new debug endpoint that isn't
inventoried, or an inventoried path no handler serves, fails tier-1.

This module is deliberately import-free so the static-analysis CLI can
read the inventory without pulling the server (and its jax/grpc
dependency chain) into the process.
"""

from __future__ import annotations

DEBUG_ENDPOINTS: dict[str, str] = {
    "/debug":
        "GET: this index — every debug endpoint with a one-liner",
    "/debug/prometheus_metrics":
        "GET: every metric series in Prometheus text exposition format",
    "/debug/traces":
        "GET: span JSON; ?trace_id= one request's spans, ?peer= proxies "
        "a cluster peer's registry, ?n= limits the recent ring",
    "/debug/events":
        "GET: the same spans as Chrome trace-event JSON — load the "
        "body in Perfetto / chrome://tracing",
    "/debug/costs":
        "GET: shape-keyed cost digests + feature means + top-N "
        "expensive shapes + the fused-program cache (per-shape "
        "hits/misses/compile µs); ?recent=true adds the raw record "
        "ring",
    "/debug/slow_queries":
        "GET: structured slow-query ring; ?trace_id= filters to one "
        "request (its span tree is one hop away at /debug/traces)",
    "/debug/profile":
        "GET: device-capture status; POST {action: start|stop} runs a "
        "single-flight jax.profiler capture (409 on conflict)",
    "/debug/scheduler":
        "GET: cost priors with hit/fallback counts, predicted-vs-"
        "actual error, lane EMAs, feature fit, admission work ahead, "
        "fused-vs-staged route counts + program cache",
    "/debug/admission":
        "GET: per-lane inflight/queued/shed counts + limits",
    "/debug/locks":
        "GET: lock-order sanitizer graph, detected cycles (both "
        "stacks), long holds",
    "/debug/races":
        "GET: Eraser lockset race sanitizer reports, each with both "
        "access stacks",
    "/debug/peers":
        "GET: per-peer circuit-breaker state, EMA latency, last error "
        "+ zero health",
    "/debug/flightrecorder":
        "GET: flight ring + watchdog state + recent dumps; POST "
        "{action: dump} writes and returns a one-shot diagnostic "
        "bundle (stacks, ring, every debug surface, metrics, config)",
    "/debug/fleet":
        "GET: cluster-wide snapshot — per-node fragments fanned out "
        "over the worker transport, exactly-merged cost digests, "
        "instance-labeled metrics; degrades per dark peer, never 500s",
    "/debug/fleet/flight":
        "GET: flight-recorder snapshot (in-flight ops with stacks, "
        "ring, watchdog); ?peer=host:port pulls a cluster peer's over "
        "the DebugFlight RPC, ?n= limits the ring tail",
    "/debug/memory":
        "GET: memory-governor snapshot — per-cache resident bytes / "
        "registrants / evictions against the device+host budgets and "
        "watermarks, OOM evict-retry counters, sticky-degraded shapes",
    "/debug/timeseries":
        "GET: retained metrics history — the sampler ring's windowed "
        "points (counters as rates, histograms as p50/p90/p99); "
        "?name= filters series by prefix, ?window= bounds the "
        "lookback seconds, ?rate=false serves raw deltas",
    "/debug/slo":
        "GET: SLO engine state — per-objective targets, fast/slow "
        "window burn rates, breach counts, and the sustained-burn "
        "conviction feed the watchdog convicts as kind=slo",
}
