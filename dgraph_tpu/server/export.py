"""Export: dump a Store snapshot as RDF N-Quads or JSON.

Reference parity: `worker/export.go` — stream every tablet at a read
timestamp into RDF/JSON files an operator (or the live/bulk loader) can
re-ingest. Round-trips with `loader.chunker.parse_rdf`.

Both exporters iterate via store/stream.py::iter_tablets — sorted
predicate order, one tablet faulted at a time on an out-of-core store
and released before the next, so an export never holds more than
budget + one tablet resident. In-core stores take the same code path
(get() is just a dict lookup), which is what makes the out-of-core
output byte-identical to the in-core one.
"""

from __future__ import annotations

import json
import re

import numpy as np

from dgraph_tpu.store.store import TYPE_PRED, Store
from dgraph_tpu.store.stream import iter_tablets
from dgraph_tpu.store.types import Kind


def _esc(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"')


_XS = {Kind.INT: "xs:int", Kind.FLOAT: "xs:float", Kind.BOOL: "xs:boolean",
       Kind.DATETIME: "xs:dateTime"}


def export_rdf(store: Store, out, pace=None) -> int:
    """Write N-Quads to a text file object; returns statement count."""
    n = 0
    for pred, pd in iter_tablets(store, pace=pace, job="export"):
        if pd.fwd is not None and pd.fwd.nnz:
            deg = pd.fwd.indptr[1:] - pd.fwd.indptr[:-1]
            src = np.repeat(np.arange(store.n_nodes), deg)
            for s_r, o_r in zip(src.tolist(), pd.fwd.indices.tolist()):
                out.write(f"<0x{int(store.uids[s_r]):x}> <{pred}> "
                          f"<0x{int(store.uids[o_r]):x}> .\n")
                n += 1
        for lang, col in sorted(pd.vals.items()):
            kind = pd.schema.kind
            for s_r, v in zip(col.subj.tolist(), col.vals):
                subj = f"<0x{int(store.uids[s_r]):x}>"
                if kind in _XS:
                    if isinstance(v, np.datetime64):
                        lit = f'"{v}"^^<xs:dateTime>'
                    elif kind == Kind.BOOL:
                        lit = f'"{"true" if v else "false"}"^^<xs:boolean>'
                    else:
                        lit = f'"{v}"^^<{_XS[kind]}>'
                else:
                    lit = f'"{_esc(str(v))}"'
                    if lang:
                        lit += f"@{lang}"
                out.write(f"{subj} <{pred}> {lit} .\n")
                n += 1
    return n


def export_json(store: Store, out, pace=None) -> int:
    """Write one JSON object per node (uid, values, edge uid refs).

    The per-node output dicts are the deliverable (O(output) host
    memory); STORE residency stays tablet-bounded via iter_tablets."""
    nodes: dict[int, dict] = {}

    def node(rank: int) -> dict:
        return nodes.setdefault(rank, {"uid": f"0x{int(store.uids[rank]):x}"})

    for pred, pd in iter_tablets(store, pace=pace, job="export"):
        if pd.fwd is not None and pd.fwd.nnz:
            deg = pd.fwd.indptr[1:] - pd.fwd.indptr[:-1]
            src = np.repeat(np.arange(store.n_nodes), deg)
            for s_r, o_r in zip(src.tolist(), pd.fwd.indices.tolist()):
                node(s_r).setdefault(pred, []).append(
                    {"uid": f"0x{int(store.uids[o_r]):x}"})
        for lang, col in sorted(pd.vals.items()):
            key = pred + (f"@{lang}" if lang else "")
            for s_r, v in zip(col.subj.tolist(), col.vals):
                d = node(s_r)
                pv = v.item() if isinstance(v, np.generic) and \
                    not isinstance(v, np.datetime64) else str(v)
                if pd.schema.is_list and pred != TYPE_PRED:
                    d.setdefault(key, []).append(pv)
                elif pred == TYPE_PRED:
                    d.setdefault("dgraph.type", []).append(pv)
                else:
                    d[key] = pv
    items = [nodes[r] for r in sorted(nodes)]
    json.dump(items, out, default=str)
    return len(items)
