"""Admission control: bounded concurrency, FIFO queueing, load shedding.

Reference parity: the reference bounds work at the `worker.Task` gRPC
boundary with context deadlines and lets gRPC's stream limits shed the
rest; a serving stack at north-star traffic (millions of users) needs
the explicit form — a token-based concurrency limit per LANE (reads and
mutations don't starve each other), a bounded FIFO wait queue in front
of each, and shedding: when the queue is full the request is REFUSED
with a retryable `ServerOverloaded` carrying a retry-after hint, rather
than queued into a latency collapse (the classic overload spiral:
everything admitted, nothing finishing inside its deadline).

The retry-after hint is not a guess: each lane keeps an EMA of observed
service time (the spirit of TpuGraphs' learned cost priors — measured
spans over assumed costs), so the hint scales with what the workload is
actually doing: `queued/inflight slots ahead × recent service time`.

Queued waiters respect the request's deadline: a request whose budget
expires while waiting is shed (`shed_total{reason="deadline"}`) instead
of being admitted to do work nobody will read. Token handoff is FIFO by
construction — release passes the token to the OLDEST waiter under the
lane lock, so a burst drains in arrival order.

The maintenance scheduler consults `saturated()` at tablet boundaries
and yields the machine while real traffic is queued
(store/maintenance.py `_pace`).
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque

from dgraph_tpu.utils import costprofile, locks, tracing
from dgraph_tpu.utils.metrics import METRICS

__all__ = ["AdmissionController", "ServerOverloaded", "LANES"]

LANES = ("read", "mutate")

# service-time EMA smoothing + the floor the retry-after hint never
# drops below (a hint of 0 would make clients hammer-retry)
_EMA_ALPHA = 0.2
_MIN_RETRY_S = 0.01


class ServerOverloaded(Exception):
    """RETRYABLE: the lane's wait queue is full — the server sheds
    rather than queue into latency collapse. `retry_after_s` is the
    server's estimate of when a slot frees up (HTTP surfaces it as a
    `Retry-After` header + 429)."""

    def __init__(self, msg: str, retry_after_s: float = _MIN_RETRY_S,
                 lane: str = ""):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.lane = lane


class _Waiter:
    __slots__ = ("event", "granted")

    def __init__(self):
        self.event = threading.Event()
        self.granted = False


class _Lane:
    """One admission lane: `max_inflight` tokens + a FIFO queue bounded
    at `queue_depth`."""

    def __init__(self, name: str, max_inflight: int, queue_depth: int):
        self.name = name
        self.max_inflight = max(1, int(max_inflight))
        self.queue_depth = max(0, int(queue_depth))
        self.lock = locks.make_lock(f"admission.{name}")
        self.inflight = 0
        self.waiters: deque[_Waiter] = deque()
        self.admitted_total = 0
        self.shed_total = 0
        self.service_ema_s = 0.05  # seeded guess; real spans take over

    # -- gauges ---------------------------------------------------------------
    def _publish(self) -> None:
        """Caller holds the lock."""
        METRICS.set_gauge("admission_inflight", float(self.inflight),
                          lane=self.name)
        METRICS.set_gauge("admission_queued", float(len(self.waiters)),
                          lane=self.name)

    def _retry_after_s(self, queued: int) -> float:
        """Slots ahead of a would-be waiter × recent service time."""
        ahead = (queued + self.inflight) / self.max_inflight
        return max(_MIN_RETRY_S, ahead * self.service_ema_s)

    # -- token protocol -------------------------------------------------------
    def acquire(self, ctx=None) -> None:
        """Take a token, queueing FIFO behind earlier waiters. Raises
        `ServerOverloaded` when the queue is full, or the context's
        `DeadlineExceeded`/`Cancelled` when the budget dies while
        queued."""
        with self.lock:
            if self.inflight < self.max_inflight and not self.waiters:
                self.inflight += 1
                self.admitted_total += 1
                self._publish()
                return
            if len(self.waiters) >= self.queue_depth:
                self.shed_total += 1
                hint = self._retry_after_s(len(self.waiters))
                METRICS.inc("shed_total", lane=self.name,
                            reason="queue_full")
                raise ServerOverloaded(
                    f"{self.name} lane overloaded: {self.inflight} "
                    f"inflight, {len(self.waiters)} queued (limits "
                    f"{self.max_inflight}/{self.queue_depth}); retry "
                    f"after {hint:.3f}s", retry_after_s=hint,
                    lane=self.name)
            w = _Waiter()
            self.waiters.append(w)
            self._publish()
        t0 = time.perf_counter()
        with tracing.span("admission.wait", lane=self.name):
            while True:
                timeout = None
                if ctx is not None:
                    rem = ctx.remaining_s()
                    if rem is not None:
                        timeout = max(rem, 0.0)
                if w.event.wait(timeout):
                    break
                # budget died while queued: withdraw — unless release
                # granted the token in the same instant (checked under
                # the lock), in which case we keep it and let the next
                # checkpoint raise
                with self.lock:
                    if w.granted:
                        break
                    self.waiters.remove(w)
                    self.shed_total += 1
                    self._publish()
                    METRICS.inc("shed_total", lane=self.name,
                                reason="deadline")
                if ctx is not None:
                    ctx.check("admission")
                raise ServerOverloaded(  # cancel-less fallback
                    f"{self.name} lane wait abandoned", lane=self.name)
        wait_us = (time.perf_counter() - t0) * 1e6
        METRICS.observe("admission_wait_us", wait_us, lane=self.name)
        costprofile.add("admission_wait_us", int(wait_us))

    def release(self, service_s: float | None = None) -> None:
        """Return a token; the OLDEST waiter inherits it (FIFO)."""
        with self.lock:
            if service_s is not None:
                self.service_ema_s += _EMA_ALPHA * (service_s
                                                    - self.service_ema_s)
            if self.waiters:
                w = self.waiters.popleft()
                w.granted = True
                self.admitted_total += 1
                # inflight unchanged: the token transfers to the waiter
                self._publish()
                w.event.set()
            else:
                self.inflight -= 1
                self._publish()

    def status(self) -> dict:
        with self.lock:
            return {"inflight": self.inflight,
                    "queued": len(self.waiters),
                    "max_inflight": self.max_inflight,
                    "queue_depth": self.queue_depth,
                    "admitted_total": self.admitted_total,
                    "shed_total": self.shed_total,
                    "service_ema_ms": round(self.service_ema_s * 1e3,
                                            3)}


class AdmissionController:
    """Separate read/mutate lanes over one Alpha (see module doc)."""

    def __init__(self, max_inflight: int, queue_depth: int):
        self.lanes = {name: _Lane(name, max_inflight, queue_depth)
                      for name in LANES}
        self._tls = threading.local()

    @contextlib.contextmanager
    def admit(self, lane: str, ctx=None):
        """Hold one `lane` token for the duration. Reentrant per
        thread: a nested server call (an upsert's query leg, a txn read
        inside a continued txn) rides the token its request already
        holds — re-admitting would deadlock a full lane against
        itself."""
        if getattr(self._tls, "holding", False):
            yield
            return
        ln = self.lanes[lane]
        ln.acquire(ctx)
        self._tls.holding = True
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._tls.holding = False
            ln.release(time.perf_counter() - t0)

    def queued(self) -> int:
        return sum(len(ln.waiters) for ln in self.lanes.values())

    def saturated(self) -> bool:
        """True while real traffic is queued — the signal maintenance
        yields to at tablet boundaries."""
        return any(ln.waiters for ln in self.lanes.values())

    def status(self) -> dict:
        return {"lanes": {name: ln.status()
                          for name, ln in self.lanes.items()},
                "queued": self.queued()}
