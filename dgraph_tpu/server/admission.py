"""Admission control: bounded concurrency, FIFO queueing, load shedding.

Reference parity: the reference bounds work at the `worker.Task` gRPC
boundary with context deadlines and lets gRPC's stream limits shed the
rest; a serving stack at north-star traffic (millions of users) needs
the explicit form — a token-based concurrency limit per LANE (reads and
mutations don't starve each other), a bounded FIFO wait queue in front
of each, and shedding: when the queue is full the request is REFUSED
with a retryable `ServerOverloaded` carrying a retry-after hint, rather
than queued into a latency collapse (the classic overload spiral:
everything admitted, nothing finishing inside its deadline).

The retry-after hint is not a guess: with cost priors armed
(utils/costprior.py), every request arrives with a PER-SHAPE predicted
cost, and the hint is the predicted work ahead of the would-be waiter
(inflight + queued predicted µs, divided across the lane's tokens).
Without a prediction each lane falls back to an EMA of observed service
time — decayed back to its seed after an idle period, so a quiet lane's
stale EMA can't poison the first hints of the next burst.

Cost-prior scheduling (ISSUE 9) changes two decisions when predictions
are present, and leaves the classic behavior untouched when they are
not (`cost_us=None`):

  * **Cheapest-predicted-first handoff** — release hands the token to
    the cheapest PREDICTED waiter instead of the oldest (shortest-job-
    first: a cheap lookup no longer waits behind a fleet of expensive
    recurse shapes). A starvation guard restores FIFO for any waiter
    older than `starvation_s`.
  * **Cost-aware displacement** — when the queue is full, an arriving
    request cheaper than the most expensive queued waiter DISPLACES it
    (the expensive waiter is shed, `shed_total{reason="displaced"}`)
    instead of being refused itself — sheds land on the work that was
    going to blow the deadline anyway (shed precision, measured by the
    bench "sched" stage). Every cost-informed shed records its
    predicted cost (`shed_predicted_cost_us`).

Queued waiters respect the request's deadline: a request whose budget
expires while waiting is shed (`shed_total{reason="deadline"}`) instead
of being admitted to do work nobody will read. Token handoff without
predictions is FIFO by construction — release passes the token to the
OLDEST waiter under the lane lock, so a burst drains in arrival order.

The maintenance scheduler consults `saturated()` at tablet boundaries
and yields the machine while real traffic is queued
(store/maintenance.py `_pace`).
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque

from dgraph_tpu.utils import (costprofile, flightrec, locks, memgov,
                              timeseries, tracing)
from dgraph_tpu.utils.metrics import METRICS

__all__ = ["AdmissionController", "ServerOverloaded", "LANES"]

LANES = ("read", "mutate")

# service-time EMA smoothing + the floor the retry-after hint never
# drops below (a hint of 0 would make clients hammer-retry)
_EMA_ALPHA = 0.2
_MIN_RETRY_S = 0.01
# EMA cold-start: the seed before any observation, and how long a lane
# may sit idle before its EMA is considered stale and reset to the seed
# (a quiet lane's last burst must not shape the next one's hints)
_EMA_SEED_S = 0.05
_EMA_IDLE_RESET_S = 30.0
# SJF starvation guard: a waiter queued longer than this is served
# FIFO regardless of predicted cost
_STARVATION_S = 5.0


class ServerOverloaded(Exception):
    """RETRYABLE: the lane's wait queue is full — the server sheds
    rather than queue into latency collapse. `retry_after_s` is the
    server's estimate of when a slot frees up (HTTP surfaces it as a
    `Retry-After` header + 429)."""

    def __init__(self, msg: str, retry_after_s: float = _MIN_RETRY_S,
                 lane: str = ""):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.lane = lane


class _Waiter:
    __slots__ = ("event", "granted", "displaced", "cost_us", "seq",
                 "enq_mono")

    def __init__(self, cost_us: float | None, seq: int):
        self.event = threading.Event()
        self.granted = False
        self.displaced = False          # shed by a cheaper arrival
        self.cost_us = cost_us          # predicted cost (None = unknown)
        self.seq = seq                  # arrival order (FIFO tie-break)
        self.enq_mono = time.monotonic()


class _Lane:
    """One admission lane: `max_inflight` tokens + a FIFO queue bounded
    at `queue_depth` (cost-aware handoff/displacement when predictions
    ride along — see module doc)."""

    def __init__(self, name: str, max_inflight: int, queue_depth: int):
        self.name = name
        self.max_inflight = max(1, int(max_inflight))
        self.queue_depth = max(0, int(queue_depth))
        self.lock = locks.make_lock(f"admission.{name}")
        self.inflight = 0
        self.waiters: deque[_Waiter] = deque()
        self.admitted_total = 0
        self.shed_total = 0
        self.service_ema_s = _EMA_SEED_S  # seed; real spans take over
        self.idle_reset_s = _EMA_IDLE_RESET_S
        self.starvation_s = _STARVATION_S
        self._seq = 0
        self._last_activity = time.monotonic()
        # predicted µs currently admitted (cost-aware retry hints)
        self.inflight_cost_us = 0.0
        locks.guarded(self, "admission.*")

    # -- gauges ---------------------------------------------------------------
    def _publish(self) -> None:
        """Caller holds the lock."""
        METRICS.set_gauge("admission_inflight", float(self.inflight),
                          lane=self.name)
        METRICS.set_gauge("admission_queued", float(len(self.waiters)),
                          lane=self.name)

    def _maybe_decay_ema(self, now: float) -> None:
        """Caller holds the lock. An idle lane's EMA is stale evidence:
        after `idle_reset_s` without activity it resets to the seed, so
        the first retry hints of the next burst aren't shaped by
        whatever the LAST burst happened to look like (the cold-start
        fix — regression-tested in tests/test_admission.py)."""
        if now - self._last_activity > self.idle_reset_s:
            self.service_ema_s = _EMA_SEED_S

    def _queued_cost_us(self) -> float:
        """Caller holds the lock: predicted µs waiting in the queue
        (unknown costs count as one EMA service time)."""
        ema_us = self.service_ema_s * 1e6
        return sum(w.cost_us if w.cost_us is not None else ema_us
                   for w in self.waiters)

    def _retry_after_s(self, queued: int,
                       cost_us: float | None = None) -> float:
        """Predicted work ahead of a would-be waiter, divided across
        the lane's tokens. With cost predictions the hint is the
        predicted µs actually in front (inflight + queued + the arrival
        itself); without, the classic slots-ahead × service-time EMA."""
        if cost_us is not None:
            ahead_us = (self.inflight_cost_us + self._queued_cost_us()
                        + cost_us)
            return max(_MIN_RETRY_S, ahead_us / self.max_inflight / 1e6)
        ahead = (queued + self.inflight) / self.max_inflight
        return max(_MIN_RETRY_S, ahead * self.service_ema_s)

    def _overloaded(self, hint: float, reason: str,
                    cost_us: float | None) -> ServerOverloaded:
        """Caller holds the lock: count one shed and build the error."""
        self.shed_total += 1
        METRICS.inc("shed_total", lane=self.name, reason=reason)
        flightrec.emit("admission.shed", lane=self.name, reason=reason,
                       cost_us=cost_us)
        if cost_us is not None:
            METRICS.observe("shed_predicted_cost_us", cost_us,
                            lane=self.name)
        return ServerOverloaded(
            f"{self.name} lane overloaded: {self.inflight} "
            f"inflight, {len(self.waiters)} queued (limits "
            f"{self.max_inflight}/{self.queue_depth}); retry "
            f"after {hint:.3f}s", retry_after_s=hint,
            lane=self.name)

    def _try_displace(self, cost_us: float) -> bool:
        """Caller holds the lock, queue full: shed the most expensive
        PREDICTED waiter if it is strictly costlier than the arrival —
        sheds land on the work least likely to finish inside anyone's
        deadline. Among equal costs the newest waiter goes (least
        sunk wait). Returns True when a slot was freed."""
        victim = None
        for w in self.waiters:
            if w.cost_us is None or w.cost_us <= cost_us:
                continue
            if victim is None or (w.cost_us, w.seq) > (victim.cost_us,
                                                       victim.seq):
                victim = w
        if victim is None:
            return False
        self.waiters.remove(victim)
        self.shed_total += 1
        METRICS.inc("shed_total", lane=self.name, reason="displaced")
        flightrec.emit("admission.shed", lane=self.name,
                       reason="displaced", cost_us=victim.cost_us)
        METRICS.observe("shed_predicted_cost_us", victim.cost_us,
                        lane=self.name)
        victim.displaced = True
        victim.event.set()
        return True

    # -- token protocol -------------------------------------------------------
    def acquire(self, ctx=None, cost_us: float | None = None) -> None:
        """Take a token, queueing behind earlier waiters (FIFO without
        predictions; cheapest-predicted-first with). Raises
        `ServerOverloaded` when the queue is full (and no costlier
        waiter could be displaced), or the context's
        `DeadlineExceeded`/`Cancelled` when the budget dies while
        queued."""
        with self.lock:
            now = time.monotonic()
            self._maybe_decay_ema(now)
            self._last_activity = now
            # every arrival counts (admitted or shed): the per-lane
            # rate the time-series sampler feeds the load forecast
            METRICS.inc("admission_requests_total", lane=self.name)
            if self.inflight < self.max_inflight and not self.waiters:
                self.inflight += 1
                self.admitted_total += 1
                if cost_us is not None:
                    self.inflight_cost_us += cost_us
                self._publish()
                return
            # sustained memory pressure sheds BEFORE queue-full
            # (ISSUE 16): when a cache kind is still above its high
            # watermark after a synchronous evict pass, every queued
            # admission only adds predicted cache footprint the budget
            # cannot hold — shed the arrival with a retry hint instead
            # of letting the queue convert memory pressure into OOMs.
            # Unarmed processes pay one attribute read here.
            pressured = memgov.GOVERNOR.admission_pressure()
            if pressured is not None:
                hint = self._retry_after_s(len(self.waiters), cost_us)
                raise self._overloaded(hint, "memory_pressure", cost_us)
            # predicted-load shedding (ISSUE 17): the Holt trend over
            # sampled arrival rates × this lane's predicted cost says
            # demand outruns the tokens before the forecast horizon —
            # shed NOW, while the retry hint is still short, instead
            # of after the queue fills. Disarmed (forecast flag off or
            # no sampler armed): one module-global load + None check.
            if timeseries.forecast_probe(self.name, cost_us,
                                         self.max_inflight):
                METRICS.inc("forecast_sheds_total", lane=self.name)
                hint = self._retry_after_s(len(self.waiters), cost_us)
                raise self._overloaded(hint, "forecast", cost_us)
            if len(self.waiters) >= self.queue_depth:
                if cost_us is None or not self._try_displace(cost_us):
                    hint = self._retry_after_s(len(self.waiters),
                                               cost_us)
                    raise self._overloaded(hint, "queue_full", cost_us)
            self._seq += 1
            w = _Waiter(cost_us, self._seq)
            self.waiters.append(w)
            self._publish()
        t0 = time.perf_counter()
        with tracing.span("admission.wait", lane=self.name):
            while True:
                timeout = None
                if ctx is not None:
                    rem = ctx.remaining_s()
                    if rem is not None:
                        timeout = max(rem, 0.0)
                if w.event.wait(timeout):
                    if w.displaced:
                        # a cheaper arrival took this slot: shed (the
                        # displacer already counted + removed us)
                        with self.lock:
                            hint = self._retry_after_s(
                                len(self.waiters), w.cost_us)
                            self._publish()
                        raise ServerOverloaded(
                            f"{self.name} lane wait displaced by a "
                            f"cheaper request; retry after "
                            f"{hint:.3f}s", retry_after_s=hint,
                            lane=self.name)
                    break
                # budget died while queued: withdraw — unless release
                # granted the token (or a displacement shed us) in the
                # same instant (checked under the lock), in which case
                # that outcome stands and the next checkpoint raises
                with self.lock:
                    if w.granted:
                        break
                    if not w.displaced:
                        # graftlint: allow(split-critical-section): the deadline-withdraw path — w.granted/w.displaced are re-validated under THIS acquisition before the waiter removes itself; a grant that raced the timeout wins (the break above)
                        self.waiters.remove(w)
                        self.shed_total += 1
                        self._publish()
                        METRICS.inc("shed_total", lane=self.name,
                                    reason="deadline")
                        flightrec.emit("admission.shed",
                                       lane=self.name,
                                       reason="deadline",
                                       cost_us=w.cost_us)
                if ctx is not None:
                    ctx.check("admission")
                raise ServerOverloaded(  # cancel-less fallback
                    f"{self.name} lane wait abandoned", lane=self.name)
        wait_us = (time.perf_counter() - t0) * 1e6
        METRICS.observe("admission_wait_us", wait_us, lane=self.name)
        costprofile.add("admission_wait_us", int(wait_us))

    def _pick_waiter(self) -> _Waiter:
        """Caller holds the lock, waiters non-empty. Without cost
        predictions: FIFO (oldest). With: cheapest-predicted-first,
        arrival order breaking ties — unless the oldest waiter has
        starved past `starvation_s`, which restores its FIFO turn."""
        if all(w.cost_us is None for w in self.waiters):
            return self.waiters.popleft()
        oldest = min(self.waiters, key=lambda w: w.seq)
        if time.monotonic() - oldest.enq_mono > self.starvation_s:
            w = oldest
        else:
            w = min(self.waiters,
                    key=lambda w: (w.cost_us if w.cost_us is not None
                                   else -1.0, w.seq))
        self.waiters.remove(w)
        return w

    def release(self, service_s: float | None = None,
                cost_us: float | None = None) -> None:
        """Return a token; a waiter inherits it (see _pick_waiter)."""
        with self.lock:
            now = time.monotonic()
            self._last_activity = now
            if service_s is not None:
                self.service_ema_s += _EMA_ALPHA * (service_s
                                                    - self.service_ema_s)
            if cost_us is not None:
                self.inflight_cost_us = max(
                    0.0, self.inflight_cost_us - cost_us)
            if self.waiters:
                w = self._pick_waiter()
                w.granted = True
                self.admitted_total += 1
                if w.cost_us is not None:
                    self.inflight_cost_us += w.cost_us
                # inflight unchanged: the token transfers to the waiter
                self._publish()
                w.event.set()
            else:
                self.inflight -= 1
                self._publish()

    def head_wait_s(self) -> tuple[float, float] | None:
        """(oldest waiter's wait seconds, service EMA seconds), or
        None when the queue is empty — the flight-recorder watchdog's
        queue-head stall signal (utils/flightrec.py)."""
        with self.lock:
            if not self.waiters:
                return None
            oldest = min(self.waiters, key=lambda w: w.seq)
            return (time.monotonic() - oldest.enq_mono,
                    self.service_ema_s)

    def status(self) -> dict:
        with self.lock:
            return {"inflight": self.inflight,
                    "queued": len(self.waiters),
                    "max_inflight": self.max_inflight,
                    "queue_depth": self.queue_depth,
                    "admitted_total": self.admitted_total,
                    "shed_total": self.shed_total,
                    "inflight_predicted_us":
                        round(self.inflight_cost_us, 1),
                    "queued_predicted_us":
                        round(self._queued_cost_us(), 1),
                    "service_ema_ms": round(self.service_ema_s * 1e3,
                                            3)}


class AdmissionController:
    """Separate read/mutate lanes over one Alpha (see module doc)."""

    def __init__(self, max_inflight: int, queue_depth: int):
        self.lanes = {name: _Lane(name, max_inflight, queue_depth)
                      for name in LANES}
        self._tls = threading.local()

    @contextlib.contextmanager
    def admit(self, lane: str, ctx=None, cost_us: float | None = None):
        """Hold one `lane` token for the duration. `cost_us` is the
        scheduler's predicted cost (utils/costprior.py) — None keeps
        the classic count-based behavior. Reentrant per thread: a
        nested server call (an upsert's query leg, a txn read inside a
        continued txn) rides the token its request already holds —
        re-admitting would deadlock a full lane against itself."""
        if getattr(self._tls, "holding", False):
            yield
            return
        ln = self.lanes[lane]
        ln.acquire(ctx, cost_us=cost_us)
        self._tls.holding = True
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._tls.holding = False
            ln.release(time.perf_counter() - t0, cost_us=cost_us)

    def queued(self) -> int:
        total = 0
        for ln in self.lanes.values():
            with ln.lock:
                total += len(ln.waiters)
        return total

    def saturated(self) -> bool:
        """True while real traffic is queued — the signal maintenance
        yields to at tablet boundaries. Reads the queues under each
        lane's lock (ISSUE-12 audit): the maintenance thread polls
        this while request threads append/remove waiters."""
        for ln in self.lanes.values():
            with ln.lock:
                if ln.waiters:
                    return True
        return False

    def head_waits(self) -> dict:
        """Per-lane queue-head wait + service EMA (lanes with empty
        queues omitted) — what the watchdog judges against its slack."""
        out = {}
        for name, ln in self.lanes.items():
            hw = ln.head_wait_s()
            if hw is not None:
                out[name] = {"wait_s": hw[0], "service_ema_s": hw[1]}
        return out

    def status(self) -> dict:
        return {"lanes": {name: ln.status()
                          for name, ln in self.lanes.items()},
                "queued": self.queued()}
