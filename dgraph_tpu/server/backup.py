"""Binary backup/restore: full + incremental-since-ts series.

Reference parity: `ee/backup` + `worker/backup*.go` (SURVEY §2.5) — the
enterprise binary backup: a SERIES of backups in one destination
directory, each either a full snapshot or an incremental carrying only
the commits since the previous backup's read timestamp, plus a restore
that folds the chain back into a serveable posting directory.

Layout under <dest>/:
    backup-<seq:04d>-<full|incr>/
        backup_manifest.json   {type, seq, since_ts, read_ts}
        (full)  the checkpoint snapshot files (store/checkpoint.py)
        (incr)  delta.log — WAL-format records in (since_ts, read_ts]

Incrementals read the source WAL, so they are only possible while the
WAL still covers the previous backup's read_ts (a checkpoint truncates
absorbed records); `backup()` falls back to a full backup automatically
when the chain can't be extended — same behavior as the reference when
the since-ts is below the oldest Badger version.

Durability/integrity contract (ISSUE 11):

* Every checkpoint-format file in a full backup carries a crc32 digest
  in its manifest (store/checkpoint.py v3); delta logs are WAL-framed
  (per-record CRC) and their manifests record the exact record count.
  `verify_chain` walks a whole series offline (`dgraph_tpu backup
  verify`, `POST /admin/backup/verify`); any failed check during
  restore raises a typed, retryable `StorageCorruption` naming the
  file — corruption is never folded into a serveable store silently.
* `restore` is CRASH-SAFE, RESUMABLE, and STREAMING: the chain folds
  tablet-at-a-time (under `memory_budget` on stores larger than RAM)
  into a `ckpt-<ts>` staging subdir, journaling each completed tablet
  to an fsync'd WAL-format restore journal. A kill at ANY point leaves
  either the previous store or the completed one serveable — never
  neither — and a re-run resumes from the last verified tablet instead
  of starting over. CURRENT flips only after every digest re-verifies.
* `_series` skips (and the next successful backup removes) half-written
  backup dirs, so a killed backup never wedges the series.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil

from dgraph_tpu.store import checkpoint, vault
from dgraph_tpu.store.wal import Journal, _mut_doc, replay
from dgraph_tpu.utils import tracing
from dgraph_tpu.utils.metrics import METRICS

MANIFEST = "backup_manifest.json"
RESTORE_JOURNAL = "restore.journal"


def _read_backup_manifest(name: str, dirpath: str, strict: bool):
    """One backup dir's manifest, or None to skip it. Half-written dirs
    (no manifest, or the writer's .tmp still present) are skipped in
    every mode — the next successful backup removes them. A manifest
    that EXISTS but won't decode is skipped while appending (counted,
    logged — the writer must not wedge) but raises a typed
    StorageCorruption under `strict` (restore: a silently shortened
    chain would quietly restore old data)."""
    from dgraph_tpu.utils import logging as xlog

    mp = os.path.join(dirpath, MANIFEST)
    if not os.path.exists(mp) or os.path.exists(mp + ".tmp"):
        return None
    try:
        with open(mp) as f:
            m = json.load(f)
        if not isinstance(m, dict) or "seq" not in m:
            raise ValueError("not a backup manifest")
    except ValueError as e:
        if strict:
            raise vault.corruption(mp, kind="manifest",
                                   detail=str(e)) from e
        METRICS.inc("sidecar_load_failures_total",
                    file="backup_manifest.json")
        xlog.get("backup").warning(
            "skipping backup dir %s: undecodable manifest (%s)",
            dirpath, e)
        return None
    m["dir"] = dirpath
    return m


def _series(dest: str, strict: bool = False) -> list[dict]:
    """Existing backups, ascending by seq. Half-written dirs are
    skipped (never crash the next backup); `strict` escalates an
    undecodable manifest to StorageCorruption (the restore path)."""
    out = []
    if not os.path.isdir(dest):
        return out
    for name in sorted(os.listdir(dest)):
        dirpath = os.path.join(dest, name)
        if not os.path.isdir(dirpath):
            continue
        m = _read_backup_manifest(name, dirpath, strict)
        if m is not None:
            out.append(m)
    return sorted(out, key=lambda m: m["seq"])


def _clean_partial(dest: str) -> int:
    """Remove half-written backup dirs (killed mid-backup: manifest
    missing or its .tmp still present) before appending — their seq
    slot is about to be reused. Never touches dirs with an intact
    manifest, even an undecodable one (that is operator evidence)."""
    n = 0
    if not os.path.isdir(dest):
        return 0
    for name in sorted(os.listdir(dest)):
        dirpath = os.path.join(dest, name)
        if not (os.path.isdir(dirpath) and name.startswith("backup-")):
            continue
        mp = os.path.join(dirpath, MANIFEST)
        if not os.path.exists(mp) or os.path.exists(mp + ".tmp"):
            shutil.rmtree(dirpath, ignore_errors=True)
            n += 1
    return n


def backup(p_dir: str, dest: str, force_full: bool = False,
           memory_budget: int | None = None) -> dict:
    """Append one backup to the series at `dest` from the posting dir
    `p_dir` (offline form: opens its own Alpha). `memory_budget` (bytes)
    opens the source OUT-OF-CORE so a store larger than RAM backs up
    tablet-at-a-time. Returns the new manifest."""
    from dgraph_tpu.server.api import Alpha

    alpha = Alpha.open(p_dir, sync=False, memory_budget=memory_budget)
    try:
        return backup_alpha(alpha, p_dir, dest, force_full=force_full)
    finally:
        if alpha.wal is not None:
            alpha.wal.close()


def backup_alpha(alpha, p_dir: str, dest: str,
                 force_full: bool = False, pace=None) -> dict:
    """Append one backup from a LIVE Alpha (the maintenance scheduler's
    backup job runs this while the node serves). Incrementals copy only
    WAL records — never materialize anything; full backups of an
    out-of-core store stream the fold tablet-at-a-time
    (store/stream.py), so resident bytes stay under budget + one
    tablet. The series manifest format is unchanged — existing
    restore() reads both in-core- and stream-written fulls."""
    from dgraph_tpu.store import stream

    _clean_partial(dest)  # a killed predecessor's seq slot is reusable
    series = _series(dest)
    seq = (series[-1]["seq"] + 1) if series else 1
    last_ts = series[-1]["read_ts"] if series else 0

    # the oracle watermark covers EVERY replayed record — including a
    # trailing DropAll, which resets mvcc state to ts 0 and would
    # otherwise regress read_ts and fall out of the incremental window
    read_ts = max(alpha.mvcc.base_ts, alpha.oracle.max_assigned,
                  max((l.commit_ts for l in alpha.mvcc.layers), default=0))

    wal_path = (alpha.wal.path if alpha.wal is not None
                else os.path.join(p_dir, "wal.log"))
    wal_floor = alpha.mvcc.base_ts  # records ≤ this were absorbed
    incremental = (not force_full and series
                   and last_ts >= wal_floor)
    kind = "incr" if incremental else "full"
    bdir = os.path.join(dest, f"backup-{seq:04d}-{kind}")
    os.makedirs(bdir, exist_ok=True)

    if incremental:
        # WAL records in (last_ts, read_ts] — the delta since the chain tip
        seg = Journal(os.path.join(bdir, "delta.log"), sync=False)
        n = 0
        for ts, k, obj in replay(wal_path):
            if ts <= last_ts or ts > read_ts:
                continue
            if k == "mut":
                seg.append({"ts": ts, "m": _mut_doc(obj)})
            elif k == "schema":
                seg.append({"ts": ts, "schema": obj})
            elif k == "drop_attr":
                seg.append({"ts": ts, "drop_attr": obj})
            else:
                seg.append({"ts": ts, "drop": 1})
            n += 1
        seg.close()
        extra = {"records": n}
    elif stream.lazy_preds(alpha.mvcc.base) is not None:
        # out-of-core full: fold + write ONE TABLET AT A TIME straight
        # into the backup dir (no fold-point install — the backup is a
        # byproduct, not a new serving snapshot)
        _ts, _guard = stream.write_fold(alpha.mvcc, bdir, pace=pace,
                                        job="backup", manifest_ts=read_ts)
        manifest_n, _dir = checkpoint.read_manifest(bdir)
        extra = {"n_nodes": manifest_n["n_nodes"]}
        last_ts = 0
    else:
        store = alpha.mvcc.rollup()
        checkpoint.save(store, bdir, base_ts=read_ts)
        extra = {"n_nodes": store.n_nodes}
        last_ts = 0

    manifest = {"type": kind, "seq": seq,
                "since_ts": last_ts if incremental else 0,
                "read_ts": read_ts, **extra}
    # tmp + fsync + os.replace: the manifest IS the backup's commit
    # point — a kill mid-write must leave a recognizably-partial dir
    # (skipped + cleaned), never a torn manifest read as a real one
    tmp = os.path.join(bdir, MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(bdir, MANIFEST))
    return manifest


# ---------------------------------------------------------------------------
# restore: crash-safe, resumable, streaming


def _chain_of(series: list[dict], dest: str):
    """(base full manifest, later incrementals) with the contiguity
    check applied — each incr's since_ts is the previous read_ts."""
    fulls = [m for m in series if m["type"] == "full"]
    if not fulls:
        raise FileNotFoundError(f"no full backup in {dest}")
    base_m = fulls[-1]
    chain = [m for m in series
             if m["seq"] > base_m["seq"] and m["type"] == "incr"]
    prev = base_m
    for m in chain:
        if m["since_ts"] != prev["read_ts"]:
            raise ValueError(
                f"backup chain broken: seq {m['seq']} covers "
                f"({m['since_ts']}, {m['read_ts']}] but previous read_ts "
                f"is {prev['read_ts']}")
        prev = m
    return base_m, chain


class _MaskedPreds:
    """Base-store predicate mapping with dropped tablets hidden: a
    predicate dropped mid-chain must not contribute its BASE content to
    the fold (post-drop rebirth records still apply as layers)."""

    def __init__(self, inner, hidden: set):
        self._inner = inner
        self._hidden = hidden

    def get(self, pred, default=None):
        if pred in self._hidden:
            return default
        return self._inner.get(pred, default)

    def __getitem__(self, pred):
        pd = self.get(pred)
        if pd is None:
            raise KeyError(pred)
        return pd

    def __contains__(self, pred):
        return pred not in self._hidden and pred in self._inner

    def keys(self):
        return [p for p in self._inner.keys() if p not in self._hidden]

    def __iter__(self):
        return iter(self.keys())

    def __len__(self):
        return len(self.keys())

    def items(self):
        return [(p, self._inner[p]) for p in self.keys()]

    def values(self):
        return [self._inner[p] for p in self.keys()]


def _resume_state(jpath: str, fp: dict, staging: str):
    """Load the restore journal's resume state: {name: meta} for every
    tablet (and the uids block, key "__uids__") whose files RE-VERIFY
    against their journaled digests. A journal from a different chain/
    target discards itself and the staging dir — resume must never mix
    two restores."""
    done: dict[str, object] = {}
    if not os.path.exists(jpath):
        return done
    docs = list(Journal.replay(jpath))
    if not docs or docs[0].get("begin") != fp:
        os.remove(jpath)
        shutil.rmtree(staging, ignore_errors=True)
        return done
    for doc in docs[1:]:
        if "uids" in doc:
            done["__uids__"] = doc["uids"]
        elif "tablet" in doc:
            done[doc["tablet"]] = doc.get("meta")
    # drop entries whose on-disk bytes no longer match the journaled
    # digests (a torn write after the journal record is impossible —
    # segment writes are atomic and journaled AFTER — but a damaged
    # disk is exactly what we must not resume over)
    for name in list(done):
        meta = done[name]
        if name == "__uids__":
            ufile = next((os.path.join(staging, f)
                          for f in ("uids.duc", "uids.npy")
                          if os.path.exists(os.path.join(staging, f))),
                         None)
            if ufile is None or not vault.file_crc_ok(ufile, meta):
                del done[name]
        elif meta is not None:
            for fname, crc in meta.get("crc", {}).items():
                if not vault.file_crc_ok(os.path.join(staging, fname),
                                         crc):
                    del done[name]
                    break
    return done


def restore(dest: str, p_dir: str,
            memory_budget: int | None = None, pace=None) -> int:
    """Rebuild a serveable posting dir from the backup series: newest
    full + every later incremental, in order (reference: ee restore
    map/reduce over backup layers). Returns the restored max commit_ts.

    Crash-safe + resumable + streaming (module docstring): folds the
    chain ONE TABLET AT A TIME (out-of-core under `memory_budget`) into
    a versioned staging subdir with an fsync'd per-tablet journal; a
    kill at any point leaves the previous store serveable and a re-run
    resumes from the last verified tablet. Every digest re-verifies
    before the CURRENT flip."""
    from dgraph_tpu.store.mvcc import MVCCStore
    from dgraph_tpu.store.schema import parse_schema
    from dgraph_tpu.store.wal import _doc_mut

    series = _series(dest, strict=True)
    base_m, chain = _chain_of(series, dest)

    if memory_budget is not None:
        from dgraph_tpu.store.outofcore import open_out_of_core
        store, base_ts = open_out_of_core(base_m["dir"], memory_budget)
    else:
        store, base_ts = checkpoint.load(base_m["dir"])
    mvcc = MVCCStore(base=store, base_ts=base_ts)
    max_ts = base_ts
    schema = None                 # merged Alter text, applied at fold
    dropped: dict[str, int] = {}  # pred → newest drop_attr ts
    for m in chain:
        dpath = os.path.join(m["dir"], "delta.log")
        n = 0
        try:
            for doc in Journal.replay(dpath):
                ts = int(doc["ts"])
                n += 1
                if "schema" in doc:
                    merged = (schema or mvcc.schema).clone()
                    merged.update(parse_schema(doc["schema"]))
                    schema = merged
                elif "drop" in doc:
                    mvcc = MVCCStore()
                    schema = None   # post-drop alters start from scratch
                    dropped = {}
                elif "drop_attr" in doc:
                    pred = doc["drop_attr"]
                    dropped[pred] = ts
                    # a later schema record must not resurrect it
                    merged = (schema or mvcc.schema).clone()
                    merged.predicates.pop(pred, None)
                    schema = merged
                else:
                    mvcc.apply(_doc_mut(doc["m"]), ts)
                max_ts = max(max_ts, ts)
        except vault.VaultError as e:
            raise vault.corruption(dpath, kind="delta",
                                   detail=str(e)) from e
        want = m.get("records")
        if want is not None and n != int(want):
            # WAL framing CRCs every record: a bit-flip or truncation
            # silently ends the replay early — the manifest's count
            # turns that into a typed refusal naming the file
            raise vault.corruption(
                dpath, kind="delta",
                detail=f"replayed {n} of {want} records "
                       f"(torn or corrupt)")
    return _restore_fold(
        mvcc, schema, dropped, p_dir, max_ts, pace=pace,
        chain_fp={"base_seq": int(base_m["seq"]),
                  "base_ts": int(base_m["read_ts"]),
                  "links": len(chain), "max_ts": int(max_ts)})


def _sweep_plain(p_dir: str) -> None:
    """Retire a superseded PLAIN-layout snapshot after the CURRENT flip
    (best-effort: resolve() already prefers CURRENT; these files are
    unreferenced bytes)."""
    for f in os.listdir(p_dir):
        if f == "manifest.json" or f.endswith(".npy") \
                or f.endswith(".facets.json") or f in ("uids.duc",):
            with contextlib.suppress(OSError):
                os.remove(os.path.join(p_dir, f))


def _restore_fold(mvcc, schema, dropped, p_dir: str, max_ts: int,
                  chain_fp: dict, pace=None) -> int:
    """Fold the replayed chain into `p_dir`, tablet-at-a-time, under a
    versioned staging subdir + fsync'd restore journal (see restore)."""
    from dgraph_tpu import native
    from dgraph_tpu.store import stream
    from dgraph_tpu.store.mvcc import (_Layer, _materialize, fold_preds,
                                       fold_vocab)
    from dgraph_tpu.store.store import Store

    os.makedirs(p_dir, exist_ok=True)
    jpath = os.path.join(p_dir, RESTORE_JOURNAL)
    sub = checkpoint.begin_versioned(p_dir, max_ts)
    if sub is None:
        # CURRENT already names this exact restore (a re-run after the
        # flip): finish the cleanup the kill skipped and report done
        with contextlib.suppress(OSError):
            os.remove(jpath)
        _sweep_plain(p_dir)
        return max_ts
    staging = os.path.join(p_dir, sub)

    plan = mvcc.fold_plan()
    _fold_ts, base, pending, _new_ts, _guard = plan
    # drop-aware effective layers: records at or below a predicate's
    # drop point are excluded (Mutation.exclude keeps the vocab touch
    # set, so the fold vocabulary is unchanged); the predicate's BASE
    # content is masked out entirely — only post-drop rebirths survive
    eff = []
    for l in pending:
        gone = {p for p, cut in dropped.items() if l.commit_ts <= cut}
        eff.append(_Layer(l.commit_ts, l.mut.exclude(gone))
                   if gone else l)
    base_eff = base
    if dropped:
        base_eff = Store(uids=base.uids, schema=base.schema,
                         preds=_MaskedPreds(base.preds, set(dropped)))
    schema_final = (schema if schema is not None else base.schema).clone()
    for pred in dropped:
        if not any(rec[1] == pred
                   for l in eff
                   for rec in (l.mut.edge_sets + l.mut.edge_dels
                               + l.mut.val_sets)):
            schema_final.predicates.pop(pred, None)
    # no pending records, drops, or alters: stream base tablets through
    # verbatim (skipping the builder round-trip keeps segments
    # byte-identical to the backup's own — the stream.write_fold rule)
    trivial = not eff and not dropped and schema is None
    vocab = base.uids if trivial else fold_vocab(base_eff, eff)
    names = fold_preds(base_eff, eff)
    alive = []
    for pred in names:
        if pred in dropped and not any(
                rec[1] == pred for l in eff
                for rec in (l.mut.edge_sets + l.mut.edge_dels
                            + l.mut.val_sets + l.mut.val_dels)):
            continue  # dropped, never reborn
        alive.append(pred)

    fp = {"sub": sub, "chain": chain_fp}
    done = _resume_state(jpath, fp, staging)
    journal = Journal(jpath, sync=True)
    if not done:
        journal.rewrite([{"begin": fp}])
    else:
        METRICS.inc("restore_resumed_total")

    compress = native.HAVE_NATIVE
    lazy = stream.lazy_preds(base)
    written = resumed = 0
    try:
        with tracing.span("maintenance.job", job="restore") as sp:
            os.makedirs(staging, exist_ok=True)
            uids_crc = done.get("__uids__")
            if uids_crc is None:
                uids_crc = checkpoint.save_uids(vocab, staging, compress)
                journal.append({"uids": uids_crc})
            preds_meta = {}
            for pred in alive:
                if pred in done:
                    meta = done[pred]
                    if meta is not None:
                        preds_meta[pred] = meta
                    resumed += 1
                    METRICS.inc("restore_tablets_total",
                                outcome="resumed")
                    continue
                was_resident = (lazy.is_resident(pred)
                                if lazy is not None else True)
                with tracing.span("maintenance.tablet", pred=pred,
                                  job="restore"):
                    if trivial:
                        pd = base.preds.get(pred)
                    else:
                        folded = _materialize(base_eff, eff,
                                              schema=schema_final,
                                              only={pred}, vocab=vocab)
                        pd = folded.preds.get(pred)
                    meta = (checkpoint.save_predicate(staging, pred, pd)
                            if pd is not None else None)
                    if meta is not None:
                        preds_meta[pred] = meta
                    # the journal record lands AFTER the tablet's atomic
                    # segment writes: a kill between them re-writes the
                    # tablet, never trusts a half-written one
                    journal.append({"tablet": pred, "meta": meta})
                del pd
                if lazy is not None and not was_resident:
                    lazy.release(pred)
                written += 1
                METRICS.inc("restore_tablets_total", outcome="written")
                if pace is not None:
                    pace()
            checkpoint.write_manifest(staging, checkpoint.manifest_doc(
                int(len(vocab)), schema_final.to_text(), preds_meta,
                max_ts, compress, uids_crc=uids_crc))
            # EVERY digest re-verifies before the flip — a restore must
            # never install a store it cannot prove intact
            problems = [p for p in checkpoint.verify_snapshot(staging)
                        if p["kind"] != "undigested"]
            if problems:
                raise vault.corruption(
                    problems[0]["file"], kind=problems[0]["kind"],
                    detail=f"restore re-verify failed "
                           f"({len(problems)} file(s))")
            # fresh empty WAL BEFORE the flip: everything restored lives
            # in the checkpoint. (Flipping first would let a crash
            # replay the REPLACED store's WAL tail onto the restored
            # snapshot; this order's worst case is the doomed old store
            # minus its tail — still serveable.)
            vault.atomic_write(os.path.join(p_dir, "wal.log"), b"")
            checkpoint.commit_versioned(p_dir, sub)
            sp.attrs["tablets_total"] = len(alive)
            sp.attrs["tablets_written"] = written
            sp.attrs["tablets_resumed"] = resumed
    finally:
        journal.close()
    _sweep_plain(p_dir)
    with contextlib.suppress(OSError):
        os.remove(jpath)
    return max_ts


# ---------------------------------------------------------------------------
# offline chain verification (`dgraph_tpu backup verify`,
# POST /admin/backup/verify)


def verify_chain(dest: str) -> dict:
    """Walk a backup series offline: manifest decode, per-file digests
    of every full (store/checkpoint.py v3), per-record CRC + exact
    record count of every delta log, and chain contiguity. Returns
    {"ok", "backups", "errors", "warnings"} — `errors` name the exact
    files; `warnings` cover advisory states (half-written dirs awaiting
    cleanup, pre-digest snapshots)."""
    report = {"dest": dest, "ok": True, "backups": [],
              "errors": [], "warnings": []}
    if not os.path.isdir(dest):
        report["ok"] = False
        report["errors"].append({"file": dest, "kind": "chain",
                                 "detail": "no such backup dir"})
        return report
    series = []
    for name in sorted(os.listdir(dest)):
        dirpath = os.path.join(dest, name)
        if not os.path.isdir(dirpath):
            continue
        mp = os.path.join(dirpath, MANIFEST)
        if not os.path.exists(mp) or os.path.exists(mp + ".tmp"):
            report["warnings"].append(
                {"dir": dirpath,
                 "detail": "half-written backup dir (skipped; the next "
                           "successful backup removes it)"})
            continue
        try:
            m = _read_backup_manifest(name, dirpath, strict=True)
        except vault.StorageCorruption as e:
            report["errors"].append({"file": e.path, "kind": e.kind,
                                     "detail": str(e)})
            continue
        if m is not None:
            series.append(m)
    series.sort(key=lambda m: m["seq"])

    for m in series:
        entry = {"dir": m["dir"], "seq": m["seq"], "type": m["type"],
                 "status": "ok"}
        if m["type"] == "full":
            try:
                problems = checkpoint.verify_snapshot(m["dir"])
            except vault.StorageCorruption as e:
                problems = [{"file": e.path, "kind": e.kind,
                             "detail": str(e)}]
            for p in problems:
                if p["kind"] == "undigested":
                    report["warnings"].append(p)
                else:
                    report["errors"].append(p)
                    entry["status"] = "corrupt"
        else:
            dpath = os.path.join(m["dir"], "delta.log")
            want = m.get("records")
            try:
                n = sum(1 for _ in Journal.replay(dpath))
            except vault.VaultError as e:
                report["errors"].append({"file": dpath, "kind": "delta",
                                         "detail": str(e)})
                entry["status"] = "corrupt"
                n = None
            if n is not None and want is not None and n != int(want):
                report["errors"].append(
                    {"file": dpath, "kind": "delta",
                     "detail": f"{n} of {want} records intact"})
                entry["status"] = "corrupt"
        report["backups"].append(entry)

    try:
        _chain_of(series, dest)
    except (FileNotFoundError, ValueError) as e:
        report["errors"].append({"file": dest, "kind": "chain",
                                 "detail": str(e)})
    report["ok"] = not report["errors"]
    return report
