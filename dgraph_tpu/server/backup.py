"""Binary backup/restore: full + incremental-since-ts series.

Reference parity: `ee/backup` + `worker/backup*.go` (SURVEY §2.5) — the
enterprise binary backup: a SERIES of backups in one destination
directory, each either a full snapshot or an incremental carrying only
the commits since the previous backup's read timestamp, plus a restore
that folds the chain back into a serveable posting directory.

Layout under <dest>/:
    backup-<seq:04d>-<full|incr>/
        backup_manifest.json   {type, seq, since_ts, read_ts}
        (full)  the checkpoint snapshot files (store/checkpoint.py)
        (incr)  delta.log — WAL-format records in (since_ts, read_ts]

Incrementals read the source WAL, so they are only possible while the
WAL still covers the previous backup's read_ts (a checkpoint truncates
absorbed records); `backup()` falls back to a full backup automatically
when the chain can't be extended — same behavior as the reference when
the since-ts is below the oldest Badger version.
"""

from __future__ import annotations

import json
import os
import shutil

from dgraph_tpu.store import checkpoint
from dgraph_tpu.store.wal import Journal, WAL, _mut_doc, replay

MANIFEST = "backup_manifest.json"


def _series(dest: str) -> list[dict]:
    """Existing backups, ascending by seq."""
    out = []
    if not os.path.isdir(dest):
        return out
    for name in sorted(os.listdir(dest)):
        mp = os.path.join(dest, name, MANIFEST)
        if os.path.exists(mp):
            with open(mp) as f:
                m = json.load(f)
            m["dir"] = os.path.join(dest, name)
            out.append(m)
    return sorted(out, key=lambda m: m["seq"])


def backup(p_dir: str, dest: str, force_full: bool = False,
           memory_budget: int | None = None) -> dict:
    """Append one backup to the series at `dest` from the posting dir
    `p_dir` (offline form: opens its own Alpha). `memory_budget` (bytes)
    opens the source OUT-OF-CORE so a store larger than RAM backs up
    tablet-at-a-time. Returns the new manifest."""
    from dgraph_tpu.server.api import Alpha

    alpha = Alpha.open(p_dir, sync=False, memory_budget=memory_budget)
    try:
        return backup_alpha(alpha, p_dir, dest, force_full=force_full)
    finally:
        if alpha.wal is not None:
            alpha.wal.close()


def backup_alpha(alpha, p_dir: str, dest: str,
                 force_full: bool = False, pace=None) -> dict:
    """Append one backup from a LIVE Alpha (the maintenance scheduler's
    backup job runs this while the node serves). Incrementals copy only
    WAL records — never materialize anything; full backups of an
    out-of-core store stream the fold tablet-at-a-time
    (store/stream.py), so resident bytes stay under budget + one
    tablet. The series manifest format is unchanged — existing
    restore() reads both in-core- and stream-written fulls."""
    from dgraph_tpu.store import stream

    series = _series(dest)
    seq = (series[-1]["seq"] + 1) if series else 1
    last_ts = series[-1]["read_ts"] if series else 0

    # the oracle watermark covers EVERY replayed record — including a
    # trailing DropAll, which resets mvcc state to ts 0 and would
    # otherwise regress read_ts and fall out of the incremental window
    read_ts = max(alpha.mvcc.base_ts, alpha.oracle.max_assigned,
                  max((l.commit_ts for l in alpha.mvcc.layers), default=0))

    wal_path = (alpha.wal.path if alpha.wal is not None
                else os.path.join(p_dir, "wal.log"))
    wal_floor = alpha.mvcc.base_ts  # records ≤ this were absorbed
    incremental = (not force_full and series
                   and last_ts >= wal_floor)
    kind = "incr" if incremental else "full"
    bdir = os.path.join(dest, f"backup-{seq:04d}-{kind}")
    os.makedirs(bdir, exist_ok=True)

    if incremental:
        # WAL records in (last_ts, read_ts] — the delta since the chain tip
        seg = Journal(os.path.join(bdir, "delta.log"), sync=False)
        n = 0
        for ts, k, obj in replay(wal_path):
            if ts <= last_ts or ts > read_ts:
                continue
            if k == "mut":
                seg.append({"ts": ts, "m": _mut_doc(obj)})
            elif k == "schema":
                seg.append({"ts": ts, "schema": obj})
            elif k == "drop_attr":
                seg.append({"ts": ts, "drop_attr": obj})
            else:
                seg.append({"ts": ts, "drop": 1})
            n += 1
        seg.close()
        extra = {"records": n}
    elif stream.lazy_preds(alpha.mvcc.base) is not None:
        # out-of-core full: fold + write ONE TABLET AT A TIME straight
        # into the backup dir (no fold-point install — the backup is a
        # byproduct, not a new serving snapshot)
        _ts, _guard = stream.write_fold(alpha.mvcc, bdir, pace=pace,
                                        job="backup", manifest_ts=read_ts)
        manifest_n, _dir = checkpoint.read_manifest(bdir)
        extra = {"n_nodes": manifest_n["n_nodes"]}
        last_ts = 0
    else:
        store = alpha.mvcc.rollup()
        checkpoint.save(store, bdir, base_ts=read_ts)
        extra = {"n_nodes": store.n_nodes}
        last_ts = 0

    manifest = {"type": kind, "seq": seq,
                "since_ts": last_ts if incremental else 0,
                "read_ts": read_ts, **extra}
    tmp = os.path.join(bdir, MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, os.path.join(bdir, MANIFEST))
    return manifest


def restore(dest: str, p_dir: str) -> int:
    """Rebuild a serveable posting dir from the backup series: newest
    full + every later incremental, in order (reference: ee restore map/
    reduce over backup layers). Returns the restored max commit_ts."""
    from dgraph_tpu.store.mvcc import MVCCStore
    from dgraph_tpu.store.schema import parse_schema
    from dgraph_tpu.store.wal import _doc_mut

    series = _series(dest)
    fulls = [m for m in series if m["type"] == "full"]
    if not fulls:
        raise FileNotFoundError(f"no full backup in {dest}")
    base_m = fulls[-1]
    chain = [m for m in series
             if m["seq"] > base_m["seq"] and m["type"] == "incr"]
    # the chain must be contiguous: each incr's since_ts is the previous
    # backup's read_ts
    prev = base_m
    for m in chain:
        if m["since_ts"] != prev["read_ts"]:
            raise ValueError(
                f"backup chain broken: seq {m['seq']} covers "
                f"({m['since_ts']}, {m['read_ts']}] but previous read_ts "
                f"is {prev['read_ts']}")
        prev = m

    store, base_ts = checkpoint.load(base_m["dir"])
    mvcc = MVCCStore(base=store, base_ts=base_ts)
    max_ts = base_ts
    schema = None
    for m in chain:
        for doc in Journal.replay(os.path.join(m["dir"], "delta.log")):
            ts = int(doc["ts"])
            if "schema" in doc:
                merged = (schema or mvcc.schema).clone()
                merged.update(parse_schema(doc["schema"]))
                schema = merged
                mvcc.rebuild_base(schema=merged)
            elif "drop" in doc:
                mvcc = MVCCStore()
                schema = None   # post-drop alters start from scratch
            elif "drop_attr" in doc:
                mvcc.drop_predicate(doc["drop_attr"], ts)
                if schema is not None:
                    # a later schema record must not resurrect it
                    schema.predicates.pop(doc["drop_attr"], None)
            else:
                mvcc.apply(_doc_mut(doc["m"]), ts)
            max_ts = max(max_ts, ts)

    final = mvcc.rollup() if mvcc.layers else mvcc.base
    if os.path.isdir(p_dir):
        shutil.rmtree(p_dir)
    checkpoint.save_versioned(final, p_dir, base_ts=max_ts)
    # a fresh (empty) WAL: everything restored lives in the checkpoint
    WAL(os.path.join(p_dir, "wal.log"), sync=False).close()
    return max_ts
