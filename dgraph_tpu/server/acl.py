"""Access control lists: users, groups, predicate permissions, login.

Reference parity: `ee/acl` (SURVEY §2.5) — ACL state lives IN the graph
itself under reserved predicates (`dgraph.xid`, `dgraph.password`,
`dgraph.user.group`, `dgraph.rule.predicate`, `dgraph.rule.permission`),
a `groot` superuser in the `guardians` group is bootstrapped on first
start, login returns a signed access token, and enforcement hides
unreadable predicates from queries / refuses unwritable mutations.

Permissions are a bitmask per (group, predicate): READ=4, WRITE=2,
MODIFY=1 (the reference's values). Guardians bypass all checks. Tokens
are HMAC-SHA256-signed JSON (userid + expiry) — the role the reference's
JWTs play, without a JWT dependency.

Enforcement is store-level: an unreadable predicate simply does not
exist in the user's view (reference: query rewriting drops unauthorized
predicates rather than erroring), so every engine path — filters,
expand, recurse — inherits the policy.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import re
import time
from dgraph_tpu.store.types import check_password, hash_password

READ, WRITE, MODIFY = 4, 2, 1
GROOT, GUARDIANS = "groot", "guardians"
RESERVED = ("dgraph.xid", "dgraph.password", "dgraph.user.group",
            "dgraph.rule.predicate", "dgraph.rule.permission",
            "dgraph.acl.rule")
ACL_SCHEMA = """
dgraph.xid: string @index(exact) @upsert .
dgraph.password: string .
dgraph.user.group: [uid] @reverse .
dgraph.acl.rule: [uid] .
dgraph.rule.predicate: string .
dgraph.rule.permission: int .
"""
TOKEN_TTL_S = 3600.0


class AclError(PermissionError):
    pass


_USERID_RE = re.compile(r"^[A-Za-z0-9_.-]{1,64}$")


def _check_userid(userid: str) -> str:
    """User ids are spliced into DQL lookups — a strict charset is the
    injection guard (reference: xid validation)."""
    if not _USERID_RE.match(userid or ""):
        raise AclError(f"invalid userid {userid!r}")
    return userid


def _hash_password(password: str) -> str:
    return hash_password(password)


def _check_password(password: str, stored: str) -> bool:
    return check_password(password, stored)


class AclManager:
    """Login + enforcement against ACL state stored in the graph."""

    def __init__(self, alpha, secret: str):
        self.alpha = alpha
        self.secret = secret.encode()
        self._perm_cache: tuple[int, dict] | None = None

    # -- bootstrap -----------------------------------------------------------
    def ensure_groot(self, password: str = "password") -> None:
        """First-start bootstrap: groot user in the guardians group
        (reference: ee/acl ResetAcl)."""
        self.alpha.alter(ACL_SCHEMA)
        out = self._query(
            '{ q(func: eq(dgraph.xid, "%s")) { uid } }' % GROOT)
        if out["q"]:
            return
        self.alpha.mutate(set_nquads=f'''
            _:g <dgraph.xid> "{GUARDIANS}" .
            _:u <dgraph.xid> "{GROOT}" .
            _:u <dgraph.password> "{_hash_password(password)}" .
            _:u <dgraph.user.group> _:g .
        ''')

    def _query(self, q: str) -> dict:
        # internal reads bypass enforcement (the manager IS the authority)
        return self.alpha.query(q)

    # -- login / tokens -------------------------------------------------------
    def login(self, userid: str, password: str) -> str:
        userid = _check_userid(userid)
        out = self._query(
            '{ q(func: eq(dgraph.xid, "%s")) { dgraph.password } }'
            % userid)
        rows = [r for r in out["q"] if "dgraph.password" in r]
        if not rows or not _check_password(password,
                                           rows[0]["dgraph.password"]):
            raise AclError("invalid credentials")
        # graftlint: allow(wall-clock): token exp is verified by any
        # alpha sharing the HMAC secret — a monotonic reading is
        # meaningless across processes
        doc = json.dumps({"u": userid,
                          "exp": time.time() + TOKEN_TTL_S},
                         separators=(",", ":")).encode()
        sig = hmac.new(self.secret, doc, hashlib.sha256).digest()
        return (base64.urlsafe_b64encode(doc).decode() + "." +
                base64.urlsafe_b64encode(sig).decode())

    def verify(self, token: str | None) -> str:
        if not token:
            raise AclError("no access token")
        try:
            doc_b64, sig_b64 = token.split(".", 1)
            doc = base64.urlsafe_b64decode(doc_b64)
            sig = base64.urlsafe_b64decode(sig_b64)
        except Exception:  # noqa: BLE001
            raise AclError("malformed access token") from None
        want = hmac.new(self.secret, doc, hashlib.sha256).digest()
        if not hmac.compare_digest(sig, want):
            raise AclError("bad token signature")
        payload = json.loads(doc)
        # graftlint: allow(wall-clock): see login() — cross-process exp
        if payload["exp"] < time.time():
            raise AclError("token expired")
        return _check_userid(payload["u"])

    # -- permissions ----------------------------------------------------------
    def perms_for(self, userid: str):
        """(is_guardian, {pred: bitmask}) for a user — union over their
        groups' rules. Cached per committed version."""
        userid = _check_userid(userid)
        ver = self.alpha.oracle.max_assigned
        if self._perm_cache is not None and self._perm_cache[0] == ver:
            cached = self._perm_cache[1].get(userid)
            if cached is not None:
                return cached
        out = self._query('''
        { q(func: eq(dgraph.xid, "%s")) {
            dgraph.user.group {
              dgraph.xid
              dgraph.acl.rule {
                dgraph.rule.predicate dgraph.rule.permission } } } }'''
                          % userid)
        guardian = False
        perms: dict[str, int] = {}
        for user in out["q"]:
            for grp in user.get("dgraph.user.group", []):
                if grp.get("dgraph.xid") == GUARDIANS:
                    guardian = True
                for rule in grp.get("dgraph.acl.rule", []):
                    p = rule.get("dgraph.rule.predicate")
                    m = rule.get("dgraph.rule.permission", 0)
                    if p:
                        perms[p] = perms.get(p, 0) | int(m)
        result = (guardian, perms)
        if self._perm_cache is None or self._perm_cache[0] != ver:
            self._perm_cache = (ver, {})
        self._perm_cache[1][userid] = result
        return result

    # -- enforcement ----------------------------------------------------------
    def check_alter(self, userid: str) -> None:
        guardian, _ = self.perms_for(userid)
        if not guardian:
            raise AclError(f"{userid!r} is not a guardian: alter denied")

    def check_mutation(self, userid: str, preds) -> None:
        guardian, perms = self.perms_for(userid)
        if guardian:
            return
        for p in preds:
            if p == "dgraph.type":
                continue  # typed nodes are writable by any user (ref)
            if p.startswith("dgraph."):
                raise AclError(f"reserved predicate {p!r}: denied")
            if not perms.get(p, 0) & WRITE:
                raise AclError(f"no write permission on {p!r}")

    def readable_view(self, userid: str, store):
        """Store view hiding unreadable predicates (reference: unauth
        predicates are dropped from the query, not errored)."""
        guardian, perms = self.perms_for(userid)
        if guardian:
            return store
        allowed = {p for p, m in perms.items() if m & READ}

        from dgraph_tpu.store.store import Store
        rs = object.__new__(Store)
        rs.uids = store.uids
        rs.schema = store.schema
        rs.preds = _AclPreds(store.preds, allowed)
        # allowed preds are the SAME objects as the underlying store's, so
        # device/sort-key caches are shared — an ACL view must not
        # re-upload the working set per query
        rs._device = store._device
        rs._empty_rel = store._empty_rel
        rs._ell_host = getattr(store, "_ell_host", store)
        for attr in ("_key_cols", "_key_cols_mesh"):
            if hasattr(store, attr):
                setattr(rs, attr, getattr(store, attr))
        rem = getattr(store, "remote_expand", None)
        if rem is not None:
            def remote_expand(pred, reverse, frontier):
                if pred not in allowed:
                    return None
                return rem(pred, reverse, frontier)
            rs.remote_expand = remote_expand
        return rs


class _AclPreds(dict):
    def __init__(self, inner, allowed):
        super().__init__()
        self._inner = inner
        self._allowed = allowed

    def _ok(self, pred) -> bool:
        if pred == "dgraph.type":
            return True  # type membership is readable by any user (ref)
        return pred in self._allowed and not str(pred).startswith("dgraph.")

    def get(self, pred, default=None):
        if not self._ok(pred):
            return default
        return self._inner.get(pred, default)

    def __getitem__(self, pred):
        out = self.get(pred)
        if out is None:
            raise KeyError(pred)
        return out

    def __contains__(self, pred):
        return self.get(pred) is not None

    def __iter__(self):
        return (p for p in self._inner if self._ok(p))

    def keys(self):
        return [p for p in self._inner if self._ok(p)]

    def items(self):
        return [(p, v) for p, v in self._inner.items() if self._ok(p)]
