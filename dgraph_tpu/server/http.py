"""HTTP API: the Alpha's REST surface.

Reference parity: `dgraph/cmd/alpha/run.go` HTTP handlers — POST /query,
/mutate, /alter, /commit; GET /health, /state (cluster topology JSON) and
/debug/prometheus_metrics (metrics endpoint, utils/metrics.py). stdlib
ThreadingHTTPServer: one Alpha process serves both transports, as the
reference serves 8080 (HTTP) beside 9080 (gRPC).
"""

from __future__ import annotations

import contextlib
import json
import select
import socket
import threading
import time
import urllib.parse
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dgraph_tpu.dql.upsert import is_upsert as _is_upsert
from dgraph_tpu.server.admission import ServerOverloaded
from dgraph_tpu.server.api import (Alpha, NoQuorum, ReadUnavailable,
                                   TxnAborted)
from dgraph_tpu.server.debug_routes import DEBUG_ENDPOINTS
from dgraph_tpu.utils import costprofile, flightrec, locks
from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.utils import logging as xlog
from dgraph_tpu.utils import tracing
from dgraph_tpu.utils.deadline import Cancelled, DeadlineExceeded
from dgraph_tpu.utils.metrics import METRICS

# runtime debug route tables: path → Handler method name. Keyed on the
# same paths as the DEBUG_ENDPOINTS inventory (server/debug_routes.py);
# tests/test_lint.py pins table ↔ inventory in both directions, so a
# handler without an inventory row (or vice versa) fails tier-1.
_DEBUG_GET = {
    "/debug": "_dbg_index",
    "/debug/prometheus_metrics": "_dbg_metrics",
    "/debug/traces": "_dbg_traces",
    "/debug/events": "_dbg_events",
    "/debug/costs": "_dbg_costs",
    "/debug/slow_queries": "_dbg_slow_queries",
    "/debug/profile": "_dbg_profile",
    "/debug/scheduler": "_dbg_scheduler",
    "/debug/admission": "_dbg_admission",
    "/debug/locks": "_dbg_locks",
    "/debug/races": "_dbg_races",
    "/debug/peers": "_dbg_peers",
    "/debug/flightrecorder": "_dbg_flightrec",
    "/debug/fleet": "_dbg_fleet",
    "/debug/fleet/flight": "_dbg_fleet_flight",
    "/debug/memory": "_dbg_memory",
    "/debug/timeseries": "_dbg_timeseries",
    "/debug/slo": "_dbg_slo",
}
_DEBUG_POST = {
    "/debug/profile": "_post_profile",
    "/debug/flightrecorder": "_post_flightrec",
}


def _route_of(path: str, table: dict) -> str | None:
    """Longest-prefix match of a request path against a route table
    ("/debug" itself matches only exactly — it is the index, not a
    catch-all)."""
    p = path.partition("?")[0].rstrip("/") or "/"
    if p == "/debug" and "/debug" in table:
        return "/debug"
    best = None
    for route in table:
        if route != "/debug" and p.startswith(route):
            if best is None or len(route) > len(best):
                best = route
    return best

# structured slow-query ring: every --slow_query_ms overrun keeps its
# trace_id alongside the log line, so GET /debug/slow_queries →
# /debug/traces?trace_id= resolves a slow query's full span tree in
# one hop (the log-line form carried the id; nothing served it)
_SLOW_MAX = 256
_SLOW_LOG: deque = deque(maxlen=_SLOW_MAX)
_SLOW_LOCK = locks.make_lock("http.slowlog")


def slow_queries_snapshot(trace_id: str | None = None) -> list[dict]:
    """The slow-query ring as served by /debug/slow_queries — shared
    with the flight-recorder bundle builder (utils/flightrec.py) so a
    dump carries the same view an operator would have pulled live."""
    now = dl.monotonic_s()
    with _SLOW_LOCK:
        entries = [e for e in _SLOW_LOG
                   if trace_id is None or e["trace_id"] == trace_id]
    return [{**{k: v for k, v in e.items() if k != "mono_s"},
             "age_s": round(now - e["mono_s"], 3)}
            for e in entries]

# how often the per-request watcher peeks the client socket for a
# mid-request disconnect (an abandoned request must release its
# admission token early instead of computing into the void)
DISCONNECT_POLL_S = 0.05


def _socket_closed(conn) -> bool:
    """Has the client closed its end? A zero-byte MSG_PEEK read on a
    readable socket means EOF; pending request bytes (pipelining) mean
    it is alive. Never consumes data, never blocks."""
    try:
        r, _w, _x = select.select([conn], [], [], 0)
        if not r:
            return False
        flags = socket.MSG_PEEK | getattr(socket, "MSG_DONTWAIT", 0)
        return conn.recv(1, flags) == b""
    except (BlockingIOError, InterruptedError):
        return False
    except OSError:
        return True  # the socket object itself is dead


def _parse_timeout_ms(val: str) -> float:
    """`?timeout=` value → ms. Accepts the Dgraph/Go duration forms the
    reference takes (`500ms`, `2s`, `1m`) and a bare number (seconds)."""
    v = val.strip().lower()
    try:
        if v.endswith("ms"):
            return float(v[:-2])
        if v.endswith("s") and not v.endswith("ms"):
            return float(v[:-1]) * 1e3
        if v.endswith("m"):
            return float(v[:-1]) * 60e3
        return float(v) * 1e3
    except ValueError:
        raise ValueError(f"bad timeout value {val!r}: want e.g. "
                         f"500ms, 2s, or seconds as a number") from None


def make_http_server(alpha: Alpha, addr: str = "127.0.0.1",
                     port: int = 0) -> ThreadingHTTPServer:
    start_time = dl.monotonic_s()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet (x.Logger role is utils.logging)
            pass

        def _send(self, code: int, body: dict | str,
                  ctype: str = "application/json"):
            data = (json.dumps(body) if not isinstance(body, str)
                    else body).encode()
            self._send_bytes(code, data, ctype)

        def _send_bytes(self, code: int, data: bytes,
                        ctype: str = "application/json",
                        headers: dict | None = None):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _deadline_ms(self):
            """Request budget from `?timeout=` (Go-duration form) or the
            `X-Deadline-Ms` header (None = server default applies)."""
            qs = urllib.parse.parse_qs(
                urllib.parse.urlsplit(self.path).query)
            t = (qs.get("timeout") or [None])[0]
            if t:
                return _parse_timeout_ms(t)
            h = self.headers.get("X-Deadline-Ms")
            return float(h) if h else None

        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(n)

        @contextlib.contextmanager
        def _disconnect_watch(self):
            """Cancel this request's context when the client hangs up
            mid-flight (ROADMAP PR-4 follow-on: the cancel flag was
            wired; this is the socket watcher). The handler thread's
            ACTIVE context is looked up per poll — the context is
            created later, inside Alpha._request, on that thread."""
            stop = threading.Event()
            ident = threading.get_ident()
            conn = self.connection

            def watch():
                while not stop.wait(DISCONNECT_POLL_S):
                    if _socket_closed(conn):
                        ctx = dl.of_thread(ident)
                        if ctx is not None and not ctx.cancelled:
                            METRICS.inc("request_cancelled_total",
                                        stage="disconnect")
                            ctx.cancel()
                        return

            t = threading.Thread(target=watch, daemon=True)
            t.start()
            try:
                yield
            finally:
                stop.set()

        def do_GET(self):
            if self.path == "/health":
                self._send(200, [{"status": "healthy",
                                  "uptime": int(dl.monotonic_s() - start_time)}])
            elif self.path == "/state":
                if alpha.groups is not None:
                    # cluster mode: real topology from Zero, including
                    # liveness (reference: /state mirrors the membership
                    # stream with health marking). Zero being down must
                    # produce an error RESPONSE, not a crashed handler.
                    import grpc as _grpc
                    try:
                        ms = alpha.groups.zero.membership()
                    except _grpc.RpcError as e:
                        self._send(503, {"errors": [{
                            "message": f"zero unreachable: {e.code()}"}]})
                        return
                    dead = {int(d) for d in ms.dead}

                    def group_doc(grp):
                        # any-coordinator design: no raft leader; the
                        # flag marks the lowest LIVE member for shape
                        # parity (none when the whole group is dark)
                        live = [int(m) for m in grp.nodes
                                if int(m) not in dead]
                        lead = min(live) if live else None
                        return {
                            "members": {str(n): {
                                "id": str(n), "addr": a,
                                "leader": int(n) == lead,
                                "alive": int(n) not in dead}
                                for n, a in grp.nodes.items()},
                            "tablets": {p: {"predicate": p}
                                        for p in grp.tablets}}

                    st = {"counter": int(ms.counter),
                          "groups": {str(g): group_doc(grp)
                                     for g, grp in ms.groups.items()},
                          "dead": sorted(dead),
                          "maxUID": alpha.mvcc.uid_high(),
                          "maxTxnTs": alpha.oracle.max_assigned}
                else:
                    st = {"counter": alpha.oracle.max_assigned,
                          "groups": {"1": {"members": {"1": {
                              "id": "1", "addr": f"{addr}:{port}",
                              "leader": True, "alive": True}},
                              "tablets": {p: {"predicate": p}
                                          for p in
                                          alpha.mvcc.schema.predicates}}},
                          "dead": [],
                          "maxUID": alpha.oracle.max_uid,
                          "maxTxnTs": alpha.oracle.max_assigned}
                self._send(200, st)
            elif (route := _route_of(self.path, _DEBUG_GET)) is not None:
                getattr(self, _DEBUG_GET[route])()
            elif self.path.startswith("/admin/maintenance"):
                # scheduler status: running/queued jobs, pause state,
                # policy knobs (reference: /admin health of background
                # ops; the metric counterparts live in
                # /debug/prometheus_metrics)
                if alpha.maintenance is None:
                    self._send(400, {"errors": [{
                        "message": "maintenance scheduler not attached"}]})
                else:
                    self._send(200, alpha.maintenance.status())
            else:
                self._send(404, {"errors": [{"message": "not found"}]})

        # -- /debug surface (dispatch via _DEBUG_GET; every route has
        # -- an inventory row in server/debug_routes.py — lint-pinned)
        def _qs(self):
            return urllib.parse.parse_qs(
                urllib.parse.urlsplit(self.path).query)

        def _dbg_index(self):
            # the operator's map: every debug endpoint with its
            # one-liner, straight from the lint-pinned inventory
            self._send(200, {"endpoints": [
                {"path": p, "doc": d}
                for p, d in sorted(DEBUG_ENDPOINTS.items())]})

        def _dbg_metrics(self):
            # identity gauges (build_info / process_uptime_s) refresh
            # at render time so every scrape carries a live uptime
            from dgraph_tpu.server import fleet
            fleet.refresh_identity_metrics()
            self._send(200, METRICS.render(), "text/plain")

        def _dbg_fleet(self):
            # cluster-wide snapshot (server/fleet.py): fan out over
            # every known node through the pooled, breaker-aware
            # clients; merge cost digests exactly and instance-label
            # the metrics. Partial on peer failure — never a 500.
            from dgraph_tpu.server import fleet
            qs = self._qs()
            budget = float((qs.get("budget_ms")
                            or [fleet.FLEET_BUDGET_MS])[0])
            self._send_bytes(200, json.dumps(
                fleet.fleet_snapshot(alpha, budget_ms=budget),
                default=str).encode())

        def _dbg_fleet_flight(self):
            # a node's flight-recorder snapshot (in-flight ops with
            # stacks + ring + watchdog); ?peer=host:port pulls a
            # cluster peer's over the DebugFlight worker RPC — the
            # operator's manual form of the watchdog's peer pull
            qs = self._qs()
            peer = (qs.get("peer") or [None])[0]
            n = int((qs.get("n") or [256])[0])
            if peer:
                from dgraph_tpu.server.task import Client
                c = Client(peer)
                try:
                    doc = c.debug_flight(n)
                finally:
                    c.close()
            else:
                doc = flightrec.flight_snapshot(n)
            self._send_bytes(200, json.dumps(doc,
                                             default=str).encode())

        def _dbg_traces(self):
            # span JSON: ?trace_id=… resolves one request's spans
            # (the id echoed in that response's extensions); bare
            # GET returns the recent ring buffer; ?peer=host:port
            # pulls a CLUSTER PEER's registry over the worker
            # transport (gRPC-leg spans, not just HTTP-originated)
            spans = self._debug_spans()
            self._send(200, {"spans": [s.to_dict() for s in spans]})

        def _dbg_events(self):
            # the same spans as Chrome trace-event JSON — load the
            # body directly in Perfetto / chrome://tracing
            spans = self._debug_spans()
            self._send(200, tracing.to_chrome(spans))

        def _dbg_costs(self):
            # shape-keyed query cost profiles: per-shape percentile
            # digests + feature means + the top-N most expensive
            # shapes (utils/costprofile.py — the cost-model dataset)
            qs = self._qs()
            n = int((qs.get("n") or [10])[0])
            doc = costprofile.summary(top_n=n)
            # whole-query fused-program cache (engine/fused.py):
            # per-shape hits/misses/compile µs + sticky-fallback bits
            from dgraph_tpu.engine import fused
            doc["fused_programs"] = fused.status()
            if (qs.get("recent") or ["false"])[0] == "true":
                doc["recent"] = costprofile.recent(min(n, 100))
            self._send(200, doc)

        def _dbg_slow_queries(self):
            # the slow-query ring; ?trace_id= filters to one
            # request, whose span tree is one hop away at
            # /debug/traces?trace_id=
            tid = (self._qs().get("trace_id") or [None])[0]
            self._send(200,
                       {"slow_queries": slow_queries_snapshot(tid)})

        def _dbg_profile(self):
            # capture status; POST starts/stops (single-flight)
            self._send(200, tracing.profile_status())

        def _dbg_scheduler(self):
            # cost-prior scheduling state (utils/costprior.py):
            # live priors with hit/fallback counts, predicted-vs-
            # actual error digests, lane-EMA fallbacks, the feature
            # least-squares fit, and the admission lanes' predicted
            # inflight/queued work
            from dgraph_tpu.utils import costprior
            n = int((self._qs().get("n") or [10])[0])
            doc = {"enabled": bool(getattr(alpha, "cost_priors",
                                           False))
                   and costprior.enabled(),
                   **costprior.status(top_n=n)}
            if alpha.admission is not None:
                doc["admission"] = alpha.admission.status()
            # mesh-route view: shard-keyed cost sums recorded by
            # mesh expansions (engine/execute.py) — how the
            # scheduler sees work land across the device mesh
            shard_cost = costprofile.shard_costs()
            if shard_cost:
                doc["mesh"] = {"shard_cost_us": shard_cost}
            # fused-vs-staged route selection (engine/fused.py):
            # per-route counts + the program cache the scheduler's
            # per-PROGRAM cost priors learn from
            from dgraph_tpu.engine import fused
            doc["fused"] = {
                "routes": {r: METRICS.get("fused_route_total", route=r)
                           for r in ("fused", "staged", "fallback")},
                **fused.status()}
            self._send(200, doc)

        def _dbg_admission(self):
            # admission-control status: per-lane inflight/queued/
            # shed counts + limits (the numbers the overload
            # acceptance test cross-checks against metrics)
            if alpha.admission is None:
                self._send(200, {"enabled": False})
            else:
                self._send(200, {"enabled": True,
                                 **alpha.admission.status()})

        def _dbg_memory(self):
            # memory-governor snapshot (utils/memgov.py): budgets +
            # watermarks, per-cache resident bytes/registrants/
            # evictions, OOM evict-retry counters, sticky-degraded
            # shapes — the surface the acceptance test reads after an
            # injected allocation fault
            from dgraph_tpu.utils import memgov
            self._send(200, memgov.GOVERNOR.status())

        def _dbg_timeseries(self):
            # retained metrics history (utils/timeseries.py): the
            # sampler ring's windowed points — ?name= filters series
            # by prefix, ?window= bounds the lookback seconds,
            # ?rate=false serves raw counter deltas instead of rates
            from dgraph_tpu.utils import timeseries
            qs = self._qs()
            name = (qs.get("name") or [None])[0]
            window = (qs.get("window") or [None])[0]
            rate = (qs.get("rate") or ["true"])[0] != "false"
            self._send_bytes(200, json.dumps(timeseries.status(
                name=name,
                window_s=float(window) if window else None,
                rate=rate), default=str).encode())

        def _dbg_slo(self):
            # SLO engine state (utils/slo.py): every inventoried
            # objective with its target and both windows' burn rates,
            # breach counts, and the sustained-burn conviction feed
            from dgraph_tpu.utils import slo
            eng = slo.ENGINE
            if eng is None:
                self._send(200, {"armed": False})
            else:
                self._send_bytes(200, json.dumps(
                    {"armed": True, **eng.status()},
                    default=str).encode())

        def _dbg_locks(self):
            # lock-order sanitizer state: acquisition-graph
            # edges, detected cycles (each with both stacks),
            # long holds (utils/locks.py; enabled under
            # DGRAPH_TPU_LOCK_SANITIZER=1, else a stub)
            self._send(200, locks.GRAPH.snapshot())

        def _dbg_races(self):
            # Eraser lockset race sanitizer state (ISSUE 12):
            # tracked classes + every report, each with both
            # access stacks (utils/locks.py; enabled under
            # DGRAPH_TPU_RACE_SANITIZER=1, else a stub)
            self._send(200, locks.RACES.snapshot())

        def _dbg_peers(self):
            # per-peer resilience state: breaker state, EMA
            # latency, consecutive failures, last error — the
            # operator's answer to "which replica is dying on us"
            # (cluster/resilience.py PeerTable.snapshot)
            if alpha.groups is None:
                self._send(200, {"enabled": False})
            else:
                res = getattr(alpha.groups, "resilience", None)
                doc = {"enabled": res is not None,
                       "peers": res.snapshot() if res else {}}
                zh = getattr(alpha.groups.zero, "health", None)
                if zh is not None:
                    doc["zero"] = zh.snapshot()
                self._send(200, doc)

        def _dbg_flightrec(self):
            # flight-recorder state (utils/flightrec.py): ring tail,
            # watchdog config + conviction counts, recent dumps
            n = int((self._qs().get("n") or [100])[0])
            self._send_bytes(200, json.dumps(flightrec.state(n),
                                             default=str).encode())

        def _post_profile(self, acl_user):
            # on-demand jax.profiler device capture (admin bar):
            # {"action": "start"|"stop", "dir"?: path}. start while
            # one is running → 409 (single-flight, tracing.py);
            # the XLA timeline lands under <dir>/plugins/profile/
            if alpha.acl is not None:
                alpha.acl.check_alter(acl_user)
            body = self._body().decode()
            req = json.loads(body) if body.strip() else {}
            action = req.get("action", "start")
            try:
                if action == "start":
                    d = tracing.profile_start(req.get("dir")
                                              or None)
                    self._send(200, {"data": {"profiling": True,
                                              "dir": d}})
                elif action == "stop":
                    d = tracing.profile_stop()
                    self._send(200, {"data": {"profiling": False,
                                              "dir": d}})
                else:
                    self._send(400, {"errors": [{
                        "message": f"unknown action {action!r} "
                                   f"(want start|stop)"}]})
            except RuntimeError as e:
                # single-flight conflict / no capture running
                self._send(409, {"errors": [{"message": str(e)}]})

        def _post_flightrec(self, acl_user):
            # one-shot diagnostic bundle (admin bar): {"action":
            # "dump"} builds the full bundle — stacks, flight ring,
            # every debug surface, metrics, config — writes it under
            # the armed diag dir (when one is configured) and returns
            # it inline so `dgraph_tpu diagnose` can pull it from a
            # live server in one POST
            if alpha.acl is not None:
                alpha.acl.check_alter(acl_user)
            body = self._body().decode()
            req = json.loads(body) if body.strip() else {}
            action = req.get("action", "dump")
            if action != "dump":
                self._send(400, {"errors": [{
                    "message": f"unknown action {action!r} "
                               f"(want dump)"}]})
                return
            out = flightrec.dump(trigger="http", alpha=alpha,
                                 reason=req.get("reason"))
            self._send_bytes(200, json.dumps(
                {"data": {"path": out["path"],
                          "bundle": out["bundle"]}},
                default=str).encode())

        def _debug_spans(self):
            qs = urllib.parse.parse_qs(
                urllib.parse.urlsplit(self.path).query)
            tid = (qs.get("trace_id") or [None])[0]
            n = int((qs.get("n") or [256])[0])
            peer = (qs.get("peer") or [None])[0]
            if peer:
                # proxy to the peer's registry over the worker
                # transport (DebugTraces RPC): peer-leg spans become
                # reachable from THIS node's debug surface
                from dgraph_tpu.server.task import Client
                c = Client(peer)
                try:
                    dicts = c.debug_traces(trace_id=tid or "", n=n)
                finally:
                    c.close()
                return [tracing.Span(**d) for d in dicts]
            if tid:
                return tracing.trace_spans(tid)
            return tracing.recent(n)

        def _slow_query_check(self, us: int, trace_id: str,
                              q: str) -> None:
            """Slow-query log (reference: the query log at --v=3 /
            slow-query tooling): queries past --slow_query_ms log with
            their trace id so the spans can be pulled from
            /debug/traces after the fact; the structured entry also
            lands in the /debug/slow_queries ring, filterable by
            ?trace_id= (one-hop correlation to the span tree)."""
            thresh_ms = getattr(alpha, "slow_query_ms", 0) or 0
            if thresh_ms <= 0 or us < thresh_ms * 1000:
                return
            METRICS.inc("slow_queries_total")
            xlog.get("http").warning(
                "slow query: %.1f ms (threshold %s ms) trace_id=%s "
                "query=%.200s", us / 1000.0, thresh_ms, trace_id,
                " ".join(q.split()))
            with _SLOW_LOCK:
                _SLOW_LOG.append({
                    "trace_id": trace_id, "us": int(us),
                    "threshold_ms": thresh_ms,
                    "query": " ".join(q.split())[:200],
                    "mono_s": dl.monotonic_s()})

        def _explain_doc(self, trace_id: str) -> dict:
            """The request's finished cost record (utils/costprofile —
            the same record /debug/costs?recent=true serves), joined
            by trace id: no new accounting, just the existing
            breakdown echoed where the caller can see it."""
            for rec in reversed(costprofile.recent(64)):
                if rec.get("trace_id") == trace_id:
                    return rec
            return {"trace_id": trace_id,
                    "note": "no finished cost record for this request "
                            "(cost profiling disabled?)"}

        def _acl_user(self):
            """Resolve the access token when ACL is on (reference: the
            accessJwt header gate on every endpoint)."""
            if alpha.acl is None:
                return None
            token = (self.headers.get("X-Dgraph-AccessToken")
                     or self.headers.get("X-Dgraph-AccessJWT"))
            return alpha.acl.verify(token)

        def _admin(self, acl_user):
            """Admin triggers for the maintenance scheduler (reference:
            /admin backup + export GraphQL mutations): POST
            /admin/backup {"dest": …, "full"?: bool}, /admin/export
            {"out": …, "format"?: "rdf"|"json"}, /admin/checkpoint,
            /admin/pause, /admin/resume. Jobs queue on the background
            scheduler; `?wait=true` blocks for the outcome (admin
            endpoints share the Alter ACL bar).

            Every admin request opens (or, via an inbound X-Trace-Id,
            joins) a trace; jobs it queues capture the trace id and
            the scheduler re-establishes it around `maintenance.job`
            (store/maintenance.py) — an operator-initiated backup is
            traceable end to end even though it runs later on the
            scheduler thread."""
            with tracing.trace(
                    "http.admin",
                    trace_id=self.headers.get("X-Trace-Id") or None,
                    path=self.path.partition("?")[0]) as tid:
                self._admin_dispatch(acl_user, tid)

        def _admin_dispatch(self, acl_user, tid):
            if alpha.acl is not None:
                alpha.acl.check_alter(acl_user)
            if self.path.startswith("/admin/backup/verify"):
                # offline chain integrity walk (no scheduler needed —
                # read-only): manifests, per-file digests, delta record
                # counts, contiguity; errors name exact files
                from dgraph_tpu.server.backup import verify_chain
                req = json.loads(self._body().decode() or "{}")
                self._send(200, {"data": verify_chain(req["dest"])})
                return
            if alpha.maintenance is None:
                self._send(400, {"errors": [{
                    "message": "maintenance scheduler not attached"}]})
                return
            sched = alpha.maintenance
            body = self._body().decode()
            req = json.loads(body) if body.strip() else {}
            wait = "wait=true" in (self.path.partition("?")[2] or "")
            if self.path.startswith("/admin/backup"):
                job = sched.request_backup(req["dest"],
                                           force_full=req.get("full",
                                                              False))
            elif self.path.startswith("/admin/export"):
                job = sched.request_export(req["out"],
                                           format=req.get("format",
                                                          "rdf"))
            elif self.path.startswith("/admin/checkpoint"):
                job = sched.request_checkpoint()
            elif self.path.startswith("/admin/pause"):
                sched.pause()
                self._send(200, {"data": {"paused": True}})
                return
            elif self.path.startswith("/admin/resume"):
                sched.resume()
                self._send(200, {"data": {"paused": False}})
                return
            else:
                self._send(404, {"errors": [{"message": "not found"}]})
                return
            if wait:
                result = job.wait(timeout=600.0)
                self._send(200, {"data": {"job": job.name,
                                          "outcome": "ok",
                                          "result": result,
                                          "trace_id": tid}})
            else:
                self._send(200, {"data": {"job": job.name,
                                          "queued": True,
                                          "trace_id": tid}})

        def do_POST(self):
            t0 = time.perf_counter()
            try:
                with self._disconnect_watch():
                    self._dispatch_post(t0)
            except TxnAborted as e:
                self._send(409, {"errors": [{"message": str(e),
                                             "code": "Aborted"}]})
            except ServerOverloaded as e:
                # RETRYABLE shed: 429 + a Retry-After hint scaled by
                # the lane's measured service time — clients and load
                # balancers back off instead of hammering
                METRICS.inc("http_overload_responses_total")
                self._send_bytes(
                    429,
                    json.dumps({"errors": [{
                        "message": str(e),
                        "code": "ServerOverloaded",
                        "retry_after_s": round(e.retry_after_s, 3)}]}
                    ).encode(),
                    headers={"Retry-After":
                             f"{max(e.retry_after_s, 0.001):.3f}"})
            except DeadlineExceeded as e:
                # RETRYABLE: the request's own budget expired — 504
                # (the server gave up inside the client's deadline
                # contract, not a client error)
                self._send(504, {"errors": [{"message": str(e),
                                             "code": "DeadlineExceeded",
                                             "stage": e.stage}]})
            except Cancelled as e:
                # 499 (client-closed-request convention): the client
                # cancelled; nothing to retry unless it wants to. On a
                # DISCONNECT cancel the socket is gone — the write
                # fails quietly; the point was releasing the request's
                # admission token and compute early.
                with contextlib.suppress(OSError):
                    self._send(499, {"errors": [{"message": str(e),
                                                 "code": "Cancelled"}]})
            except (NoQuorum, ReadUnavailable) as e:
                # RETRYABLE partition refusals, not client errors: the
                # minority side refuses writes (NoQuorum) and refuses
                # unverifiable reads (ReadUnavailable) — 503 so clients
                # and load balancers retry elsewhere
                self._send(503, {"errors": [{"message": str(e),
                                             "code": "Unavailable"}]})
            except PermissionError as e:
                self._send(401, {"errors": [{"message": str(e),
                                             "code": "Unauthorized"}]})
            except Exception as e:  # surface parse/exec errors as the
                # reference does: 200-with-errors JSON is api-breaking,
                # use 400 + errors list (`query_errors_total{lane=}` is
                # counted once, in the api._request lifecycle, so gRPC
                # and embedded callers burn the same SLO budget)
                self._send(400, {"errors": [{"message": str(e)}]})

        def _dispatch_post(self, t0):
            """POST endpoint dispatch; raised errors map to
            HTTP codes in do_POST's handler chain."""
            if self.path.startswith("/login"):
                req = json.loads(self._body().decode())
                if alpha.acl is None:
                    self._send(400, {"errors": [
                        {"message": "ACL is not enabled"}]})
                    return
                token = alpha.acl.login(req.get("userid", ""),
                                        req.get("password", ""))
                self._send(200, {"data": {"accessJWT": token}})
                return
            acl_user = self._acl_user()
            post_route = _route_of(self.path, _DEBUG_POST)
            if post_route is not None:
                getattr(self, _DEBUG_POST[post_route])(acl_user)
                return
            deadline_ms = self._deadline_ms()
            # inbound X-Trace-Id joins the caller's trace (the HTTP
            # twin of the gRPC metadata propagation); the id echoes
            # back as an X-Trace-Id response header either way
            inbound_tid = self.headers.get("X-Trace-Id") or None
            if self.path.startswith("/query/batch"):
                req = json.loads(self._body().decode())
                with tracing.trace("http.query_batch",
                                   trace_id=inbound_tid,
                                   queries=len(req["queries"])) as tid:
                    outs = alpha.query_batch(req["queries"],
                                             acl_user=acl_user,
                                             deadline_ms=deadline_ms)
                us = int((time.perf_counter() - t0) * 1e6)
                METRICS.observe("query_latency_us", us,
                                endpoint="query_batch")
                self._slow_query_check(us, tid,
                                       f"<batch of "
                                       f"{len(req['queries'])}>")
                self._send_bytes(
                    200,
                    json.dumps({"data": outs,
                                "extensions": {"trace_id": tid}}
                               ).encode(),
                    headers={"X-Trace-Id": tid})
            elif self.path.startswith("/query"):
                body = self._body().decode()
                if "application/json" in (
                        self.headers.get("Content-Type") or ""):
                    req = json.loads(body)
                    q, variables = req["query"], req.get("variables")
                else:
                    q, variables = body, None
                # ?explain=true (or an X-Explain request header):
                # echo the request's cost-Recorder breakdown — route
                # per hop, kernel launches, launch-gap µs, cache hit
                # bits, admission wait — in the response extensions.
                # One-hop introspection over EXISTING accounting.
                explain = ("explain=true" in self.path.partition("?")[2]
                           or (self.headers.get("X-Explain") or ""
                               ).lower() in ("1", "true"))
                with tracing.trace("http.query",
                                   trace_id=inbound_tid) as tid:
                    raw = alpha.query_raw(q, variables,
                                          acl_user=acl_user,
                                          deadline_ms=deadline_ms)
                us = int((time.perf_counter() - t0) * 1e6)
                METRICS.observe("query_latency_us", us,
                                endpoint="query")
                self._slow_query_check(us, tid, q)
                # splice the emitter's bytes into the envelope — the
                # response body is never re-parsed server-side
                env = (b'{"data":' + raw +
                       b',"extensions":{"server_latency":'
                       b'{"total_us":%d},"trace_id":"%s"'
                       % (us, tid.encode()))
                headers = {"X-Trace-Id": tid}
                if explain:
                    env += (b',"explain":'
                            + json.dumps(self._explain_doc(tid),
                                         default=str).encode())
                    headers["X-Explain"] = "true"
                self._send_bytes(200, env + b'}}', headers=headers)
            elif self.path.startswith("/mutate"):
                ctype = self.headers.get("Content-Type") or ""
                body = self._body().decode()
                qs = self.path.partition("?")[2]
                start_ts = None
                for part in qs.split("&"):
                    if part.startswith("startTs="):
                        start_ts = int(part.split("=", 1)[1])
                commit_now = "commitNow=true" in qs or \
                    (self.headers.get("X-Dgraph-CommitNow") == "true")
                if "application/json" in ctype:
                    req = json.loads(body)
                    if req.get("query"):
                        # upsert: set/delete may be JSON mutation
                        # lists (upsert_json) or RDF strings (the
                        # block form, via Alpha.upsert)
                        cn = commit_now or req.get("commitNow", False)
                        if any(isinstance(req.get(k), str)
                               for k in ("set", "delete")):
                            parts = [
                                "%s { %s }" % (k if k != "delete"
                                               else "delete", req[k])
                                for k in ("set", "delete")
                                if isinstance(req.get(k), str)]
                            src = ("upsert { query %s mutation %s "
                                   "{ %s } }"
                                   % (req["query"],
                                      req.get("cond", ""),
                                      "\n".join(parts)))
                            res = alpha.upsert(
                                src, commit_now=cn,
                                start_ts=start_ts,
                                acl_user=acl_user,
                                deadline_ms=deadline_ms)
                        else:
                            res = alpha.upsert_json(
                                req["query"], req.get("cond", ""),
                                set_json=req.get("set"),
                                del_json=req.get("delete"),
                                commit_now=cn, start_ts=start_ts,
                                acl_user=acl_user,
                                deadline_ms=deadline_ms)
                    else:
                        res = alpha.mutate(
                            set_json=req.get("set"),
                            del_json=req.get("delete"),
                            commit_now=(commit_now or
                                        req.get("commitNow", False)),
                            start_ts=start_ts, acl_user=acl_user,
                            deadline_ms=deadline_ms)
                elif _is_upsert(body):
                    res = alpha.upsert(body, commit_now=commit_now,
                                       start_ts=start_ts,
                                       acl_user=acl_user,
                                       deadline_ms=deadline_ms)
                else:
                    res = alpha.mutate(set_nquads=body,
                                       commit_now=commit_now,
                                       start_ts=start_ts,
                                       acl_user=acl_user,
                                       deadline_ms=deadline_ms)
                self._send(200, {"data": res})
            elif self.path.startswith("/commit"):
                qs = self.path.partition("?")[2]
                start_ts = abort = None
                for part in qs.split("&"):
                    if part.startswith("startTs="):
                        start_ts = int(part.split("=", 1)[1])
                    if part.startswith("abort="):
                        abort = part.split("=", 1)[1] == "true"
                if start_ts is None:
                    self._send(400, {"errors": [
                        {"message": "startTs required"}]})
                    return
                cts = alpha.commit_or_abort(start_ts,
                                            abort=bool(abort),
                                            deadline_ms=deadline_ms)
                self._send(200, {"data": {
                    "code": "Success", "commit_ts": cts}})
            elif self.path.startswith("/admin/"):
                self._admin(acl_user)
            elif self.path.startswith("/alter"):
                if alpha.acl is not None:
                    alpha.acl.check_alter(acl_user)
                body = self._body().decode()
                if body.strip().startswith("{"):
                    op = json.loads(body)
                    if op.get("drop_all"):
                        alpha.drop_all()
                    elif op.get("drop_attr"):
                        alpha.drop_attr(op["drop_attr"])
                    else:
                        alpha.alter(op.get("schema", ""))
                else:
                    alpha.alter(body)
                self._send(200, {"data": {"code": "Success"}})
            else:
                self._send(404, {"errors": [{"message": "not found"}]})

    srv = ThreadingHTTPServer((addr, port), Handler)
    port = srv.server_address[1]
    return srv


def serve_background(srv: ThreadingHTTPServer) -> threading.Thread:
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return t
