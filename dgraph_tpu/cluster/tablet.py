"""Tablet snapshot codec: a whole predicate's postings as one blob.

Reference parity: Badger `Stream` snapshot shipping (worker/snapshot.go,
tablet moves in zero/tablet.go) — how a tablet's data crosses node
boundaries. Here a tablet is already a columnar bundle (CSR pair, value
columns, facet columns), so the wire format is just npz + a JSON sidecar
for object-typed columns; indexes are NOT shipped — the receiver rebuilds
them locally (cheap, and keeps tokenizer versions node-local, the same
call checkpoint.load makes).
"""

from __future__ import annotations

import io
import json

import numpy as np

from dgraph_tpu.store.store import (
    EdgeRel, FacetCol, PredicateData, ValueColumn, build_indexes)
from dgraph_tpu.store.wal import dec_scalar, enc_scalar


def pack_tablet(pd: PredicateData) -> bytes:
    """PredicateData → blob (schema rides separately: receiver has it)."""
    arrays: dict[str, np.ndarray] = {}
    meta: dict = {"langs": sorted(pd.vals), "efacets": sorted(pd.efacets),
                  "vfacets": {}}
    for side, rel in (("fwd", pd.fwd), ("rev", pd.rev)):
        if rel is not None:
            arrays[f"{side}_indptr"] = rel.indptr
            arrays[f"{side}_indices"] = rel.indices
    for i, lang in enumerate(meta["langs"]):
        col = pd.vals[lang]
        arrays[f"val{i}_subj"] = col.subj
        vals = col.vals
        if vals.dtype == object:
            meta[f"val{i}_obj"] = [enc_scalar(v) for v in vals]
        else:
            arrays[f"val{i}_vals"] = vals
    for i, key in enumerate(meta["efacets"]):
        fc = pd.efacets[key]
        arrays[f"ef{i}_pos"] = fc.pos
        meta[f"ef{i}_vals"] = [enc_scalar(v) for v in fc.vals]
    meta["vfacets"] = {k: {str(r): enc_scalar(v) for r, v in m.items()}
                       for k, m in pd.vfacets.items()}
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    blob_meta = json.dumps(meta).encode()
    return (len(blob_meta).to_bytes(4, "little") + blob_meta
            + buf.getvalue())


def unpack_tablet(blob: bytes, pred: str, schema) -> PredicateData:
    """Blob → PredicateData with locally rebuilt indexes."""
    mlen = int.from_bytes(blob[:4], "little")
    meta = json.loads(blob[4:4 + mlen])
    arrays = np.load(io.BytesIO(blob[4 + mlen:]), allow_pickle=False)
    pd = PredicateData(schema=schema.get(pred))
    for side in ("fwd", "rev"):
        if f"{side}_indptr" in arrays:
            setattr(pd, side, EdgeRel(indptr=arrays[f"{side}_indptr"],
                                      indices=arrays[f"{side}_indices"]))
    for i, lang in enumerate(meta["langs"]):
        subj = arrays[f"val{i}_subj"]
        if f"val{i}_obj" in meta:
            vals = np.empty(len(meta[f"val{i}_obj"]), dtype=object)
            vals[:] = [dec_scalar(v) for v in meta[f"val{i}_obj"]]
        else:
            vals = arrays[f"val{i}_vals"]
        pd.vals[lang] = ValueColumn(subj=subj, vals=vals)
    for i, key in enumerate(meta["efacets"]):
        vals = np.empty(len(meta[f"ef{i}_vals"]), dtype=object)
        vals[:] = [dec_scalar(v) for v in meta[f"ef{i}_vals"]]
        pd.efacets[key] = FacetCol(pos=arrays[f"ef{i}_pos"], vals=vals)
    for k, m in meta["vfacets"].items():
        pd.vfacets[k] = {int(r): dec_scalar(v) for r, v in m.items()}
    build_indexes({pred: pd})
    return pd
