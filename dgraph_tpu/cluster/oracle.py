"""The cluster Oracle: timestamps, uid leases, commit arbitration.

Reference parity: Zero's oracle (`dgraph/cmd/zero/oracle.go` — Timestamps,
commit with conflict checks, MaxAssigned watermark) and uid leasing
(`zero.Server.AssignUids`, `dgraph/cmd/zero/assign.go`). In the reference
this state machine is replicated via group-0 Raft; here it is a single
authority object the Alpha process owns (multi-node replication of the
oracle is a host-side concern, deliberately outside the TPU data path —
SURVEY §2.3: Zero never touches posting data).

Transaction model (snapshot isolation, first-committer-wins):
- `read_ts()` issues a fresh start timestamp; a txn reads the snapshot of
  everything committed at or before it.
- Each mutation produces *conflict keys* (predicate+subject, and index
  tokens for indexed values — reference: `posting.addConflictKeys`).
- `commit(start_ts, keys)` aborts iff any key was committed by another txn
  after `start_ts`; otherwise assigns the next commit timestamp.
"""

from __future__ import annotations

import hashlib
import time

from dataclasses import dataclass, field

from dgraph_tpu.utils import locks


class TxnAborted(Exception):
    """Raised on commit conflict (reference: pb.TxnContext.Aborted)."""


def fingerprint(key) -> str:
    """Deterministic cross-process conflict-key fingerprint. Python's
    hash() is salted per process, which both risks collisions and makes
    keys unshareable between nodes; sha1 hex is stable and collision-free
    for distinct keys (reference: farm fingerprints on posting keys)."""
    return hashlib.sha1(str(key).encode()).hexdigest()


@dataclass
class TxnStatus:
    start_ts: int
    commit_ts: int  # 0 while pending, -1 if aborted
    created: float = field(default_factory=time.monotonic)


class Oracle:
    """Timestamp + uid authority with commit conflict detection."""

    def __init__(self, first_ts: int = 1, first_uid: int = 1):
        self._lock = locks.make_lock("oracle.state")
        self._next_ts = first_ts
        self._next_uid = first_uid
        self._pending: dict[int, TxnStatus] = {}
        # sha1 fingerprint of conflict key → commit_ts of the last writer
        self._commits: dict[str, int] = {}
        self._max_assigned = first_ts - 1
        locks.guarded(self, "oracle.state")

    # -- timestamps ---------------------------------------------------------
    def read_ts(self) -> int:
        """New start timestamp for a TRANSACTION — tracked as pending until
        commit/abort (reference: Zero.Timestamps lease)."""
        with self._lock:
            ts = self._next_ts
            self._next_ts += 1
            self._pending[ts] = TxnStatus(start_ts=ts, commit_ts=0)
            self._max_assigned = max(self._max_assigned, ts)
            return ts

    def read_only_ts(self) -> int:
        """Timestamp for a one-shot read — not tracked, so it never blocks
        the gc watermark (reference: best-effort/read-only queries)."""
        with self._lock:
            ts = self._next_ts
            self._next_ts += 1
            self._max_assigned = max(self._max_assigned, ts)
            return ts

    @property
    def max_assigned(self) -> int:
        """Watermark below which all timestamps are decided
        (reference: pb.OracleDelta.MaxAssigned)."""
        with self._lock:
            return self._max_assigned

    def min_active_ts(self) -> int:
        """Oldest start_ts an undecided txn still reads at — the snapshot
        retention watermark (reference: oracle doneUntil)."""
        with self._lock:
            active = [st.start_ts for st in self._pending.values()
                      if st.commit_ts == 0]
            return min(active) if active else self._next_ts

    def gc(self) -> int:
        """Drop decided txn records and conflict entries no active txn can
        collide with; returns the min-active watermark."""
        with self._lock:
            active = [st.start_ts for st in self._pending.values()
                      if st.commit_ts == 0]
            floor = min(active) if active else self._next_ts
            self._pending = {ts: st for ts, st in self._pending.items()
                             if st.commit_ts == 0}
            self._commits = {k: c for k, c in self._commits.items()
                             if c > floor}
            return floor

    # -- uid leases ---------------------------------------------------------
    def assign_uids(self, n: int) -> range:
        """Lease `n` fresh uids (reference: zero assign.go AssignUids)."""
        if n <= 0:
            raise ValueError("need n > 0 uids")
        with self._lock:
            lo = self._next_uid
            self._next_uid += n
            return range(lo, lo + n)

    def bump_ts(self, ts: int) -> None:
        """Ensure future timestamps start above a replayed commit_ts
        (reference: oracle restore from raft snapshot + WAL)."""
        with self._lock:
            self._next_ts = max(self._next_ts, ts + 1)
            self._max_assigned = max(self._max_assigned, ts)

    def bump_uid(self, uid: int) -> None:
        """Ensure future leases start above an externally-loaded uid
        (reference: bulk-load → zero lease handoff)."""
        with self._lock:
            self._next_uid = max(self._next_uid, uid + 1)

    @property
    def max_uid(self) -> int:
        """Highest uid ever leased or bumped — the watermark a rejoining
        node must hand Zero so leases never reuse uids minted in a WAL
        tail (reference: zero assign.go lease restore)."""
        with self._lock:
            return self._next_uid - 1

    # -- commit arbitration -------------------------------------------------
    def commit(self, start_ts: int, conflict_keys) -> int:
        """First-committer-wins commit; returns commit_ts or raises
        TxnAborted (reference: zero oracle.go `commit`)."""
        with self._lock:
            st = self._pending.get(start_ts)
            if st is None or st.commit_ts != 0:
                raise TxnAborted(f"txn {start_ts} is not pending")
            keys = {fingerprint(k) for k in conflict_keys}
            for k in keys:
                if self._commits.get(k, 0) > start_ts:
                    st.commit_ts = -1
                    raise TxnAborted(
                        f"conflict on key committed after ts {start_ts}")
            commit_ts = self._next_ts
            self._next_ts += 1
            for k in keys:
                self._commits[k] = commit_ts
            st.commit_ts = commit_ts
            self._max_assigned = max(self._max_assigned, commit_ts)
            return commit_ts

    def abort(self, start_ts: int) -> None:
        with self._lock:
            st = self._pending.get(start_ts)
            if st is not None and st.commit_ts == 0:
                st.commit_ts = -1

    def expire_older_than(self, max_age_s: float) -> int:
        """Abort pending txns OLDER than max_age_s (age since start, not
        idleness — Zero only hears from a txn again at commit). A
        coordinator that crashed without abort must not pin the gc
        watermark forever (reference: Zero lease timeouts). A later
        commit of an expired txn raises TxnAborted, exactly like a lost
        conflict; max_age_s is therefore also the ceiling on transaction
        lifetime and should be generous."""
        cutoff = time.monotonic() - max_age_s
        n = 0
        with self._lock:
            for st in self._pending.values():
                if st.commit_ts == 0 and st.created < cutoff:
                    st.commit_ts = -1
                    n += 1
        return n

    def status(self, start_ts: int) -> TxnStatus | None:
        with self._lock:
            return self._pending.get(start_ts)
