"""Routed read views: transparent foreign-tablet access for the engine.

Reference parity: the read half of `worker/task.go ProcessTaskOverNetwork`
— a query touching a predicate another group owns goes over the wire. The
TPU build's shared dense rank space lets the routing live BELOW the
engine: a routed view looks exactly like a local Store, but predicate data
the local node doesn't maintain is pulled from the owning group as a
whole-tablet snapshot (cluster/tablet.py) and cached by version. The
engine, kernels, and renderer are untouched — they cannot tell a pulled
tablet from a local one.

Freshness: every node learns each tablet's latest commit_ts from the
mutation broadcast (Alpha.apply_committed), even for predicates it does
not apply. A cached foreign tablet is valid while its version matches;
reads at older timestamps fetch an as-of snapshot without caching.
"""

from __future__ import annotations

import grpc

from dgraph_tpu.store.store import Store
from dgraph_tpu.utils import deadline
from dgraph_tpu.utils.metrics import METRICS


class _RoutedPreds(dict):
    """preds mapping that faults in foreign tablets on access."""

    def __init__(self, local: dict, alpha, read_ts: int):
        super().__init__(local)
        self.alpha = alpha
        self.read_ts = read_ts

    def _fetch(self, pred):
        # budget gate before faulting a whole foreign tablet over the
        # wire (the remaining budget rides the RPC as its gRPC timeout)
        deadline.checkpoint("tablet_fault")
        try:
            pd = self.alpha._fetch_tablet(pred, self.read_ts)
        except grpc.RpcError as e:
            # EVERY replica of the owning group was exhausted (failover
            # + breaker + retries all refused): the refusal contract is
            # ReadUnavailable — retryable, never a raw transport error
            # leaking through the engine to the client
            from dgraph_tpu.server.api import ReadUnavailable
            METRICS.inc("read_unavailable_total",
                        reason="replicas_exhausted")
            raise ReadUnavailable(
                f"tablet {pred!r}: every replica of its owning group "
                f"is unreachable ({e.code() if hasattr(e, 'code') else e}"
                f"); retry") from e
        if pd is not None:
            super().__setitem__(pred, pd)
        return pd

    def get(self, pred, default=None):
        present = dict.__contains__(self, pred) or None
        if self.alpha._needs_fetch(pred, self.read_ts, present):
            pd = self._fetch(pred)
            return pd if pd is not None else default
        return super().get(pred, default)

    def __getitem__(self, pred):
        out = self.get(pred)
        if out is None:
            raise KeyError(pred)
        return out

    def __contains__(self, pred):
        return self.get(pred) is not None


def routed_view(alpha, store: Store, read_ts: int) -> Store:
    """Wrap a local read view so foreign predicates resolve remotely:
    small-frontier hops route per-hop through the owner's ServeTask
    (remote_expand — O(frontier+result) bytes), everything else faults in
    the whole tablet through the preds mapping."""
    rs = object.__new__(Store)
    rs.uids = store.uids
    rs.schema = store.schema
    rs.preds = _RoutedPreds(store.preds, alpha, read_ts)
    rs._device = {}
    rs._empty_rel = store._empty_rel
    # per-snapshot kernel caches key off the underlying immutable store,
    # not this per-request wrapper (engine/batch.py _cache_host)
    rs._ell_host = getattr(store, "_ell_host", store)

    def remote_expand(pred, reverse, frontier):
        return alpha.remote_hop(pred, reverse, frontier, read_ts, rs)

    rs.remote_expand = remote_expand
    return rs
