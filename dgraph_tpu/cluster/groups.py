"""Groups: cluster membership, tablet routing, connection pooling.

Reference parity: `worker/groups.go` (`groups()`, `BelongsTo`, tablet map
kept fresh from Zero's membership stream) + `conn/pool.go` (one cached
gRPC channel per peer address, reused by every request). Membership is
refreshed by polling Zero's counter; tablet claims go through ShouldServe
exactly as the reference's first-asker rule.
"""

from __future__ import annotations

import threading

import grpc

from dgraph_tpu.cluster.zero import ZeroClient


class Groups:
    def __init__(self, zero: ZeroClient, my_addr: str, group: int = 0,
                 max_ts: int = 0, max_uid: int = 0):
        self.zero = zero
        self.my_addr = my_addr
        self.node_id, self.gid = zero.connect(my_addr, group,
                                              max_ts=max_ts,
                                              max_uid=max_uid)
        self._lock = threading.Lock()
        self._pools: dict[str, object] = {}
        self._tablets: dict[str, int] = {}
        self._groups: dict[int, dict[int, str]] = {}
        self._counter = -1
        self.refresh()

    # -- membership ----------------------------------------------------------
    def refresh(self) -> None:
        st = self.zero.membership()
        with self._lock:
            self._counter = int(st.counter)
            self._tablets = {}
            self._groups = {}
            for gid, g in st.groups.items():
                self._groups[int(gid)] = {int(n): a
                                          for n, a in g.nodes.items()}
                for p in g.tablets:
                    self._tablets[p] = int(gid)

    def tablet_owner(self, pred: str, claim: bool = True) -> int | None:
        """Owning group of a predicate; unowned predicates are claimed for
        THIS group (reference: ShouldServe first-asker)."""
        with self._lock:
            owner = self._tablets.get(pred)
        if owner is not None:
            return owner
        self.refresh()
        with self._lock:
            owner = self._tablets.get(pred)
        if owner is None and claim:
            owner = self.zero.should_serve(pred, self.gid)
            self.refresh()
        return owner

    def serves(self, pred: str) -> bool:
        return self.tablet_owner(pred) == self.gid

    def group_addrs(self, gid: int) -> list[str]:
        with self._lock:
            return sorted(self._groups.get(gid, {}).values())

    def addr_of_node(self, node_id: int) -> str | None:
        """Address of a node id anywhere in the cluster (broadcast-chain
        catch-up needs the origin's address)."""
        with self._lock:
            for nodes in self._groups.values():
                if node_id in nodes:
                    return nodes[node_id]
        self.refresh()
        with self._lock:
            for nodes in self._groups.values():
                if node_id in nodes:
                    return nodes[node_id]
        return None

    def node_of_addr(self, addr: str) -> int | None:
        """Node id at an address (the read gate tracks chains per ORIGIN
        node id; an unreachable peer's id comes from membership)."""
        with self._lock:
            for nodes in self._groups.values():
                for nid, a in nodes.items():
                    if a == addr:
                        return nid
        return None

    def other_addrs(self) -> list[str]:
        """Every node in the cluster except this one (broadcast targets).
        Always re-polls membership first: a commit must reach nodes that
        joined after our last refresh (reference: the membership stream
        keeps this continuously fresh; polling at each broadcast is the
        same guarantee at our scale)."""
        self.refresh()
        with self._lock:
            return sorted({a for nodes in self._groups.values()
                           for a in nodes.values() if a != self.my_addr})

    # -- conn pooling ---------------------------------------------------------
    def pool(self, addr: str):
        """Cached worker client per peer address (conn/pool.go)."""
        from dgraph_tpu.server.task import Client
        with self._lock:
            c = self._pools.get(addr)
            if c is None:
                c = self._pools[addr] = Client(addr)
            return c

    def invalidate(self, addr: str) -> None:
        """Drop a pooled channel after a failure: a cached grpc channel
        sits in reconnect backoff and fails fast long after the peer is
        healthy again; a fresh dial on the next call finds it immediately
        (reference: conn/pool.go re-dials dead connections)."""
        with self._lock:
            c = self._pools.pop(addr, None)
        if c is not None:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — already broken
                pass

    def call_group(self, gid: int, fn, exclude=()):
        """Run `fn(client)` against any live node of a group, trying
        replicas in order — read failover (reference: reads served by any
        replica; pool pick + retry). `exclude` skips peers known to be
        lagging (suspects from a failed broadcast); if every replica is
        excluded they are retried anyway — a possibly-stale answer beats
        none."""
        last = None
        addrs = self.group_addrs(gid)
        ordered = ([a for a in addrs if a not in exclude]
                   + [a for a in addrs if a in exclude])
        for addr in ordered:
            try:
                return fn(self.pool(addr))
            except grpc.RpcError as e:
                last = e
                continue
        raise last if last is not None else RuntimeError(
            f"group {gid} has no nodes")
