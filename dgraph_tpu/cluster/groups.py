"""Groups: cluster membership, tablet routing, connection pooling.

Reference parity: `worker/groups.go` (`groups()`, `BelongsTo`, tablet map
kept fresh from Zero's membership stream) + `conn/pool.go` (one cached
gRPC channel per peer address, reused by every request). Membership is
refreshed by polling Zero's counter; tablet claims go through ShouldServe
exactly as the reference's first-asker rule.
"""

from __future__ import annotations

import threading

import grpc

from dgraph_tpu.cluster.zero import ZeroClient


class Groups:
    def __init__(self, zero: ZeroClient, my_addr: str, group: int = 0,
                 max_ts: int = 0, max_uid: int = 0):
        self.zero = zero
        self.my_addr = my_addr
        self.node_id, self.gid = zero.connect(my_addr, group,
                                              max_ts=max_ts,
                                              max_uid=max_uid)
        self._lock = threading.Lock()
        self._pools: dict[str, object] = {}
        self._tablets: dict[str, int] = {}
        self._groups: dict[int, dict[int, str]] = {}
        self._counter = -1
        self.refresh()

    # -- membership ----------------------------------------------------------
    def refresh(self) -> None:
        st = self.zero.membership()
        with self._lock:
            self._counter = int(st.counter)
            self._tablets = {}
            self._groups = {}
            for gid, g in st.groups.items():
                self._groups[int(gid)] = {int(n): a
                                          for n, a in g.nodes.items()}
                for p in g.tablets:
                    self._tablets[p] = int(gid)

    def tablet_owner(self, pred: str, claim: bool = True) -> int | None:
        """Owning group of a predicate; unowned predicates are claimed for
        THIS group (reference: ShouldServe first-asker)."""
        with self._lock:
            owner = self._tablets.get(pred)
        if owner is not None:
            return owner
        self.refresh()
        with self._lock:
            owner = self._tablets.get(pred)
        if owner is None and claim:
            owner = self.zero.should_serve(pred, self.gid)
            self.refresh()
        return owner

    def serves(self, pred: str) -> bool:
        return self.tablet_owner(pred) == self.gid

    def group_addrs(self, gid: int) -> list[str]:
        with self._lock:
            return sorted(self._groups.get(gid, {}).values())

    def other_addrs(self) -> list[str]:
        """Every node in the cluster except this one (broadcast targets).
        Always re-polls membership first: a commit must reach nodes that
        joined after our last refresh (reference: the membership stream
        keeps this continuously fresh; polling at each broadcast is the
        same guarantee at our scale)."""
        self.refresh()
        with self._lock:
            return sorted({a for nodes in self._groups.values()
                           for a in nodes.values() if a != self.my_addr})

    # -- conn pooling ---------------------------------------------------------
    def pool(self, addr: str):
        """Cached worker client per peer address (conn/pool.go)."""
        from dgraph_tpu.server.task import Client
        with self._lock:
            c = self._pools.get(addr)
            if c is None:
                c = self._pools[addr] = Client(addr)
            return c

    def call_group(self, gid: int, fn):
        """Run `fn(client)` against any live node of a group, trying
        replicas in order — read failover (reference: reads served by any
        replica; pool pick + retry)."""
        last = None
        for addr in self.group_addrs(gid):
            try:
                return fn(self.pool(addr))
            except grpc.RpcError as e:
                last = e
                continue
        raise last if last is not None else RuntimeError(
            f"group {gid} has no nodes")
