"""Groups: cluster membership, tablet routing, connection pooling.

Reference parity: `worker/groups.go` (`groups()`, `BelongsTo`, tablet map
kept fresh from Zero's membership stream) + `conn/pool.go` (one cached
gRPC channel per peer address, reused by every request). Membership is
refreshed by polling Zero's counter; tablet claims go through ShouldServe
exactly as the reference's first-asker rule.
"""

from __future__ import annotations

import grpc

from dgraph_tpu.cluster.resilience import PeerTable
from dgraph_tpu.utils import locks
from dgraph_tpu.cluster.zero import ZeroClient
from dgraph_tpu.utils.metrics import METRICS


class Groups:
    def __init__(self, zero: ZeroClient, my_addr: str, group: int = 0,
                 max_ts: int = 0, max_uid: int = 0,
                 breaker_threshold: int = 5,
                 breaker_cooldown_ms: float = 500.0,
                 rpc_retries: int = 2):
        self.zero = zero
        self.my_addr = my_addr
        self.node_id, self.gid = zero.connect(my_addr, group,
                                              max_ts=max_ts,
                                              max_uid=max_uid)
        # this node's view of every peer it dials: circuit breakers +
        # retry policy shared by all pooled clients (--breaker_threshold,
        # --breaker_cooldown_ms, --rpc_retries)
        self.resilience = PeerTable(threshold=breaker_threshold,
                                    cooldown_ms=breaker_cooldown_ms,
                                    retries=rpc_retries)
        self._lock = locks.make_lock("groups.pool")
        self._pools: dict[str, object] = {}
        self._tablets: dict[str, int] = {}
        self._groups: dict[int, dict[int, str]] = {}
        self._counter = -1
        self.refresh()
        locks.guarded(self, "groups.pool")

    # -- membership ----------------------------------------------------------
    def refresh(self) -> None:
        st = self.zero.membership()
        with self._lock:
            self._counter = int(st.counter)
            self._tablets = {}
            self._groups = {}
            for gid, g in st.groups.items():
                self._groups[int(gid)] = {int(n): a
                                          for n, a in g.nodes.items()}
                for p in g.tablets:
                    self._tablets[p] = int(gid)

    def tablet_owner(self, pred: str, claim: bool = True) -> int | None:
        """Owning group of a predicate; unowned predicates are claimed for
        THIS group (reference: ShouldServe first-asker)."""
        with self._lock:
            owner = self._tablets.get(pred)
        if owner is not None:
            return owner
        self.refresh()
        with self._lock:
            owner = self._tablets.get(pred)
        if owner is None and claim:
            owner = self.zero.should_serve(pred, self.gid)
            self.refresh()
        return owner

    def serves(self, pred: str) -> bool:
        return self.tablet_owner(pred) == self.gid

    def group_addrs(self, gid: int) -> list[str]:
        with self._lock:
            return sorted(self._groups.get(gid, {}).values())

    def addr_of_node(self, node_id: int) -> str | None:
        """Address of a node id anywhere in the cluster (broadcast-chain
        catch-up needs the origin's address)."""
        with self._lock:
            for nodes in self._groups.values():
                if node_id in nodes:
                    return nodes[node_id]
        self.refresh()
        with self._lock:
            for nodes in self._groups.values():
                if node_id in nodes:
                    return nodes[node_id]
        return None

    def node_of_addr(self, addr: str) -> int | None:
        """Node id at an address (the read gate tracks chains per ORIGIN
        node id; an unreachable peer's id comes from membership)."""
        with self._lock:
            for nodes in self._groups.values():
                for nid, a in nodes.items():
                    if a == addr:
                        return nid
        return None

    def other_addrs(self) -> list[str]:
        """Every node in the cluster except this one (broadcast targets).
        Always re-polls membership first: a commit must reach nodes that
        joined after our last refresh (reference: the membership stream
        keeps this continuously fresh; polling at each broadcast is the
        same guarantee at our scale)."""
        self.refresh()
        with self._lock:
            return sorted({a for nodes in self._groups.values()
                           for a in nodes.values() if a != self.my_addr})

    def known_addrs(self) -> list[str]:
        """Every node in the cluster INCLUDING this one — the fleet
        fan-out's target list (server/fleet.py). Re-polls membership
        first, like other_addrs: a fleet snapshot must see nodes that
        joined after our last refresh."""
        self.refresh()
        with self._lock:
            return sorted({a for nodes in self._groups.values()
                           for a in nodes.values()})

    def peer_health(self) -> dict[str, dict]:
        """This node's breaker/latency view of every peer it dials —
        the `/debug/peers` data in heartbeat form (ISSUE 9: Zero's
        tablet-move decisions read it via ReportHealth, so moves never
        target a peer this node's breaker already knows is down)."""
        out = {}
        for addr, p in self.resilience.snapshot().items():
            out[addr] = {"state": p["state"],
                         "ema_latency_us": p["ema_latency_us"]}
        return out

    # -- conn pooling ---------------------------------------------------------
    def pool(self, addr: str):
        """Cached worker client per peer address (conn/pool.go). Every
        pooled client shares this node's PeerTable, so its calls run
        under the per-peer breaker + retry policy."""
        from dgraph_tpu.server.task import Client
        with self._lock:
            c = self._pools.get(addr)
            if c is None:
                c = self._pools[addr] = Client(
                    addr, resilience=self.resilience, peer_addr=addr)
            return c

    def invalidate(self, addr: str) -> None:
        """Drop a pooled channel after a failure: a cached grpc channel
        sits in reconnect backoff and fails fast long after the peer is
        healthy again; a fresh dial on the next call finds it immediately
        (reference: conn/pool.go re-dials dead connections)."""
        with self._lock:
            c = self._pools.pop(addr, None)
        if c is not None:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — already broken
                pass

    def call_group(self, gid: int, fn, exclude=(), rpc: str = ""):
        """Run `fn(client)` against any live node of a group, trying
        replicas in order — read failover (reference: reads served by any
        replica; pool pick + retry). `exclude` skips peers known to be
        lagging (suspects from a failed broadcast); peers whose circuit
        breaker is OPEN are tried last (they fail instantly, but a
        possibly-stale or known-dead answer beats none — when every
        replica is exhausted the caller's refusal, ReadUnavailable,
        stands). A call served by anyone but the preferred replica
        counts `failover_total{rpc=}`."""
        last = None
        addrs = self.group_addrs(gid)
        fresh = [a for a in addrs if a not in exclude]
        ordered = ([a for a in fresh if self.resilience.available(a)]
                   + [a for a in fresh
                      if not self.resilience.available(a)]
                   + [a for a in addrs if a in exclude])
        # the historical preference is the first non-excluded replica:
        # serving from anyone else — because the preferred breaker is
        # open OR its attempt failed — is a failover
        preferred = fresh[0] if fresh else (ordered[0] if ordered
                                            else None)
        for addr in ordered:
            try:
                out = fn(self.pool(addr))
            except grpc.RpcError as e:
                last = e
                continue
            if addr != preferred and rpc:
                METRICS.inc("failover_total", rpc=rpc)
                from dgraph_tpu.utils import costprofile
                costprofile.add("rpc_failovers", 1)
            return out
        raise last if last is not None else RuntimeError(
            f"group {gid} has no nodes")
