"""Peer-failure resilience: circuit breakers + budget-aware retries.

Reference parity: the reference rides on grpc-go's connection backoff
plus raft's leader liveness — a dead peer stops being asked because the
raft group re-elects around it, and conn/pool.go health-checks dials.
Our any-coordinator legs (server/task.py `Client._call`) had neither: a
dead peer was an instant terminal error on every call, paid at full
dial-timeout price, forever. This module gives every outbound cluster
RPC a shared health layer:

* **Per-peer circuit breaker** — consecutive transport failures open
  the breaker (closed → open); while open, calls fail INSTANTLY with
  `BreakerOpen` (an UNAVAILABLE-shaped `grpc.RpcError`, so every
  existing `except grpc.RpcError` failover/suspect path treats it as an
  unreachable peer — without burning a wire attempt). After a jittered
  cool-down the breaker goes half-open and admits exactly ONE probe:
  success closes it, failure re-opens with exponentially longer
  cool-down (capped). Concurrent callers during the probe fail fast —
  the retry-storm guard: total wire attempts against a dead peer stay
  bounded no matter how many threads are calling.

* **Budget-aware retry policy** — transient transport failures
  (`UNAVAILABLE`: connect errors, a just-restarting peer, an injected
  `LinkDown`) re-attempt with exponential backoff + jitter. NEVER
  retried: `DEADLINE_EXCEEDED` (the budget died, not the peer),
  application status codes (the peer answered — retrying would double
  apply), or our own `DeadlineExceeded`/`Cancelled`. Backoff sleeps are
  capped by the REMAINING `RequestContext` budget (utils/deadline.py),
  so retries can never outlive the caller's deadline — a retry that
  cannot afford another attempt gives up with the real error.

Observability: `breaker_state{peer=}` gauge (0 closed, 0.5 half-open,
1 open), `rpc_retries_total{rpc=,outcome=}`, per-peer EMA latency and
last error surfaced at `/debug/peers`, and every breaker transition
emitted as a `breaker.transition` span/event.

One `PeerTable` lives per `Groups` (NOT process-global: in-process
multi-node tests run several Alphas side by side, and node A's view of
peer C must never leak into node B's).
"""

from __future__ import annotations

import random
import threading
import time

import grpc

from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.utils import flightrec, locks
from dgraph_tpu.utils import tracing
from dgraph_tpu.utils.metrics import METRICS

__all__ = ["BreakerOpen", "PeerTable", "RETRYABLE_CODES"]

# transport-level failure codes worth a retry: the peer may be briefly
# unreachable (connect refused, restarting, link fault). Everything
# else either means "the peer answered" (app errors) or "our budget
# died" (DEADLINE_EXCEEDED) — neither is evidence of a dead peer.
RETRYABLE_CODES = frozenset({grpc.StatusCode.UNAVAILABLE})

_EMA_ALPHA = 0.2  # per-peer latency EMA smoothing


class BreakerOpen(grpc.RpcError):
    """Instant refusal for a peer whose breaker is open — shaped like
    UNAVAILABLE so failover/suspect paths treat it exactly like an
    unreachable peer, minus the wire attempt."""

    def __init__(self, addr: str, retry_in_s: float):
        msg = (f"circuit breaker for peer {addr} is open "
               f"(probe in {max(retry_in_s, 0.0) * 1e3:.0f} ms)")
        super().__init__(msg)
        self._msg = msg

    def code(self):
        return grpc.StatusCode.UNAVAILABLE

    def details(self):
        return self._msg


class _Peer:
    """One peer's health state (guarded by the owning table's lock)."""

    __slots__ = ("state", "fails", "open_until", "open_level", "probing",
                 "ema_us", "last_error", "last_error_mono", "calls",
                 "failures", "opened")

    def __init__(self):
        self.state = "closed"      # closed | open | half_open
        self.fails = 0             # consecutive transport failures
        self.open_until = 0.0      # monotonic end of the cool-down
        self.open_level = 0        # re-open count → cool-down backoff
        self.probing = False       # half-open single-probe token
        self.ema_us = 0.0          # latency EMA of successful calls
        self.last_error = ""
        self.last_error_mono = 0.0
        self.calls = 0
        self.failures = 0
        self.opened = 0


_STATE_GAUGE = {"closed": 0.0, "half_open": 0.5, "open": 1.0}


class PeerTable:
    """Per-node breaker + retry policy over every peer it dials.

    `threshold` consecutive transport failures open a peer's breaker;
    `cooldown_ms` (jittered, doubling per re-open up to
    `max_cooldown_ms`) gates the half-open probe. `retries` is the
    number of RE-attempts a retryable failure earns, with exponential
    backoff from `backoff_ms` capped at `max_backoff_ms` and always by
    the remaining request budget."""

    def __init__(self, threshold: int = 5, cooldown_ms: float = 500.0,
                 retries: int = 2, backoff_ms: float = 10.0,
                 max_backoff_ms: float = 250.0,
                 max_cooldown_ms: float = 30_000.0):
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = max(cooldown_ms, 1.0) / 1e3
        self.retries = max(int(retries), 0)
        self.backoff_s = max(backoff_ms, 0.1) / 1e3
        self.max_backoff_s = max(max_backoff_ms, backoff_ms) / 1e3
        self.max_cooldown_s = max(max_cooldown_ms, cooldown_ms) / 1e3
        self._lock = locks.make_lock("resilience.peers")
        self._peers: dict[str, _Peer] = {}
        self._rng = random.Random(0xD6B2E55)  # jitter only, never schedules
        locks.guarded(self, "resilience.peers")

    # -- state machine -------------------------------------------------------
    def _peer(self, addr: str) -> _Peer:
        p = self._peers.get(addr)
        if p is None:
            p = self._peers[addr] = _Peer()
            METRICS.set_gauge("breaker_state", 0.0, peer=addr)
        return p

    def _transition(self, addr: str, p: _Peer, to: str) -> None:
        frm, p.state = p.state, to
        if to == "open":
            p.opened += 1
        METRICS.set_gauge("breaker_state", _STATE_GAUGE[to], peer=addr)
        flightrec.emit("breaker.transition", peer=addr, frm=frm, to=to,
                       consecutive_failures=p.fails)
        # transitions are rare; a zero-duration span doubles as the
        # event record (/debug/traces, OTLP export)
        with tracing.span("breaker.transition", peer=addr, frm=frm,
                          to=to, consecutive_failures=p.fails):
            pass

    def acquire(self, addr: str) -> None:
        """Admission gate before a wire attempt; raises `BreakerOpen`
        without touching the wire when the peer is known-dead (open
        inside cool-down, or a half-open probe already in flight)."""
        now = time.monotonic()
        with self._lock:
            p = self._peer(addr)
            p.calls += 1
            if p.state == "open":
                if now < p.open_until:
                    raise BreakerOpen(addr, p.open_until - now)
                self._transition(addr, p, "half_open")
                p.probing = True
            elif p.state == "half_open":
                if p.probing:
                    raise BreakerOpen(addr, 0.0)
                p.probing = True

    def on_success(self, addr: str, latency_s: float | None) -> None:
        """A call reached the peer (a successful response OR an
        application-level status): the peer is alive."""
        with self._lock:
            p = self._peer(addr)
            p.fails = 0
            p.probing = False
            if latency_s is not None:
                us = latency_s * 1e6
                p.ema_us = (us if not p.ema_us
                            else p.ema_us + _EMA_ALPHA * (us - p.ema_us))
            if p.state != "closed":
                p.open_level = 0
                self._transition(addr, p, "closed")

    def on_failure(self, addr: str, err: Exception) -> None:
        """A transport-level failure: count it; open (or re-open with a
        longer cool-down) past the threshold."""
        now = time.monotonic()
        with self._lock:
            p = self._peer(addr)
            p.fails += 1
            p.failures += 1
            p.probing = False
            p.last_error = f"{type(err).__name__}: {err}"[:300]
            p.last_error_mono = now
            reopen = p.state == "half_open"
            if reopen or (p.state == "closed"
                          and p.fails >= self.threshold):
                if reopen:
                    p.open_level += 1
                cd = min(self.cooldown_s * (2 ** p.open_level),
                         self.max_cooldown_s)
                p.open_until = now + cd * self._rng.uniform(1.0, 1.5)
                self._transition(addr, p, "open")

    def reset(self, addr: str) -> None:
        """Forget a peer's health history (a healed fault-injection
        link, an operator reset): next call starts closed."""
        with self._lock:
            if addr in self._peers:
                self._peers[addr] = _Peer()
                METRICS.set_gauge("breaker_state", 0.0, peer=addr)

    def available(self, addr: str) -> bool:
        """Would `acquire` let a call through right now? (Failover uses
        this to order replicas: open-breaker peers go last.)"""
        with self._lock:
            p = self._peers.get(addr)
            if p is None:
                return True
            if p.state == "open":
                return time.monotonic() >= p.open_until
            return True

    def state(self, addr: str) -> str:
        with self._lock:
            p = self._peers.get(addr)
            return p.state if p is not None else "closed"

    # -- the resilient call wrapper -----------------------------------------
    def call(self, addr: str, rpc_name: str, attempt,
             retryable: bool = True):
        """Run `attempt()` against `addr` under the breaker, retrying
        retryable transport failures within the remaining request
        budget. `attempt` performs exactly one wire call."""
        from dgraph_tpu.utils import costprofile
        tries = (self.retries + 1) if retryable else 1
        delay = self.backoff_s
        last: Exception | None = None
        for i in range(tries):
            if i:
                # re-attempts join the request's cost record: a shape
                # whose p99 is retry-dominated names a sick peer set,
                # not an expensive plan
                costprofile.add("rpc_retries", 1)
            self.acquire(addr)
            t0 = time.perf_counter()
            try:
                out = attempt()
            except (dl.DeadlineExceeded, dl.Cancelled):
                # OUR budget died mid-call: says nothing about the peer
                self._release_probe(addr)
                raise
            except grpc.RpcError as e:
                if isinstance(e, BreakerOpen):
                    raise  # a nested guard refused: not a wire failure
                code = e.code() if hasattr(e, "code") else None
                if code == grpc.StatusCode.DEADLINE_EXCEEDED:
                    # never retried: a slow answer is not a dead peer,
                    # and re-spending an expired budget helps nobody
                    self._release_probe(addr)
                    if i:
                        METRICS.inc("rpc_retries_total", rpc=rpc_name,
                                    outcome="failure")
                    raise
                if code not in RETRYABLE_CODES:
                    # application status: the peer answered — alive
                    self.on_success(addr, None)
                    if i:
                        METRICS.inc("rpc_retries_total", rpc=rpc_name,
                                    outcome="success")
                    raise
                self.on_failure(addr, e)
                if i:
                    METRICS.inc("rpc_retries_total", rpc=rpc_name,
                                outcome="failure")
                last = e
                if i + 1 >= tries or not self.available(addr):
                    break  # out of attempts, or the breaker just opened
                sleep = delay * self._rng.uniform(1.0, 1.25)
                rem = dl.remaining_s()
                if rem is not None:
                    if rem <= 0.002:
                        break  # the budget cannot afford another try
                    sleep = min(sleep, max(rem - 0.001, 0.0))
                time.sleep(sleep)
                delay = min(delay * 2, self.max_backoff_s)
                continue
            except BaseException:
                # anything unexpected (serialization bug, interrupt):
                # the half-open probe token must not stay held, or the
                # breaker wedges permanently half-open
                self._release_probe(addr)
                raise
            self.on_success(addr, time.perf_counter() - t0)
            if i:
                METRICS.inc("rpc_retries_total", rpc=rpc_name,
                            outcome="success")
            return out
        raise last

    def _release_probe(self, addr: str) -> None:
        with self._lock:
            p = self._peers.get(addr)
            if p is not None:
                p.probing = False

    # -- surfacing -----------------------------------------------------------
    def snapshot(self) -> dict:
        """Per-peer health for `/debug/peers`."""
        now = time.monotonic()
        with self._lock:
            out = {}
            for addr, p in sorted(self._peers.items()):
                out[addr] = {
                    "state": p.state,
                    "consecutive_failures": p.fails,
                    "ema_latency_us": round(p.ema_us, 1),
                    "calls_total": p.calls,
                    "failures_total": p.failures,
                    "opened_total": p.opened,
                    "last_error": p.last_error,
                    "last_error_age_s": (
                        round(now - p.last_error_mono, 3)
                        if p.last_error else None),
                    "cooldown_remaining_s": (
                        round(max(p.open_until - now, 0.0), 3)
                        if p.state == "open" else 0.0),
                }
            return out
