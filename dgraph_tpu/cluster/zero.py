"""Zero: the standalone cluster manager service.

Reference parity: `dgraph/cmd/zero/` — group-0 authority for timestamp and
uid leases (assign.go), txn commit arbitration (oracle.go), Alpha
membership (Connect + membership stream), and tablet→group assignment
(tablet.go ShouldServe: first group to ask for an unowned predicate gets
it). The reference replicates this state machine via group-0 Raft; here it
is one process whose state is the cluster's source of truth — Alphas are
stateless against it (restart = reconnect), which matches the
reloadable-sidecar failure model (SURVEY §5).

Membership is polled (`Membership` RPC + a change counter) instead of
streamed — same information, simpler transport.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures

import grpc

from dgraph_tpu.cluster.oracle import Oracle, TxnAborted
from dgraph_tpu.utils import locks
from dgraph_tpu.protos import task_pb2 as pb

SERVICE_ZERO = "dgraph_tpu.Zero"


LEASE_BLOCK = 1000   # ts/uid leases persist at block granularity
# HA tuning: how far (in lease blocks) issuance may outrun the standby's
# replication ack, how long a silent standby stays attached (and gating),
# and the doc_log length that triggers compaction when nothing is tailing
MAX_UNACKED_BLOCKS = 4
STANDBY_GRACE_S = 15.0
DOC_LOG_CAP = 8192
# peer-health reports (ISSUE 9): alphas ship their breaker/latency view
# (/debug/peers) + per-tablet cost sums in a health heartbeat; reports
# older than this no longer veto a move target (a healed peer must not
# stay blacklisted by a stale report)
HEALTH_TTL_S = 60.0


class ZeroState:
    """Membership + tablets + the oracle, under one lock.

    With `journal_path` set, every state transition (join, tablet claim,
    move, removal) and lease-block boundary is fsync'd to a Journal and
    replayed on restart — Zero's tablet map and watermarks survive without
    any Alpha rejoining (reference: group-0 raft WAL + snapshots). Leases
    persist per LEASE_BLOCK: a restart skips to the end of the last
    persisted block, burning at most one block of unused ids — the same
    trade the reference's batched lease makes."""

    def __init__(self, replicas: int = 1, journal_path: str | None = None,
                 txn_timeout_s: float = 0.0, liveness_s: float = 10.0,
                 standby: bool = False):
        self.oracle = Oracle()
        self.replicas = replicas
        self.txn_timeout_s = txn_timeout_s
        self.liveness_s = liveness_s
        self._lock = locks.make_lock("zero.state")
        self._next_node = 1
        self._next_group = 1
        # group_id -> {node_id: addr}
        self.groups: dict[int, dict[int, str]] = {}
        # pred -> group_id
        self.tablets: dict[str, int] = {}
        # group_id -> {pred: approx bytes} (rebalance input)
        self.tablet_sizes: dict[int, dict[str, int]] = {}
        # node_id -> freshest health report (peer breaker states +
        # per-tablet cost sums; see report_health) — placement input
        self.alpha_health: dict[int, dict] = {}
        self.counter = 0
        # node_id -> monotonic last-heard time (liveness; reference: the
        # membership-stream health Zero keeps per Alpha)
        self.last_seen: dict[int, float] = {}
        # every state-machine doc in order, JSON-encoded — the standby
        # replication log (reference: the group-0 raft log followers
        # tail). _doc_base is the absolute index of doc_log[0]: a primary
        # with no attached standby compacts the prefix, and a follower
        # landing below the base bootstraps from a state snapshot doc.
        self.doc_log: list[str] = []
        self._doc_base = 0
        # (ts_block, uid_block) AFTER each doc — journal_tail derives the
        # follower's acked lease floor from these
        self._blocks_at: list[tuple[int, int]] = []
        # identity of this doc stream; a follower seeing it change knows
        # the primary restarted with a fresh log and resyncs from zero
        self.log_id = ""
        # replication ack state (primary side): highest doc index a
        # standby confirmed + the lease blocks covered by it; issuance is
        # gated so a promoted standby's floor always clears every id the
        # primary ever returned (see lease_headroom_ok)
        self._standby_acked = 0
        self._standby_seen_at = 0.0
        self._acked_ts_block = 0
        self._acked_uid_block = 0
        # standby mode: replays a primary's journal, refuses
        # lease/commit/connect RPCs until promoted
        self.standby = standby
        # after promotion: txns started under the old primary (start_ts
        # at or below this) abort — their conflict history died with it
        self.promote_floor = 0
        self._journal = None
        self._ts_block = 0
        self._uid_block = 0
        if journal_path:
            from dgraph_tpu.store.wal import Journal
            for doc in Journal.replay(journal_path):
                self._replay(doc)
            self._journal = Journal(journal_path)
        if not standby and not self.log_id:
            import uuid
            self.log_id = uuid.uuid4().hex
            self._log({"k": "logid", "v": self.log_id})
        # nodes restored from the journal get a full liveness window to
        # report in before being declared dead
        import time as _time
        now = _time.monotonic()
        for nodes in self.groups.values():
            for nid in nodes:
                self.last_seen.setdefault(nid, now)
        locks.guarded(self, "zero.state")

    def _replay(self, doc: dict) -> None:
        import time as _time
        k = doc["k"]
        if k == "join":
            self.groups.setdefault(doc["g"], {})[doc["n"]] = doc["a"]
            self._next_node = max(self._next_node, doc["n"] + 1)
            self._next_group = max(self._next_group, doc["g"] + 1)
            self.last_seen.setdefault(doc["n"], _time.monotonic())
        elif k == "tablet":
            self.tablets[doc["p"]] = doc["g"]
        elif k == "remove":
            for nodes in self.groups.values():
                nodes.pop(doc["n"], None)
        elif k == "tablet_del":
            self.tablets.pop(doc["p"], None)
        elif k == "ts":
            self._ts_block = max(self._ts_block, doc["v"])
            self.oracle.bump_ts(doc["v"])
        elif k == "uid":
            self._uid_block = max(self._uid_block, doc["v"])
            self.oracle.bump_uid(doc["v"])
        elif k == "promote":
            self.promote_floor = max(self.promote_floor, doc["v"])
        elif k == "logid":
            self.log_id = doc["v"]
        elif k == "snap":
            # full-state bootstrap (the primary compacted its log below
            # our cursor): replace membership/tablets wholesale; lease
            # floors only ever ratchet up
            self.groups = {int(g): {int(n): a for n, a in nodes.items()}
                           for g, nodes in doc["groups"].items()}
            self.tablets = dict(doc["tablets"])
            self._next_node = doc["nn"]
            self._next_group = doc["ng"]
            self._ts_block = max(self._ts_block, doc["tsb"])
            self._uid_block = max(self._uid_block, doc["uidb"])
            self.oracle.bump_ts(doc["tsb"])
            self.oracle.bump_uid(doc["uidb"])
            self.promote_floor = max(self.promote_floor, doc["pf"])
            now = _time.monotonic()
            for nodes in self.groups.values():
                for nid in nodes:
                    self.last_seen.setdefault(nid, now)
        self.counter += 1
        self._append_doc(doc)

    def _append_doc(self, doc: dict) -> None:
        import json as _json
        self.doc_log.append(_json.dumps(doc, separators=(",", ":")))
        self._blocks_at.append((self._ts_block, self._uid_block))

    def _log(self, doc: dict) -> None:
        self._append_doc(doc)
        if self._journal is not None:
            self._journal.append(doc)
        self._maybe_compact()

    def _snap_doc(self) -> dict:
        return {"k": "snap",
                "groups": {g: dict(n) for g, n in self.groups.items()},
                "tablets": dict(self.tablets),
                "nn": self._next_node, "ng": self._next_group,
                "tsb": self._ts_block, "uidb": self._uid_block,
                "pf": self.promote_floor}

    def _maybe_compact(self) -> None:
        """Bound doc_log memory on a primary nothing is tailing (lease
        docs accrete one per block forever). With a recently-attached
        standby the log is left alone; a follower that lands below the
        compacted base bootstraps from a snapshot doc instead."""
        import time as _time
        if len(self.doc_log) <= DOC_LOG_CAP:
            return
        if self._standby_seen_at and \
                _time.monotonic() - self._standby_seen_at < STANDBY_GRACE_S:
            return
        drop = len(self.doc_log) // 2
        self._doc_base += drop
        del self.doc_log[:drop]
        del self._blocks_at[:drop]

    def replica_cursor(self) -> tuple:
        """(applied journal seq, standby?, log identity) read under
        the lock — what every journal-tail response, election probe,
        and standby resume needs. These fields are written under the
        lock by the replay/promote/reset paths on OTHER threads; the
        race sanitizer caught the former unlocked reads (a restarted
        standby daemon racing its predecessor's epoch)."""
        with self._lock:
            return (self._doc_base + len(self.doc_log), self.standby,
                    self.log_id)

    def persist_leases(self) -> None:
        """Journal the lease watermarks at block granularity — called on
        the issuing paths, fsyncs only when a block boundary is crossed.
        Runs even without a file journal: the in-memory doc_log is what a
        STANDBY tails, and it must see lease blocks to keep its oracle
        floor current."""
        ts = self.oracle.max_assigned
        uid = self.oracle.max_uid
        with self._lock:
            if ts >= self._ts_block:
                self._ts_block = (ts // LEASE_BLOCK + 1) * LEASE_BLOCK
                self._log({"k": "ts", "v": self._ts_block})
            if uid >= self._uid_block:
                self._uid_block = (uid // LEASE_BLOCK + 1) * LEASE_BLOCK
                self._log({"k": "uid", "v": self._uid_block})

    def expire_stale_txns(self) -> int:
        """Abort pending transactions older than txn_timeout_s — a crashed
        coordinator must not pin the gc watermark forever (reference: Zero
        expires via MaxAssigned + timeouts). Returns the abort count."""
        if not self.txn_timeout_s:
            return 0
        return self.oracle.expire_older_than(self.txn_timeout_s)

    # -- liveness + standby replication (reference: membership health
    # stream + group-0 raft log shipping) --------------------------------
    def heartbeat(self, node_id: int, group: int = 0, max_ts: int = 0,
                  max_uid: int = 0) -> None:
        """Alpha liveness ping. The applied watermarks ride along so a
        freshly-promoted standby's lease space climbs past everything any
        live Alpha has actually seen."""
        import time as _time
        with self._lock:
            self.last_seen[node_id] = _time.monotonic()
        if max_ts:
            self.oracle.bump_ts(max_ts)
        if max_uid:
            self.oracle.bump_uid(max_uid)

    def dead_nodes(self) -> list[int]:
        """Known nodes not heard from within the liveness window."""
        import time as _time
        if not self.liveness_s:
            return []
        now = _time.monotonic()
        with self._lock:
            known = {nid for nodes in self.groups.values() for nid in nodes}
            return sorted(
                nid for nid in known
                if now - self.last_seen.get(nid, now) > self.liveness_s)

    def journal_tail(self, since: int) -> tuple[list[str], int]:
        """State-machine docs after absolute index `since` (follower
        pull). The call doubles as the replication ACK: everything below
        `since` provably arrived, which advances the acked lease floor
        that gates issuance (lease_headroom_ok). A cursor below the
        compacted base gets a full-state snapshot doc instead."""
        import json as _json
        import time as _time
        with self._lock:
            self._standby_seen_at = _time.monotonic()
            if since > self._standby_acked:
                self._standby_acked = since
                pos = since - self._doc_base - 1
                if 0 <= pos < len(self._blocks_at):
                    self._acked_ts_block, self._acked_uid_block = \
                        self._blocks_at[pos]
            end = self._doc_base + len(self.doc_log)
            if since < self._doc_base:
                return [_json.dumps(self._snap_doc(),
                                    separators=(",", ":"))], end
            return self.doc_log[since - self._doc_base:], end

    def lease_headroom_ok(self, n_ts: int = 1, n_uid: int = 0) -> bool:
        """Issuance gate: with a standby attached, never hand out an id
        more than MAX_UNACKED_BLOCKS lease blocks past what the standby
        has confirmed — so its promotion floor (replayed blocks + the
        same margin) always clears every id this primary ever returned.
        The WHOLE grant counts (AssignUids hands out n ids in one call:
        the last id of the grant must stay under the margin, not just
        the first). A standby dark past STANDBY_GRACE_S detaches and the
        gate lifts (availability over safety, as any 2-node HA must
        choose)."""
        import time as _time
        with self._lock:
            if not self._standby_seen_at or _time.monotonic() - \
                    self._standby_seen_at > STANDBY_GRACE_S:
                return True
            margin = MAX_UNACKED_BLOCKS * LEASE_BLOCK
            return (self.oracle.max_assigned + n_ts
                    <= self._acked_ts_block + margin
                    and self.oracle.max_uid + n_uid
                    <= self._acked_uid_block + margin)

    def apply_remote(self, docs_json: list[str]) -> None:
        """Standby: replay docs pulled from the primary, persisting them
        to our own journal so a standby restart (or chained standby)
        keeps the full log."""
        import json as _json
        for dj in docs_json:
            doc = _json.loads(dj)
            with self._lock:
                # _replay appends to doc_log; mirror into our file journal
                self._replay(doc)
            if self._journal is not None:
                self._journal.append(doc)

    def reset_replica(self) -> None:
        """Standby resync-from-scratch (the primary's log identity
        changed): drop replicated membership state and our journal, keep
        the oracle floors and promote_floor — those only ratchet up and
        guard ts/uid uniqueness across regimes."""
        with self._lock:
            self.groups.clear()
            self.tablets.clear()
            self.tablet_sizes.clear()
            self.doc_log.clear()
            self._blocks_at.clear()
            self._doc_base = 0
            self.counter = 0
            self.log_id = ""
            self._next_node = 1
            self._next_group = 1
            if self._journal is not None:
                self._journal.rewrite([])

    def promote(self) -> None:
        """Standby → primary. The primary's issuance gate guarantees it
        never returned an id more than MAX_UNACKED_BLOCKS blocks past our
        last acked pull, so replayed blocks + that margin + 1 clears
        everything it ever handed out; the promote floor then aborts
        txns whose conflict history died with the old process."""
        from dgraph_tpu.utils.metrics import METRICS
        METRICS.inc("election_promoted_total")
        margin = (MAX_UNACKED_BLOCKS + 1) * LEASE_BLOCK
        # read the replayed lease blocks under the lock (a straggling
        # apply_remote pull may still be advancing them); the oracle
        # bumps stay outside — the oracle has its own lock
        with self._lock:
            ts_block, uid_block = self._ts_block, self._uid_block
        floor = max(self.oracle.max_assigned, ts_block)
        self.oracle.bump_ts((floor // LEASE_BLOCK) * LEASE_BLOCK + margin)
        self.oracle.bump_uid(
            (max(self.oracle.max_uid, uid_block) // LEASE_BLOCK)
            * LEASE_BLOCK + margin)
        import time as _time
        now = _time.monotonic()
        with self._lock:
            self.promote_floor = max(self.promote_floor,
                                     self.oracle.max_assigned)
            self._log({"k": "promote", "v": self.promote_floor})
            self.counter += 1
            self.standby = False
            # the failover window ate everyone's heartbeats: restart the
            # liveness clocks rather than declaring the fleet dead
            for nodes in self.groups.values():
                for nid in nodes:
                    self.last_seen[nid] = now
        self.persist_leases()

    def report_sizes(self, group: int, sizes: dict[str, int]) -> None:
        with self._lock:
            self.tablet_sizes[group] = dict(sizes)

    # -- peer health + tablet cost reports (ISSUE 9 placement input) -----
    def report_health(self, doc: dict) -> None:
        """One alpha's health heartbeat: its breaker/latency view of
        every peer it dials (cluster/resilience.py snapshot) plus the
        per-tablet cost sums it measured (utils/costprofile.py). Zero
        keeps the freshest report per node; move/rebalance decisions
        read the aggregate (peer_unhealthy / group_cost_load)."""
        import time as _time
        node_id = int(doc.get("node_id", 0))
        with self._lock:
            self.alpha_health[node_id] = {
                "at": _time.monotonic(),
                "group": int(doc.get("group", 0)),
                "addr": str(doc.get("addr", "")),
                "peers": dict(doc.get("peers", {})),
                "tablet_costs": {str(p): int(c) for p, c in
                                 dict(doc.get("tablet_costs",
                                              {})).items()},
            }

    def unhealthy_addrs(self) -> set[str]:
        """Addresses NO tablet move should target right now: any peer a
        FRESH health report marks breaker open/half-open (some alpha is
        actively failing to reach it), plus every liveness-dead node's
        address. Stale reports (past HEALTH_TTL_S) don't veto — a
        healed peer must come back into rotation."""
        import time as _time
        now = _time.monotonic()
        dead = set(self.dead_nodes())
        with self._lock:
            bad: set[str] = set()
            for nodes in self.groups.values():
                for nid, addr in nodes.items():
                    if nid in dead:
                        bad.add(addr)
            for rep in self.alpha_health.values():
                if now - rep["at"] > HEALTH_TTL_S:
                    continue
                for addr, p in rep["peers"].items():
                    if p.get("state") in ("open", "half_open"):
                        bad.add(addr)
            return bad

    def group_cost_load(self, group: int) -> int:
        """Measured µs-equivalents of tablet work the group's nodes
        reported (freshest report per node) — the load half of the
        placement decision the byte sizes alone can't see (a small, hot
        tablet)."""
        import time as _time
        now = _time.monotonic()
        with self._lock:
            total = 0
            for rep in self.alpha_health.values():
                if rep["group"] != group \
                        or now - rep["at"] > HEALTH_TTL_S:
                    continue
                total += sum(rep["tablet_costs"].values())
            return total

    def move_tablet(self, pred: str, dst_group: int) -> bool:
        """Flip a tablet's owner (the map half of a move; the data ship
        happens first — see ZeroService.MoveTablet / rebalance_once)."""
        with self._lock:
            if dst_group not in self.groups or \
                    self.tablets.get(pred) == dst_group:
                return False
            self.tablets[pred] = dst_group
            self._log({"k": "tablet", "p": pred, "g": dst_group})
            self.counter += 1
            return True

    def rebalance_candidate(self):
        """Pick (pred, src_group, dst_group): move the smallest tablet
        of the most-loaded group to the least-loaded HEALTHY group, if
        the imbalance is worth it (reference: zero/tablet.go rebalance
        loop). Load is the reported byte size PLUS the reported tablet
        cost sums (µs-equivalents — a small but hot tablet weighs in),
        and a group none of whose nodes are currently healthy is never
        a destination (`zero_moves_skipped_unhealthy_total`)."""
        from dgraph_tpu.utils.metrics import METRICS
        bad = self.unhealthy_addrs()           # takes the lock itself
        # snapshot the group ids under the lock; group_cost_load takes
        # the (non-reentrant) lock itself, so it cannot run inside it
        with self._lock:
            gids = list(self.groups)
        cost = {g: self.group_cost_load(g) for g in gids}
        with self._lock:
            if len(self.groups) < 2:
                return None
            load = {g: sum(self.tablet_sizes.get(g, {}).values())
                    + cost.get(g, 0)
                    for g in self.groups}
            src = max(load, key=load.get)
            ranked = [g for g in sorted(load, key=load.get) if g != src]
            healthy_dst = [g for g in ranked
                           if any(a not in bad
                                  for a in self.groups[g].values())]
            if not healthy_dst:
                # every candidate destination is unhealthy: no move
                METRICS.inc("zero_moves_skipped_unhealthy_total")
                return None
            dst = healthy_dst[0]
            if dst != ranked[0]:
                # the least-loaded group was vetoed by peer health
                METRICS.inc("zero_moves_skipped_unhealthy_total")
            if load[src] <= 1.5 * max(load[dst], 1):
                return None
            movable = {p: s for p, s in self.tablet_sizes[src].items()
                       if self.tablets.get(p) == src}
            if not movable:
                return None
            pred = min(movable, key=movable.get)
            return pred, src, dst

    def connect(self, addr: str, group: int = 0, max_ts: int = 0,
                max_uid: int = 0) -> tuple[int, int]:
        """Join the cluster (reference: zero.Server.Connect). With group=0
        Zero fills existing groups up to `replicas` before opening a new
        one — the --replicas elasticity model. The joiner's persisted
        watermarks bump the lease space: a node with replayed history must
        never see Zero hand out timestamps or uids below what it already
        holds (reference: Zero restores these from its raft snapshot; this
        Zero is memory-only, so joiners carry them)."""
        self.oracle.bump_ts(max_ts)
        if max_uid:
            self.oracle.bump_uid(max_uid)
        # the bumped watermarks must hit the journal NOW: a crash before
        # the next lease-issuing RPC would otherwise replay lower blocks
        # and re-lease ids the joiner's store already holds
        self.persist_leases()
        import time as _time
        with self._lock:
            # a rejoining node reclaims its recorded identity by address —
            # a journal-replayed membership must not trap a restarted
            # cluster's tablets in ghost groups (reference: raft id reuse
            # on rejoin)
            for g, nodes in self.groups.items():
                for nid, a in nodes.items():
                    if a == addr and (not group or group == g):
                        self.last_seen[nid] = _time.monotonic()
                        return nid, g
            node_id = self._next_node
            self._next_node += 1
            gid = group
            if not gid:
                for g, nodes in sorted(self.groups.items()):
                    if len(nodes) < self.replicas:
                        gid = g
                        break
                else:
                    gid = self._next_group
            self.groups.setdefault(gid, {})[node_id] = addr
            self._next_group = max(self._next_group, gid + 1)
            self.last_seen[node_id] = _time.monotonic()
            self._log({"k": "join", "n": node_id, "g": gid, "a": addr})
            self.counter += 1
            return node_id, gid

    def remove_node(self, node_id: int) -> None:
        """Operator removal (reference: /removeNode)."""
        with self._lock:
            for nodes in self.groups.values():
                nodes.pop(node_id, None)
            self._log({"k": "remove", "n": node_id})
            self.counter += 1

    def remove_tablet(self, pred: str) -> None:
        """Drop a predicate's tablet assignment (reference: DropAttr
        deletes the tablet from Zero's map)."""
        with self._lock:
            if pred in self.tablets:
                del self.tablets[pred]
                for sizes in self.tablet_sizes.values():
                    sizes.pop(pred, None)
                self._log({"k": "tablet_del", "p": pred})
                self.counter += 1

    def should_serve(self, pred: str, group: int) -> int:
        """Tablet assignment: first group to ask for an unowned predicate
        gets it (reference: zero/tablet.go ShouldServe)."""
        with self._lock:
            owner = self.tablets.get(pred)
            if owner is None:
                self.tablets[pred] = owner = group
                self._log({"k": "tablet", "p": pred, "g": group})
                self.counter += 1
            return owner

    def membership(self) -> pb.MembershipState:
        dead = self.dead_nodes()
        with self._lock:
            st = pb.MembershipState(counter=self.counter)
            st.dead.extend(dead)
            for gid, nodes in self.groups.items():
                g = pb.Group()
                for nid, addr in nodes.items():
                    g.nodes[nid] = addr
                g.tablets.extend(
                    sorted(p for p, og in self.tablets.items() if og == gid))
                st.groups[gid].CopyFrom(g)
            return st


class ZeroService:
    def __init__(self, state: ZeroState):
        self.state = state

    def _primary_only(self, ctx) -> None:
        """Lease/commit/membership-mutating RPCs are refused while in
        standby — a client holding both addresses must not split-brain
        the lease space (reference: only the group-0 raft leader
        serves)."""
        if self.state.replica_cursor()[1]:
            ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                      "zero is a standby (not promoted)")

    def _lease_gate(self, ctx, n_ts: int = 1, n_uid: int = 0) -> None:
        """Refuse id issuance that would outrun the attached standby's
        replication ack — the invariant a safe promotion floor rests on."""
        if n_ts + n_uid >= MAX_UNACKED_BLOCKS * LEASE_BLOCK:
            ctx.abort(grpc.StatusCode.INVALID_ARGUMENT,
                      "grant larger than the replication margin")
        if not self.state.lease_headroom_ok(n_ts, n_uid):
            # RESOURCE_EXHAUSTED (not UNAVAILABLE): a deliberate answer
            # for THIS caller — connectivity-style codes would invite
            # client-side failover to the standby, which can only refuse
            ctx.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                      "lease space awaiting standby replication; retry")

    def Connect(self, req: pb.ConnectRequest, ctx) -> pb.ConnectResponse:
        self._primary_only(ctx)
        nid, gid = self.state.connect(req.addr, int(req.group),
                                      int(req.max_ts), int(req.max_uid))
        return pb.ConnectResponse(node_id=nid, group_id=gid)

    def Membership(self, req: pb.Empty, ctx) -> pb.MembershipState:
        return self.state.membership()

    def ShouldServe(self, req: pb.TabletRequest, ctx) -> pb.Tablet:
        self._primary_only(ctx)
        owner = self.state.should_serve(req.pred, int(req.group))
        return pb.Tablet(pred=req.pred, group=owner)

    def Timestamps(self, req: pb.TsRequest, ctx) -> pb.AssignedIds:
        self._primary_only(ctx)
        self._lease_gate(ctx)
        o = self.state.oracle
        ts = o.read_only_ts() if req.read_only else o.read_ts()
        self.state.persist_leases()
        return pb.AssignedIds(start_id=ts, end_id=ts)

    def AssignUids(self, req: pb.AssignRequest, ctx) -> pb.AssignedIds:
        self._primary_only(ctx)
        self._lease_gate(ctx, n_ts=0, n_uid=int(req.num))
        r = self.state.oracle.assign_uids(int(req.num))
        self.state.persist_leases()
        return pb.AssignedIds(start_id=r.start, end_id=r.stop - 1)

    def Heartbeat(self, req: pb.HeartbeatMsg, ctx) -> pb.Payload:
        # standbys accept heartbeats too: the watermarks seed their lease
        # floor for promotion
        self.state.heartbeat(int(req.node_id), int(req.group),
                             int(req.max_ts), int(req.max_uid))
        return pb.Payload(data=b"ok")

    def JournalTail(self, req: pb.JournalTailRequest, ctx) -> pb.JournalDocs:
        if req.peek:
            # election probe: report applied seq WITHOUT the replication
            # ACK side effect (journal_tail treats `since` as an ack and
            # would pin the lease floor / freshen standby liveness)
            nxt, standby, log_id = self.state.replica_cursor()
            return pb.JournalDocs(docs_json=[], next=nxt,
                                  standby=standby, log_id=log_id)
        docs, nxt = self.state.journal_tail(int(req.since))
        _seq, standby, log_id = self.state.replica_cursor()
        return pb.JournalDocs(docs_json=docs, next=nxt,
                              standby=standby, log_id=log_id)

    def ReportTablets(self, req: pb.TabletSizes, ctx) -> pb.Payload:
        self.state.report_sizes(int(req.group), dict(req.sizes))
        return pb.Payload(data=b"ok")

    def ReportHealth(self, req: pb.Payload, ctx) -> pb.Payload:
        """Alpha health heartbeat (ISSUE 9): a JSON doc in Payload.data
        — {node_id, group, addr, peers: {addr: {state, ema_latency_us}},
        tablet_costs: {pred: µs}} — no proto change needed (Payload is
        the existing opaque envelope). Malformed docs are dropped, never
        a crashed heartbeat loop. Re-establishes the caller's trace
        context from metadata (server/task._inbound_trace) so a traced
        report shows up as ONE cross-process trace."""
        import json as _json

        from dgraph_tpu.server.task import _inbound_trace
        with _inbound_trace(ctx):
            try:
                doc = _json.loads(req.data.decode() or "{}")
            except (UnicodeDecodeError, ValueError):
                return pb.Payload(data=b"bad")
            self.state.report_health(doc)
            return pb.Payload(data=b"ok")

    def RemoveTablet(self, req: pb.TabletRequest, ctx) -> pb.Payload:
        self._primary_only(ctx)
        self.state.remove_tablet(req.pred)
        return pb.Payload(data=b"ok")

    def MoveTablet(self, req: pb.MoveTabletRequest, ctx) -> pb.Payload:
        ok = move_tablet(self.state, req.pred, int(req.dst_group))
        return pb.Payload(data=b"ok" if ok else b"noop")

    def Commit(self, req: pb.CommitRequest, ctx) -> pb.TxnContext:
        self._primary_only(ctx)
        if req.abort:
            self.state.oracle.abort(int(req.start_ts))
            return pb.TxnContext(start_ts=req.start_ts, aborted=True)
        self._lease_gate(ctx)
        with self.state._lock:
            promote_floor = self.state.promote_floor
        if promote_floor and int(req.start_ts) <= promote_floor:
            # the txn began under the dead primary: its conflict history
            # (and any concurrent committers it raced) died with that
            # process — abort rather than risk a lost-update
            ctx.abort(grpc.StatusCode.ABORTED,
                      "txn predates zero failover; retry")
        try:
            cts = self.state.oracle.commit(int(req.start_ts),
                                           list(req.keys))
        except TxnAborted as e:
            ctx.abort(grpc.StatusCode.ABORTED, str(e))
        self.state.persist_leases()
        return pb.TxnContext(start_ts=req.start_ts, commit_ts=cts)


def move_tablet(state: ZeroState, pred: str, dst_group: int) -> bool:
    """Orchestrate a tablet move (reference: zero/tablet.go
    movePredicate): ship a snapshot to EVERY destination replica, flip
    the map once, then ship the copy-window delta to each. Queries keep
    answering throughout — before the flip the old group serves; after
    it, the new owners (already loaded) do. The flip only happens after
    at least one replica holds the bulk copy; delta failures retry and
    are loudly logged (the replica heals fully on its next rejoin
    resync).

    Peer health gates the TARGETS (ISSUE 9): a destination replica that
    any fresh alpha health report marks breaker-open/half-open — or
    that liveness declares dead — is never pulled to; with EVERY
    destination replica unhealthy the move is refused outright
    (`zero_moves_skipped_unhealthy_total`). Shipping a tablet onto a
    half-dead node would hand its reads to the one peer the fleet
    already can't reach."""
    import contextlib
    import time as _time

    from dgraph_tpu.server.task import Client
    from dgraph_tpu.utils import logging as xlog
    from dgraph_tpu.utils.metrics import METRICS
    log = xlog.get("zero")
    bad = state.unhealthy_addrs()
    with state._lock:
        src_group = state.tablets.get(pred)
        src_nodes = dict(state.groups.get(src_group, {}))
        dst_nodes = dict(state.groups.get(dst_group, {}))
    if src_group is None or src_group == dst_group or not dst_nodes \
            or not src_nodes:
        return False
    healthy_dst = {n: a for n, a in dst_nodes.items() if a not in bad}
    if not healthy_dst:
        METRICS.inc("zero_moves_skipped_unhealthy_total")
        log.warning(
            "move of %s to group %d refused: every destination replica "
            "%s is breaker-open or dead per peer health reports",
            pred, dst_group, sorted(dst_nodes.values()))
        return False
    if len(healthy_dst) < len(dst_nodes):
        log.info("move of %s: skipping unhealthy replica(s) %s",
                 pred, sorted(set(dst_nodes.values())
                              - set(healthy_dst.values())))
    dst_nodes = healthy_dst
    src_addr = sorted(src_nodes.values())[0]
    with contextlib.ExitStack() as stack:
        clients = []
        for addr in sorted(dst_nodes.values()):
            c = Client(addr)
            stack.callback(c.close)
            clients.append((addr, c))
        loaded = []
        for addr, c in clients:                # bulk copy, map unflipped
            try:
                c.pull_tablet(pred, src_addr)
                loaded.append((addr, c))
            except grpc.RpcError as e:
                log.warning("bulk pull of %s to %s failed: %s",
                            pred, addr, e)
        if not loaded:
            return False
        if not state.move_tablet(pred, dst_group):
            return False
        # graftlint: allow(retry-deadline): zero-side tablet move — no
        # request budget; pull_tablet is idempotent (full-state copy)
        for addr, c in loaded:                 # copy-window delta
            # graftlint: allow(retry-deadline): see outer loop
            for attempt in range(3):
                try:
                    c.pull_tablet(pred, src_addr)
                    break
                except grpc.RpcError as e:
                    if attempt == 2:
                        log.error(
                            "delta pull of %s to %s failed after flip "
                            "(%s); replica misses copy-window writes "
                            "until it resyncs", pred, addr, e)
                    else:
                        _time.sleep(0.2)
    return True


def rebalance_once(state: ZeroState) -> bool:
    """One sweep of the size-based rebalance loop (reference:
    zero/tablet.go runRebalance)."""
    cand = state.rebalance_candidate()
    if cand is None:
        return False
    pred, _src, dst = cand
    return move_tablet(state, pred, dst)


# election outcome when require_quorum is set and too few standbys are
# reachable: the caller must NOT promote (consistency over availability)
NO_QUORUM = object()


def elect_better(state: ZeroState, my_addr: str, peers,
                 require_quorum: bool = False):
    """Highest-acked-index election among standbys (reference: raft's
    up-to-date-log vote rule, collapsed to a deterministic comparison):
    returns the address of a peer strictly ahead of this standby under
    (applied journal seq, addr) ordering — that peer should promote
    instead — None when THIS standby wins, or NO_QUORUM. A reachable
    peer that already promoted wins outright.

    With require_quorum=False (availability mode): unreachable peers
    don't vote — a standby cut off from every other standby still
    promotes, trading raft's vote quorum for availability;
    log-identity divergence stays operator-visible via log_id. With
    require_quorum=True (the DEFAULT whenever run_standby has peers
    configured) the raft trade is made instead: promotion needs a
    MAJORITY of the standby electorate (self + peers) reachable, so
    standbys partitioned from each other defer (NO_QUORUM) rather
    than dual-promote.

    Mixed-version `peek` hazard: the probe uses JournalTail(peek=true).
    A peer running a build that predates the peek field ignores it and
    serves journal_tail(0) WITH its side effects — the call refreshes
    `_standby_seen_at`, so a probed PRIMARY would believe a standby is
    attached and gate its lease issuance (lease_headroom_ok) until
    STANDBY_GRACE_S lapses. since=0 never regresses the acked floor
    (the ack only ratchets up), so safety holds — the cost is spurious
    RESOURCE_EXHAUSTED retries during a mixed-version rollout."""
    from dgraph_tpu.utils.metrics import METRICS
    my_seq = state.replica_cursor()[0]
    best = None
    reachable = 1                     # self
    for addr in peers:
        try:
            docs_, nxt, standby, _lid = ZeroClient(addr).journal_tail_full(
                0, peek=True)
        except grpc.RpcError:
            METRICS.inc("election_peer_unreachable_total")
            continue
        reachable += 1
        if not standby:
            return addr               # someone already took over
        if (nxt, addr) > (my_seq, my_addr) and \
                (best is None or (nxt, addr) > best):
            best = (nxt, addr)
    if best:
        METRICS.inc("election_lost_total")
        return best[1]
    if require_quorum and reachable < (len(peers) + 1) // 2 + 1:
        METRICS.inc("election_deferred_total")
        return NO_QUORUM
    return None


def run_standby(state: ZeroState, primary_addr: str, poll_s: float = 1.0,
                promote_after_s: float = 5.0, stop_event=None,
                peers=(), my_addr: str = "",
                require_quorum: bool | None = None) -> bool:
    """Standby loop: tail the primary's state-machine journal into
    `state`; when the primary stays unreachable past `promote_after_s`,
    run the highest-acked-index election over `peers` (other standby
    addresses) — the most caught-up standby promotes, the rest re-target
    it (reference: group-0 raft follower election; with no peers this
    collapses to the designated-successor behavior). Returns True when
    promoted, False when stopped externally.

    require_quorum=None (default) resolves to SAFE-BY-DEFAULT: with an
    electorate configured (peers non-empty), promotion requires a
    majority of it reachable — a symmetric standby partition defers
    instead of dual-promoting (raft's consistency choice). Availability
    mode (require_quorum=False with peers) is an explicit opt-out and
    logs loudly. A standby with NO peers keeps the designated-successor
    behavior — there is no electorate to consult.

    A restarted standby resumes from its own replayed log length; a
    log-identity change (the primary restarted with a fresh log) resets
    the replica and resyncs from zero."""
    import time as _time
    if require_quorum is None:
        require_quorum = bool(peers)
    elif peers and not require_quorum:
        from dgraph_tpu.utils import logging as xlog
        from dgraph_tpu.utils.metrics import METRICS
        METRICS.set_gauge("election_availability_mode", 1.0)
        xlog.get("zero").warning(
            "election AVAILABILITY mode (quorum opt-out): a symmetric "
            "partition between standbys can DUAL-PROMOTE — two primaries "
            "issuing from divergent lease spaces (split-brain). Quorum "
            "elections are the default; this opt-out trades that safety "
            "for promotion while the electorate is unreachable.")
    client = ZeroClient(primary_addr)
    since, _standby_now, my_log_id = state.replica_cursor()
    expect_id = my_log_id or None
    last_ok = _time.monotonic()
    apply_fails = 0  # consecutive replica-apply failures (backoff)
    # graftlint: allow(hot-loop-checkpoint, retry-deadline): daemon tail
    # loop — no request budget exists here; lifecycle is stop_event, and
    # an RpcError drives the ELECTION path, never a blind re-spend
    while stop_event is None or not stop_event.is_set():
        try:
            docs, nxt, _standby, log_id = client.journal_tail_full(since)
            if (expect_id is not None and log_id and log_id != expect_id) \
                    or nxt < since:
                state.reset_replica()
                since = 0
                expect_id = log_id or None
                continue
            if log_id and expect_id is None:
                expect_id = log_id
            if docs:
                state.apply_remote(docs)
            since = nxt
            last_ok = _time.monotonic()
        except grpc.RpcError:
            if _time.monotonic() - last_ok > promote_after_s:
                winner = elect_better(state, my_addr, peers,
                                      require_quorum=require_quorum)
                if winner is NO_QUORUM:
                    # too few standbys reachable to vote safely: defer
                    # and retry next poll (raft's consistency choice)
                    from dgraph_tpu.utils import logging as xlog
                    xlog.get("zero").warning(
                        "election deferred: standby quorum unreachable")
                elif winner is None:
                    state.promote()
                    return True
                else:
                    # a more caught-up standby exists: it promotes, this
                    # one keeps tailing FROM it (same journal lineage,
                    # log_id unchanged through promotion)
                    primary_addr = winner
                    client = ZeroClient(winner)
                    since = state._doc_base + len(state.doc_log)
                    last_ok = _time.monotonic()
        except Exception:  # noqa: BLE001 — a malformed doc must not kill
            # the standby thread silently (failover would be lost with no
            # log line); resync the replica from zero and keep tailing.
            # A deterministically-bad doc would otherwise re-download the
            # whole journal every poll — back off exponentially and log
            # loudly only on the first consecutive failure.
            from dgraph_tpu.utils import logging as xlog
            if apply_fails == 0:
                xlog.get("zero").error(
                    "standby apply failed; resetting replica",
                    exc_info=True)
            else:
                xlog.get("zero").debug(
                    "standby apply still failing (attempt %d)",
                    apply_fails + 1, exc_info=True)
            state.reset_replica()
            since = 0
            expect_id = None
            _time.sleep(min(poll_s * (2 ** apply_fails), 30.0))
            apply_fails += 1
            continue
        apply_fails = 0
        _time.sleep(poll_s)
    return False


def _unary(fn, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn, request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString())


def make_zero_server(state: ZeroState | None = None,
                     addr: str = "127.0.0.1:0", max_workers: int = 8):
    """Build (grpc server, bound port, state)."""
    state = state or ZeroState()
    svc = ZeroService(state)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(SERVICE_ZERO, {
            "Connect": _unary(svc.Connect, pb.ConnectRequest),
            "Membership": _unary(svc.Membership, pb.Empty),
            "ShouldServe": _unary(svc.ShouldServe, pb.TabletRequest),
            "Timestamps": _unary(svc.Timestamps, pb.TsRequest),
            "AssignUids": _unary(svc.AssignUids, pb.AssignRequest),
            "Commit": _unary(svc.Commit, pb.CommitRequest),
            "ReportTablets": _unary(svc.ReportTablets, pb.TabletSizes),
            "ReportHealth": _unary(svc.ReportHealth, pb.Payload),
            "MoveTablet": _unary(svc.MoveTablet, pb.MoveTabletRequest),
            "RemoveTablet": _unary(svc.RemoveTablet, pb.TabletRequest),
            "Heartbeat": _unary(svc.Heartbeat, pb.HeartbeatMsg),
            "JournalTail": _unary(svc.JournalTail, pb.JournalTailRequest),
        }),))
    port = server.add_insecure_port(addr)
    return server, port, state


class ZeroClient:
    """Client to a Zero service (reference: the zero conn every Alpha
    holds). `target` may be a comma-separated failover list
    ("primary:5080,standby:5081"): connectivity errors and standby
    refusals rotate to the next address; semantic errors (txn aborts)
    propagate.

    Dead-target marking reuses the cluster breaker signals
    (cluster/resilience.py): each zero target carries per-peer breaker
    state, and the rotation starts at targets whose breaker is NOT
    open — an alpha stops paying the full dial timeout to a dead
    primary on every lease call once the breaker has seen it down.
    Every target is still tried when all breakers are open (leases
    must never be refused outright on client-side suspicion alone)."""

    def __init__(self, target: str):
        from dgraph_tpu.cluster.resilience import PeerTable
        self.targets = [t.strip() for t in target.split(",") if t.strip()]
        self._chans: dict[str, grpc.Channel] = {}
        self._cur = 0
        # retries=0: the target LIST is the retry policy here — the
        # breaker only orders/skips known-dead zeros during cool-down
        self.health = PeerTable(threshold=2, cooldown_ms=1000.0,
                                retries=0)

    @property
    def channel(self) -> grpc.Channel:
        t = self.targets[self._cur]
        ch = self._chans.get(t)
        if ch is None:
            # graftlint: allow(direct-io): ZeroClient pools its own
            # channels — target rotation + PeerTable IS the resilience
            # layer for zero legs (leases must try every target)
            ch = self._chans[t] = grpc.insecure_channel(t)
        return ch

    def _call(self, method: str, req, resp_cls):
        last_err = None
        # ambient trace context rides zero legs too (the task.Client
        # pattern): a traced request whose leg reaches Zero — or a
        # traced health report — stays one cross-process trace
        from dgraph_tpu.utils import tracing as _tracing
        kw = {}
        tid = _tracing.current_trace_id()
        if tid and _tracing.enabled():
            kw["metadata"] = (("x-dgraph-trace-id", tid),
                              ("x-dgraph-parent-span",
                               str(_tracing.current_span_id())))
        # rotation order: current-first, but known-dead targets
        # (breaker open inside cool-down) sink to the back
        order = [(self._cur + i) % len(self.targets)
                 for i in range(len(self.targets))]
        if len(self.targets) > 1:
            order = ([i for i in order
                      if self.health.available(self.targets[i])]
                     + [i for i in order
                        if not self.health.available(self.targets[i])])
        for idx in order:
            self._cur = idx
            target = self.targets[idx]
            rpc = self.channel.unary_unary(
                f"/{SERVICE_ZERO}/{method}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_cls.FromString)
            t0 = time.monotonic()
            try:
                out = rpc(req, **kw)
            except grpc.RpcError as e:
                code = e.code()
                if code == grpc.StatusCode.UNAVAILABLE:
                    # connectivity: breaker signal for dead-marking
                    self.health.on_failure(target, e)
                else:
                    self.health.on_success(target, None)
                if (code == grpc.StatusCode.ABORTED
                        or code == grpc.StatusCode.INVALID_ARGUMENT
                        or code == grpc.StatusCode.RESOURCE_EXHAUSTED
                        or len(self.targets) == 1):
                    # semantic errors (txn abort, oversized grant, the
                    # primary's lease gate asking THIS caller to retry)
                    # must reach the caller — rotating to the standby
                    # would mask them behind its FAILED_PRECONDITION
                    raise
                # connectivity / standby refusal: try the next zero
                last_err = e
                continue
            self.health.on_success(target, time.monotonic() - t0)
            return out
        raise last_err

    def connect(self, addr: str, group: int = 0, max_ts: int = 0,
                max_uid: int = 0) -> tuple[int, int]:
        r = self._call("Connect", pb.ConnectRequest(
            addr=addr, group=group, max_ts=max_ts, max_uid=max_uid),
            pb.ConnectResponse)
        return int(r.node_id), int(r.group_id)

    def membership(self) -> pb.MembershipState:
        return self._call("Membership", pb.Empty(), pb.MembershipState)

    def should_serve(self, pred: str, group: int) -> int:
        r = self._call("ShouldServe",
                       pb.TabletRequest(pred=pred, group=group), pb.Tablet)
        return int(r.group)

    def read_ts(self) -> int:
        r = self._call("Timestamps", pb.TsRequest(num=1), pb.AssignedIds)
        return int(r.start_id)

    def read_only_ts(self) -> int:
        r = self._call("Timestamps", pb.TsRequest(num=1, read_only=True),
                       pb.AssignedIds)
        return int(r.start_id)

    def assign_uids(self, n: int) -> range:
        r = self._call("AssignUids", pb.AssignRequest(num=n),
                       pb.AssignedIds)
        return range(int(r.start_id), int(r.end_id) + 1)

    def commit(self, start_ts: int, keys) -> int:
        try:
            r = self._call("Commit", pb.CommitRequest(
                start_ts=start_ts, keys=sorted(keys)), pb.TxnContext)
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.ABORTED:
                raise TxnAborted(e.details()) from None
            raise
        return int(r.commit_ts)

    def abort(self, start_ts: int) -> None:
        self._call("Commit", pb.CommitRequest(start_ts=start_ts, abort=True),
                   pb.TxnContext)

    def report_tablets(self, group: int, sizes: dict[str, int]) -> None:
        self._call("ReportTablets",
                   pb.TabletSizes(group=group, sizes=sizes), pb.Payload)

    def report_health(self, doc: dict) -> None:
        """Ship one health heartbeat doc (see ZeroService.ReportHealth);
        the JSON rides the existing Payload envelope."""
        import json as _json
        self._call("ReportHealth", pb.Payload(
            data=_json.dumps(doc, separators=(",", ":")).encode()),
            pb.Payload)

    def heartbeat(self, node_id: int, group: int = 0, max_ts: int = 0,
                  max_uid: int = 0) -> None:
        self._call("Heartbeat", pb.HeartbeatMsg(
            node_id=node_id, group=group, max_ts=max_ts, max_uid=max_uid),
            pb.Payload)

    def journal_tail(self, since: int) -> tuple[list[str], int, bool]:
        docs, nxt, standby, _ = self.journal_tail_full(since)
        return docs, nxt, standby

    def journal_tail_full(self, since: int, peek: bool = False) \
            -> tuple[list[str], int, bool, str]:
        r = self._call("JournalTail",
                       pb.JournalTailRequest(since=since, peek=peek),
                       pb.JournalDocs)
        return (list(r.docs_json), int(r.next), bool(r.standby),
                str(r.log_id))

    def remove_tablet(self, pred: str) -> None:
        self._call("RemoveTablet", pb.TabletRequest(pred=pred),
                   pb.Payload)

    def move_tablet(self, pred: str, dst_group: int) -> bool:
        r = self._call("MoveTablet", pb.MoveTabletRequest(
            pred=pred, dst_group=dst_group), pb.Payload)
        return r.data == b"ok"

    def close(self):
        for ch in self._chans.values():
            ch.close()
        self._chans.clear()


class RemoteOracle:
    """Oracle facade backed by a Zero service — what an Alpha's txn path
    talks to in cluster mode (reference: Alphas never arbitrate commits
    themselves; Zero's oracle does). Local bookkeeping only tracks which
    timestamps THIS node handed out, for its own gc watermark."""

    def __init__(self, zero: ZeroClient):
        self.zero = zero
        self._lock = locks.make_lock("zero.remote_oracle")
        self._local_pending: set[int] = set()
        self._max_seen = 0
        locks.guarded(self, "zero.remote_oracle")

    def read_ts(self) -> int:
        ts = self.zero.read_ts()
        with self._lock:
            self._local_pending.add(ts)
            self._max_seen = max(self._max_seen, ts)
        return ts

    def read_only_ts(self) -> int:
        ts = self.zero.read_only_ts()
        with self._lock:
            self._max_seen = max(self._max_seen, ts)
        return ts

    def assign_uids(self, n: int) -> range:
        return self.zero.assign_uids(n)

    def commit(self, start_ts: int, conflict_keys) -> int:
        cts = self.zero.commit(start_ts, list(conflict_keys))
        with self._lock:
            self._local_pending.discard(start_ts)
            self._max_seen = max(self._max_seen, cts)
        return cts

    def abort(self, start_ts: int) -> None:
        with self._lock:
            self._local_pending.discard(start_ts)
        self.zero.abort(start_ts)

    def min_active_ts(self) -> int:
        with self._lock:
            return (min(self._local_pending) if self._local_pending
                    else self._max_seen + 1)

    def gc(self) -> int:
        return self.min_active_ts()

    @property
    def max_assigned(self) -> int:
        with self._lock:
            return self._max_seen

    def bump_ts(self, ts: int) -> None:
        with self._lock:
            self._max_seen = max(self._max_seen, ts)

    def bump_uid(self, uid: int) -> None:
        pass  # Zero owns the uid lease space
