"""Zero: the standalone cluster manager service.

Reference parity: `dgraph/cmd/zero/` — group-0 authority for timestamp and
uid leases (assign.go), txn commit arbitration (oracle.go), Alpha
membership (Connect + membership stream), and tablet→group assignment
(tablet.go ShouldServe: first group to ask for an unowned predicate gets
it). The reference replicates this state machine via group-0 Raft; here it
is one process whose state is the cluster's source of truth — Alphas are
stateless against it (restart = reconnect), which matches the
reloadable-sidecar failure model (SURVEY §5).

Membership is polled (`Membership` RPC + a change counter) instead of
streamed — same information, simpler transport.
"""

from __future__ import annotations

import threading
from concurrent import futures

import grpc

from dgraph_tpu.cluster.oracle import Oracle, TxnAborted
from dgraph_tpu.protos import task_pb2 as pb

SERVICE_ZERO = "dgraph_tpu.Zero"


LEASE_BLOCK = 1000   # ts/uid leases persist at block granularity


class ZeroState:
    """Membership + tablets + the oracle, under one lock.

    With `journal_path` set, every state transition (join, tablet claim,
    move, removal) and lease-block boundary is fsync'd to a Journal and
    replayed on restart — Zero's tablet map and watermarks survive without
    any Alpha rejoining (reference: group-0 raft WAL + snapshots). Leases
    persist per LEASE_BLOCK: a restart skips to the end of the last
    persisted block, burning at most one block of unused ids — the same
    trade the reference's batched lease makes."""

    def __init__(self, replicas: int = 1, journal_path: str | None = None,
                 txn_timeout_s: float = 0.0):
        self.oracle = Oracle()
        self.replicas = replicas
        self.txn_timeout_s = txn_timeout_s
        self._lock = threading.Lock()
        self._next_node = 1
        self._next_group = 1
        # group_id -> {node_id: addr}
        self.groups: dict[int, dict[int, str]] = {}
        # pred -> group_id
        self.tablets: dict[str, int] = {}
        # group_id -> {pred: approx bytes} (rebalance input)
        self.tablet_sizes: dict[int, dict[str, int]] = {}
        self.counter = 0
        self._journal = None
        self._ts_block = 0
        self._uid_block = 0
        if journal_path:
            from dgraph_tpu.store.wal import Journal
            for doc in Journal.replay(journal_path):
                self._replay(doc)
            self._journal = Journal(journal_path)

    def _replay(self, doc: dict) -> None:
        k = doc["k"]
        if k == "join":
            self.groups.setdefault(doc["g"], {})[doc["n"]] = doc["a"]
            self._next_node = max(self._next_node, doc["n"] + 1)
            self._next_group = max(self._next_group, doc["g"] + 1)
        elif k == "tablet":
            self.tablets[doc["p"]] = doc["g"]
        elif k == "remove":
            for nodes in self.groups.values():
                nodes.pop(doc["n"], None)
        elif k == "ts":
            self._ts_block = max(self._ts_block, doc["v"])
            self.oracle.bump_ts(doc["v"])
        elif k == "uid":
            self._uid_block = max(self._uid_block, doc["v"])
            self.oracle.bump_uid(doc["v"])
        self.counter += 1

    def _log(self, doc: dict) -> None:
        if self._journal is not None:
            self._journal.append(doc)

    def persist_leases(self) -> None:
        """Journal the lease watermarks at block granularity — called on
        the issuing paths, fsyncs only when a block boundary is crossed."""
        if self._journal is None:
            return
        ts = self.oracle.max_assigned
        uid = self.oracle.max_uid
        with self._lock:
            if ts >= self._ts_block:
                self._ts_block = (ts // LEASE_BLOCK + 1) * LEASE_BLOCK
                self._log({"k": "ts", "v": self._ts_block})
            if uid >= self._uid_block:
                self._uid_block = (uid // LEASE_BLOCK + 1) * LEASE_BLOCK
                self._log({"k": "uid", "v": self._uid_block})

    def expire_stale_txns(self) -> int:
        """Abort pending transactions older than txn_timeout_s — a crashed
        coordinator must not pin the gc watermark forever (reference: Zero
        expires via MaxAssigned + timeouts). Returns the abort count."""
        if not self.txn_timeout_s:
            return 0
        return self.oracle.expire_older_than(self.txn_timeout_s)

    def report_sizes(self, group: int, sizes: dict[str, int]) -> None:
        with self._lock:
            self.tablet_sizes[group] = dict(sizes)

    def move_tablet(self, pred: str, dst_group: int) -> bool:
        """Flip a tablet's owner (the map half of a move; the data ship
        happens first — see ZeroService.MoveTablet / rebalance_once)."""
        with self._lock:
            if dst_group not in self.groups or \
                    self.tablets.get(pred) == dst_group:
                return False
            self.tablets[pred] = dst_group
            self._log({"k": "tablet", "p": pred, "g": dst_group})
            self.counter += 1
            return True

    def rebalance_candidate(self):
        """Pick (pred, src_group, dst_group): move the smallest tablet of
        the most-loaded group to the least-loaded group, if the imbalance
        is worth it (reference: zero/tablet.go rebalance loop)."""
        with self._lock:
            if len(self.groups) < 2:
                return None
            load = {g: sum(self.tablet_sizes.get(g, {}).values())
                    for g in self.groups}
            src = max(load, key=load.get)
            dst = min(load, key=load.get)
            if src == dst or load[src] <= 1.5 * max(load[dst], 1):
                return None
            movable = {p: s for p, s in self.tablet_sizes[src].items()
                       if self.tablets.get(p) == src}
            if not movable:
                return None
            pred = min(movable, key=movable.get)
            return pred, src, dst

    def connect(self, addr: str, group: int = 0, max_ts: int = 0,
                max_uid: int = 0) -> tuple[int, int]:
        """Join the cluster (reference: zero.Server.Connect). With group=0
        Zero fills existing groups up to `replicas` before opening a new
        one — the --replicas elasticity model. The joiner's persisted
        watermarks bump the lease space: a node with replayed history must
        never see Zero hand out timestamps or uids below what it already
        holds (reference: Zero restores these from its raft snapshot; this
        Zero is memory-only, so joiners carry them)."""
        self.oracle.bump_ts(max_ts)
        if max_uid:
            self.oracle.bump_uid(max_uid)
        # the bumped watermarks must hit the journal NOW: a crash before
        # the next lease-issuing RPC would otherwise replay lower blocks
        # and re-lease ids the joiner's store already holds
        self.persist_leases()
        with self._lock:
            # a rejoining node reclaims its recorded identity by address —
            # a journal-replayed membership must not trap a restarted
            # cluster's tablets in ghost groups (reference: raft id reuse
            # on rejoin)
            for g, nodes in self.groups.items():
                for nid, a in nodes.items():
                    if a == addr and (not group or group == g):
                        return nid, g
            node_id = self._next_node
            self._next_node += 1
            gid = group
            if not gid:
                for g, nodes in sorted(self.groups.items()):
                    if len(nodes) < self.replicas:
                        gid = g
                        break
                else:
                    gid = self._next_group
            self.groups.setdefault(gid, {})[node_id] = addr
            self._next_group = max(self._next_group, gid + 1)
            self._log({"k": "join", "n": node_id, "g": gid, "a": addr})
            self.counter += 1
            return node_id, gid

    def remove_node(self, node_id: int) -> None:
        """Operator removal (reference: /removeNode)."""
        with self._lock:
            for nodes in self.groups.values():
                nodes.pop(node_id, None)
            self._log({"k": "remove", "n": node_id})
            self.counter += 1

    def should_serve(self, pred: str, group: int) -> int:
        """Tablet assignment: first group to ask for an unowned predicate
        gets it (reference: zero/tablet.go ShouldServe)."""
        with self._lock:
            owner = self.tablets.get(pred)
            if owner is None:
                self.tablets[pred] = owner = group
                self._log({"k": "tablet", "p": pred, "g": group})
                self.counter += 1
            return owner

    def membership(self) -> pb.MembershipState:
        with self._lock:
            st = pb.MembershipState(counter=self.counter)
            for gid, nodes in self.groups.items():
                g = pb.Group()
                for nid, addr in nodes.items():
                    g.nodes[nid] = addr
                g.tablets.extend(
                    sorted(p for p, og in self.tablets.items() if og == gid))
                st.groups[gid].CopyFrom(g)
            return st


class ZeroService:
    def __init__(self, state: ZeroState):
        self.state = state

    def Connect(self, req: pb.ConnectRequest, ctx) -> pb.ConnectResponse:
        nid, gid = self.state.connect(req.addr, int(req.group),
                                      int(req.max_ts), int(req.max_uid))
        return pb.ConnectResponse(node_id=nid, group_id=gid)

    def Membership(self, req: pb.Empty, ctx) -> pb.MembershipState:
        return self.state.membership()

    def ShouldServe(self, req: pb.TabletRequest, ctx) -> pb.Tablet:
        owner = self.state.should_serve(req.pred, int(req.group))
        return pb.Tablet(pred=req.pred, group=owner)

    def Timestamps(self, req: pb.TsRequest, ctx) -> pb.AssignedIds:
        o = self.state.oracle
        ts = o.read_only_ts() if req.read_only else o.read_ts()
        self.state.persist_leases()
        return pb.AssignedIds(start_id=ts, end_id=ts)

    def AssignUids(self, req: pb.AssignRequest, ctx) -> pb.AssignedIds:
        r = self.state.oracle.assign_uids(int(req.num))
        self.state.persist_leases()
        return pb.AssignedIds(start_id=r.start, end_id=r.stop - 1)

    def ReportTablets(self, req: pb.TabletSizes, ctx) -> pb.Payload:
        self.state.report_sizes(int(req.group), dict(req.sizes))
        return pb.Payload(data=b"ok")

    def MoveTablet(self, req: pb.MoveTabletRequest, ctx) -> pb.Payload:
        ok = move_tablet(self.state, req.pred, int(req.dst_group))
        return pb.Payload(data=b"ok" if ok else b"noop")

    def Commit(self, req: pb.CommitRequest, ctx) -> pb.TxnContext:
        if req.abort:
            self.state.oracle.abort(int(req.start_ts))
            return pb.TxnContext(start_ts=req.start_ts, aborted=True)
        try:
            cts = self.state.oracle.commit(int(req.start_ts),
                                           list(req.keys))
        except TxnAborted as e:
            ctx.abort(grpc.StatusCode.ABORTED, str(e))
        self.state.persist_leases()
        return pb.TxnContext(start_ts=req.start_ts, commit_ts=cts)


def move_tablet(state: ZeroState, pred: str, dst_group: int) -> bool:
    """Orchestrate a tablet move (reference: zero/tablet.go
    movePredicate): ship a snapshot to EVERY destination replica, flip
    the map once, then ship the copy-window delta to each. Queries keep
    answering throughout — before the flip the old group serves; after
    it, the new owners (already loaded) do. The flip only happens after
    at least one replica holds the bulk copy; delta failures retry and
    are loudly logged (the replica heals fully on its next rejoin
    resync)."""
    import contextlib
    import time as _time

    from dgraph_tpu.server.task import Client
    from dgraph_tpu.utils import logging as xlog
    log = xlog.get("zero")
    with state._lock:
        src_group = state.tablets.get(pred)
        src_nodes = dict(state.groups.get(src_group, {}))
        dst_nodes = dict(state.groups.get(dst_group, {}))
    if src_group is None or src_group == dst_group or not dst_nodes \
            or not src_nodes:
        return False
    src_addr = sorted(src_nodes.values())[0]
    with contextlib.ExitStack() as stack:
        clients = []
        for addr in sorted(dst_nodes.values()):
            c = Client(addr)
            stack.callback(c.close)
            clients.append((addr, c))
        loaded = []
        for addr, c in clients:                # bulk copy, map unflipped
            try:
                c.pull_tablet(pred, src_addr)
                loaded.append((addr, c))
            except grpc.RpcError as e:
                log.warning("bulk pull of %s to %s failed: %s",
                            pred, addr, e)
        if not loaded:
            return False
        if not state.move_tablet(pred, dst_group):
            return False
        for addr, c in loaded:                 # copy-window delta
            for attempt in range(3):
                try:
                    c.pull_tablet(pred, src_addr)
                    break
                except grpc.RpcError as e:
                    if attempt == 2:
                        log.error(
                            "delta pull of %s to %s failed after flip "
                            "(%s); replica misses copy-window writes "
                            "until it resyncs", pred, addr, e)
                    else:
                        _time.sleep(0.2)
    return True


def rebalance_once(state: ZeroState) -> bool:
    """One sweep of the size-based rebalance loop (reference:
    zero/tablet.go runRebalance)."""
    cand = state.rebalance_candidate()
    if cand is None:
        return False
    pred, _src, dst = cand
    return move_tablet(state, pred, dst)


def _unary(fn, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn, request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString())


def make_zero_server(state: ZeroState | None = None,
                     addr: str = "127.0.0.1:0", max_workers: int = 8):
    """Build (grpc server, bound port, state)."""
    state = state or ZeroState()
    svc = ZeroService(state)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(SERVICE_ZERO, {
            "Connect": _unary(svc.Connect, pb.ConnectRequest),
            "Membership": _unary(svc.Membership, pb.Empty),
            "ShouldServe": _unary(svc.ShouldServe, pb.TabletRequest),
            "Timestamps": _unary(svc.Timestamps, pb.TsRequest),
            "AssignUids": _unary(svc.AssignUids, pb.AssignRequest),
            "Commit": _unary(svc.Commit, pb.CommitRequest),
            "ReportTablets": _unary(svc.ReportTablets, pb.TabletSizes),
            "MoveTablet": _unary(svc.MoveTablet, pb.MoveTabletRequest),
        }),))
    port = server.add_insecure_port(addr)
    return server, port, state


class ZeroClient:
    """Client to a Zero service (reference: the zero conn every Alpha
    holds)."""

    def __init__(self, target: str):
        self.channel = grpc.insecure_channel(target)

    def _call(self, method: str, req, resp_cls):
        rpc = self.channel.unary_unary(
            f"/{SERVICE_ZERO}/{method}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString)
        return rpc(req)

    def connect(self, addr: str, group: int = 0, max_ts: int = 0,
                max_uid: int = 0) -> tuple[int, int]:
        r = self._call("Connect", pb.ConnectRequest(
            addr=addr, group=group, max_ts=max_ts, max_uid=max_uid),
            pb.ConnectResponse)
        return int(r.node_id), int(r.group_id)

    def membership(self) -> pb.MembershipState:
        return self._call("Membership", pb.Empty(), pb.MembershipState)

    def should_serve(self, pred: str, group: int) -> int:
        r = self._call("ShouldServe",
                       pb.TabletRequest(pred=pred, group=group), pb.Tablet)
        return int(r.group)

    def read_ts(self) -> int:
        r = self._call("Timestamps", pb.TsRequest(num=1), pb.AssignedIds)
        return int(r.start_id)

    def read_only_ts(self) -> int:
        r = self._call("Timestamps", pb.TsRequest(num=1, read_only=True),
                       pb.AssignedIds)
        return int(r.start_id)

    def assign_uids(self, n: int) -> range:
        r = self._call("AssignUids", pb.AssignRequest(num=n),
                       pb.AssignedIds)
        return range(int(r.start_id), int(r.end_id) + 1)

    def commit(self, start_ts: int, keys) -> int:
        try:
            r = self._call("Commit", pb.CommitRequest(
                start_ts=start_ts, keys=sorted(keys)), pb.TxnContext)
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.ABORTED:
                raise TxnAborted(e.details()) from None
            raise
        return int(r.commit_ts)

    def abort(self, start_ts: int) -> None:
        self._call("Commit", pb.CommitRequest(start_ts=start_ts, abort=True),
                   pb.TxnContext)

    def report_tablets(self, group: int, sizes: dict[str, int]) -> None:
        self._call("ReportTablets",
                   pb.TabletSizes(group=group, sizes=sizes), pb.Payload)

    def move_tablet(self, pred: str, dst_group: int) -> bool:
        r = self._call("MoveTablet", pb.MoveTabletRequest(
            pred=pred, dst_group=dst_group), pb.Payload)
        return r.data == b"ok"

    def close(self):
        self.channel.close()


class RemoteOracle:
    """Oracle facade backed by a Zero service — what an Alpha's txn path
    talks to in cluster mode (reference: Alphas never arbitrate commits
    themselves; Zero's oracle does). Local bookkeeping only tracks which
    timestamps THIS node handed out, for its own gc watermark."""

    def __init__(self, zero: ZeroClient):
        self.zero = zero
        self._lock = threading.Lock()
        self._local_pending: set[int] = set()
        self._max_seen = 0

    def read_ts(self) -> int:
        ts = self.zero.read_ts()
        with self._lock:
            self._local_pending.add(ts)
            self._max_seen = max(self._max_seen, ts)
        return ts

    def read_only_ts(self) -> int:
        ts = self.zero.read_only_ts()
        with self._lock:
            self._max_seen = max(self._max_seen, ts)
        return ts

    def assign_uids(self, n: int) -> range:
        return self.zero.assign_uids(n)

    def commit(self, start_ts: int, conflict_keys) -> int:
        cts = self.zero.commit(start_ts, list(conflict_keys))
        with self._lock:
            self._local_pending.discard(start_ts)
            self._max_seen = max(self._max_seen, cts)
        return cts

    def abort(self, start_ts: int) -> None:
        with self._lock:
            self._local_pending.discard(start_ts)
        self.zero.abort(start_ts)

    def min_active_ts(self) -> int:
        with self._lock:
            return (min(self._local_pending) if self._local_pending
                    else self._max_seen + 1)

    def gc(self) -> int:
        return self.min_active_ts()

    @property
    def max_assigned(self) -> int:
        with self._lock:
            return self._max_seen

    def bump_ts(self, ts: int) -> None:
        with self._lock:
            self._max_seen = max(self._max_seen, ts)

    def bump_uid(self, uid: int) -> None:
        pass  # Zero owns the uid lease space
