"""Message-level fault injection for cluster tests.

Reference parity: the reference has no in-repo fault-injection framework
(Jepsen is external — SURVEY §5); deterministic partition tests need one
here. `FaultyGroups` wraps a node's `Groups` so individual DIRECTED links
(this node → peer) can be dropped or delayed — asymmetric partitions
(A hears B while B cannot reach A) become one-line test setup, which
server stops can never simulate.

Injection point: `pool(addr)` — every outbound RPC of the wrapped node
goes through it (broadcasts, decisions, FetchLog catch-up, ServeTask
routing, read failover), so a blocked link fails exactly like an
unreachable peer (grpc UNAVAILABLE), and a delayed link stalls like a
congested one."""

from __future__ import annotations

import time

import grpc


class LinkDown(grpc.RpcError):
    """UNAVAILABLE-shaped error for a dropped directed link."""

    def __init__(self, src: str, dst: str):
        super().__init__(f"link {src} -> {dst} is partitioned (injected)")
        self._msg = f"link {src} -> {dst} is partitioned (injected)"

    def code(self):
        return grpc.StatusCode.UNAVAILABLE

    def details(self):
        return self._msg


class _FaultyClient:
    """Per-call guard in front of a pooled worker client."""

    def __init__(self, inner, groups: "FaultyGroups", addr: str):
        self._inner = inner
        self._groups = groups
        self._addr = addr

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if not callable(attr):
            return attr

        def guarded(*a, **kw):
            self._groups.check_link(self._addr)
            return attr(*a, **kw)

        return guarded


class FaultyGroups:
    """Transparent `Groups` wrapper with per-directed-link drop/delay.

    Wraps an EXISTING Groups (attribute delegation keeps membership,
    node id, tablet routing intact); only `pool()` is intercepted.
    """

    def __init__(self, inner):
        self._inner = inner
        self._dropped: set[str] = set()       # peer addrs this node can't reach
        self._delay_s: dict[str, float] = {}  # peer addr → injected latency

    # -- fault control -------------------------------------------------------
    def drop_link(self, addr: str) -> None:
        """Partition the DIRECTED link this-node → addr."""
        self._dropped.add(addr)

    def heal_link(self, addr: str) -> None:
        self._dropped.discard(addr)
        self._delay_s.pop(addr, None)  # a healed link runs at full speed
        # the real pool may hold a channel poisoned by earlier failures
        self._inner.invalidate(addr)

    def heal_all(self) -> None:
        for a in list(self._dropped):
            self.heal_link(a)
        self._delay_s.clear()

    def delay_link(self, addr: str, seconds: float) -> None:
        self._delay_s[addr] = seconds

    def check_link(self, addr: str) -> None:
        if addr in self._dropped:
            raise LinkDown(self._inner.my_addr, addr)
        d = self._delay_s.get(addr)
        if d:
            time.sleep(d)

    # -- Groups surface ------------------------------------------------------
    def pool(self, addr: str):
        self.check_link(addr)  # fail fast even before the first call
        return _FaultyClient(self._inner.pool(addr), self, addr)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultSchedule:
    """Seeded randomized drop/heal/delay events over every DIRECTED link
    of a replica group — the nemesis of a Jepsen-shaped exploration
    (SURVEY §5: the reference leans on external Jepsen runs; the
    fuzzing harness in tests/test_partition_fuzz.py drives this).

    Deterministic per seed: the same seed regenerates the exact fault
    sequence, so a failing run replays bit-for-bit
    (DGRAPH_TPU_FUZZ_SEED=<seed>). Events are (op, src, dst, seconds)
    over node INDICES; `apply_event` maps them onto each node's
    FaultyGroups wrapper and tracks the current drop set so tests can
    ask which nodes are minority-isolated.

    `wal_trunc=True` adds WAL-truncation-race events to the schedule
    space (ROADMAP: "WAL truncation races"): node `src` crashes with a
    TORN WAL TAIL — its newest durable record is cut — and restarts.
    Records it acked into the cluster before the crash survive on its
    peers; the restarted node must heal the hole via FetchLog before
    serving, never expose the gap. The event carries no link state;
    the HARNESS performs the crash-restart through the `wal_trunc_cb`
    hook (the schedule stays transport-agnostic). Off by default so
    historical seeds keep their exact schedules.

    `deadline=True` adds DEADLINE-FAULT events: a read on node `src`
    runs with a tight budget (the `seconds` field) while the current
    link faults are live — a heal-in-progress FetchLog leg gets
    cancelled mid-flight. The harness performs the read through
    `deadline_cb(src, budget_s)` and asserts the lifecycle contract:
    the cancelled read raised retryably, leaked no pend, and a retry
    with a full budget serves or refuses CLEANLY. Also off by default
    (same seed-stability rule); with both flags on, the extended slice
    splits between them."""

    def __init__(self, seed: int, n_nodes: int, steps: int = 8,
                 max_delay_s: float = 0.03, wal_trunc: bool = False,
                 deadline: bool = False):
        import random
        self.seed = seed
        self.n_nodes = n_nodes
        self.dropped: set[tuple[int, int]] = set()
        rng = random.Random(seed)
        links = [(i, j) for i in range(n_nodes) for j in range(n_nodes)
                 if i != j]
        self.events: list[tuple[str, int, int, float]] = []
        for _ in range(steps):
            src, dst = rng.choice(links)
            r = rng.random()
            extended = None
            if r >= 0.85:
                # the extended slice: split between whichever extended
                # fault families are armed (order fixed so a given
                # (flags, seed) pair always regenerates identically)
                if wal_trunc and deadline:
                    extended = "wal_trunc" if r < 0.925 else "deadline"
                elif wal_trunc:
                    extended = "wal_trunc"
                elif deadline:
                    extended = "deadline"
            if extended == "wal_trunc":
                # a crash-restart with a torn tail; dst/seconds unused
                self.events.append(("wal_trunc", src, dst, 0.0))
            elif extended == "deadline":
                # a read on src with this budget, under the live faults
                self.events.append(("deadline", src, dst,
                                    round(rng.uniform(0.001, 0.05), 4)))
            elif r < 0.40:
                self.events.append(("drop", src, dst, 0.0))
            elif r < 0.70:
                self.events.append(("heal", src, dst, 0.0))
            else:
                self.events.append(("delay", src, dst,
                                    round(rng.uniform(0.002,
                                                      max_delay_s), 4)))

    def __repr__(self) -> str:
        return (f"FaultSchedule(seed={self.seed}, "
                f"n_nodes={self.n_nodes}, events={self.events})")

    def apply_event(self, ev: tuple[str, int, int, float],
                    faulty_groups, addrs, wal_trunc_cb=None,
                    deadline_cb=None) -> None:
        """Apply one event; `faulty_groups[i]` is node i's FaultyGroups
        wrapper, `addrs[i]` its address. `wal_trunc_cb(src)` performs a
        crash-restart-with-torn-tail of node src; `deadline_cb(src,
        budget_s)` runs the harness's tight-budget read on node src
        (either is skipped when the harness passes None)."""
        op, src, dst, secs = ev
        if op == "deadline":
            if deadline_cb is not None:
                deadline_cb(src, secs)
            return
        if op == "wal_trunc":
            if wal_trunc_cb is not None:
                # the node's links come back clean after a restart
                faulty_groups[src].heal_all()
                self.dropped = {(s, d) for s, d in self.dropped
                                if s != src}
                wal_trunc_cb(src)
            return
        fg = faulty_groups[src]
        if op == "drop":
            fg.drop_link(addrs[dst])
            self.dropped.add((src, dst))
        elif op == "heal":
            fg.heal_link(addrs[dst])
            self.dropped.discard((src, dst))
        else:
            fg.delay_link(addrs[dst], secs)

    def heal_all(self, faulty_groups) -> None:
        for fg in faulty_groups:
            fg.heal_all()
        self.dropped.clear()

    def isolated(self, i: int) -> bool:
        """True when node i currently reaches NO peer: its commits must
        refuse with NoQuorum and its reads with ReadUnavailable (the
        minority side of the partition)."""
        return all((i, j) in self.dropped
                   for j in range(self.n_nodes) if j != i)
