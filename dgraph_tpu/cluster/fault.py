"""Message-level fault injection for cluster tests.

Reference parity: the reference has no in-repo fault-injection framework
(Jepsen is external — SURVEY §5); deterministic partition tests need one
here. `FaultyGroups` wraps a node's `Groups` so individual DIRECTED links
(this node → peer) can be dropped or delayed — asymmetric partitions
(A hears B while B cannot reach A) become one-line test setup, which
server stops can never simulate.

Injection point: the pooled client's `fault_check` hook — it fires
before EVERY wire attempt of every outbound RPC of the wrapped node
(broadcasts, decisions, FetchLog catch-up, ServeTask routing, read
failover), INSIDE the resilience layer's retry loop
(cluster/resilience.py), so a blocked link fails exactly like an
unreachable peer (grpc UNAVAILABLE) — retried, breaker-counted — and a
delayed link stalls like a congested one."""

from __future__ import annotations

import time

import grpc


class LinkDown(grpc.RpcError):
    """UNAVAILABLE-shaped error for a dropped directed link."""

    def __init__(self, src: str, dst: str):
        super().__init__(f"link {src} -> {dst} is partitioned (injected)")
        self._msg = f"link {src} -> {dst} is partitioned (injected)"

    def code(self):
        return grpc.StatusCode.UNAVAILABLE

    def details(self):
        return self._msg


class FaultyGroups:
    """Transparent `Groups` wrapper with per-directed-link drop/delay.

    Wraps an EXISTING Groups (attribute delegation keeps membership,
    node id, tablet routing intact); only `pool()` is intercepted: the
    pooled client's `fault_check` hook fires before EVERY wire attempt
    (server/task.py Client._attempt), INSIDE the resilience layer's
    retry loop — so an injected LinkDown exercises the same
    retry/breaker machinery a real connect failure does.
    """

    def __init__(self, inner):
        self._inner = inner
        self._dropped: set[str] = set()       # peer addrs this node can't reach
        self._delay_s: dict[str, float] = {}  # peer addr → injected latency
        # clock-free delays (ROADMAP follow-on): instead of
        # time.sleep, a delayed link CONSUMES the ambient request
        # budget virtually (RequestContext.consume) — tight budgets
        # expire exactly as under a real stall, at zero wall time
        self.clock_free = False
        # instrument the INNER pool too: methods reached through
        # attribute delegation (call_group's read failover) bind the
        # inner Groups as self, so only hooking FaultyGroups.pool would
        # leave those legs fault-free
        inner_pool = inner.pool

        def hooked_pool(addr):
            c = inner_pool(addr)
            c.fault_check = lambda: self.check_link(addr)
            return c

        inner.pool = hooked_pool

    # -- fault control -------------------------------------------------------
    def drop_link(self, addr: str) -> None:
        """Partition the DIRECTED link this-node → addr."""
        self._dropped.add(addr)

    def heal_link(self, addr: str) -> None:
        self._dropped.discard(addr)
        self._delay_s.pop(addr, None)  # a healed link runs at full speed
        # the real pool may hold a channel poisoned by earlier failures
        self._inner.invalidate(addr)
        # a circuit breaker opened by the injected fault would refuse
        # the healed link until its cool-down expires — a heal restores
        # full connectivity, exactly like a peer restart does
        res = getattr(self._inner, "resilience", None)
        if res is not None:
            res.reset(addr)

    def heal_all(self) -> None:
        for a in list(self._dropped):
            self.heal_link(a)
        self._delay_s.clear()

    def delay_link(self, addr: str, seconds: float) -> None:
        self._delay_s[addr] = seconds

    def check_link(self, addr: str) -> None:
        if addr in self._dropped:
            raise LinkDown(self._inner.my_addr, addr)
        d = self._delay_s.get(addr)
        if d:
            if self.clock_free:
                from dgraph_tpu.utils import deadline as dl
                from dgraph_tpu.utils.metrics import METRICS
                METRICS.inc("fault_virtual_delays_total")
                ctx = dl.current()
                if ctx is not None:
                    ctx.consume(d)
                    # a budget the virtual stall exhausted dies HERE,
                    # exactly where a real sleep would have died
                    ctx.check("fault.delay")
            else:
                time.sleep(d)

    # -- Groups surface ------------------------------------------------------
    def pool(self, addr: str):
        return self._inner.pool(addr)  # hooked in __init__

    def __getattr__(self, name):
        return getattr(self._inner, name)


class FaultSchedule:
    """Seeded randomized drop/heal/delay events over every DIRECTED link
    of a replica group — the nemesis of a Jepsen-shaped exploration
    (SURVEY §5: the reference leans on external Jepsen runs; the
    fuzzing harness in tests/test_partition_fuzz.py drives this).

    Deterministic per seed: the same seed regenerates the exact fault
    sequence, so a failing run replays bit-for-bit
    (DGRAPH_TPU_FUZZ_SEED=<seed>). Events are (op, src, dst, seconds)
    over node INDICES; `apply_event` maps them onto each node's
    FaultyGroups wrapper and tracks the current drop set so tests can
    ask which nodes are minority-isolated.

    `wal_trunc=True` adds WAL-truncation-race events to the schedule
    space (ROADMAP: "WAL truncation races"): node `src` crashes with a
    TORN WAL TAIL — its newest durable record is cut — and restarts.
    Records it acked into the cluster before the crash survive on its
    peers; the restarted node must heal the hole via FetchLog before
    serving, never expose the gap. The event carries no link state;
    the HARNESS performs the crash-restart through the `wal_trunc_cb`
    hook (the schedule stays transport-agnostic). Off by default so
    historical seeds keep their exact schedules.

    `deadline=True` adds DEADLINE-FAULT events: a read on node `src`
    runs with a tight budget (the `seconds` field) while the current
    link faults are live — a heal-in-progress FetchLog leg gets
    cancelled mid-flight. The harness performs the read through
    `deadline_cb(src, budget_s)` and asserts the lifecycle contract:
    the cancelled read raised retryably, leaked no pend, and a retry
    with a full budget serves or refuses CLEANLY. Also off by default
    (same seed-stability rule); armed extended families split the
    extended slice equally, in a fixed order, so a given (flags, seed)
    pair always regenerates identically — and historical seeds replay
    byte-for-byte when the newer flags are off.

    `clock_free=True` applies every delay event WITHOUT wall-clock
    sleeps (ROADMAP follow-on): the delayed link virtually consumes
    the ambient request budget (`RequestContext.consume`) and counts
    `fault_virtual_delays_total`, so a schedule heavy with 30 ms
    stalls fuzzes at full speed while tight budgets still expire
    exactly as under real stalls. Application-time only — the flag
    consumes NO rng draw, so historical-seed schedules replay
    byte-identically with it on or off.

    `crash=True` adds WHOLE-NODE CRASH faults: a `crash` event kills
    node `src` outright — it refuses all RPCs in both directions and
    loses every bit of volatile state (tablet caches, chain positions,
    staged-pend bookkeeping) — and a later `restart` event for the same
    node rebuilds it from its durable WAL/checkpoint (the torn-tail
    restart machinery), after which it must catch up via
    FetchLog/tablet_snapshot and serve again. The harness performs both
    through `crash_cb(src, up)`; crash events count
    `peer_crashes_total`. Generation pairs them: a crash on an
    already-down node regenerates as its restart.

    `disk=True` adds DISK-FAULT events (ISSUE 11 — the PR-1/PR-5
    fault-fuzzing lineage extended from the network to the disk):
    node `src`'s next durable write is damaged through the `vault` IO
    hook (store/vault.py `set_io_fault`) — `disk_bitflip` corrupts the
    written bytes (a bad sector under a WAL record: detected by the
    frame CRC as a torn tail on restart, healed via FetchLog),
    `disk_trunc` cuts the write short (a torn sector), and
    `disk_enospc` raises ENOSPC (the write refuses BEFORE any ack —
    the commit fails retryably, never applies unlogged). The harness
    performs the injection + any crash-restart through
    `disk_cb(src, kind)`; events count `fault_disk_events_total{kind=}`.
    Off by default — historical (flags, seed) schedules replay
    byte-identically (the golden-schedule tests pin this); armed, the
    extended slice re-splits equally with "disk" LAST in the fixed
    family order.

    `alloc=True` adds ALLOCATION-FAULT events (ISSUE 16 — the memory
    governor's OOM lifecycle under fuzz): node `src`'s next governed
    device launch fails its allocation through the memgov process hook
    (utils/memgov.py `set_alloc_fault` — the vault `set_io_fault`
    idiom moved from the disk to the accelerator), exercising the
    evict-retry-once → sticky-degrade protocol under live partitions
    and crashes. The harness arms the one-shot hook through
    `alloc_cb(src)`; events count `fault_alloc_events_total`. Off by
    default — same seed-stability rule; armed, the family slots LAST
    after "disk" in the fixed order, so every historical (flags, seed)
    schedule replays byte-identically and new goldens pin the alloc
    space."""

    def __init__(self, seed: int, n_nodes: int, steps: int = 8,
                 max_delay_s: float = 0.03, wal_trunc: bool = False,
                 deadline: bool = False, crash: bool = False,
                 clock_free: bool = False, disk: bool = False,
                 alloc: bool = False):
        import random
        self.seed = seed
        self.n_nodes = n_nodes
        # clock-free delays change APPLICATION only, never generation:
        # the flag consumes no rng draw, so every historical (flags,
        # seed) pair replays byte-identically with it on or off (the
        # golden-schedule test pins this)
        self.clock_free = clock_free
        self.dropped: set[tuple[int, int]] = set()
        self.crashed: set[int] = set()  # nodes currently down (apply-time)
        rng = random.Random(seed)
        links = [(i, j) for i in range(n_nodes) for j in range(n_nodes)
                 if i != j]
        self.events: list[tuple[str, int, int, float]] = []
        families = [f for f, on in (("wal_trunc", wal_trunc),
                                    ("deadline", deadline),
                                    ("crash", crash),
                                    ("disk", disk),
                                    ("alloc", alloc)) if on]
        gen_down: set[int] = set()  # crash/restart pairing at generation
        for _ in range(steps):
            src, dst = rng.choice(links)
            r = rng.random()
            extended = None
            if r >= 0.85 and families:
                # the extended slice splits equally between the armed
                # families, in the fixed order above (a given
                # (flags, seed) pair always regenerates identically;
                # with only the historical flags armed the cut points
                # match the historical schedule exactly)
                idx = int((r - 0.85) / (0.15 / len(families)))
                extended = families[min(idx, len(families) - 1)]
            if extended == "wal_trunc":
                # a crash-restart with a torn tail; dst/seconds unused
                self.events.append(("wal_trunc", src, dst, 0.0))
                gen_down.discard(src)  # the restart brings it back
            elif extended == "deadline":
                # a read on src with this budget, under the live faults
                self.events.append(("deadline", src, dst,
                                    round(rng.uniform(0.001, 0.05), 4)))
            elif extended == "crash":
                if src in gen_down:
                    self.events.append(("restart", src, dst, 0.0))
                    gen_down.discard(src)
                else:
                    self.events.append(("crash", src, dst, 0.0))
                    gen_down.add(src)
            elif extended == "disk":
                # sub-kind draw happens only inside the disk branch, so
                # schedules with the flag off never consume it
                kind = rng.choice(("bitflip", "trunc", "enospc"))
                self.events.append((f"disk_{kind}", src, dst, 0.0))
                if kind != "enospc":
                    # bitflip/trunc damage durable state; the harness
                    # crash-restarts the node so recovery runs
                    gen_down.discard(src)
            elif extended == "alloc":
                # one injected allocation failure on src's next governed
                # launch; dst/seconds unused, no extra rng draw — the
                # alloc family never perturbs other families' schedules
                self.events.append(("alloc", src, dst, 0.0))
            elif r < 0.40:
                self.events.append(("drop", src, dst, 0.0))
            elif r < 0.70:
                self.events.append(("heal", src, dst, 0.0))
            else:
                self.events.append(("delay", src, dst,
                                    round(rng.uniform(0.002,
                                                      max_delay_s), 4)))

    def __repr__(self) -> str:
        return (f"FaultSchedule(seed={self.seed}, "
                f"n_nodes={self.n_nodes}, events={self.events})")

    def apply_event(self, ev: tuple[str, int, int, float],
                    faulty_groups, addrs, wal_trunc_cb=None,
                    deadline_cb=None, crash_cb=None,
                    disk_cb=None, alloc_cb=None) -> None:
        """Apply one event; `faulty_groups[i]` is node i's FaultyGroups
        wrapper, `addrs[i]` its address. `wal_trunc_cb(src)` performs a
        crash-restart-with-torn-tail of node src; `deadline_cb(src,
        budget_s)` runs the harness's tight-budget read on node src;
        `crash_cb(src, up)` kills (up=False) or rebuilds-from-WAL
        (up=True) node src; `disk_cb(src, kind)` injects one
        bitflip/trunc/enospc write fault on node src through the vault
        IO hook; `alloc_cb(src)` arms one allocation failure on node
        src's next governed launch through the memgov process hook (any
        callback is skipped when the harness passes None)."""
        from dgraph_tpu.utils.metrics import METRICS
        op, src, dst, secs = ev
        if op.startswith("disk_"):
            if disk_cb is not None and src not in self.crashed:
                METRICS.inc("fault_disk_events_total", kind=op[5:])
                disk_cb(src, op[5:])
            return
        if op == "alloc":
            if alloc_cb is not None and src not in self.crashed:
                METRICS.inc("fault_alloc_events_total")
                alloc_cb(src)
            return
        if op == "deadline":
            if deadline_cb is not None:
                deadline_cb(src, secs)
            return
        if op == "wal_trunc":
            if wal_trunc_cb is not None:
                # the node's links come back clean after a restart
                faulty_groups[src].heal_all()
                self.dropped = {(s, d) for s, d in self.dropped
                                if s != src}
                self.crashed.discard(src)
                wal_trunc_cb(src)
            return
        if op == "crash":
            if crash_cb is not None and src not in self.crashed:
                self.crashed.add(src)
                METRICS.inc("peer_crashes_total")
                crash_cb(src, False)
            return
        if op == "restart":
            if crash_cb is not None and src in self.crashed:
                # the restarted node's links come back clean
                faulty_groups[src].heal_all()
                self.dropped = {(s, d) for s, d in self.dropped
                                if s != src}
                self.crashed.discard(src)
                crash_cb(src, True)
            return
        fg = faulty_groups[src]
        if op == "drop":
            fg.drop_link(addrs[dst])
            self.dropped.add((src, dst))
        elif op == "heal":
            fg.heal_link(addrs[dst])
            self.dropped.discard((src, dst))
        else:
            fg.clock_free = self.clock_free
            fg.delay_link(addrs[dst], secs)

    def heal_all(self, faulty_groups, crash_cb=None) -> None:
        for fg in faulty_groups:
            fg.heal_all()
        self.dropped.clear()
        # crashed nodes restart as part of the global heal (the harness
        # passes the same crash_cb apply_event used)
        if crash_cb is not None:
            for src in sorted(self.crashed):
                crash_cb(src, True)
            self.crashed.clear()

    def isolated(self, i: int) -> bool:
        """True when node i currently reaches NO peer: its commits must
        refuse with NoQuorum and its reads with ReadUnavailable (the
        minority side of the partition). A live node whose every peer
        CRASHED is just as alone as one whose links all dropped."""
        return all((i, j) in self.dropped or j in self.crashed
                   for j in range(self.n_nodes) if j != i)
