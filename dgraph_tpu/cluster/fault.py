"""Message-level fault injection for cluster tests.

Reference parity: the reference has no in-repo fault-injection framework
(Jepsen is external — SURVEY §5); deterministic partition tests need one
here. `FaultyGroups` wraps a node's `Groups` so individual DIRECTED links
(this node → peer) can be dropped or delayed — asymmetric partitions
(A hears B while B cannot reach A) become one-line test setup, which
server stops can never simulate.

Injection point: `pool(addr)` — every outbound RPC of the wrapped node
goes through it (broadcasts, decisions, FetchLog catch-up, ServeTask
routing, read failover), so a blocked link fails exactly like an
unreachable peer (grpc UNAVAILABLE), and a delayed link stalls like a
congested one."""

from __future__ import annotations

import time

import grpc


class LinkDown(grpc.RpcError):
    """UNAVAILABLE-shaped error for a dropped directed link."""

    def __init__(self, src: str, dst: str):
        super().__init__(f"link {src} -> {dst} is partitioned (injected)")
        self._msg = f"link {src} -> {dst} is partitioned (injected)"

    def code(self):
        return grpc.StatusCode.UNAVAILABLE

    def details(self):
        return self._msg


class _FaultyClient:
    """Per-call guard in front of a pooled worker client."""

    def __init__(self, inner, groups: "FaultyGroups", addr: str):
        self._inner = inner
        self._groups = groups
        self._addr = addr

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if not callable(attr):
            return attr

        def guarded(*a, **kw):
            self._groups.check_link(self._addr)
            return attr(*a, **kw)

        return guarded


class FaultyGroups:
    """Transparent `Groups` wrapper with per-directed-link drop/delay.

    Wraps an EXISTING Groups (attribute delegation keeps membership,
    node id, tablet routing intact); only `pool()` is intercepted.
    """

    def __init__(self, inner):
        self._inner = inner
        self._dropped: set[str] = set()       # peer addrs this node can't reach
        self._delay_s: dict[str, float] = {}  # peer addr → injected latency

    # -- fault control -------------------------------------------------------
    def drop_link(self, addr: str) -> None:
        """Partition the DIRECTED link this-node → addr."""
        self._dropped.add(addr)

    def heal_link(self, addr: str) -> None:
        self._dropped.discard(addr)
        # the real pool may hold a channel poisoned by earlier failures
        self._inner.invalidate(addr)

    def heal_all(self) -> None:
        for a in list(self._dropped):
            self.heal_link(a)
        self._delay_s.clear()

    def delay_link(self, addr: str, seconds: float) -> None:
        self._delay_s[addr] = seconds

    def check_link(self, addr: str) -> None:
        if addr in self._dropped:
            raise LinkDown(self._inner.my_addr, addr)
        d = self._delay_s.get(addr)
        if d:
            time.sleep(d)

    # -- Groups surface ------------------------------------------------------
    def pool(self, addr: str):
        self.check_link(addr)  # fail fast even before the first call
        return _FaultyClient(self._inner.pool(addr), self, addr)

    def __getattr__(self, name):
        return getattr(self._inner, name)
