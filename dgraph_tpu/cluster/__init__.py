
def start_cluster_alpha(zero_target: str, base=None, group: int = 0,
                        device_threshold: int = 512,
                        addr: str = "127.0.0.1:0", wal_dir: str | None = None):
    """Boot one cluster-mode Alpha: grpc server + Zero connect + Groups.

    Returns (alpha, grpc_server, bound_addr). Reference: alpha run() —
    serve pb.Worker, Connect to Zero for node id + group assignment, then
    keep membership fresh (SURVEY §3.4). `wal_dir` arms the fsync'd WAL —
    required for commit-quorum staging to be durable (reference: the
    raft WAL under every Alpha)."""
    from dgraph_tpu.cluster.groups import Groups
    from dgraph_tpu.cluster.zero import RemoteOracle, ZeroClient
    from dgraph_tpu.server.api import Alpha
    from dgraph_tpu.server.task import make_server

    wal = None
    if wal_dir is not None:
        import os

        from dgraph_tpu.store.wal import WAL
        wal = WAL(os.path.join(wal_dir, "wal.log"))
    zero = ZeroClient(zero_target)
    alpha = Alpha(base=base, device_threshold=device_threshold,
                  oracle=RemoteOracle(zero), wal=wal)
    server, port = make_server(alpha, addr)
    server.start()
    bound = f"127.0.0.1:{port}"
    alpha.groups = Groups(
        zero, bound, group=group, max_ts=alpha.mvcc.base_ts,
        max_uid=int(base.uids[-1]) if base is not None and base.n_nodes
        else 0)
    return alpha, server, bound
