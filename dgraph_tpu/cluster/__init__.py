
def start_cluster_alpha(zero_target: str, base=None, group: int = 0,
                        device_threshold: int = 512,
                        addr: str = "127.0.0.1:0",
                        wal_dir: str | None = None,
                        breaker_threshold: int = 5,
                        breaker_cooldown_ms: float = 500.0,
                        rpc_retries: int = 2):
    """Boot one cluster-mode Alpha: grpc server + Zero connect + Groups.

    Returns (alpha, grpc_server, bound_addr). Reference: alpha run() —
    serve pb.Worker, Connect to Zero for node id + group assignment, then
    keep membership fresh (SURVEY §3.4). `wal_dir` arms the fsync'd WAL —
    required for commit-quorum staging to be durable (reference: the
    raft WAL under every Alpha). The breaker/retry knobs parameterize
    the node's resilience layer (cluster/resilience.py)."""
    from dgraph_tpu.cluster.groups import Groups
    from dgraph_tpu.cluster.zero import RemoteOracle, ZeroClient
    from dgraph_tpu.server.api import Alpha
    from dgraph_tpu.server.task import make_server

    zero = ZeroClient(zero_target)
    alpha = Alpha(base=base, device_threshold=device_threshold,
                  oracle=RemoteOracle(zero))
    max_ts, max_uid = alpha.mvcc.base_ts, 0
    if wal_dir is not None:
        import os

        # replay + re-arm before serving: a restarted replica's stage
        # acks certified durable records that MUST be visible again
        # (Alpha.open's boot leg, shared via attach_wal)
        wal_ts, wal_uid = alpha.attach_wal(
            os.path.join(wal_dir, "wal.log"))
        max_ts = max(max_ts, wal_ts)
        max_uid = max(max_uid, wal_uid)
    server, port = make_server(alpha, addr)
    server.start()
    bound = f"127.0.0.1:{port}"
    if base is not None and base.n_nodes:
        max_uid = max(max_uid, int(base.uids[-1]))
    alpha.groups = Groups(zero, bound, group=group, max_ts=max_ts,
                          max_uid=max_uid,
                          breaker_threshold=breaker_threshold,
                          breaker_cooldown_ms=breaker_cooldown_ms,
                          rpc_retries=rpc_retries)
    return alpha, server, bound
