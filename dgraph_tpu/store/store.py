"""The posting store: uid vocabulary + predicate-sharded CSR blocks.

Reference parity: `posting/` (posting lists keyed `(predicate, uid)`,
`posting/list.go List.Uids/Value`, `posting/index.go` secondary indexes) and
`codec/` (compact uid blocks). Where the reference stores one Badger entry
per `(pred, uid)` holding a varint-packed posting list, this store keeps one
**CSR block per predicate per direction** over a dense int32 *rank* space:

    uids[int64, N]            sorted global uid vocabulary (rank = position)
    indptr[int32, N+1]        per-predicate row offsets
    indices[int32, nnz]       object ranks, sorted within each row

Rank space is what lives in HBM; 64-bit uids exist only at the host
boundary (JSON in/out). Compactness comes from int32 ranks + sharding, not
varint blocks — the decode step the reference burns CPU on simply doesn't
exist here.

Scalar values ride columnar `(subj_ranks, values)` pairs sorted by subject;
string-ish indexes are host-side inverted dicts (token → sorted rank
array), numeric/datetime comparisons use the sorted columns directly.

This object is an immutable snapshot at a commit timestamp; the MVCC layer
(store/mvcc.py) layers transactional deltas above it and rebuilds blocks on
rollup, mirroring the reference's immutable-layer + mutable-delta design.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import numpy as np

from dgraph_tpu.store.schema import PredicateSchema, Schema
from dgraph_tpu.store.tok import tokens_for
from dgraph_tpu.store.types import NUMPY_DTYPE, Kind, convert

TYPE_PRED = "dgraph.type"


@dataclass
class EdgeRel:
    """One direction of a uid predicate as CSR over rank space."""

    indptr: np.ndarray  # int32 [N+1]
    indices: np.ndarray  # int32 [nnz], sorted within each row

    @property
    def nnz(self) -> int:
        return int(self.indices.shape[0])

    def degree(self, ranks: np.ndarray) -> np.ndarray:
        return self.indptr[ranks + 1] - self.indptr[ranks]

    def row(self, rank: int) -> np.ndarray:
        return self.indices[self.indptr[rank]:self.indptr[rank + 1]]


@dataclass
class ValueColumn:
    """Scalar predicate values, columnar, sorted by subject rank.

    `subj` may repeat for list-valued predicates. (Reference: value
    postings in posting/list.go, `ValueFor`.)
    """

    subj: np.ndarray  # int32 [k] sorted
    vals: np.ndarray  # typed per schema kind

    def get(self, rank: int) -> list:
        lo = np.searchsorted(self.subj, rank, side="left")
        hi = np.searchsorted(self.subj, rank, side="right")
        return list(self.vals[lo:hi])

    def get_many(self, ranks: np.ndarray) -> dict[int, list]:
        """Values for a whole batch of ranks in two searchsorted calls
        (the render path's replacement for per-node get()); ranks with
        no value are absent from the result."""
        ranks = np.asarray(ranks)
        lo = np.searchsorted(self.subj, ranks, side="left")
        hi = np.searchsorted(self.subj, ranks, side="right")
        out: dict[int, list] = {}
        single = (hi - lo) == 1  # the common, fully-vectorizable case
        if single.any():
            # iterate the numpy array, NOT .tolist(): tolist() would
            # down-convert np scalars (datetime64 → datetime) and change
            # downstream JSON rendering
            out.update((int(r), [v]) for r, v in
                       zip(ranks[single].tolist(), self.vals[lo[single]]))
        multi = (hi - lo) > 1
        for r, l, h in zip(ranks[multi].tolist(), lo[multi].tolist(),
                           hi[multi].tolist()):
            out[int(r)] = list(self.vals[l:h])
        return out

    def has(self) -> np.ndarray:
        """Sorted unique ranks that have a value."""
        return np.unique(self.subj)


@dataclass
class FacetCol:
    """Edge facets for one key, columnar by edge position.

    Reference: facets stored per posting (pb.Posting.Facets); here a
    sparse column aligned to `EdgeRel.indices` positions — the layout the
    hop kernel's `edge_pos` output gathers from (ops/hop.py)."""

    pos: np.ndarray   # sorted int64 positions into fwd.indices
    vals: np.ndarray  # object array of facet values

    def _locate(self, positions: np.ndarray):
        """(clamped indexes, hit mask) for edge positions — the one
        sorted-position lookup both accessors share."""
        idx = np.searchsorted(self.pos, positions)
        idx_c = np.minimum(idx, max(len(self.pos) - 1, 0))
        hit = (len(self.pos) > 0) & (self.pos[idx_c] == positions)
        return np.atleast_1d(idx_c), np.atleast_1d(hit)

    def get(self, positions: np.ndarray) -> list:
        """Facet values at edge positions; None where absent."""
        idx_c, hit = self._locate(positions)
        return [self.vals[i] if h else None
                for i, h in zip(idx_c.tolist(), hit.tolist())]

    def numeric_at(self, positions: np.ndarray):
        """(values float64, hit mask) at edge positions — the vectorized
        form weighted shortest-path relaxation batches over (reference:
        the weight facet read per relaxed edge). None unless EVERY value
        is genuinely numeric (bool/int/float — numeric STRINGS must not
        parse here: the per-value path treats them as weight 1, and the
        two paths must agree). The float cast computes once."""
        if not hasattr(self, "_num"):
            if all(isinstance(v, (bool, int, float, np.integer,
                                  np.floating, np.bool_))
                   for v in self.vals):
                self._num = self.vals.astype(np.float64)
            else:
                self._num = None
        if self._num is None or not len(self.pos):
            return None
        idx_c, hit = self._locate(positions)
        return self._num[idx_c], hit


@dataclass
class PredicateData:
    schema: PredicateSchema
    fwd: EdgeRel | None = None
    rev: EdgeRel | None = None
    # lang tag → column; "" is the untagged default column
    vals: dict[str, ValueColumn] = field(default_factory=dict)
    # tokenizer → token → sorted int32 rank array
    index: dict[str, dict[str, np.ndarray]] = field(default_factory=dict)
    # facet key → edge-position column (forward direction)
    efacets: dict[str, FacetCol] = field(default_factory=dict)
    # facet key → {subject rank: value} for value postings
    vfacets: dict[str, dict[int, object]] = field(default_factory=dict)
    # reverse-CSR position → forward-CSR position: facets live on the
    # forward posting, but the reference serves them on ~pred expansions
    # too; this map makes reverse edge_pos facet-addressable
    rev_pos: np.ndarray | None = None

    def build_rev_pos(self, n: int) -> None:
        if self.rev is None or self.fwd is None or not self.rev.nnz:
            return
        o_arr = np.repeat(np.arange(n, dtype=np.int64),
                          np.diff(self.rev.indptr).astype(np.int64))
        s_arr = self.rev.indices.astype(np.int64)
        # both CSRs are sorted by (subject, object), so the flattened
        # (s * n + o) keys of the forward edges are ascending
        fwd_src = np.repeat(np.arange(n, dtype=np.int64),
                            np.diff(self.fwd.indptr).astype(np.int64))
        fwd_keys = fwd_src * n + self.fwd.indices.astype(np.int64)
        self.rev_pos = np.searchsorted(fwd_keys, s_arr * n + o_arr)


def _register_device_caches(store) -> None:
    """Join the snapshot's HBM caches (`_device` CSR blocks,
    `_sharded` mesh stacks) to the process memory governor. Callbacks
    close over a weakref — a dropped snapshot's registrations die with
    it. Eviction pops oldest-inserted (first-use order ≈ coldest);
    `device_rel`/`sharded_rel` simply re-place an evicted tablet."""
    import weakref

    from dgraph_tpu.utils import memgov

    ref = weakref.ref(store)

    def _dict_of(attr):
        s = ref()
        return getattr(s, attr, None) if s is not None else None

    def make_cbs(attr):
        def nbytes():
            d = _dict_of(attr)
            if not d:
                return 0
            return sum(memgov.estimate_nbytes(v)
                       for v in list(d.values()))

        def evict_one():
            d = _dict_of(attr)
            if not d:
                return 0
            try:
                v = d.pop(next(iter(d)))
            except (KeyError, StopIteration):
                return 0
            return memgov.estimate_nbytes(v)

        return nbytes, evict_one

    def vec_detail():
        """Resident vector stacks with their dims — the /debug/memory
        rows that make eviction thrash on `store.vec` visible."""
        s = ref()
        if s is None:
            return []
        out = []
        for (pred, kind), v in sorted(getattr(s, "_vec_dev", {}).items()):
            if kind == "mesh":
                _subj, vecs, rows = v
                out.append({"pred": pred, "placement": "mesh",
                            "shards": int(vecs.shape[0]),
                            "rows": int(rows),
                            "dim": int(vecs.shape[-1])})
            else:
                _subj, vecs = v
                out.append({"pred": pred, "placement": "device",
                            "rows": int(vecs.shape[0]),
                            "dim": int(vecs.shape[1])})
        return out

    for attr, name in (("_device", "store.device"),
                       ("_sharded", "store.sharded"),
                       ("_vec_dev", "store.vec")):
        nbytes, evict_one = make_cbs(attr)
        memgov.GOVERNOR.register(
            name, "device", nbytes, evict_one, owner=store,
            detail_cb=vec_detail if name == "store.vec" else None)


class Store:
    """Immutable posting-store snapshot (host arrays + device cache)."""

    def __init__(self, uids: np.ndarray, schema: Schema,
                 preds: dict[str, PredicateData]):
        assert uids.dtype == np.int64 and np.all(np.diff(uids) > 0)
        self.uids = uids
        self.schema = schema
        self.preds = preds
        self._device: dict[tuple[str, str], tuple[jax.Array, jax.Array]] = {}
        self._sharded: dict = {}
        self._sharded_mesh = None
        # float32vector tablets: host stacks (cheap, rebuilt from the
        # value column) and device/mesh placements (governed: store.vec)
        self._vec_tab: dict = {}
        self._vec_dev: dict = {}
        self._vec_mesh = None
        # keys ever placed: a rebuild of one of these is a RE-placement
        # (memgov evicted it, or the mesh changed) — metered so
        # eviction thrash on the vector stacks is visible
        self._vec_placed: set = set()
        self._empty_rel = EdgeRel(np.zeros(self.n_nodes + 1, np.int32),
                                  np.zeros(0, np.int32))
        _register_device_caches(self)

    def rev_to_fwd_pos(self, pred: str, pos: np.ndarray) -> np.ndarray:
        """Map reverse-CSR edge positions to their forward positions (the
        space facet columns key on). Built lazily per predicate."""
        pd = self.preds.get(pred)
        if pd is None or not len(pos):
            return pos
        if pd.rev_pos is None:
            pd.build_rev_pos(self.n_nodes)
        return pd.rev_pos[pos] if pd.rev_pos is not None else pos

    # -- uid ↔ rank ---------------------------------------------------------
    @property
    def n_nodes(self) -> int:
        return int(self.uids.shape[0])

    def rank_of(self, uid_arr) -> np.ndarray:
        """Global uids → ranks; -1 for unknown uids."""
        uid_arr = np.asarray(uid_arr, np.int64)
        pos = np.searchsorted(self.uids, uid_arr)
        pos_c = np.minimum(pos, self.n_nodes - 1) if self.n_nodes else pos * 0
        ok = self.n_nodes > 0
        hit = ok & (self.uids[pos_c] == uid_arr) if ok else np.zeros_like(uid_arr, bool)
        return np.where(hit, pos_c, -1).astype(np.int32)

    def uid_of(self, ranks) -> np.ndarray:
        return self.uids[np.asarray(ranks)]

    # -- relations ----------------------------------------------------------
    def rel(self, pred: str, reverse: bool = False) -> EdgeRel:
        p = self.preds.get(pred)
        r = (p.rev if reverse else p.fwd) if p else None
        return r if r is not None else self._empty_rel

    def device_rel(self, pred: str, reverse: bool = False):
        """CSR block on the default device, cached (HBM residency —
        reference analog: posting-list cache, posting/lists.go)."""
        key = (pred, "rev" if reverse else "fwd")
        out = self._device.get(key)
        if out is None:
            r = self.rel(pred, reverse)
            out = self._device[key] = (jax.device_put(r.indptr),
                                       jax.device_put(r.indices))
            from dgraph_tpu.utils import memgov
            # `out` is returned even if the pass evicts it: the caller's
            # launch still holds the arrays; next lookup re-places
            memgov.GOVERNOR.maybe_evict("device")
        return out

    def sharded_rel(self, pred: str, reverse: bool, mesh):
        """Row-sharded CSR placed on a mesh, cached per (pred, direction)
        — the tablet residency of the distributed path (reference analog:
        worker/groups.go tablet ownership; here every device owns a row
        slab of every predicate, SURVEY §2.3 S1)."""
        from dgraph_tpu.parallel.pshard import device_put_rel, shard_rel
        key = (pred, "rev" if reverse else "fwd")
        cache = getattr(self, "_sharded", None)
        if cache is None or self._sharded_mesh is not mesh:
            cache = {}
            self._sharded = cache
            self._sharded_mesh = mesh
        out = cache.get(key)
        if out is None:
            srel = shard_rel(self.rel(pred, reverse), mesh.devices.size)
            out = cache[key] = device_put_rel(srel, mesh)
            self._note_mesh_residency(srel)
            from dgraph_tpu.utils import memgov
            memgov.GOVERNOR.maybe_evict("device")
        return out

    def _note_mesh_residency(self, srel) -> None:
        """Residency gauges for a newly placed sharded tablet:
        `mesh_shard_bytes{shard=}` accumulates each shard's resident
        bytes across this snapshot's cached tablets (padded widths —
        what actually occupies device memory), `mesh_shard_balance`
        tracks max/mean TRUE edges per shard (1.0 = perfectly
        balanced; the padding hides imbalance from the bytes gauge)."""
        from dgraph_tpu.utils.metrics import METRICS
        ptr = np.asarray(srel.indptr_s)
        d = ptr.shape[0]
        per_bytes = (ptr[0].nbytes
                     + np.asarray(srel.indices_s[0]).nbytes + 4)
        nnz = ptr[:, -1].astype(np.int64)
        tot_b = getattr(self, "_mesh_shard_bytes", None)
        if tot_b is None or len(tot_b) != d:
            tot_b = self._mesh_shard_bytes = np.zeros(d, np.int64)
            self._mesh_shard_nnz = np.zeros(d, np.int64)
        tot_b += per_bytes
        self._mesh_shard_nnz += nnz
        for s in range(d):
            METRICS.set_gauge("mesh_shard_bytes", float(tot_b[s]),
                              shard=s)
        mean = float(self._mesh_shard_nnz.mean())
        if mean > 0:
            METRICS.set_gauge("mesh_shard_balance",
                              float(self._mesh_shard_nnz.max()) / mean)

    # -- vector tablets ------------------------------------------------------
    def vec_tablet(self, pred: str):
        """Host `[n, d]` embedding stack of a float32vector predicate,
        built lazily from the value column and cached on this snapshot.
        None for non-vector predicates."""
        t = self._vec_tab.get(pred)
        if t is None:
            ps = self.schema.peek(pred)
            if ps is None or ps.kind != Kind.VECTOR:
                return None
            from dgraph_tpu.store import vec as _vec
            t = self._vec_tab[pred] = _vec.build_tablet(
                self.value_col(pred), ps.vector_dim)
        return t

    def vec_device(self, pred: str):
        """Embedding stack on the default device, cached + governed
        under `store.vec` (the device_rel residency discipline)."""
        key = (pred, "dev")
        out = self._vec_dev.get(key)
        if out is None:
            t = self.vec_tablet(pred)
            out = self._vec_dev[key] = (jax.device_put(t.subj),
                                        jax.device_put(t.vecs))
            if key in self._vec_placed:
                from dgraph_tpu.utils.metrics import METRICS
                METRICS.inc("vec_replacements_total", kind="device")
            self._vec_placed.add(key)
            from dgraph_tpu.utils import memgov
            memgov.GOVERNOR.maybe_evict("device")
        return out

    def vec_sharded(self, pred: str, mesh):
        """Row-sharded embedding stack placed on a mesh, cached per
        predicate (the sharded_rel tablet discipline — residency
        carried across folds while the mesh object is unchanged).
        Shard-stacked layout: subj `[d, rows]` padded with sentinel
        ranks, vecs `[d, rows, dim]` padded with zero rows. Returns
        (subj_s, vecs_s, rows_per_shard)."""
        from dgraph_tpu.ops.uidalgebra import SENTINEL32
        from dgraph_tpu.parallel.mesh import shard_leading
        key = (pred, "mesh")
        if self._vec_mesh is not mesh:
            for k in [k for k in self._vec_dev if k[1] == "mesh"]:
                self._vec_dev.pop(k, None)
            self._vec_mesh = mesh
        out = self._vec_dev.get(key)
        if out is None:
            t = self.vec_tablet(pred)
            d = int(mesh.devices.size)
            rows = -(-max(t.rows, 1) // d)
            pad = rows * d - t.rows
            subj = np.concatenate(
                [t.subj, np.full(pad, SENTINEL32, np.int32)])
            vecs = np.concatenate(
                [t.vecs, np.zeros((pad, t.dim), np.float32)])
            sh = shard_leading(mesh)
            out = self._vec_dev[key] = (
                jax.device_put(subj.reshape(d, rows), sh),
                jax.device_put(vecs.reshape(d, rows, t.dim), sh),
                rows)
            if key in self._vec_placed:
                from dgraph_tpu.utils.metrics import METRICS
                METRICS.inc("vec_replacements_total", kind="mesh")
            self._vec_placed.add(key)
            from dgraph_tpu.utils import memgov
            memgov.GOVERNOR.maybe_evict("device")
        return out

    # -- values -------------------------------------------------------------
    def value_col(self, pred: str, lang: str = "") -> ValueColumn | None:
        p = self.preds.get(pred)
        if not p:
            return None
        return p.vals.get(lang)

    def values_for(self, pred: str, rank: int, lang: str = "") -> list:
        """Values of `pred` on `rank`. `lang` may be a fallback chain like
        "en:fr:." (reference: language preference lists; "." = ANY
        language, untagged preferred — gql lang fallback semantics)."""
        if not lang:
            col = self.value_col(pred, "")
            return col.get(rank) if col is not None else []
        pd = self.preds.get(pred)
        for l in lang.split(":"):
            if l == ".":
                langs = [""] + sorted(k for k in (pd.vals if pd else {})
                                      if k)
            else:
                langs = [l]
            for lk in langs:
                col = self.value_col(pred, lk)
                if col is not None:
                    vs = col.get(rank)
                    if vs:
                        return vs
        return []

    def values_for_many(self, pred: str, ranks: np.ndarray,
                        lang: str = "") -> dict[int, list]:
        """Batched values_for over a rank set — the JSON render path
        fetches each (level, predicate) column ONCE instead of a
        searchsorted pair per node. Same per-rank lang-chain fallback
        semantics as values_for."""
        ranks = np.asarray(ranks)
        if not lang:
            col = self.value_col(pred, "")
            return col.get_many(ranks) if col is not None else {}
        pd = self.preds.get(pred)
        out: dict[int, list] = {}
        remaining = ranks
        for l in lang.split(":"):
            if not len(remaining):
                break
            if l == ".":
                langs = [""] + sorted(k for k in (pd.vals if pd else {})
                                      if k)
            else:
                langs = [l]
            for lk in langs:
                if not len(remaining):
                    break
                col = self.value_col(pred, lk)
                if col is None:
                    continue
                got = col.get_many(remaining)
                if got:
                    out.update(got)
                    keep = np.array([r not in got
                                     for r in remaining.tolist()])
                    remaining = remaining[keep]
        return out

    def has_ranks(self, pred: str) -> np.ndarray:
        """Sorted ranks of subjects that have `pred` (edges or values);
        `~pred` counts incoming edges. Reference: `has(pred)` root function."""
        reverse = pred.startswith("~")
        p = self.preds.get(pred.lstrip("~"))
        if not p:
            return np.zeros(0, np.int32)
        if reverse:
            rel = p.rev
            if rel is None:
                return np.zeros(0, np.int32)
            deg = rel.indptr[1:] - rel.indptr[:-1]
            return np.nonzero(deg > 0)[0].astype(np.int32)
        parts = []
        if p.fwd is not None:
            deg = p.fwd.indptr[1:] - p.fwd.indptr[:-1]
            parts.append(np.nonzero(deg > 0)[0].astype(np.int32))
        for col in p.vals.values():
            parts.append(col.has().astype(np.int32))
        if not parts:
            return np.zeros(0, np.int32)
        return np.unique(np.concatenate(parts))

    # -- facets -------------------------------------------------------------
    def edge_facets(self, pred: str, positions: np.ndarray,
                    keys=None) -> dict[str, list]:
        """Facet values per requested key at forward edge positions.
        `keys=None` → every key present (reference: @facets with no args)."""
        p = self.preds.get(pred)
        if not p or not p.efacets:
            return {}
        use = p.efacets.keys() if keys is None else \
            [k for k in keys if k in p.efacets]
        return {k: p.efacets[k].get(np.asarray(positions, np.int64))
                for k in use}

    def value_facets(self, pred: str, rank: int, keys=None) -> dict:
        """Facets on a value posting (reference: facets on scalar edges)."""
        p = self.preds.get(pred)
        if not p or not p.vfacets:
            return {}
        use = p.vfacets.keys() if keys is None else \
            [k for k in keys if k in p.vfacets]
        out = {}
        for k in use:
            if rank in p.vfacets[k]:
                out[k] = p.vfacets[k][rank]
        return out

    def index_lookup(self, pred: str, tokenizer: str, token: str) -> np.ndarray:
        """token → sorted rank posting list (reference: index key get)."""
        p = self.preds.get(pred)
        if not p:
            return np.zeros(0, np.int32)
        return p.index.get(tokenizer, {}).get(token, np.zeros(0, np.int32))

    def predicates_of_types(self, type_names) -> list[str]:
        fields: list[str] = []
        for t in type_names:
            td = self.schema.types.get(t)
            if td:
                fields.extend(td.fields)
        seen = set()
        return [f for f in fields if not (f in seen or seen.add(f))]


class StoreBuilder:
    """Accumulates triples, then finalizes into an immutable Store.

    Plays the role of the reference's bulk-load reduce phase
    (dgraph/cmd/bulk/reduce.go): group edges by predicate, sort, emit
    packed blocks — here CSR + columnar values + inverted indexes.
    """

    def __init__(self, schema: Schema | None = None):
        self.schema = schema or Schema()
        self.schema.get(TYPE_PRED).kind = Kind.STRING
        self.schema.get(TYPE_PRED).is_list = True
        if not self.schema.get(TYPE_PRED).index_tokenizers:
            self.schema.get(TYPE_PRED).index_tokenizers = ("exact",)
        self._edges: dict[str, list[tuple[int, int]]] = {}
        self._values: dict[tuple[str, str], list[tuple[int, object]]] = {}
        self._known_uids: set[int] = set()
        # facets keyed by the (subject, object) uid pair / subject uid
        self._efacets: dict[str, dict[tuple[int, int], dict]] = {}
        self._vfacets: dict[str, dict[int, dict]] = {}

    def add_edge(self, subj: int, pred: str, obj: int,
                 facets: dict | None = None) -> None:
        ps = self.schema.get(pred)
        if ps.kind == Kind.DEFAULT and not any(
                p == pred for p, _ in self._values):
            ps.kind = Kind.UID
        elif ps.kind != Kind.UID:
            raise ValueError(f"predicate {pred!r} holds {ps.kind} values, not uids")
        self._edges.setdefault(pred, []).append((subj, obj))
        if facets:
            self._efacets.setdefault(pred, {})[(subj, obj)] = dict(facets)
        self._known_uids.add(subj)
        self._known_uids.add(obj)

    def add_edges(self, pred: str, subjs, objs) -> None:
        """Vectorised bulk form of add_edge (no facets): the bulk-load
        mapper hands whole columns over instead of 10^7 Python calls."""
        ps = self.schema.get(pred)
        if ps.kind == Kind.DEFAULT and not any(
                p == pred for p, _ in self._values):
            ps.kind = Kind.UID
        elif ps.kind != Kind.UID:
            raise ValueError(
                f"predicate {pred!r} holds {ps.kind} values, not uids")
        subjs = np.asarray(subjs, np.int64)
        objs = np.asarray(objs, np.int64)
        self._edges.setdefault(pred, []).extend(
            zip(subjs.tolist(), objs.tolist()))
        self._known_uids.update(subjs.tolist())
        self._known_uids.update(objs.tolist())

    def touch(self, uid: int) -> None:
        """Register a uid in the vocabulary without any posting (cluster
        vocab sync: nodes whose data lives on other groups still occupy a
        rank so the dense rank space is identical everywhere)."""
        self._known_uids.add(int(uid))

    def touch_many(self, uids) -> None:
        self._known_uids.update(int(u) for u in uids)

    def add_value(self, subj: int, pred: str, value, lang: str = "",
                  facets: dict | None = None) -> None:
        ps = self.schema.get(pred)
        if ps.kind == Kind.UID or pred in self._edges:
            raise ValueError(f"predicate {pred!r} is a uid predicate")
        if ps.kind == Kind.DEFAULT and not isinstance(value, str):
            # auto-type from first value (reference: first-mutation typing)
            if isinstance(value, bool):
                ps.kind = Kind.BOOL
            elif isinstance(value, int):
                ps.kind = Kind.INT
            elif isinstance(value, float):
                ps.kind = Kind.FLOAT
        if ps.kind == Kind.VECTOR:
            # convert NOW so a width mismatch is refused at schema time
            # (load time), not discovered mid-query; first vector fixes
            # the width when the schema didn't declare @dim
            value = convert(value, Kind.VECTOR)
            if ps.vector_dim == 0:
                ps.vector_dim = int(len(value))
            elif len(value) != ps.vector_dim:
                raise ValueError(
                    f"predicate {pred!r}: vector of dim {len(value)} "
                    f"does not match schema dim {ps.vector_dim}")
        self._values.setdefault((pred, lang), []).append((subj, value))
        if facets:
            self._vfacets.setdefault(pred, {})[subj] = dict(facets)
        self._known_uids.add(subj)

    def add_type(self, subj: int, type_name: str) -> None:
        self.add_value(subj, TYPE_PRED, type_name)

    def finalize(self) -> Store:
        uids = np.array(sorted(self._known_uids), np.int64)
        n = len(uids)
        rank = {int(u): i for i, u in enumerate(uids)}

        preds: dict[str, PredicateData] = {}
        for pred, pairs in self._edges.items():
            ps = self.schema.get(pred)
            pd = preds.setdefault(pred, PredicateData(schema=ps))
            sr = np.array([(rank[s], rank[o]) for s, o in pairs], np.int32)
            pd.fwd = _csr_from_pairs(sr[:, 0], sr[:, 1], n)
            if ps.reverse:
                pd.rev = _csr_from_pairs(sr[:, 1], sr[:, 0], n)
            # align edge facets to final CSR positions
            fmap = self._efacets.get(pred)
            if fmap:
                by_key: dict[str, list[tuple[int, object]]] = {}
                for (s, o), fd in fmap.items():
                    sr_, or_ = rank[s], rank[o]
                    row = pd.fwd.row(sr_)
                    j = int(np.searchsorted(row, or_))
                    if j >= len(row) or row[j] != or_:
                        continue  # edge was not retained
                    pos = int(pd.fwd.indptr[sr_]) + j
                    for k, v in fd.items():
                        by_key.setdefault(k, []).append((pos, v))
                for k, pv in by_key.items():
                    pv.sort()
                    pd.efacets[k] = FacetCol(
                        pos=np.array([p for p, _ in pv], np.int64),
                        vals=np.array([v for _, v in pv], object))

        for (pred, lang), pairs in self._values.items():
            ps = self.schema.get(pred)
            pd = preds.setdefault(pred, PredicateData(schema=ps))
            kind = ps.kind if ps.kind != Kind.DEFAULT else Kind.STRING
            # dedupe exact (subj, value) repeats (set semantics, as the
            # reference's posting lists are sets); keep list multiplicity
            # for distinct values only
            seen: set = set()
            dpairs = []
            for s, v in pairs:
                cv = convert(v, kind)
                if isinstance(cv, np.datetime64):
                    key = (rank[s], cv.astype("int64").item())
                elif isinstance(cv, np.ndarray):  # vectors: hash bytes
                    key = (rank[s], cv.tobytes())
                else:
                    key = (rank[s], cv)
                if key in seen:
                    continue
                seen.add(key)
                dpairs.append((rank[s], cv))
            subj = np.array([s for s, _ in dpairs], np.int32)
            order = np.argsort(subj, kind="stable")
            subj = subj[order]
            vals = np.empty(len(dpairs), dtype=NUMPY_DTYPE[kind])
            for i, j in enumerate(order):
                vals[i] = dpairs[j][1]
            pd.vals[lang] = ValueColumn(subj=subj, vals=vals)

        for pred, vmap in self._vfacets.items():
            pd = preds.get(pred)
            if pd is None:
                continue
            for s, fd in vmap.items():
                for k, v in fd.items():
                    pd.vfacets.setdefault(k, {})[int(rank[s])] = v

        build_indexes(preds)
        return Store(uids=uids, schema=self.schema, preds=preds)


def build_indexes(preds: dict[str, PredicateData]) -> None:
    """Build inverted token indexes from value columns (reference:
    posting/index.go BuildTokens / RebuildIndex). Shared by StoreBuilder
    and checkpoint load."""
    for pred, pd in preds.items():
        ps = pd.schema
        if not ps.index_tokenizers:
            continue
        for tk in ps.index_tokenizers:
            if tk not in ("exact", "hash", "term", "fulltext", "trigram",
                          "geo"):
                continue  # numeric/datetime ranges use sorted columns
            inv: dict[str, list[int]] = {}
            for lang, col in pd.vals.items():
                for s, v in zip(col.subj, col.vals):
                    for t in tokens_for(tk, v):
                        inv.setdefault(t, []).append(int(s))
            pd.index[tk] = {t: np.unique(np.array(s_list, np.int32))
                            for t, s_list in inv.items()}


def _csr_from_pairs(src: np.ndarray, dst: np.ndarray, n: int) -> EdgeRel:
    """Sorted-by-(src, dst), deduped CSR from edge pairs. Uses the native
    C++ builder when built (native/csr.cpp — the bulk-reduce hot loop);
    numpy otherwise. Outputs are bit-identical either way."""
    if len(src) and n < 2**31:
        from dgraph_tpu import native
        if native.HAVE_NATIVE:
            indptr, indices = native.build_csr(src, dst, n)
            return EdgeRel(indptr=indptr, indices=indices)
    return _csr_from_pairs_np(src, dst, n)


def _csr_from_pairs_np(src: np.ndarray, dst: np.ndarray, n: int) -> EdgeRel:
    order = np.lexsort((dst, src))
    src, dst = src[order], dst[order]
    if len(src):
        keep = np.concatenate([[True], (src[1:] != src[:-1]) | (dst[1:] != dst[:-1])])
        src, dst = src[keep], dst[keep]
    counts = np.bincount(src, minlength=n).astype(np.int32)
    indptr = np.zeros(n + 1, np.int32)
    np.cumsum(counts, out=indptr[1:])
    return EdgeRel(indptr=indptr, indices=dst.astype(np.int32))
