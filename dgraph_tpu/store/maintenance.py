"""Background maintenance scheduler: paced, budget-bounded jobs.

Reference parity: the reference runs rollups, snapshots, and backups as
background Badger jobs WHILE serving (posting/mvcc.go's rollup ticker,
worker/snapshot.go, ee/backup) — a serving system cannot stop the world
to compact. This scheduler is that loop for the TPU build: a daemon
thread on Alpha that runs

    rollup       when the delta-layer stack is `rollup_after` deep
                 (keeps read-path folds shallow; on an out-of-core base
                 it streams the fold to disk, store/stream.py)
    checkpoint   every `checkpoint_every_s` seconds (fold + WAL truncate)
    backup       on request (admin trigger / request_backup)
    export       on request (RDF/JSON dump at the newest fold)

with strict priorities (requested jobs first), pacing between tablets
(`pacing_ms` — the serving path gets the disk/CPU back between
tablets), retry-with-backoff on transient failure (a FoldRaced straggler
race, a full disk that got cleaned), and a pause/drain gate: `pause()`
parks the running job at the next tablet boundary, so quorum-staged
applies and reads never contend with maintenance for more than one
tablet's work; `drain()` finishes the in-flight job and stops — the
shutdown path runs it before the final checkpoint.

Observability (PR 2 registry): every job runs inside a
`maintenance.job` span (tablet spans nest under it via the streaming
layer), outcomes land in `maintenance_jobs_total{job=,outcome=}`,
residency in the `maintenance_resident_bytes` gauge +
`maintenance_evictions_total`, pauses in `maintenance_pauses_total` and
`maintenance_pause_wait_us`.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from dgraph_tpu.utils import flightrec, locks
from dgraph_tpu.utils import logging as xlog
from dgraph_tpu.utils import tracing
from dgraph_tpu.utils.metrics import METRICS

# priorities: lower runs first
PRIO_REQUESTED = 0   # operator-triggered backup/export/checkpoint
PRIO_ROLLUP = 1      # delta stack too deep: read-path folds get slow
PRIO_CHECKPOINT = 2  # periodic durability sweep

MAX_ATTEMPTS = 4
BACKOFF_S = 0.25     # doubles per attempt, capped
BACKOFF_CAP_S = 5.0


@dataclass
class Job:
    """One maintenance work item (requested or policy-scheduled)."""

    name: str                 # rollup | checkpoint | backup | export
    fn: object                # () -> result; may raise (retried)
    priority: int = PRIO_REQUESTED
    attempts: int = 0
    not_before: float = 0.0   # monotonic backoff gate
    seq: int = 0              # FIFO tiebreak within a priority
    done: threading.Event = field(default_factory=threading.Event)
    result: object = None
    error: BaseException | None = None
    # trace id of the request that triggered this job ("" for policy
    # jobs): _run re-establishes it, so an operator-initiated backup's
    # maintenance.job span JOINS the admin request's trace instead of
    # starting an anonymous one on the scheduler thread
    trace_id: str = ""

    def wait(self, timeout: float | None = None):
        """Block until the job finished; re-raise its terminal error."""
        if not self.done.wait(timeout):
            raise TimeoutError(f"maintenance job {self.name} still "
                               f"running after {timeout}s")
        if self.error is not None:
            raise self.error
        return self.result


class MaintenanceScheduler:
    """Daemon-thread job runner over one Alpha (see module docstring)."""

    def __init__(self, alpha, p_dir: str, *, rollup_after: int = 0,
                 checkpoint_every_s: float = 0.0, pacing_ms: float = 0.0):
        self.alpha = alpha
        self.p_dir = p_dir
        self.rollup_after = int(rollup_after)
        self.checkpoint_every_s = float(checkpoint_every_s)
        self.pacing_ms = float(pacing_ms)
        self._log = xlog.get("maintenance")
        self._queue: list[Job] = []
        self._seq = 0
        self._cv = locks.make_condition("maintenance.cv")
        self._resume = threading.Event()
        self._resume.set()              # not paused
        self._stop = False
        self._thread: threading.Thread | None = None
        self._running: str | None = None
        self._last_checkpoint = time.monotonic()
        self.jobs_done = 0
        self.jobs_failed = 0
        # tablet-boundary progress counter: bumped only by the single
        # scheduler thread (at job start and every _pace call), read by
        # the flight-recorder watchdog — a RUNNING job whose progress
        # stops advancing is the stall signal (utils/flightrec.py)
        self.progress = 0
        locks.guarded(self, "maintenance.cv")

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MaintenanceScheduler":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dgraph-maintenance")
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the loop. With `drain`, the in-flight job and every
        already-REQUESTED job finish first (policy jobs are dropped) —
        the shutdown hook (`Alpha.shutdown` / cli SIGINT) uses this so a
        triggered backup is never half-written."""
        if drain:
            self.drain(timeout)
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._resume.set()  # a paused job must observe the stop
        if self._thread is not None:
            self._thread.join(timeout)

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait for the queue of requested jobs + the running job to
        finish. Returns False on timeout."""
        deadline = time.monotonic() + timeout
        self._resume.set()
        while time.monotonic() < deadline:
            with self._cv:
                idle = (self._running is None
                        and not any(j.priority == PRIO_REQUESTED
                                    for j in self._queue))
            if idle:
                return True
            time.sleep(0.02)
        return False

    # -- pause gate ----------------------------------------------------------
    def pause(self) -> None:
        """Park the running job at its next tablet boundary (the pace
        hook blocks) — a heavy foreground phase (bulk apply, tablet
        move) takes the machine for itself without killing the job."""
        self._resume.clear()

    def resume(self) -> None:
        self._resume.set()

    @property
    def paused(self) -> bool:
        return not self._resume.is_set()

    # longest a job yields to queued foreground traffic per tablet
    # boundary: bounded so a permanently-saturated server still makes
    # maintenance progress (one tablet per window) instead of starving
    # rollups until the delta stack kills read latency anyway
    LOAD_YIELD_MAX_S = 2.0

    def _pace(self) -> None:
        """Between-tablet hook handed to the streaming layer: apply the
        configured pacing, honor the pause gate, then YIELD to queued
        foreground traffic — when the admission controller reports
        waiters (server/admission.py `saturated()`), the job parks at
        this tablet boundary (bounded by LOAD_YIELD_MAX_S) so overload
        never competes with maintenance for the disk/CPU."""
        self.progress += 1
        if self.pacing_ms > 0:
            time.sleep(self.pacing_ms / 1e3)
        if not self._resume.is_set():
            METRICS.inc("maintenance_pauses_total")
            t0 = time.perf_counter()
            with tracing.span("maintenance.pause", job=self._running or ""):
                self._resume.wait()
            METRICS.observe("maintenance_pause_wait_us",
                            (time.perf_counter() - t0) * 1e6)
        adm = getattr(self.alpha, "admission", None)
        if adm is not None and adm.saturated():
            METRICS.inc("maintenance_load_pauses_total")
            t0 = time.perf_counter()
            with tracing.span("maintenance.load_pause",
                              job=self._running or ""):
                limit = t0 + self.LOAD_YIELD_MAX_S
                while (adm.saturated() and self._resume.is_set()
                       and not self._stopping()
                       and time.perf_counter() < limit):
                    time.sleep(0.01)
            METRICS.observe("maintenance_pause_wait_us",
                            (time.perf_counter() - t0) * 1e6)

    def _stopping(self) -> bool:
        """`_stop` read under the cv — the yield loop above polls it
        from the job thread while stop() flips it under the same lock
        (10 ms cadence: an uncontended acquire per poll is noise)."""
        with self._cv:
            return self._stop

    # -- requests ------------------------------------------------------------
    def _submit(self, job: Job) -> Job:
        with self._cv:
            job.seq = self._seq = self._seq + 1
            self._queue.append(job)
            self._cv.notify_all()
        return job

    def request_backup(self, dest: str, force_full: bool = False) -> Job:
        from dgraph_tpu.server.backup import backup_alpha
        return self._submit(Job("backup", lambda: backup_alpha(
            self.alpha, self.p_dir, dest, force_full=force_full),
            trace_id=tracing.current_trace_id()))

    def request_export(self, out_path: str, format: str = "rdf") -> Job:
        return self._submit(Job("export", lambda: self.alpha.export_to(
            out_path, format=format, pace=self._pace),
            trace_id=tracing.current_trace_id()))

    def request_checkpoint(self) -> Job:
        return self._submit(Job("checkpoint", self._run_checkpoint,
                                trace_id=tracing.current_trace_id()))

    def status(self) -> dict:
        with self._cv:
            queued = [{"job": j.name, "priority": j.priority,
                       "attempts": j.attempts} for j in self._queue]
            running = self._running
        return {"running": running, "paused": self.paused,
                "queued": queued, "jobs_done": self.jobs_done,
                "jobs_failed": self.jobs_failed,
                "progress": self.progress,
                "rollup_after": self.rollup_after,
                "checkpoint_every_s": self.checkpoint_every_s,
                "pacing_ms": self.pacing_ms}

    # -- policy jobs ---------------------------------------------------------
    def _run_checkpoint(self):
        ts = self.alpha.checkpoint_to(self.p_dir, pace=self._pace)
        self._last_checkpoint = time.monotonic()
        return ts

    def _run_rollup(self):
        return self.alpha.maintenance_rollup(self.p_dir, pace=self._pace)

    def _due_policy_job(self, exclude=()) -> Job | None:
        """Policy triggers (called with no locks): rollup when the delta
        stack is deep, checkpoint on the period. `exclude` names jobs
        currently backing off in the queue — a failed rollup must not
        bypass its backoff via a fresh policy twin, nor starve the
        periodic checkpoint behind it.

        A due checkpoint outranks a due rollup: a checkpoint folds the
        same layers AND truncates the WAL, and under a constant write
        load the rollup trigger re-arms instantly — rollup-first would
        starve the durability sweep forever."""
        if "checkpoint" not in exclude and self.checkpoint_every_s > 0 \
                and time.monotonic() - self._last_checkpoint \
                >= self.checkpoint_every_s:
            return Job("checkpoint", self._run_checkpoint,
                       priority=PRIO_CHECKPOINT)
        if "rollup" not in exclude and self.rollup_after > 0 and \
                self.alpha.mvcc.pending_layer_count() >= self.rollup_after:
            return Job("rollup", self._run_rollup, priority=PRIO_ROLLUP)
        return None

    # -- loop ----------------------------------------------------------------
    def _next_job(self) -> Job | None:
        now = time.monotonic()
        with self._cv:
            ready = [j for j in self._queue if j.not_before <= now]
            if ready:
                job = min(ready, key=lambda j: (j.priority, j.seq))
                self._queue.remove(job)
                return job
            # a failed job backing off blocks its policy twin — spawning
            # a fresh rollup every tick would bypass the backoff
            backing_off = {j.name for j in self._queue}
        # queued foreground traffic defers policy jobs entirely (an
        # operator-REQUESTED job still runs — they asked): starting a
        # rollup while the admission queue is non-empty would hand the
        # machine to background work exactly when it's scarcest
        adm = getattr(self.alpha, "admission", None)
        if adm is not None and adm.saturated():
            return None
        if not self.paused:
            return self._due_policy_job(exclude=backing_off)
        return None

    def _loop(self) -> None:
        while True:
            with self._cv:
                if self._stop:
                    return
            job = None if self.paused else self._next_job()
            if job is None:
                with self._cv:
                    if not self._stop:
                        self._cv.wait(0.05)
                continue
            self._run(job)

    def _run(self, job: Job) -> None:
        with self._cv:
            self._running = job.name
        self.progress += 1  # a fresh job is progress (scheduler thread)
        flightrec.emit("maintenance.job", job=job.name,
                       outcome="started", attempt=job.attempts)
        t0 = time.perf_counter()
        try:
            # re-join the triggering request's trace (attach is a
            # no-op for policy jobs, whose trace_id is empty)
            with tracing.attach(job.trace_id), \
                    tracing.span("maintenance.job", job=job.name,
                                 attempt=job.attempts) as sp:
                job.result = job.fn()
                sp.attrs["outcome"] = "ok"
            METRICS.inc("maintenance_jobs_total", job=job.name,
                        outcome="ok")
            flightrec.emit("maintenance.job", job=job.name,
                           outcome="ok", attempt=job.attempts)
            METRICS.observe("maintenance_job_us",
                            (time.perf_counter() - t0) * 1e6,
                            job=job.name)
            self.jobs_done += 1
            job.done.set()
        except Exception as e:  # noqa: BLE001 — retried below
            job.attempts += 1
            flightrec.emit("maintenance.job", job=job.name,
                           outcome=("failed" if job.attempts
                                    >= MAX_ATTEMPTS else "retry"),
                           attempt=job.attempts, error=str(e)[:200])
            if job.attempts >= MAX_ATTEMPTS:
                METRICS.inc("maintenance_jobs_total", job=job.name,
                            outcome="failed")
                self.jobs_failed += 1
                job.error = e
                job.done.set()
                self._log.exception(
                    "maintenance %s failed permanently after %d attempts",
                    job.name, job.attempts)
            else:
                METRICS.inc("maintenance_jobs_total", job=job.name,
                            outcome="retry")
                backoff = min(BACKOFF_S * (2 ** (job.attempts - 1)),
                              BACKOFF_CAP_S)
                job.not_before = time.monotonic() + backoff
                self._log.warning(
                    "maintenance %s attempt %d failed (%s); retrying "
                    "in %.2fs", job.name, job.attempts, e, backoff)
                self._submit(job)
        finally:
            with self._cv:
                self._running = None
                self._cv.notify_all()
