"""Streaming maintenance layer: tablet-granular passes over the store.

Reference parity: Badger's Stream framework + the background jobs the
reference runs over it — posting-list rollups, raft snapshots, and
incremental backups all iterate the LSM key range in order, never
holding the whole store in memory (SURVEY §2.5, §5). This module is
that leg for the CSR block store: iterate predicate tablets in stable
(sorted) order, fault one in, process it, release it before the next —
so every write-shaped maintenance pass (MVCC fold/rollup, checkpoint
save, backup, RDF/JSON export) over an out-of-core store
(store/outofcore.py) holds at most `max(budget, largest_tablet)`
resident, byte-accounted through the same `_pd_nbytes` ledger the read
path evicts by.

The partitioned checkpoint writer reuses store/checkpoint.py's
per-tablet segment format verbatim (checkpoint.save_predicate), so a
streaming save is byte-identical per segment to an in-core save, and
the fold writer routes each tablet through the SAME
mvcc._materialize code path (restricted to one predicate, vocabulary
pinned to the full-fold union) — outputs are bit-identical to the
in-core rollup, just never all resident at once.

Observability: each pass emits `maintenance.tablet` spans and keeps the
`maintenance_resident_bytes` gauge + `maintenance_evictions_total`
counter fresh (PR 2 registry). The `pace` hook runs between tablets —
the maintenance scheduler (store/maintenance.py) uses it to sleep
`--maintenance_pacing_ms` and to park at its pause gate, which is what
bounds how long a quorum-staged apply or read can contend with a
maintenance job: one tablet's work.
"""

from __future__ import annotations

import os

from dgraph_tpu.store import checkpoint
# fold_vocab / fold_preds live in store/mvcc.py (the lazily-folding
# read view shares them); re-exported here for the existing callers.
from dgraph_tpu.store.mvcc import (MVCCStore, _materialize, fold_preds,
                                   fold_vocab)
from dgraph_tpu.store.store import Store
from dgraph_tpu.utils import tracing
from dgraph_tpu.utils.metrics import METRICS


def lazy_preds(store: Store):
    """The store's LazyPreds when it is out-of-core, else None."""
    from dgraph_tpu.store.outofcore import LazyPreds
    preds = getattr(store, "preds", None)
    return preds if isinstance(preds, LazyPreds) else None


def _evicted(lazy) -> int:
    st = lazy.stats()  # locked accessor: serving threads fault/evict
    return st["evictions"] + st["releases"]


def _account(lazy, evicted_before: int) -> None:
    st = lazy.stats()
    METRICS.set_gauge("maintenance_resident_bytes",
                      st["resident_bytes"])
    delta = (st["evictions"] + st["releases"]) - evicted_before
    if delta > 0:
        METRICS.inc("maintenance_evictions_total", float(delta))


def iter_tablets(store: Store, release: bool = True, pace=None,
                 job: str = ""):
    """Yield (pred, PredicateData) in stable sorted order, one tablet
    resident at a time on an out-of-core store.

    Tablets that were already resident when the pass reached them (the
    serving path's hot set) are NOT released — only tablets this pass
    itself faulted in. Consumer work per tablet runs inside a
    `maintenance.tablet` span; `pace` runs between tablets."""
    lazy = lazy_preds(store)
    for pred in sorted(store.preds.keys()):
        was_resident = lazy.is_resident(pred) if lazy is not None else True
        evicted0 = _evicted(lazy) if lazy else 0
        with tracing.span("maintenance.tablet", pred=pred, job=job):
            pd = store.preds.get(pred)
            if pd is not None:
                yield pred, pd
        del pd
        if lazy is not None:
            if release and not was_resident:
                lazy.release(pred)
            _account(lazy, evicted0)
        if pace is not None:
            pace()


def save_streaming(store: Store, dirname: str, base_ts: int = 0,
                   compress: bool | None = None, pace=None,
                   job: str = "checkpoint") -> None:
    """checkpoint.save(), one tablet resident at a time: same segment
    files, same manifest fields — an out-of-core store is saved without
    ever holding more than budget + one tablet resident."""
    from dgraph_tpu import native
    if compress is None:
        compress = native.HAVE_NATIVE
    os.makedirs(dirname, exist_ok=True)
    uids_crc = checkpoint.save_uids(store.uids, dirname, compress)
    preds_meta = {}
    for pred, pd in iter_tablets(store, pace=pace, job=job):
        preds_meta[pred] = checkpoint.save_predicate(dirname, pred, pd)
    checkpoint.write_manifest(dirname, checkpoint.manifest_doc(
        store.n_nodes, store.schema.to_text(), preds_meta, base_ts,
        compress, uids_crc=uids_crc))




def write_fold(mvcc: MVCCStore, dirname: str, plan=None,
               compress: bool | None = None, pace=None,
               job: str = "rollup",
               manifest_ts: int | None = None) -> tuple[int, tuple]:
    """Fold (newest fold point + pending delta layers) into a plain
    snapshot dir, ONE TABLET AT A TIME. Returns (new_ts, guard) for
    MVCCStore.install_fold. With no pending layers this degrades to a
    streaming save of the base (the builder round-trip is skipped so
    segments stay byte-identical to the base's own). `manifest_ts`
    overrides the base_ts recorded in the manifest (a full backup
    stamps its read watermark, which may sit above the newest commit)."""
    from dgraph_tpu import native
    if compress is None:
        compress = native.HAVE_NATIVE
    if plan is None:
        plan = mvcc.fold_plan()
    _fold_ts, base, pending, new_ts, guard = plan
    stamp = new_ts if manifest_ts is None else manifest_ts
    if not pending:
        save_streaming(base, dirname, base_ts=stamp, compress=compress,
                       pace=pace, job=job)
        return new_ts, guard

    vocab = fold_vocab(base, pending)
    schema = base.schema.clone()
    os.makedirs(dirname, exist_ok=True)
    uids_crc = checkpoint.save_uids(vocab, dirname, compress)
    lazy = lazy_preds(base)
    preds_meta = {}
    for pred in fold_preds(base, pending):
        was_resident = lazy.is_resident(pred) if lazy is not None else True
        evicted0 = _evicted(lazy) if lazy else 0
        with tracing.span("maintenance.tablet", pred=pred, job=job):
            # the same fold code path the in-core rollup runs, restricted
            # to one predicate with the vocabulary pinned — per-tablet
            # output is bit-identical to the full materialize's slice
            folded = _materialize(base, pending, schema=schema,
                                  only={pred}, vocab=vocab)
            pd = folded.preds.get(pred)
            if pd is not None:
                preds_meta[pred] = checkpoint.save_predicate(
                    dirname, pred, pd)
        del folded, pd
        if lazy is not None:
            if not was_resident:
                lazy.release(pred)
            _account(lazy, evicted0)
        if pace is not None:
            pace()
    checkpoint.write_manifest(dirname, checkpoint.manifest_doc(
        int(len(vocab)), schema.to_text(), preds_meta, stamp, compress,
        uids_crc=uids_crc))
    return new_ts, guard


def checkpoint_streaming(mvcc: MVCCStore, root_dir: str,
                         budget_bytes: int, pace=None,
                         job: str = "checkpoint") -> int:
    """Crash-safe streaming checkpoint of an out-of-core MVCC store:
    fold into a fresh `ckpt-<ts>` subdir tablet-at-a-time, reopen it
    OUT-OF-CORE, install it as the newest fold point, then flip the
    CURRENT pointer. Returns the new base_ts.

    Ordering matters for crash safety: the fold installs (guard-checked
    against stragglers) BEFORE the CURRENT flip — a crash in between
    recovers from the old snapshot + an untruncated WAL; an install
    refusal (FoldRaced) deletes the orphan subdir and leaves everything
    as it was, for the scheduler's retry. Superseded ckpt dirs survive
    the flip while an older fold point in MVCC history still faults
    tablets from them (gc drops the fold; the next checkpoint sweeps
    the dir)."""
    import shutil

    from dgraph_tpu.store.outofcore import open_out_of_core

    plan = mvcc.fold_plan()
    new_ts = plan[3]
    sub = checkpoint.begin_versioned(root_dir, new_ts)
    if sub is None:
        return new_ts  # CURRENT already names this exact fold
    subdir = os.path.join(root_dir, sub)
    try:
        write_fold(mvcc, subdir, plan=plan, pace=pace, job=job)
        new_base, _ts = open_out_of_core(subdir, budget_bytes)
        new_base.preds.root_dir = root_dir  # next fold writes beside it
        # a clustered Alpha's corruption-heal hook (replica
        # TabletSnapshot) carries onto every new fold point
        old_lazy = lazy_preds(mvcc.base)
        if old_lazy is not None:
            new_base.preds.heal_cb = old_lazy.heal_cb
        mvcc.install_fold(new_ts, new_base, plan[4])
    except BaseException:
        shutil.rmtree(subdir, ignore_errors=True)
        raise
    keep = {sub}
    for _ts, st in mvcc.history_stores():
        lp = lazy_preds(st)
        if lp is not None and os.path.dirname(
                os.path.abspath(lp._dir)) == os.path.abspath(root_dir):
            keep.add(os.path.basename(lp._dir))
    checkpoint.commit_versioned(root_dir, sub, keep=keep)
    return new_ts


_GC_RECLAIMED = 0  # cumulative bytes reclaimed (gauge backing store)


def gc_superseded(root_dir: str, mvcc: MVCCStore) -> int:
    """Remove superseded `ckpt-*` subdirs no retained MVCC fold point
    faults tablets from anymore (PR-3 kept them alive while an older
    fold referenced them, but only the NEXT checkpoint swept — a store
    that stopped checkpointing leaked them forever). Runs from the
    watermark gc path (Alpha._maybe_gc): once `mvcc.gc` drops a fold,
    its on-disk dir is reclaimable here. Returns bytes reclaimed;
    cumulative total in the `checkpoint_gc_reclaimed_bytes` gauge."""
    import shutil
    global _GC_RECLAIMED

    cur = os.path.join(root_dir, "CURRENT")
    if not os.path.exists(cur):
        return 0
    with open(cur) as f:
        keep = {f.read().strip()}
    for _ts, st in mvcc.history_stores():
        lp = lazy_preds(st)
        if lp is not None and os.path.dirname(
                os.path.abspath(lp._dir)) == os.path.abspath(root_dir):
            keep.add(os.path.basename(lp._dir))
    reclaimed = 0
    for name in os.listdir(root_dir):
        if not name.startswith("ckpt-") or name in keep:
            continue
        d = os.path.join(root_dir, name)
        if not os.path.isdir(d):
            continue
        size = sum(os.path.getsize(os.path.join(d, f))
                   for f in os.listdir(d))
        shutil.rmtree(d, ignore_errors=True)
        reclaimed += size
    if reclaimed:
        _GC_RECLAIMED += reclaimed
        METRICS.set_gauge("checkpoint_gc_reclaimed_bytes", _GC_RECLAIMED)
    return reclaimed
