"""Write-ahead log for committed mutations.

Reference parity: the durability role Badger plays in the reference —
every committed txn is on disk before the commit call returns, so a crash
between checkpoints loses nothing (SURVEY §5 mechanisms 1-2: raft WAL +
Badger LSM). The TPU build keeps CSR snapshots as the queryable format
(checkpoint.py) and this log as the fsync'd tail between snapshots:
recovery = load newest checkpoint + replay records above its base_ts.

Record format (torn-write safe, append-only):
    MAGIC(4) | len(u32 LE) | crc32(u32 LE) | payload JSON(len)
Replay stops at the first corrupt/short record — exactly the crash tail a
partially-flushed append leaves — and reports how many bytes were dropped.

Values are JSON-native scalars; non-JSON types (datetimes arriving as
numpy scalars) round-trip via a {"__t": ..., "v": ...} tag.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib

from dgraph_tpu.utils import locks
from typing import Iterator

import numpy as np

from dgraph_tpu.store import vault
from dgraph_tpu.store.mvcc import Mutation

MAGIC = b"DGW1"   # legacy frames (pre ordinal binding) — read-only
MAGIC2 = b"DGW2"  # current frames: payload AAD-bound to the ordinal
_HEADER = struct.Struct("<II")  # len, crc32


def enc_scalar(v):
    if isinstance(v, (np.bool_, bool)):
        return bool(v)
    if isinstance(v, (np.integer, int)):
        return int(v)
    if isinstance(v, (np.floating, float)):
        return float(v)
    if isinstance(v, np.datetime64):
        return {"__t": "dt", "v": np.datetime_as_string(v)}
    from dgraph_tpu.store.geo import GeoVal
    if isinstance(v, GeoVal):
        return {"__t": "geo", "v": v.gj}
    if v is None or isinstance(v, str):
        return v
    return {"__t": "s", "v": str(v)}


def dec_scalar(v):
    if isinstance(v, dict) and "__t" in v:
        if v["__t"] == "dt":
            return np.datetime64(v["v"])
        if v["__t"] == "geo":
            from dgraph_tpu.store.geo import GeoVal
            return GeoVal(v["v"])
        return v["v"]
    return v


def _enc_facets(f):
    return {k: enc_scalar(v) for k, v in f.items()} if f else None


def _mut_doc(mut: Mutation) -> dict:
    doc = {
        "es": [[s, p, o, _enc_facets(f)]
               for s, p, o, *rest in mut.edge_sets
               for f in [rest[0] if rest else None]],
        "ed": [[s, p, o] for s, p, o in mut.edge_dels],
        "vs": [[s, p, enc_scalar(v), lang, _enc_facets(f)]
               for s, p, v, lang, *rest in mut.val_sets
               for f in [rest[0] if rest else None]],
        "vd": [[s, p, None, lang] for s, p, _v, lang in mut.val_dels],
    }
    if mut.touch_uids:
        doc["tu"] = [int(u) for u in mut.touch_uids]
    return doc


def _doc_mut(doc: dict) -> Mutation:
    return Mutation(
        edge_sets=[(s, p, o, f) for s, p, o, f in doc["es"]],
        edge_dels=[(s, p, o) for s, p, o in doc["ed"]],
        val_sets=[(s, p, dec_scalar(v), lang, f)
                  for s, p, v, lang, f in doc["vs"]],
        val_dels=[(s, p, None, lang) for s, p, _v, lang in doc["vd"]],
        touch_uids=list(doc.get("tu", [])),
    )


def mut_to_bytes(mut: Mutation) -> bytes:
    """Standalone Mutation codec (cluster broadcast payloads reuse the WAL
    JSON encoding)."""
    return json.dumps(_mut_doc(mut), separators=(",", ":")).encode()


def mut_from_bytes(b: bytes) -> Mutation:
    return _doc_mut(json.loads(b))


class Journal:
    """Generic fsync'd append-only JSON-record log (torn-tail safe). The
    WAL layers mutation semantics on top; Zero journals its state machine
    through it directly (reference: the group-0 raft WAL role)."""

    def __init__(self, path: str, sync: bool = True):
        self.path = path
        self.sync = sync
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # A torn tail from a previous crash must be cut BEFORE appending:
        # records written after corrupt bytes would be unreachable by
        # replay (it stops at the first bad record) — acked-but-invisible.
        self._seq = 0  # ordinal of the next record (encryption AAD)
        needs_reseal = False
        if os.path.exists(path):
            valid_end, self._seq, needs_reseal = _scan_state(path)
            if valid_end < os.path.getsize(path):
                with open(path, "r+b") as f:
                    f.truncate(valid_end)
                    f.flush()
                    os.fsync(f.fileno())
        self._wlock = locks.make_lock("wal.write")
        self._f = open(path, "ab")
        if needs_reseal:
            self._reseal_legacy()
        locks.guarded(self, "wal.write")

    def _reseal_legacy(self) -> None:
        """Legacy frames (pre-ordinal DGW1, or plaintext written before
        the key was enabled) would otherwise validate at every position
        forever — an indefinite replay/reorder window. The frame magic
        makes detection free (_scan_state flags them during the normal
        open scan); when any are present the whole file rewrites as
        ordinal-sealed DGW2 frames, closing the migration path eagerly."""
        with open(self.path, "rb") as f:
            data = f.read()
        self.rewrite(json.loads(_dec_payload(p, seq, legacy))
                     for seq, (_off, p, legacy) in enumerate(_scan(data)))

    @staticmethod
    def _frame(doc: dict, seq: int) -> bytes:
        # with encryption-at-rest active, each record payload is
        # AES-GCM-sealed individually with its ORDINAL as associated
        # data — a sealed record cannot be reordered, duplicated, or
        # spliced in at another position without failing the tag. The
        # CRC covers the ciphertext so torn-tail truncation still works
        # without the key (store/vault.py).
        payload = vault.encrypt(
            json.dumps(doc, separators=(",", ":")).encode(),
            aad=_rec_aad(seq))
        return MAGIC2 + _HEADER.pack(len(payload),
                                     zlib.crc32(payload)) + payload

    def append(self, doc: dict) -> None:
        # concurrent appenders (apply broadcasts race local commits) must
        # not interleave record bytes
        with self._wlock:
            rec = self._frame(doc, self._seq)
            # disk-fault injection seam (cluster/fault.py disk family):
            # the hook may corrupt/shorten the frame (detected by the
            # CRC on replay — exactly a torn tail) or raise ENOSPC
            # (the append fails BEFORE the in-memory apply, so the
            # commit refuses instead of acking an unlogged record)
            rec = vault.io_faulted(self.path, rec)
            self._f.write(rec)
            self._f.flush()
            if self.sync:
                os.fsync(self._f.fileno())
            self._seq += 1

    def rewrite(self, docs) -> None:
        """Atomically replace the log's contents (temp file + rename).
        Holds the write lock for the whole rewrite — a concurrent append
        must neither hit a closed file nor land on the replaced inode."""
        with self._wlock:
            tmp = self.path + ".tmp"
            seq = 0
            with open(tmp, "wb") as f:
                for doc in docs:
                    f.write(self._frame(doc, seq))
                    seq += 1
                f.flush()
                os.fsync(f.fileno())
            self._f.close()
            os.replace(tmp, self.path)
            self._f = open(self.path, "ab")
            self._seq = seq

    @staticmethod
    def replay(path: str):
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            data = f.read()
        for seq, (_off, payload, legacy) in enumerate(_scan(data)):
            yield json.loads(_dec_payload(payload, seq, legacy))

    def close(self) -> None:
        # under the write lock: a crash-stop (test harness _kill_node)
        # closes from another thread while appenders may be mid-frame —
        # closing out from under an in-flight write tears the tail the
        # CRC scan then has to cut
        with self._wlock:
            self._f.close()


class WAL(Journal):
    """Append-only fsync'd mutation log, one file per store directory."""

    def append(self, mut: Mutation, commit_ts: int) -> None:  # type: ignore[override]
        """Durably record a committed mutation. Called AFTER the oracle
        assigns commit_ts and BEFORE the in-memory apply — a crash between
        the two replays the record (apply is idempotent set-semantics)."""
        super().append({"ts": commit_ts, "m": _mut_doc(mut)})

    def append_schema(self, schema_text: str, ts: int) -> None:
        """Durably record an Alter's schema text (replay re-runs the
        rebuild; reference: schema mutations ride the same raft log)."""
        super().append({"ts": ts, "schema": schema_text})

    def append_drop(self, ts: int) -> None:
        """Durably record a DropAll (replay resets, not resurrects)."""
        super().append({"ts": ts, "drop": 1})

    def append_drop_attr(self, pred: str, ts: int) -> None:
        """Durably record a DropAttr (replay re-drops the predicate)."""
        super().append({"ts": ts, "drop_attr": pred})

    def append_pend(self, mut: Mutation, commit_ts: int) -> None:
        """Durably log a STAGED mutation (commit-quorum phase 1,
        reference: raft log append before commit). Not applied until a
        matching decision marker commits it; an unresolved pend is
        invisible to readers and was never acked to any client."""
        super().append({"ts": commit_ts, "pend": _mut_doc(mut)})

    def append_decision(self, commit_ts: int, commit: bool) -> None:
        """Durably record the coordinator's commit/abort decision for a
        staged ts (commit-quorum phase 2; the raft commit-index analog)."""
        super().append({"ts": commit_ts, "dec": 1 if commit else 0})

    def truncate(self, upto_ts: int) -> None:
        """Drop records with commit_ts ≤ upto_ts (checkpoint just absorbed
        them); the tail survives atomically. Unresolved pends survive
        regardless of ts — they were never applied, so no checkpoint
        absorbed them. Two STREAMING passes (decision index, then the
        rewrite): truncate runs inside checkpoint_to next to the rollup's
        materialization, so buffering every decoded record here would
        stack two whole-store memory spikes."""
        def doc_of(ts, kind, obj):
            if kind == "mut":
                return {"ts": ts, "m": _mut_doc(obj)}
            if kind == "pend":
                return {"ts": ts, "pend": _mut_doc(obj)}
            if kind == "dec":
                return {"ts": ts, "dec": obj}
            if kind == "drop":
                return {"ts": ts, "drop": 1}
            if kind == "drop_attr":
                return {"ts": ts, "drop_attr": obj}
            return {"ts": ts, "schema": obj}

        decided = {ts for ts, kind, _obj in replay(self.path)
                   if kind == "dec"}
        self.rewrite(
            doc_of(ts, kind, obj) for ts, kind, obj in replay(self.path)
            if ts > upto_ts or (kind == "pend" and ts not in decided))


def _scan(data: bytes) -> Iterator[tuple[int, bytes, bool]]:
    """Yield (record_end_offset, payload, is_legacy_frame) for every
    intact record. Legacy = a DGW1 frame (sealed before ordinal AAD
    binding); only those may use the no-AAD decrypt fallback."""
    off = 0
    hdr = len(MAGIC) + _HEADER.size
    while off + hdr <= len(data):
        magic = data[off:off + len(MAGIC)]
        if magic != MAGIC and magic != MAGIC2:
            return
        ln, crc = _HEADER.unpack(data[off + len(MAGIC):off + hdr])
        payload = data[off + hdr:off + hdr + ln]
        if len(payload) < ln or zlib.crc32(payload) != crc:
            return
        off += hdr + ln
        yield off, payload, magic == MAGIC


def _rec_aad(seq: int) -> bytes:
    return b"wal-rec:%d" % seq


def _dec_payload(payload: bytes, seq: int, legacy: bool = False) -> bytes:
    """Unseal a record at ordinal `seq`. ONLY legacy (DGW1) frames may
    fall back to the no-AAD seal — a DGW2 frame that fails its ordinal
    check is tampering, not migration (Journal.__init__ re-seals legacy
    files on open, so the fallback only runs for read-only replay of a
    not-yet-migrated file)."""
    if not legacy:
        return vault.decrypt(payload, aad=_rec_aad(seq))
    try:
        return vault.decrypt(payload, aad=_rec_aad(seq))
    except vault.VaultError:
        return vault.decrypt(payload)


def _scan_state(path: str) -> tuple[int, int, bool]:
    """(intact-prefix end offset, record count, needs_reseal): the last
    is True when encryption is active and any frame is legacy (DGW1) or
    still plaintext — detected from the frame headers alone, so a fully
    migrated log pays nothing extra on open."""
    with open(path, "rb") as f:
        data = f.read()
    end = n = 0
    mig = False
    enc = vault.active()
    for off, payload, legacy in _scan(data):
        end = off
        n += 1
        if enc and (legacy or not vault.is_encrypted(payload)):
            mig = True
    return end, n, mig


def _valid_end(path: str) -> int:
    """Byte offset where the intact record prefix ends."""
    return _scan_state(path)[0]


def replay(path: str) -> Iterator[tuple[int, str, object]]:
    """Yield (ts, kind, obj) in append order — kind "mut" with a Mutation,
    or "schema" with the merged schema text. Stops cleanly at a
    torn/corrupt tail (reference: raft WAL replay below HardState)."""
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        data = f.read()
    for seq, (_off, payload, legacy) in enumerate(_scan(data)):
        doc = json.loads(_dec_payload(payload, seq, legacy))
        if "schema" in doc:
            yield int(doc["ts"]), "schema", doc["schema"]
        elif "drop" in doc:
            yield int(doc["ts"]), "drop", None
        elif "drop_attr" in doc:
            yield int(doc["ts"]), "drop_attr", doc["drop_attr"]
        elif "pend" in doc:
            yield int(doc["ts"]), "pend", _doc_mut(doc["pend"])
        elif "dec" in doc:
            yield int(doc["ts"]), "dec", int(doc["dec"])
        else:
            yield int(doc["ts"]), "mut", _doc_mut(doc["m"])


def resolved_replay(path: str) -> Iterator[tuple[int, str, object]]:
    """Replay with commit-quorum staging RESOLVED: a pend followed by its
    dec:1 yields kind "mut" at the decision point (the commit-index
    analog — ordering against schema/drop records is the decision's,
    not the stage's); dec:0 yields kind "abort" (peers drop their
    matching pending entry); an unresolved trailing pend is skipped —
    it was never applied or acked anywhere."""
    pend: dict[int, object] = {}
    for ts, kind, obj in replay(path):
        if kind == "pend":
            pend[ts] = obj
        elif kind == "dec":
            mut = pend.pop(ts, None)
            if obj and mut is not None:
                yield ts, "mut", mut
            elif not obj:
                yield ts, "abort", None
        else:
            yield ts, kind, obj
