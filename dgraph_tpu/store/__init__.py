"""Posting store, schema, types, tokenizers.

Reference parity: posting/ (lists, MVCC, indexes), schema/, types/, tok/.
"""

from dgraph_tpu.store.schema import PredicateSchema, Schema, TypeDef, parse_schema
from dgraph_tpu.store.store import (
    TYPE_PRED,
    EdgeRel,
    PredicateData,
    Store,
    StoreBuilder,
    ValueColumn,
)
from dgraph_tpu.store.types import Kind, convert, parse_datetime

__all__ = [
    "PredicateSchema", "Schema", "TypeDef", "parse_schema",
    "TYPE_PRED", "EdgeRel", "PredicateData", "Store", "StoreBuilder",
    "ValueColumn", "Kind", "convert", "parse_datetime",
]
