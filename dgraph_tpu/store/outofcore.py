"""Out-of-core store: fault predicate tablets in on first touch, evict LRU.

Reference parity: Badger is an LSM — the reference's data set is NEVER
required to fit in RAM; posting lists page in from disk through the block
cache (SURVEY §2.1), and SURVEY §5 pins the build-side contract: "CSR
block store on host disk …; HBM is a cache, never the source of truth".
This module is the host-RAM leg of that contract: a Store whose
per-predicate tablets live in a versioned checkpoint (store/checkpoint.py)
and materialize on first access, with least-recently-used eviction
holding resident bytes under a budget.

Granularity is the PREDICATE TABLET — the same unit the reference
shards, moves, and snapshots (zero/tablet.go). The uid vocabulary and
schema stay resident (they are the rank dictionary every lookup needs;
their size is O(nodes), not O(edges)).

The returned Store is immutable, like every snapshot: mutations go
through MVCC layers on top, and eviction is invisible to readers —
a re-fault reloads bit-identical arrays from the checkpoint.

SCOPE: the budget governs the read path AND every write-shaped
maintenance pass — rollup, checkpoint save, backup, and export run
through store/stream.py, which faults one tablet at a time and releases
it before the next, so resident bytes never exceed
`budget + one tablet`. The remaining full-materialization paths are a
mutation-bearing READ (MVCC fold at a read_ts above the newest fold
point — kept shallow by the maintenance scheduler's rollup job) and the
rare straggler-absorb/rebuild legs. The tablet-size heartbeat reads
manifest size hints and never faults.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from dgraph_tpu.store import checkpoint, vault
from dgraph_tpu.utils import locks
from dgraph_tpu.utils.metrics import METRICS
from dgraph_tpu.store.schema import parse_schema
from dgraph_tpu.store.store import PredicateData, Store, build_indexes


def _pd_nbytes(pd: PredicateData) -> int:
    """Resident-byte estimate for a faulted tablet (arrays dominate;
    python-object columns are counted at pointer width plus a flat
    per-value estimate)."""
    total = 0
    for rel in (pd.fwd, pd.rev):
        if rel is not None:
            total += rel.indptr.nbytes + rel.indices.nbytes
    if pd.rev_pos is not None:
        total += pd.rev_pos.nbytes
    for col in pd.vals.values():
        total += col.subj.nbytes
        total += (col.vals.nbytes if col.vals.dtype != object
                  else len(col.vals) * 64)
    for fcol in pd.efacets.values():
        total += fcol.pos.nbytes + len(fcol.vals) * 64
    for tok_map in pd.index.values():
        for arr in tok_map.values():
            total += arr.nbytes
    return total


class LazyPreds:
    """Mapping of predicate → PredicateData backed by a checkpoint dir.

    First access faults the tablet in (checkpoint.load_predicate + its
    inverted indexes); every access touches LRU order; loads past the
    byte budget evict the least-recently-used tablets (never the one
    being returned). Thread-safe — the serving path reads from many
    request threads."""

    def __init__(self, dirname: str, manifest: dict, schema,
                 budget_bytes: int, root_dir: str | None = None):
        self._dir = dirname
        # the UNRESOLVED open path (versioned root with CURRENT, or the
        # plain dir itself): where a streaming checkpoint writes the
        # next fold of this store (store/stream.py)
        self.root_dir = root_dir if root_dir is not None else dirname
        self._meta = manifest["predicates"]
        self._schema = schema
        self.budget_bytes = budget_bytes
        self._resident: OrderedDict[str, PredicateData] = OrderedDict()
        self._sizes: dict[str, int] = {}
        self._lock = locks.make_rlock("outofcore.residency")
        self._inflight: dict[str, threading.Event] = {}
        self.resident_bytes = 0
        self.peak_resident_bytes = 0  # high-water mark of resident_bytes
        self.faults = 0       # tablets loaded from disk
        self.evictions = 0    # tablets dropped under budget pressure
        self.releases = 0     # tablets dropped by a streaming pass
        # corruption-heal hook (clustered Alpha): called with the
        # predicate when a fault fails its integrity check; returns a
        # replacement PredicateData pulled from a group replica
        # (TabletSnapshot + PeerTable failover) or None to refuse.
        # The healed copy serves in memory; the corrupt on-disk segment
        # is rewritten by the next checkpoint/fold.
        self.heal_cb = None
        locks.guarded(self, "outofcore.residency")
        # join the process memory governor: residency already runs its
        # own LRU under budget_bytes; the governor adds the CROSS-cache
        # budget on top (evict_one surrenders the LRU-coldest tablet —
        # a re-fault reloads bit-identical arrays, so value density is
        # just the disk reload cost spread over the tablet's bytes)
        import weakref

        from dgraph_tpu.utils import memgov
        ref = weakref.ref(self)

        def _gov_bytes():
            lp = ref()
            return lp.stats()["resident_bytes"] if lp is not None else 0

        def _gov_evict():
            lp = ref()
            return lp._evict_coldest() if lp is not None else 0

        memgov.GOVERNOR.register("outofcore.resident", "host",
                                 _gov_bytes, _gov_evict, owner=self)

    def _evict_coldest(self) -> int:
        """Governor callback: drop the least-recently-used resident
        tablet (bytes freed; 0 when nothing is resident)."""
        with self._lock:
            if not self._resident:
                return 0
            victim = next(iter(self._resident))
            del self._resident[victim]
            freed = self._sizes.pop(victim)
            self.resident_bytes -= freed
            self.evictions += 1
            return freed

    def stats(self) -> dict[str, int]:
        """Residency counters read under the lock — the ONLY way other
        threads (streaming maintenance accounting, debug surfaces) may
        observe them: fault/evict mutate the set pairwise and an
        unlocked peek is exactly the race the sanitizer flags."""
        with self._lock:
            return {"resident_bytes": self.resident_bytes,
                    "peak_resident_bytes": self.peak_resident_bytes,
                    "faults": self.faults,
                    "evictions": self.evictions,
                    "releases": self.releases}

    def size_hints(self) -> dict[str, int]:
        """Per-tablet byte sizes from the manifest, WITHOUT faulting —
        the tablet-size heartbeat (Zero rebalancing input) must not page
        the whole store in. Old checkpoints without recorded sizes
        report resident tablets only."""
        out = {}
        with self._lock:  # fault/evict threads mutate _sizes pairwise
            for pred, meta in self._meta.items():
                nb = meta.get("nbytes")
                if nb is not None:
                    out[pred] = int(nb)
                elif pred in self._sizes:
                    out[pred] = self._sizes[pred]
        return out

    # -- mapping surface the engine uses -------------------------------------
    def get(self, pred, default=None):
        pd = self._fault(pred)
        return pd if pd is not None else default

    def __getitem__(self, pred):
        pd = self._fault(pred)
        if pd is None:
            raise KeyError(pred)
        return pd

    def __contains__(self, pred) -> bool:
        return pred in self._meta

    def __iter__(self):
        return iter(self._meta)

    def __len__(self) -> int:
        return len(self._meta)

    def keys(self):
        return self._meta.keys()

    def items(self):
        """Faults EVERYTHING in — debug/full-materialize paths only.
        Serving code uses get()/[] (one tablet at a time) and
        maintenance passes use store/stream.py::iter_tablets, which
        also releases as it goes."""
        return [(p, self[p]) for p in self._meta]

    def values(self):
        return [self[p] for p in self._meta]

    # -- fault/evict ---------------------------------------------------------
    def is_resident(self, pred: str) -> bool:
        """Whether a tablet is currently faulted in (no LRU touch) —
        the streaming layer uses this to release only tablets IT pulled
        in, leaving the serving path's hot set alone."""
        with self._lock:
            return pred in self._resident

    def release(self, pred: str) -> bool:
        """Explicitly drop one resident tablet (streaming maintenance:
        process a tablet, release it before faulting the next, so a
        whole-store pass never holds more than one tablet above the
        serving working set). Readers holding the PredicateData keep a
        valid immutable reference; the next access re-faults."""
        with self._lock:
            pd = self._resident.pop(pred, None)
            if pd is None:
                return False
            self.resident_bytes -= self._sizes.pop(pred)
            self.releases += 1
            return True

    def _fault(self, pred: str):
        """Resident hit: one cheap lock hop. Cold fault: the disk load +
        index build runs OUTSIDE the lock (a seconds-long cold load must
        not freeze readers of already-resident tablets); concurrent
        requests for the same cold tablet wait on a per-predicate
        in-flight event instead of loading twice."""
        while True:
            with self._lock:
                pd = self._resident.get(pred)
                if pd is not None:
                    self._resident.move_to_end(pred)
                    return pd
                meta = self._meta.get(pred)
                if meta is None:
                    return None
                ev = self._inflight.get(pred)
                if ev is None:
                    ev = self._inflight[pred] = threading.Event()
                    break            # this thread loads
            ev.wait()                # another thread is loading it
            # loop: usually resident now; retry covers an eviction race

        try:
            try:
                pd = checkpoint.load_predicate(self._dir, pred, meta,
                                               self._schema)
                build_indexes({pred: pd})
            except vault.StorageCorruption:
                # a clustered Alpha heals the bad tablet from a group
                # replica (TabletSnapshot + PeerTable failover) before
                # refusing — the PR-1 FetchLog heal, for disk faults
                heal = self.heal_cb
                pd = heal(pred) if heal is not None else None
                if pd is None:
                    raise
                build_indexes({pred: pd})
                METRICS.inc("storage_heals_total")
            size = _pd_nbytes(pd)
            with self._lock:
                self.faults += 1
                prev = self._sizes.pop(pred, None)
                if prev is not None:
                    # a concurrent path re-installed this tablet while we
                    # were loading: replacing must not double-charge the
                    # budget — retire the old accounting first
                    # graftlint: allow(split-critical-section): the in-flight-event protocol — the cold load runs outside the lock BY DESIGN (a seconds-long load must not freeze readers), and this reacquisition re-validates _sizes/_resident before installing
                    self._resident.pop(pred, None)
                    self.resident_bytes -= prev
                self._resident[pred] = pd
                self._sizes[pred] = size
                self.resident_bytes += size
                self.peak_resident_bytes = max(self.peak_resident_bytes,
                                               self.resident_bytes)
                if self.resident_bytes > self.budget_bytes:
                    # evict LRU-first, skipping the tablet being returned
                    # (it must survive even when it alone exceeds the
                    # budget). NOTE: no early break on encountering it —
                    # the historical `break` left the budget exceeded
                    # with evictable tablets still resident.
                    for victim in list(self._resident):
                        if self.resident_bytes <= self.budget_bytes:
                            break
                        if victim == pred:
                            continue
                        del self._resident[victim]
                        self.resident_bytes -= self._sizes.pop(victim)
                        self.evictions += 1
            return pd
        finally:
            with self._lock:
                # graftlint: allow(split-critical-section): the in-flight event this same thread INSTALLED in the first acquisition is retired here; waiters re-loop and re-validate residency themselves
                self._inflight.pop(pred, None)
            ev.set()


def open_out_of_core(dirname: str,
                     budget_bytes: int) -> tuple[Store, int]:
    """Open a checkpoint as an out-of-core Store: tablets fault in on
    first touch, LRU-evicted under `budget_bytes` of resident tablet
    data. Returns (store, base_ts) like checkpoint.load."""
    manifest, resolved = checkpoint.read_manifest(dirname)
    uids = checkpoint.load_uids(resolved, manifest)
    schema = parse_schema(manifest["schema"])
    preds = LazyPreds(resolved, manifest, schema, budget_bytes,
                      root_dir=dirname)
    store = Store(uids=np.asarray(uids, np.int64), schema=schema,
                  preds=preds)
    return store, manifest["base_ts"]
