"""Geo scalar type + geohash cell index.

Reference parity: `types/geo.go` + `tok/tok.go` geo tokenizer — the
reference stores GeoJSON values (Point/Polygon) and indexes them with S2
cell coverings; queries (`near`, `within`, `contains`) look up covering
cells then post-filter exactly. Here the cell scheme is classic geohash
(base32 quad subdivision) instead of S2 — same two-phase shape: coarse
cell-token candidates from the inverted index, exact haversine /
point-in-polygon verification after.

Values are wrapped in `GeoVal` — hashable (canonical compact JSON), so
set-semantics dedup, WAL round-trip, and checkpoint string columns all
work unchanged.
"""

from __future__ import annotations

import functools
import json
import math
from dataclasses import dataclass

_B32 = "0123456789bcdefghjkmnpqrstuvwxyz"
M_PER_DEG_LAT = 111_320.0
# points index at every precision in this ladder; query covers pick the
# finest precision whose cells still dominate the query radius/box
PRECISIONS = (2, 3, 4, 5, 6, 7)
MAX_COVER_CELLS = 96   # bbox covers larger than this fall back to scan


class GeoError(ValueError):
    pass


@dataclass(frozen=True)
class GeoVal:
    """Canonical GeoJSON value (compact-JSON string, hashable)."""

    gj: str

    @functools.cached_property
    def obj(self) -> dict:
        # cached: verify phases call point()/rings() repeatedly per value
        # (cached_property writes to __dict__, bypassing frozen setattr)
        return json.loads(self.gj)

    @property
    def kind(self) -> str:
        return self.obj.get("type", "")

    def point(self) -> tuple[float, float] | None:
        o = self.obj
        if o.get("type") == "Point":
            lon, lat = o["coordinates"][:2]
            return float(lon), float(lat)
        return None

    def rings(self) -> list[list[tuple[float, float]]]:
        """Polygon rings (outer first, then holes); [] for non-polygons."""
        o = self.obj
        if o.get("type") == "Polygon":
            return [[(float(x), float(y)) for x, y in ring]
                    for ring in o["coordinates"]]
        return []

    def __str__(self) -> str:  # export/RDF literal form
        return self.gj


def parse_geo(value) -> GeoVal:
    """GeoJSON from a JSON string, dict, or GeoVal (idempotent)."""
    if isinstance(value, GeoVal):
        return value
    if isinstance(value, str):
        try:
            obj = json.loads(value)
        except json.JSONDecodeError as e:
            raise GeoError(f"invalid GeoJSON string: {e}") from e
    elif isinstance(value, dict):
        obj = value
    else:
        raise GeoError(f"cannot convert {type(value).__name__} to geo")
    def _finite(x) -> bool:
        return isinstance(x, (int, float)) and math.isfinite(x)

    t = obj.get("type")
    if t == "Point":
        c = obj.get("coordinates")
        if (not isinstance(c, (list, tuple)) or len(c) < 2
                or not all(_finite(x) for x in c[:2])):
            raise GeoError("Point needs finite [lon, lat] coordinates")
    elif t == "Polygon":
        rings = obj.get("coordinates")
        if not isinstance(rings, (list, tuple)) or not rings or any(
                len(r) < 4 for r in rings):
            raise GeoError("Polygon needs rings of >= 4 positions")
        # json.loads admits Infinity/NaN literals (and 1e400 → inf);
        # a non-finite longitude would spin unwrap_lons forever, so
        # coordinates are validated finite at the boundary
        for r in rings:
            for p in r:
                if (not isinstance(p, (list, tuple)) or len(p) < 2
                        or not all(_finite(x) for x in p[:2])):
                    raise GeoError(
                        "Polygon positions need finite [lon, lat]")
    else:
        raise GeoError(f"unsupported GeoJSON type {t!r}")
    return GeoVal(json.dumps(obj, separators=(",", ":"), sort_keys=True))


# -- geohash cells ----------------------------------------------------------

def geohash(lon: float, lat: float, precision: int) -> str:
    lat_lo, lat_hi = -90.0, 90.0
    lon_lo, lon_hi = -180.0, 180.0
    bits = bit_count = 0
    out = []
    even = True
    while len(out) < precision:
        if even:
            mid = (lon_lo + lon_hi) / 2
            if lon >= mid:
                bits = bits * 2 + 1
                lon_lo = mid
            else:
                bits = bits * 2
                lon_hi = mid
        else:
            mid = (lat_lo + lat_hi) / 2
            if lat >= mid:
                bits = bits * 2 + 1
                lat_lo = mid
            else:
                bits = bits * 2
                lat_hi = mid
        even = not even
        bit_count += 1
        if bit_count == 5:
            out.append(_B32[bits])
            bits = bit_count = 0
    return "".join(out)


def cell_dims(precision: int) -> tuple[float, float]:
    """(dlon_degrees, dlat_degrees) of one cell at `precision`."""
    lon_bits = (5 * precision + 1) // 2
    lat_bits = (5 * precision) // 2
    return 360.0 / (1 << lon_bits), 180.0 / (1 << lat_bits)


def _cell_meters(precision: int, lat: float) -> float:
    """Smallest cell dimension in meters at `precision` near `lat`."""
    dlon, dlat = cell_dims(precision)
    w = dlon * M_PER_DEG_LAT * max(math.cos(math.radians(lat)), 0.05)
    h = dlat * M_PER_DEG_LAT
    return min(w, h)


def haversine_m(lon1: float, lat1: float, lon2: float, lat2: float) -> float:
    r = 6_371_000.0
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = p2 - p1
    dl = math.radians(lon2 - lon1)
    a = math.sin(dp / 2) ** 2 + \
        math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2
    return 2 * r * math.asin(min(1.0, math.sqrt(a)))


def point_tokens(lon: float, lat: float, prefix: str = "pt") -> list[str]:
    """One token per ladder precision for a coordinate. Point and
    polygon tokens live in SEPARATE namespaces ("pt:"/"py:") so polygon
    lookups can scan the whole precision ladder without dragging every
    nearby point in as a candidate."""
    return [f"{prefix}:{p}:{geohash(lon, lat, p)}" for p in PRECISIONS]


def polygon_cover_tokens(min_lon, min_lat, max_lon, max_lat) -> list[str]:
    """bbox-cover tokens per precision, stopping at the first precision
    whose cover exceeds the cap (the coarsest is UNCAPPED so even a
    continent-scale polygon is always reachable through the index)."""
    out = []
    for p in PRECISIONS:
        cells = _bbox_cells(min_lon, min_lat, max_lon, max_lat, p,
                            cap=None if p == PRECISIONS[0] else
                            MAX_COVER_CELLS)
        if cells is None:
            break  # finer precisions only cost more cells
        out.extend(f"py:{p}:{c}" for c in cells)
    return out


def tokens_for_geo(g: GeoVal) -> list[str]:
    """Index tokens: points at every ladder precision; polygons by bbox
    cover per precision (see polygon_cover_tokens). A polygon whose ring
    spans >180° of longitude crosses the antimeridian: its bbox splits
    at ±180 into two covers so index lookups from either side find it."""
    pt = g.point()
    if pt is not None:
        return point_tokens(*pt)
    rings = g.rings()
    if rings:
        xs = [x for x, _ in rings[0]]
        ys = [y for _, y in rings[0]]
        out = []
        for lo, hi in lon_spans(xs):
            out.extend(polygon_cover_tokens(lo, min(ys), hi, max(ys)))
        return sorted(set(out))
    return []


def unwrap_lons(xs: list[float]) -> list[float]:
    """Consecutive ring longitudes made CONTINUOUS: every edge follows
    its shorter longitudinal arc (≤180°), so an antimeridian-crossing
    ring extends past ±180 instead of jumping across the axis. Identity
    for rings whose edges all stay under 180° of longitude."""
    if not xs:
        return []
    out = [xs[0]]
    for x in xs[1:]:
        px = out[-1]
        while x - px > 180.0:
            x -= 360.0
        while x - px < -180.0:
            x += 360.0
        out.append(x)
    return out


def ring_crosses(ring) -> bool:
    """Whether any edge's shorter arc wraps ±180 — the PER-EDGE crossing
    rule shared by indexing (lon_spans) and the exact verifiers
    (point_in_polygon, dist_to_polygon_m), so they can never disagree."""
    return any(abs(x2 - x1) > 180.0
               for (x1, _y1), (x2, _y2) in zip(ring, ring[1:]))


def lon_spans(xs: list[float]) -> list[tuple[float, float]]:
    """Longitude interval(s) of a ring, deciding antimeridian crossing
    PER EDGE (shorter arc): consecutive lons are unwrapped so each step
    takes the arc under 180°. A planar ring that merely spans a wide
    bbox (no single wrapping edge, e.g. lons -100, 0, 100) keeps its
    full (min, max) span; a crossing ring splits into covers at ±180 so
    lookups from either side find it."""
    ux = unwrap_lons(xs)
    lo, hi = min(ux), max(ux)
    if hi - lo >= 360.0:       # wraps the whole axis
        return [(-180.0, 180.0)]
    if lo >= -180.0 and hi <= 180.0:
        return [(lo, hi)]
    if hi > 180.0:
        return [(lo, 180.0), (-180.0, hi - 360.0)]
    return [(lo + 360.0, 180.0), (-180.0, hi)]


def _bbox_cells(min_lon, min_lat, max_lon, max_lat, precision,
                cap=MAX_COVER_CELLS):
    """Cell hashes covering a bbox at `precision`, or None past the cap."""
    dlon, dlat = cell_dims(precision)
    nx = int((max_lon - min_lon) / dlon) + 2
    ny = int((max_lat - min_lat) / dlat) + 2
    if cap is not None and nx * ny > cap:
        return None
    cells = set()
    for i in range(nx):
        for j in range(ny):
            lon = min(min_lon + i * dlon, max_lon)
            lat = min(min_lat + j * dlat, max_lat)
            cells.add(geohash(lon, lat, precision))
    return cells


def cover_near(lon: float, lat: float, meters: float):
    """Tokens covering a radius: finest precision whose cell dimension
    still exceeds the radius, 3x3 block around the center (the circle
    cannot escape the block then). None when even the COARSEST cell is
    smaller than the radius — the caller must fall back to a scan, a
    3x3 block could not contain the circle."""
    if _cell_meters(PRECISIONS[0], lat) < meters:
        return None
    prec = PRECISIONS[0]
    for p in PRECISIONS:
        if _cell_meters(p, lat) >= meters:
            prec = p
        else:
            break
    toks = set()
    # points: the 3x3 block at the radius-matched precision. Polygons:
    # the 3x3 block at EVERY precision up to it — a large polygon's
    # capped cover may only exist at coarser precisions than the query's
    # (its tokens are rare, so the coarse lookups stay cheap).
    for p in PRECISIONS:
        if p > prec:
            break
        dlon, dlat = cell_dims(p)
        for di in (-1, 0, 1):
            for dj in (-1, 0, 1):
                # wrap longitude across the antimeridian (a clamp would
                # fold the western neighbor into the easternmost cell)
                lo = ((lon + di * dlon + 180.0) % 360.0) - 180.0
                la = min(max(lat + dj * dlat, -90.0), 90.0)
                toks.add(f"py:{p}:{geohash(lo, la, p)}")
                if p == prec:
                    toks.add(f"pt:{p}:{geohash(lo, la, p)}")
    return toks


def dist_to_polygon_m(lon: float, lat: float,
                      rings: list[list[tuple[float, float]]]) -> float:
    """Distance from a point to a polygon: 0 inside, else the minimum
    distance to any outer-ring edge (local equirectangular projection —
    accurate at query-radius scales)."""
    if point_in_polygon(lon, lat, rings):
        return 0.0
    kx = M_PER_DEG_LAT * max(math.cos(math.radians(lat)), 0.05)
    ky = M_PER_DEG_LAT
    best = math.inf
    # ALL rings: a point inside a hole is closest to the hole's edge.
    # Rings measure in unwrapped longitudes with the query point tried
    # at ALL ±360 shifts — the nearest representation wins whether the
    # RING crosses or the QUERY POINT sits across ±180 from a
    # non-crossing ring (near() wraps its candidate cover, so both
    # shapes reach this verifier).
    for ring in rings:
        xs = unwrap_lons([x for x, _ in ring])
        ys = [y for _, y in ring]
        for k in (-360.0, 0.0, 360.0):
            L = lon + k
            for i in range(len(ring) - 1):
                x1, y1, x2, y2 = xs[i], ys[i], xs[i + 1], ys[i + 1]
                ax, ay = (x1 - L) * kx, (y1 - lat) * ky
                bx, by = (x2 - L) * kx, (y2 - lat) * ky
                dx, dy = bx - ax, by - ay
                L2 = dx * dx + dy * dy
                t = 0.0 if L2 == 0 else max(
                    0.0, min(1.0, -(ax * dx + ay * dy) / L2))
                px, py = ax + t * dx, ay + t * dy
                best = min(best, math.hypot(px, py))
    return best


def cover_bbox(min_lon, min_lat, max_lon, max_lat):
    """Tokens covering a bbox: points at the finest under-cap precision,
    polygons across the ladder (mirrors their capped index cover, which
    always shares at least the uncapped coarsest precision); None →
    caller should scan."""
    if max_lon - min_lon > 180.0:
        # a >180° span means the ring crosses the antimeridian and the
        # naive min/max bbox covers the WRONG side — cells would silently
        # miss every matching value. Force the exact-scan fallback.
        return None
    chosen = None
    for p in PRECISIONS:
        cells = _bbox_cells(min_lon, min_lat, max_lon, max_lat, p)
        if cells is None:
            break
        chosen = (p, cells)
    if chosen is None:
        return None
    p, cells = chosen
    toks = {f"pt:{p}:{c}" for c in cells}
    toks.update(polygon_cover_tokens(min_lon, min_lat, max_lon, max_lat))
    return toks


def point_in_polygon(lon: float, lat: float,
                     rings: list[list[tuple[float, float]]]) -> bool:
    """Ray casting; ring 0 is the outer boundary, the rest are holes.
    Edges follow their SHORTER longitudinal arc (the same per-edge
    antimeridian rule lon_spans indexes by): rings are unwrapped to
    continuous longitudes and the point is tested at lon and lon±360,
    so crossing polygons verify exactly where their index tokens say."""
    def in_ring(ring):
        xs = unwrap_lons([x for x, _ in ring])
        lo, hi = min(xs), max(xs)
        ys = [y for _, y in ring]
        for k in (-360.0, 0.0, 360.0):
            L = lon + k
            if not lo <= L <= hi:
                continue
            inside = False
            j = len(ring) - 1
            for i in range(len(ring)):
                xi, yi = xs[i], ys[i]
                xj, yj = xs[j], ys[j]
                if ((yi > lat) != (yj > lat)) and \
                        L < (xj - xi) * (lat - yi) / (yj - yi) + xi:
                    inside = not inside
                j = i
            if inside:
                return True
        return False

    if not rings or not in_ring(rings[0]):
        return False
    return not any(in_ring(h) for h in rings[1:])
