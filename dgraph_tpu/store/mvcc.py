"""MVCC layering over immutable Store snapshots.

Reference parity: `posting/mvcc.go` + `posting/list.go` — each posting list
is an immutable Badger layer plus an in-memory mutable delta layer keyed by
commit timestamp; readers at `read_ts` see base ∪ {deltas with commit_ts ≤
read_ts}; `Rollup` folds deltas into a new immutable layer.

TPU-first shape: the immutable layer here is the whole CSR `Store` snapshot
(what lives in HBM); deltas are small host-side edge/value logs per commit.
A read view materialises base+visible-deltas into a fresh Store (cached per
visible-set), and `rollup()` promotes the current view to the new base —
the moral analog of posting-list rollups plus Badger compaction, with HBM
as a cache over host state (SURVEY §5 checkpoint model: device memory is
never the source of truth).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from dgraph_tpu.store.schema import Schema
from dgraph_tpu.store.store import TYPE_PRED, Store, StoreBuilder
from dgraph_tpu.store.types import Kind


@dataclass
class Mutation:
    """One txn's buffered edits (reference: pb.Mutations / DirectedEdge).

    `*_DEL` entries use object/value None to mean "delete all postings of
    (subject, predicate)" (reference: S P * deletion).
    """

    edge_sets: list = field(default_factory=list)   # (s, pred, o)
    edge_dels: list = field(default_factory=list)   # (s, pred, o|None)
    val_sets: list = field(default_factory=list)    # (s, pred, value, lang)
    val_dels: list = field(default_factory=list)    # (s, pred, None, lang)

    def conflict_keys(self):
        """Keys Zero arbitrates on: (pred, subject) per touched list
        (reference: posting key fingerprints sent in pb.TxnContext)."""
        keys = set()
        for s, p, _ in self.edge_sets + self.edge_dels:
            keys.add((p, s))
        for s, p, *_ in self.val_sets + self.val_dels:
            keys.add((p, s))
        return keys

    def is_empty(self) -> bool:
        return not (self.edge_sets or self.edge_dels
                    or self.val_sets or self.val_dels)


@dataclass
class _Layer:
    commit_ts: int
    mut: Mutation


class MVCCStore:
    """Versioned posting store: base snapshot + committed delta layers."""

    def __init__(self, base: Store | None = None, base_ts: int = 0):
        self._lock = threading.Lock()
        self.base = base if base is not None else StoreBuilder().finalize()
        self.base_ts = base_ts
        self.layers: list[_Layer] = []       # sorted by commit_ts
        self._views: dict[tuple, Store] = {}

    @property
    def schema(self) -> Schema:
        return self.base.schema

    # -- write path ---------------------------------------------------------
    def apply(self, mut: Mutation, commit_ts: int) -> None:
        """Install a committed delta layer (reference: oracle watermark
        moving a txn's mutable layer to committed at commit_ts)."""
        with self._lock:
            if self.layers and commit_ts <= self.layers[-1].commit_ts:
                raise ValueError("commit_ts must be monotonic")
            if commit_ts <= self.base_ts:
                raise ValueError("commit_ts below base snapshot")
            self.layers.append(_Layer(commit_ts, mut))

    # -- read path ----------------------------------------------------------
    def read_view(self, read_ts: int) -> Store:
        """Store snapshot visible at `read_ts` (base ∪ deltas ≤ read_ts)."""
        with self._lock:
            visible = tuple(l.commit_ts for l in self.layers
                            if l.commit_ts <= read_ts)
            if not visible:
                return self.base
            view = self._views.get(visible)
            if view is None:
                view = self._materialize(
                    [l for l in self.layers if l.commit_ts <= read_ts])
                self._views[visible] = view
            return view

    def rollup(self, upto_ts: int | None = None) -> Store:
        """Fold layers ≤ upto_ts into a new base (reference: List.Rollup +
        snapshot compaction). Returns the new base snapshot."""
        with self._lock:
            if upto_ts is None:
                upto_ts = self.layers[-1].commit_ts if self.layers else self.base_ts
            folded = [l for l in self.layers if l.commit_ts <= upto_ts]
            if folded:
                self.base = self._materialize(folded)
                self.base_ts = folded[-1].commit_ts
                self.layers = [l for l in self.layers
                               if l.commit_ts > upto_ts]
                self._views.clear()
            return self.base

    # -- merge --------------------------------------------------------------
    def _materialize(self, layers: list[_Layer]) -> Store:
        """Rebuild a Store from base + deltas (host-side; the new CSR blocks
        re-enter HBM via Store.device_rel on first use)."""
        base = self.base
        b = StoreBuilder(schema=base.schema.clone())

        # live edges/values from base, as dicts for delete application
        import numpy as np
        edges: dict[str, set] = {}
        for pred, pd in base.preds.items():
            if pd.fwd is not None and pd.fwd.nnz:
                deg = pd.fwd.indptr[1:] - pd.fwd.indptr[:-1]
                src_r = np.repeat(np.arange(base.n_nodes), deg)
                s_uid = base.uids[src_r]
                o_uid = base.uids[pd.fwd.indices]
                edges[pred] = set(zip(s_uid.tolist(), o_uid.tolist()))
        vals: dict[tuple, dict] = {}
        for pred, pd in base.preds.items():
            for lang, col in pd.vals.items():
                d = vals.setdefault((pred, lang), {})
                for s, v in zip(col.subj, col.vals):
                    d.setdefault(int(base.uids[s]), []).append(v)

        for layer in layers:
            m = layer.mut
            for s, p, o in m.edge_dels:
                if o is None:
                    edges[p] = {e for e in edges.get(p, set())
                                if e[0] != s}
                else:
                    edges.get(p, set()).discard((s, o))
            for s, p, o in m.edge_sets:
                edges.setdefault(p, set()).add((s, o))
            for s, p, _v, lang in m.val_dels:
                if lang == "*":  # delete across every language column
                    for (vp, _vl), d in vals.items():
                        if vp == p:
                            d.pop(s, None)
                else:
                    vals.get((p, lang), {}).pop(s, None)
            for s, p, v, lang in m.val_sets:
                ps = base.schema.peek(p)
                if ps is not None and ps.is_list:
                    vals.setdefault((p, lang), {}).setdefault(s, []).append(v)
                else:
                    vals.setdefault((p, lang), {})[s] = [v]

        for pred, es in edges.items():
            for s, o in sorted(es):
                b.add_edge(s, pred, o)
        for (pred, lang), d in vals.items():
            for s, vlist in sorted(d.items()):
                for v in vlist:
                    if pred == TYPE_PRED:
                        b.add_type(s, str(v))
                    else:
                        b.add_value(s, pred, _to_py(v), lang)
        return b.finalize()


def _to_py(v):
    """numpy scalar → python for StoreBuilder.add_value re-ingestion."""
    import numpy as np
    if isinstance(v, np.generic) and not isinstance(v, np.datetime64):
        return v.item()
    return v
