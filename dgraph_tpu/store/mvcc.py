"""MVCC layering over immutable Store snapshots.

Reference parity: `posting/mvcc.go` + `posting/list.go` — each posting list
is an immutable Badger layer plus in-memory delta layers keyed by commit
timestamp; readers at `read_ts` see base ∪ {deltas with commit_ts ≤
read_ts}; `Rollup` folds deltas into a new immutable layer, and Badger
retains old versions for open readers.

TPU-first shape: the immutable layer here is a whole CSR `Store` snapshot
(what lives in HBM); deltas are small host-side edge/value logs per commit.
Version retention works like Badger's: `rollup()` adds a *fold point* (a
materialised snapshot at some commit_ts) without discarding the layers
older readers still need; `gc(min_active_ts)` is the watermark-driven
cleanup (reference: oracle MaxAssigned / doneUntil watermarks) that drops
history no open transaction can reach. HBM is a cache over host state,
never the source of truth (SURVEY §5 checkpoint model).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from dgraph_tpu.store.schema import Schema
from dgraph_tpu.utils import locks
from dgraph_tpu.utils.metrics import METRICS
from dgraph_tpu.store.store import TYPE_PRED, Store, StoreBuilder
from dgraph_tpu.store.types import Kind

_VIEW_CACHE = 8  # non-fold-point views retained (newest win)


class FoldRaced(ValueError):
    """An externally-materialised fold (store/stream.py streaming
    rollup/checkpoint) cannot install: the layer set at or below its
    fold ts changed while it streamed (a straggler absorb or a
    predicate drop raced it). The written fold is missing that record —
    the caller discards it and retries (the maintenance scheduler's
    retry-with-backoff does this automatically)."""


@dataclass
class Mutation:
    """One txn's buffered edits (reference: pb.Mutations / DirectedEdge).

    `*_DEL` entries use object/value None to mean "delete all postings of
    (subject, predicate)" (reference: S P * deletion).
    """

    edge_sets: list = field(default_factory=list)   # (s, pred, o[, facets])
    edge_dels: list = field(default_factory=list)   # (s, pred, o|None)
    val_sets: list = field(default_factory=list)    # (s, pred, v, lang[, facets])
    val_dels: list = field(default_factory=list)    # (s, pred, None, lang)
    # uids to register in the vocabulary even without local postings —
    # cluster mode ships these to every group so the shared dense rank
    # space stays identical on all nodes (SURVEY §7 hard part 2)
    touch_uids: list = field(default_factory=list)

    def all_uids(self) -> set:
        """Every uid this mutation mentions (vocab sync set)."""
        out = set(self.touch_uids)
        for s, _p, o, *_ in self.edge_sets:
            out.add(s)
            out.add(o)
        for s, _p, *_ in self.edge_dels + self.val_sets + self.val_dels:
            out.add(s)
        return out

    def exclude(self, preds) -> "Mutation":
        """Complement of restrict: everything EXCEPT the given tablets
        (straggler absorption filters predicates dropped between the
        commit and a fold point)."""
        return Mutation(
            edge_sets=[e for e in self.edge_sets if e[1] not in preds],
            edge_dels=[e for e in self.edge_dels if e[1] not in preds],
            val_sets=[v for v in self.val_sets if v[1] not in preds],
            val_dels=[v for v in self.val_dels if v[1] not in preds],
            touch_uids=sorted(self.all_uids()),
        )

    def restrict(self, preds) -> "Mutation":
        """Subset for the tablets in `preds`, carrying the FULL vocab set
        (reference: per-group pb.Mutations split in MutateOverNetwork)."""
        return Mutation(
            edge_sets=[e for e in self.edge_sets if e[1] in preds],
            edge_dels=[e for e in self.edge_dels if e[1] in preds],
            val_sets=[v for v in self.val_sets if v[1] in preds],
            val_dels=[v for v in self.val_dels if v[1] in preds],
            touch_uids=sorted(self.all_uids()),
        )

    def conflict_keys(self, schema=None):
        """Keys Zero arbitrates on, as deterministic serialized strings
        (reference: posting key fingerprints sent in pb.TxnContext —
        posting.addConflictKeys): "<pred>|<subj>" per touched list, plus
        "<pred>|tok|<tokenizer>:<token>" per index token of values written
        to @upsert predicates, so two txns upserting the same value collide
        even under different subjects. Strings (not Python hash()) so keys
        are stable across processes — the multi-node oracle ships them over
        the wire."""
        keys = set()
        for s, p, *_ in self.edge_sets + self.edge_dels:
            keys.add(f"{p}|{s}")
        for s, p, *_ in self.val_sets + self.val_dels:
            keys.add(f"{p}|{s}")
        if schema is not None:
            from dgraph_tpu.store.tok import tokens_for
            for s, p, v, *_rest in self.val_sets:
                ps = schema.peek(p)
                if not ps or not ps.upsert or v is None:
                    continue
                for t in ps.index_tokenizers:
                    for token in tokens_for(t, v):
                        keys.add(f"{p}|tok|{t}:{token}")
        return keys

    def is_empty(self) -> bool:
        return not (self.edge_sets or self.edge_dels
                    or self.val_sets or self.val_dels or self.touch_uids)


@dataclass
class _Layer:
    commit_ts: int
    mut: Mutation


def fold_vocab(base: Store, pending) -> "np.ndarray":
    """The full-fold uid vocabulary: base vocab ∪ every uid the pending
    layers mention — O(nodes), resident by the out-of-core contract
    (the uid dictionary never pages out). Shared by the streaming fold
    writer (store/stream.py) and the lazily-folding read view, so every
    per-tablet materialization pins the SAME dense rank space."""
    import numpy as np
    extra: set[int] = set()
    for layer in pending:
        extra.update(layer.mut.all_uids())
    if not extra:
        return base.uids
    return np.union1d(base.uids,
                      np.array(sorted(extra), np.int64)).astype(np.int64)


def fold_preds(base: Store, pending) -> list[str]:
    """Stable order over every tablet a fold must visit: base tablets
    plus predicates the deltas introduce."""
    names = set(base.preds.keys())
    for layer in pending:
        m = layer.mut
        for e in m.edge_sets + m.edge_dels:
            names.add(e[1])
        for v in m.val_sets + m.val_dels:
            names.add(v[1])
    return sorted(names)


class _LazyFoldPreds:
    """Predicate mapping of a LAZILY-FOLDING read view over an
    out-of-core base: each tablet materializes (base tablet + pending
    delta layers, vocabulary pinned to the full-fold union) on first
    touch, through the same `_materialize(only=)` path the streaming
    fold writer uses — per-tablet content is bit-identical to the slice
    of a full materialize. A mutation-bearing read above the newest
    fold point therefore faults in only the tablets the query touches
    instead of the whole store (the second PR-3 in-core cliff). Base
    tablets this view itself faulted are released after folding, so the
    serving budget holds; folded tablets are retained on the view (it
    lives in the MVCC view cache, bounded by _VIEW_CACHE)."""

    def __init__(self, base: Store, pending, schema, vocab):
        self._base = base
        self._pending = pending
        self._schema = schema
        self._vocab = vocab
        self._names = set(fold_preds(base, pending))
        self._done: dict[str, object] = {}
        self._lock = locks.make_lock("mvcc.lazyview")
        locks.guarded(self, "mvcc.lazyview")

    def size_hints(self) -> dict:
        """Delegate to the base checkpoint's manifest sizes (the
        tablet-size heartbeat must not fold the view in); pending-layer
        growth is below the hint's own accuracy."""
        hints = getattr(self._base.preds, "size_hints", None)
        return hints() if hints is not None else {}

    # -- mapping surface the engine uses (mirrors outofcore.LazyPreds) --
    def get(self, pred, default=None):
        if pred not in self._names:
            return default
        with self._lock:
            if pred in self._done:
                pd = self._done[pred]
                return pd if pd is not None else default
        pd = self._fold(pred)
        with self._lock:
            # graftlint: allow(split-critical-section): double-checked fold — setdefault re-validates under the reacquisition; when two threads fold the same tablet concurrently the first install wins and both return it
            self._done.setdefault(pred, pd)
            pd = self._done[pred]
        return pd if pd is not None else default

    def _fold(self, pred):
        from dgraph_tpu.store.outofcore import LazyPreds
        lazy = (self._base.preds
                if isinstance(self._base.preds, LazyPreds) else None)
        was_resident = lazy.is_resident(pred) if lazy is not None else True
        folded = _materialize(self._base, self._pending,
                              schema=self._schema, only={pred},
                              vocab=self._vocab)
        if lazy is not None and not was_resident:
            lazy.release(pred)
        METRICS.inc("read_view_lazy_tablets_total")
        return folded.preds.get(pred)

    def __getitem__(self, pred):
        pd = self.get(pred)
        if pd is None:
            raise KeyError(pred)
        return pd

    def __contains__(self, pred) -> bool:
        return pred in self._names

    def __iter__(self):
        return iter(sorted(self._names))

    def __len__(self) -> int:
        return len(self._names)

    def keys(self):
        return sorted(self._names)

    def items(self):
        """Folds EVERY tablet — debug/full-materialize paths only; the
        serving path uses get()/[] one tablet at a time."""
        return [(p, self[p]) for p in sorted(self._names)
                if self.get(p) is not None]

    def values(self):
        return [pd for _p, pd in self.items()]


class MVCCStore:
    """Versioned posting store: fold-point snapshots + delta layers."""

    def __init__(self, base: Store | None = None, base_ts: int = 0):
        self._lock = locks.make_lock("mvcc.store")
        base = base if base is not None else StoreBuilder().finalize()
        # history of fold points, ascending by ts; first entry is the
        # oldest snapshot still reachable by an open reader
        self._history: list[tuple[int, Store]] = [(base_ts, base)]
        self.layers: list[_Layer] = []       # all retained, ascending ts
        self._views: dict[tuple, Store] = {}
        # pred -> [drop_ts, ...]: DropAttr history; stragglers landing
        # below a drop must not resurrect the predicate in post-drop
        # folds (see absorb_straggler)
        self.dropped: dict[str, list[int]] = {}
        # highest uid this store has ever held — the heartbeat watermark
        # that seeds a promoted standby zero's uid lease floor
        self.max_uid_seen = int(base.uids[-1]) if base.n_nodes else 0
        locks.guarded(self, "mvcc.store")

    # -- current base (newest fold point) ------------------------------------
    @property
    def base(self) -> Store:
        with self._lock:
            return self._history[-1][1]

    @property
    def base_ts(self) -> int:
        with self._lock:
            return self._history[-1][0]

    @property
    def schema(self) -> Schema:
        return self.base.schema

    # -- write path ---------------------------------------------------------
    def apply(self, mut: Mutation, commit_ts: int) -> None:
        """Install a committed delta layer (reference: oracle watermark
        moving a txn's mutable layer to committed at commit_ts). Layers
        may arrive OUT OF ORDER in cluster mode (broadcasts from multiple
        coordinators race); they are kept sorted by commit_ts."""
        with self._lock:
            if commit_ts <= self._history[-1][0]:
                raise ValueError("commit_ts below newest fold point")
            if any(l.commit_ts == commit_ts for l in self.layers):
                raise ValueError(f"duplicate commit_ts {commit_ts}")
            import bisect
            bisect.insort(self.layers, _Layer(commit_ts, mut),
                          key=lambda l: l.commit_ts)
            self.max_uid_seen = max(self.max_uid_seen,
                                    max(mut.all_uids(), default=0))

    def uid_high(self) -> int:
        """`max_uid_seen` read under the lock — the accessor debug
        surfaces (`/state`) use while apply threads advance it."""
        with self._lock:
            return self.max_uid_seen

    def has_applied(self, commit_ts: int) -> bool:
        """Whether a commit_ts is present as a retained delta layer.
        (Folded history can't be interrogated per-ts; callers treat
        ts ≤ the fold floor separately — see absorb_straggler.)"""
        with self._lock:
            return any(l.commit_ts == commit_ts for l in self.layers)

    def absorb_straggler(self, mut: Mutation, commit_ts: int) -> None:
        """Install a commit whose ts landed at or below an existing fold
        point (a broadcast raced a local rollup, or catch-up recovered a
        record older than the newest fold). Every fold snapshot at or
        above commit_ts is re-materialised WITH the record, and the record
        also joins the layer list so readers choosing an older fold see it
        too — reads at any ts ≥ commit_ts now include it, reads below
        don't (reference: raft replay reorders applies below the applied
        index; here the fold is patched instead)."""
        with self._lock:
            if any(l.commit_ts == commit_ts for l in self.layers):
                return
            patched = []
            for fold_ts, store in self._history:
                if fold_ts >= commit_ts:
                    # a predicate dropped between this commit and the
                    # fold must stay dropped — resurrecting it here
                    # would diverge from nodes that applied the commit
                    # BEFORE the drop
                    gone = {p for p, dts in self.dropped.items()
                            if any(commit_ts < d <= fold_ts
                                   for d in dts)}
                    eff = mut.exclude(gone) if gone else mut
                    store = _materialize(store, [_Layer(commit_ts, eff)])
                patched.append((fold_ts, store))
            self._history = patched
            import bisect
            bisect.insort(self.layers, _Layer(commit_ts, mut),
                          key=lambda l: l.commit_ts)
            self.max_uid_seen = max(self.max_uid_seen,
                                    max(mut.all_uids(), default=0))
            self._views.clear()

    # -- read path ----------------------------------------------------------
    def read_view(self, read_ts: int) -> Store:
        """Store snapshot visible at `read_ts` — nearest fold point at or
        below, plus the delta layers in between."""
        with self._lock:
            fold_ts, fold_store = self._fold_at(read_ts)
            pending = [l for l in self.layers
                       if fold_ts < l.commit_ts <= read_ts]
            if not pending:
                return fold_store
            # key on the exact layer set: a late out-of-order arrival
            # below an already-cached newest ts must not serve stale views
            key = (fold_ts, tuple(l.commit_ts for l in pending))
            view = self._views.get(key)
            if view is None:
                view = self._make_view(fold_store, pending)
                self._views[key] = view
                while len(self._views) > _VIEW_CACHE:
                    self._views.pop(next(iter(self._views)))
            return view

    @staticmethod
    def _make_view(fold_store: Store, pending) -> Store:
        """A read view over (fold point + pending layers). In-core:
        the eager full materialize (unchanged). Out-of-core: a
        LAZILY-FOLDING view — only the tablets a query touches
        materialize (`_materialize(only=)` with the fold vocabulary
        pinned), so a mutation-bearing read above the newest fold point
        no longer faults the whole store into RAM."""
        from dgraph_tpu.store.outofcore import LazyPreds
        if not isinstance(fold_store.preds, LazyPreds):
            return _materialize(fold_store, pending)
        vocab = fold_vocab(fold_store, pending)
        schema = fold_store.schema.clone()
        return Store(uids=vocab, schema=schema,
                     preds=_LazyFoldPreds(fold_store, pending, schema,
                                          vocab))

    def _fold_at(self, ts: int) -> tuple[int, Store]:
        for fold_ts, store in reversed(self._history):
            if fold_ts <= ts:
                return fold_ts, store
        raise ValueError(
            f"read_ts {ts} predates the oldest retained snapshot "
            f"({self._history[0][0]}); raise the gc watermark lag")

    # -- compaction ---------------------------------------------------------
    def rollup(self, upto_ts: int | None = None) -> Store:
        """Create a fold point at `upto_ts` (default: newest layer).
        Older layers/snapshots are RETAINED for open readers until gc()
        (reference: Badger keeps versions until the watermark moves)."""
        with self._lock:
            if upto_ts is None:
                upto_ts = (self.layers[-1].commit_ts if self.layers
                           else self._history[-1][0])
            fold_ts, fold_store = self._fold_at(upto_ts)
            pending = [l for l in self.layers
                       if fold_ts < l.commit_ts <= upto_ts]
            if not pending:
                return fold_store
            new_ts = pending[-1].commit_ts
            store = _materialize(fold_store, pending)
            self._history.append((new_ts, store))
            def preds_of(layers_):
                return {rec[1] for l in layers_
                        for rec in (l.mut.edge_sets + l.mut.edge_dels
                                    + l.mut.val_sets + l.mut.val_dels)}

            touched = preds_of(pending)
            # the freshest cached view over a PREFIX of the folded layer
            # set differs from the fold only by the suffix layers — its
            # kernel caches carry for every predicate the suffix left
            # untouched
            pend_ts = tuple(l.commit_ts for l in pending)
            view, vlen = None, -1
            for (f_ts, ts_tup), v in self._views.items():
                if (f_ts == fold_ts and len(ts_tup) > vlen
                        and ts_tup == pend_ts[:len(ts_tup)]):
                    view, vlen = v, len(ts_tup)
            view_touched = (preds_of(pending[vlen:])
                            if view is not None else set())
        # outside self._lock: the fold rebuilds untouched predicates to
        # identical CSR blocks (vocab willing), so existing ELL/device/
        # kernel caches stay valid — carry them instead of re-running a
        # full build_ell on the next batch
        from dgraph_tpu.engine.batch import carry_kernel_caches
        if view is not None:
            carry_kernel_caches(view, store, view_touched)
        carry_kernel_caches(fold_store, store, touched)
        return store

    def _fold_guard(self, fold_ts: int, upto_ts: int) -> tuple:
        """Fingerprint of what an external fold over (fold_ts, upto_ts]
        absorbed: the exact pending-layer ts set, the retained layers at
        or below the fold seed (a straggler absorbed BELOW the seed
        patches folds in place — ours isn't in history yet, so it must
        refuse), and the drop history. Checked at install time (caller
        holds the lock)."""
        return (fold_ts,
                tuple(l.commit_ts for l in self.layers
                      if fold_ts < l.commit_ts <= upto_ts),
                frozenset(l.commit_ts for l in self.layers
                          if l.commit_ts <= fold_ts),
                tuple(sorted((p, tuple(t for t in dts if t <= upto_ts))
                             for p, dts in self.dropped.items()
                             if any(t <= upto_ts for t in dts))))

    def _guard_ok(self, upto_ts: int, guard: tuple) -> bool:
        fold_ts, pend, below, drops = guard
        now_fold, now_pend, now_below, now_drops = \
            self._fold_guard(fold_ts, upto_ts)
        # gc REMOVING already-folded layers is benign; anything NEW at
        # or below upto_ts (a straggler) or a drop is not
        return (now_pend == pend and now_below <= below
                and now_drops == drops)

    def fold_plan(self, upto_ts: int | None = None):
        """Immutable snapshot of what a fold up to `upto_ts` covers:
        (fold_ts, fold_store, pending_layers, new_ts, guard). The
        streaming writer (store/stream.py) materialises OUTSIDE the
        store lock from these references — layers are immutable and the
        fold store is an immutable snapshot, so concurrent applies
        (which land above upto_ts) never invalidate the plan; the guard
        catches the rare straggler that lands below it."""
        with self._lock:
            if upto_ts is None:
                upto_ts = (self.layers[-1].commit_ts if self.layers
                           else self._history[-1][0])
            fold_ts, fold_store = self._fold_at(upto_ts)
            pending = [l for l in self.layers
                       if fold_ts < l.commit_ts <= upto_ts]
            new_ts = pending[-1].commit_ts if pending else fold_ts
            return (fold_ts, fold_store, pending, new_ts,
                    self._fold_guard(fold_ts, new_ts))

    def install_fold(self, new_ts: int, store: Store, guard: tuple) -> None:
        """Install an externally-materialised fold point (a streaming
        rollup/checkpoint that wrote per-tablet segments to disk and
        reopened them out-of-core). Raises FoldRaced when the layer/drop
        state below new_ts changed since the plan was taken — the fold
        on disk is missing those records and must not serve.

        Kernel caches CARRY exactly as the in-core `rollup` path's do:
        predicates the folded layers didn't touch stream out to
        byte-identical CSR content (same pinned vocabulary), so the
        seed fold point's ELL blocks / device uploads / compiled
        kernels stay valid on the new snapshot
        (`ell_cache_carried_total`; the vocab-growth guard inside
        carry_kernel_caches refuses when the fold added uids)."""
        import bisect
        with self._lock:
            if not self._guard_ok(new_ts, guard):
                raise FoldRaced(
                    f"fold at ts {new_ts} raced a straggler/drop; "
                    f"discard and re-plan")
            if any(ts == new_ts for ts, _ in self._history):
                return  # identical content by the MVCC ts contract
            fold_ts = guard[0]
            seed = next((s for t, s in self._history if t == fold_ts),
                        None)
            touched = {rec[1]
                       for l in self.layers
                       if fold_ts < l.commit_ts <= new_ts
                       for rec in (l.mut.edge_sets + l.mut.edge_dels
                                   + l.mut.val_sets + l.mut.val_dels)}
            bisect.insort(self._history, (new_ts, store),
                          key=lambda e: e[0])
            self._views.clear()
        # outside the lock, like rollup: the carry only reads immutable
        # snapshot attributes + the batch-module cache lock
        if seed is not None:
            from dgraph_tpu.engine.batch import carry_kernel_caches
            carry_kernel_caches(seed, store, touched)

    def pending_layer_count(self) -> int:
        """Delta layers ABOVE the newest fold point — what a rollup
        would absorb. (len(self.layers) also counts already-folded
        layers retained for open readers until gc; triggering policy on
        that spins forever.)"""
        with self._lock:
            floor = self._history[-1][0]
            return sum(1 for l in self.layers if l.commit_ts > floor)

    def history_stores(self) -> list[tuple[int, Store]]:
        """Copy of the retained fold points (ts ascending) — the
        streaming checkpoint's cleanup uses this to keep on-disk ckpt
        dirs that older fold points still fault tablets from."""
        with self._lock:
            return list(self._history)

    def drop_predicate(self, pred: str, drop_ts: int) -> None:
        """Remove a predicate's data and schema at drop_ts (reference:
        api.Operation{DropAttr}). Materialises the newest state minus the
        predicate as a fold point: reads at or above drop_ts see it gone,
        reads below still resolve against the prior folds/layers."""
        with self._lock:
            def strip(st: Store) -> Store:
                schema = st.schema.clone()
                schema.predicates.pop(pred, None)
                return Store(uids=st.uids, schema=schema,
                             preds={p: pd for p, pd in st.preds.items()
                                    if p != pred})

            # Folds strictly below the drop are untouched. The drop fold
            # materialises seed + commits BELOW drop_ts; commits ABOVE it
            # stay layered (a post-drop write legitimately re-creates the
            # predicate, and an out-of-order commit with ts > drop_ts
            # must stay visible exactly as on a node that applied the
            # drop first). Folds already AT/ABOVE the drop (a rollup or
            # tablet resync raced the broadcast) are patched IN PLACE —
            # only the dropped predicate is removed, so snapshot-derived
            # content (install_tablet, rebuild_base) survives; rebirth
            # commits absorbed into such a raced fold are lost with it,
            # the same outcome the drop's issuer intended.
            below = [(t, s) for t, s in self._history if t < drop_ts]
            above = [(t, s) for t, s in self._history if t >= drop_ts]
            new_hist = list(below)
            if below:
                seed_ts, seed = below[-1]
                pend = [l for l in self.layers
                        if seed_ts < l.commit_ts < drop_ts]
                st = _materialize(seed, pend) if pend else seed
                fold_ts = max(drop_ts, seed_ts)
                if not above or above[0][0] > fold_ts:
                    new_hist.append((fold_ts, strip(st)))
            for t, s in above:
                st = strip(s)
                # re-apply the dropped predicate's REBIRTH commits (ts
                # in (drop_ts, t]) from retained layers, so a rollup
                # that absorbed them before the drop arrived does not
                # make visibility depend on local rollup timing
                reb = []
                for l in self.layers:
                    if drop_ts < l.commit_ts <= t:
                        r = l.mut.restrict({pred})
                        if (r.edge_sets or r.edge_dels or r.val_sets
                                or r.val_dels):
                            reb.append(_Layer(l.commit_ts, r))
                if reb:
                    st = _materialize(st, reb)
                new_hist.append((t, st))
            self._history = new_hist
            self.dropped.setdefault(pred, []).append(drop_ts)
            self._views.clear()

    def rebuild_base(self, schema: Schema | None = None) -> Store:
        """Re-materialise the newest state under `schema` and fold — the
        index/reverse rebuild behind Alter (reference: RebuildIndex). The
        swap is atomic: readers hold either the old or the new snapshot."""
        with self._lock:
            fold_ts, fold_store = self._history[-1]
            pending = [l for l in self.layers if l.commit_ts > fold_ts]
            new_ts = pending[-1].commit_ts if pending else fold_ts
            store = _materialize(fold_store, pending, schema=schema)
            self._history.append((new_ts, store))
            self._views.clear()
            return store

    def floor_ts(self) -> int:
        """Oldest retained fold point — reads below this would fail."""
        with self._lock:
            return self._history[0][0]

    def install_tablet(self, pred: str, pd) -> None:
        """Swap a whole predicate's data into the newest fold (snapshot
        resync of an owned tablet from a replica — reference: Badger
        Stream snapshot install). Point-in-time reads below the newest
        fold keep their old view; new reads see the resynced tablet.

        The incoming blocks are rank-indexed against the CURRENT
        vocabulary (identical cluster-wide by the vocab-touch broadcast),
        so the state is folded to a snapshot carrying that vocabulary
        before the swap — patching an older fold would mis-index."""
        from dgraph_tpu.store.store import Store, build_indexes
        self.rollup()
        with self._lock:
            fold_ts, store = self._history[-1]
            preds = dict(store.preds)
            preds[pred] = pd
            build_indexes({pred: pd})
            self._history[-1] = (fold_ts, Store(
                uids=store.uids, schema=store.schema, preds=preds))
            self._views.clear()

    def gc(self, min_active_ts: int) -> None:
        """Drop snapshots/layers unreachable by any ts ≥ min_active_ts."""
        with self._lock:
            keep = 0
            for i, (fold_ts, _) in enumerate(self._history):
                if fold_ts <= min_active_ts:
                    keep = i
            self._history = self._history[keep:]
            floor = self._history[0][0]
            self.layers = [l for l in self.layers if l.commit_ts > floor]
            self._views = {k: v for k, v in self._views.items()
                           if k[0] >= floor}

    # -- vocabulary ----------------------------------------------------------
    # (rank-space contract: once a uid is in the vocabulary it never
    # leaves — the reference likewise never reuses uids)


def _materialize(base: Store, layers: list[_Layer],
                 schema: Schema | None = None, only=None,
                 vocab=None) -> Store:
    """Rebuild a Store from base + deltas (host-side; the new CSR blocks
    re-enter HBM via Store.device_rel on first use).

    `only` restricts the rebuild to that predicate set — the unit the
    streaming fold (store/stream.py) processes one tablet at a time so
    an out-of-core base faults exactly one tablet per call. `vocab`
    pins the uid vocabulary (the caller precomputed the full-fold
    union), keeping every per-tablet build in the SAME dense rank space
    the whole-store build would use — per-tablet CSR blocks come out
    bit-identical to the corresponding slice of a full materialize."""
    import numpy as np
    b = StoreBuilder(schema=(schema if schema is not None
                             else base.schema.clone()))
    # vocabulary is monotone: nodes with no local postings (cluster mode:
    # foreign-tablet-only nodes) must keep their rank — preserve the whole
    # base vocab plus every uid the deltas mention
    if vocab is not None:
        b.touch_many(vocab)
    else:
        b.touch_many(base.uids)
        for layer_ in layers:
            b.touch_many(sorted(layer_.mut.all_uids()))
    if only is not None:
        # one lock-free get() per requested tablet (faults just that
        # tablet on an out-of-core base), and each layer restricted to it
        base_items = [(p, base.preds.get(p)) for p in sorted(only)]
        base_items = [(p, pd) for p, pd in base_items if pd is not None]
        layers = [_Layer(l.commit_ts, l.mut.restrict(only))
                  for l in layers]
    else:
        base_items = base.preds.items()

    # live edges/values from base, as dicts for delete application
    edges: dict[str, set] = {}
    efacets: dict[str, dict] = {}   # pred → {(s,o): facet dict}
    vfacets: dict[str, dict] = {}   # pred → {s: facet dict}
    for pred, pd in base_items:
        if pd.fwd is not None and pd.fwd.nnz:
            deg = pd.fwd.indptr[1:] - pd.fwd.indptr[:-1]
            src_r = np.repeat(np.arange(base.n_nodes), deg)
            s_uid = base.uids[src_r]
            o_uid = base.uids[pd.fwd.indices]
            edges[pred] = set(zip(s_uid.tolist(), o_uid.tolist()))
            for key, fc in pd.efacets.items():
                fm = efacets.setdefault(pred, {})
                for pos, v in zip(fc.pos.tolist(), fc.vals):
                    pair = (int(s_uid[pos]), int(o_uid[pos]))
                    fm.setdefault(pair, {})[key] = v
        for key, d in pd.vfacets.items():
            fm = vfacets.setdefault(pred, {})
            for s_rank, v in d.items():
                fm.setdefault(int(base.uids[s_rank]), {})[key] = v
    vals: dict[tuple, dict] = {}
    for pred, pd in base_items:
        for lang, col in pd.vals.items():
            d = vals.setdefault((pred, lang), {})
            for s, v in zip(col.subj, col.vals):
                d.setdefault(int(base.uids[s]), []).append(v)

    for layer in layers:
        m = layer.mut
        for s, p, o in m.edge_dels:
            if o is None:
                edges[p] = {e for e in edges.get(p, set()) if e[0] != s}
                efacets[p] = {pair: f for pair, f in
                              efacets.get(p, {}).items() if pair[0] != s}
            else:
                edges.get(p, set()).discard((s, o))
                efacets.get(p, {}).pop((s, o), None)
        for s, p, o, *f in m.edge_sets:
            edges.setdefault(p, set()).add((s, o))
            if f and f[0]:
                efacets.setdefault(p, {})[(s, o)] = dict(f[0])
        for s, p, _v, lang in m.val_dels:
            if lang == "*":  # delete across every language column
                for (vp, _vl), d in vals.items():
                    if vp == p:
                        d.pop(s, None)
                vfacets.get(p, {}).pop(s, None)
            else:
                vals.get((p, lang), {}).pop(s, None)
        for s, p, v, lang, *f in m.val_sets:
            ps = b.schema.peek(p)
            if ps is not None and ps.is_list:
                vals.setdefault((p, lang), {}).setdefault(s, []).append(v)
            else:
                vals.setdefault((p, lang), {})[s] = [v]
            if f and f[0]:
                vfacets.setdefault(p, {})[s] = dict(f[0])

    for pred, es in edges.items():
        fm = efacets.get(pred, {})
        for s, o in sorted(es):
            b.add_edge(s, pred, o, facets=fm.get((s, o)))
    for (pred, lang), d in vals.items():
        fm = vfacets.get(pred, {})
        for s, vlist in sorted(d.items()):
            for v in vlist:
                if pred == TYPE_PRED:
                    b.add_type(s, str(v))
                else:
                    b.add_value(s, pred, _to_py(v), lang,
                                facets=fm.get(s))
    return b.finalize()


def _to_py(v):
    """numpy scalar → python for StoreBuilder.add_value re-ingestion."""
    import numpy as np
    if isinstance(v, np.generic) and not isinstance(v, np.datetime64):
        return v.item()
    return v
