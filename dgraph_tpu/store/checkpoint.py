"""Checkpoint: versioned on-disk Store snapshots.

Reference parity: the reference's three persistence mechanisms (SURVEY §5)
— Badger's LSM as durable posting storage, raft snapshots, and
export/binary-backup — collapse here into one: the host-disk CSR block
store with a versioned manifest. TPU HBM is a cache over this, never the
source of truth; recovery = reload (the stateless-sidecar failure model).

Layout:  <dir>/manifest.json
         <dir>/uids.npy
         <dir>/<pred-hash>.<fwd|rev>.indptr.npy / .indices.npy
         <dir>/<pred-hash>.val.<lang>.subj.npy / .vals.npy
Index blocks are rebuilt on load (cheap, and keeps the format stable
against tokenizer changes — the reference likewise rebuilds indexes on
schema migration rather than shipping them in backups).
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

from dgraph_tpu.store import vault
from dgraph_tpu.store.schema import parse_schema
from dgraph_tpu.store.types import Kind
from dgraph_tpu.store.store import (
    EdgeRel, FacetCol, PredicateData, Store, ValueColumn, build_indexes)
# facet scalars use the WAL's codec so both durability paths (checkpoint
# vs WAL replay) recover identical types
from dgraph_tpu.store.wal import dec_scalar, enc_scalar

FORMAT_VERSION = 2  # v2: facet persistence (<slug>.facets.json)
MIN_FORMAT_VERSION = 1  # v1 checkpoints load (they predate facet storage)


def _slug(pred: str) -> str:
    h = hashlib.sha1(pred.encode()).hexdigest()[:12]
    safe = "".join(c if c.isalnum() or c in "._-" else "_" for c in pred)
    return f"{safe[:40]}.{h}"


def save(store: Store, dirname: str, base_ts: int = 0,
         compress: bool | None = None) -> None:
    """Write a Store snapshot (reference: export/backup at a timestamp).

    `compress` (default: auto when the native lib is built) delta-varint
    packs the sorted uid vocabulary via native/codec.cpp — the role the
    reference's codec.UidPack plays for posting storage."""
    from dgraph_tpu import native
    if compress is None:
        compress = native.HAVE_NATIVE
    os.makedirs(dirname, exist_ok=True)
    if compress:
        vault.write_bytes(os.path.join(dirname, "uids.duc"),
                          native.codec_encode(store.uids))
    else:
        vault.save_np(os.path.join(dirname, "uids.npy"), store.uids)
    preds_meta = {}
    for pred, pd in store.preds.items():
        slug = _slug(pred)
        nbytes = sum(r.indptr.nbytes + r.indices.nbytes
                     for r in (pd.fwd, pd.rev) if r is not None)
        nbytes += sum(c.subj.nbytes
                      + (c.vals.nbytes if c.vals.dtype != object
                         else len(c.vals) * 64)
                      for c in pd.vals.values())
        # nbytes: size hint for out-of-core eviction accounting and the
        # tablet-size heartbeat (neither may fault the tablet in)
        meta = {"slug": slug, "langs": sorted(pd.vals), "nbytes": nbytes}
        for side, rel in (("fwd", pd.fwd), ("rev", pd.rev)):
            if rel is not None:
                vault.save_np(
                    os.path.join(dirname, f"{slug}.{side}.indptr.npy"),
                    rel.indptr)
                vault.save_np(
                    os.path.join(dirname, f"{slug}.{side}.indices.npy"),
                    rel.indices)
                meta[side] = True
        for lang, col in pd.vals.items():
            lslug = lang or "_"
            vault.save_np(
                os.path.join(dirname, f"{slug}.val.{lslug}.subj.npy"),
                col.subj)
            vals = col.vals
            if vals.dtype == object:  # strings: store as fixed-width UTF
                vals = np.array([str(v) for v in vals], dtype=np.str_)
            vault.save_np(
                os.path.join(dirname, f"{slug}.val.{lslug}.vals.npy"),
                vals)
        if pd.efacets or pd.vfacets:
            # facets ride in a JSON sidecar (they are sparse; the reference
            # persists them inside each posting — same durability contract)
            fdoc = {
                "efacets": {k: {"pos": col.pos.tolist(),
                                "vals": [enc_scalar(v) for v in col.vals]}
                            for k, col in pd.efacets.items()},
                "vfacets": {k: {str(r): enc_scalar(v)
                                for r, v in m.items()}
                            for k, m in pd.vfacets.items()},
            }
            vault.write_bytes(os.path.join(dirname, f"{slug}.facets.json"),
                              json.dumps(fdoc).encode())
            meta["facets"] = True
        preds_meta[pred] = meta
    manifest = {
        "format_version": FORMAT_VERSION,
        "base_ts": base_ts,
        "n_nodes": store.n_nodes,
        "uids_codec": bool(compress),
        "schema": store.schema.to_text(),
        "predicates": preds_meta,
    }
    tmp = os.path.join(dirname, "manifest.json.tmp")
    # manifest is encrypted too — it carries the schema text and
    # predicate names (the reference likewise keeps schema inside the
    # encrypted store, exposing only sizes/timestamps in plaintext)
    vault.write_bytes(tmp, json.dumps(manifest, indent=1).encode())
    os.replace(tmp, os.path.join(dirname, "manifest.json"))


def resolve(dirname: str) -> str:
    """Follow a CURRENT pointer (versioned-checkpoint layout written by
    save_versioned) if present; plain snapshot dirs resolve to themselves."""
    cur = os.path.join(dirname, "CURRENT")
    if os.path.exists(cur):
        with open(cur) as f:
            return os.path.join(dirname, f.read().strip())
    return dirname


def exists(dirname: str) -> bool:
    return os.path.exists(os.path.join(resolve(dirname), "manifest.json"))


def save_versioned(store: Store, dirname: str, base_ts: int = 0) -> None:
    """Crash-safe checkpoint: write a fresh `ckpt-<ts>` subdir, then flip
    the CURRENT pointer atomically, then delete superseded subdirs. A kill
    at ANY point leaves either the old or the new snapshot fully intact —
    never a half-written mix (the durability role of Badger's MANIFEST)."""
    os.makedirs(dirname, exist_ok=True)
    sub = f"ckpt-{base_ts:016d}"
    cur = os.path.join(dirname, "CURRENT")
    if os.path.exists(cur):
        with open(cur) as f:
            if (f.read().strip() == sub and os.path.exists(
                    os.path.join(dirname, sub, "manifest.json"))):
                # CURRENT already names a fully-written ckpt-<base_ts>:
                # re-saving would scribble over the live snapshot in place
                # and a crash mid-save would leave NO intact snapshot. The
                # MVCC contract makes base_ts identify the content, so the
                # existing snapshot is exactly what we'd write — no-op.
                return
    save(store, os.path.join(dirname, sub), base_ts=base_ts)
    tmp = os.path.join(dirname, "CURRENT.tmp")
    with open(tmp, "w") as f:
        f.write(sub)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dirname, "CURRENT"))
    for name in os.listdir(dirname):
        if name.startswith("ckpt-") and name != sub:
            import shutil
            shutil.rmtree(os.path.join(dirname, name), ignore_errors=True)


def read_manifest(dirname: str) -> tuple[dict, str]:
    """(manifest, resolved dir) with the format gate applied."""
    dirname = resolve(dirname)
    manifest = json.loads(
        vault.read_bytes(os.path.join(dirname, "manifest.json")))
    if not (MIN_FORMAT_VERSION <= manifest["format_version"]
            <= FORMAT_VERSION):
        raise ValueError(
            f"checkpoint format {manifest['format_version']} not in "
            f"[{MIN_FORMAT_VERSION}, {FORMAT_VERSION}]")
    return manifest, dirname


def load_uids(dirname: str, manifest: dict) -> np.ndarray:
    if manifest.get("uids_codec"):
        from dgraph_tpu import native
        return native.codec_decode(
            vault.read_bytes(os.path.join(dirname, "uids.duc")),
            manifest["n_nodes"])
    return vault.load_np(os.path.join(dirname, "uids.npy"))


def load_predicate(dirname: str, pred: str, meta: dict,
                   schema) -> PredicateData:
    """Load ONE predicate's tablet from a snapshot dir — the unit the
    out-of-core store faults in on first touch (store/outofcore.py) and
    the loop body of a full load()."""
    slug = meta["slug"]
    pd = PredicateData(schema=schema.get(pred))
    for side in ("fwd", "rev"):
        if meta.get(side):
            indptr = vault.load_np(
                os.path.join(dirname, f"{slug}.{side}.indptr.npy"))
            indices = vault.load_np(
                os.path.join(dirname, f"{slug}.{side}.indices.npy"))
            setattr(pd, side, EdgeRel(indptr=indptr, indices=indices))
    for lang in meta["langs"]:
        lslug = lang or "_"
        vals = vault.load_np(
            os.path.join(dirname, f"{slug}.val.{lslug}.vals.npy"),
            allow_pickle=False)
        if vals.dtype.kind == "U":  # restore string columns to object
            vals = vals.astype(object)
        ps = schema.get(pred)
        if ps is not None and ps.kind == Kind.GEO and len(vals):
            # geo columns persist as GeoJSON strings; re-wrap
            from dgraph_tpu.store.geo import parse_geo
            out = np.empty(len(vals), dtype=object)
            out[:] = [parse_geo(v) for v in vals]
            vals = out
        pd.vals[lang] = ValueColumn(
            subj=vault.load_np(
                os.path.join(dirname, f"{slug}.val.{lslug}.subj.npy")),
            vals=vals)
    if meta.get("facets"):
        fdoc = json.loads(vault.read_bytes(
            os.path.join(dirname, f"{slug}.facets.json")))
        for k, col in fdoc.get("efacets", {}).items():
            vals = np.empty(len(col["vals"]), dtype=object)
            vals[:] = [dec_scalar(v) for v in col["vals"]]
            pd.efacets[k] = FacetCol(
                pos=np.array(col["pos"], np.int64), vals=vals)
        for k, m in fdoc.get("vfacets", {}).items():
            pd.vfacets[k] = {int(r): dec_scalar(v)
                             for r, v in m.items()}
    return pd


def load(dirname: str) -> tuple[Store, int]:
    """Load (store, base_ts). Reference: restore / bulk-load handoff.
    Accepts both plain snapshot dirs and versioned (CURRENT) layouts."""
    manifest, dirname = read_manifest(dirname)
    uids = load_uids(dirname, manifest)
    schema = parse_schema(manifest["schema"])
    preds: dict[str, PredicateData] = {}
    for pred, meta in manifest["predicates"].items():
        preds[pred] = load_predicate(dirname, pred, meta, schema)
    build_indexes(preds)
    return Store(uids=uids, schema=schema, preds=preds), manifest["base_ts"]
