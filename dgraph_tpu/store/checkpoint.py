"""Checkpoint: versioned on-disk Store snapshots.

Reference parity: the reference's three persistence mechanisms (SURVEY §5)
— Badger's LSM as durable posting storage, raft snapshots, and
export/binary-backup — collapse here into one: the host-disk CSR block
store with a versioned manifest. TPU HBM is a cache over this, never the
source of truth; recovery = reload (the stateless-sidecar failure model).

Layout:  <dir>/manifest.json
         <dir>/uids.npy
         <dir>/<pred-hash>.<fwd|rev>.indptr.npy / .indices.npy
         <dir>/<pred-hash>.val.<lang>.subj.npy / .vals.npy
Index blocks are rebuilt on load (cheap, and keeps the format stable
against tokenizer changes — the reference likewise rebuilds indexes on
schema migration rather than shipping them in backups).
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

from dgraph_tpu.store import vault
from dgraph_tpu.store.schema import parse_schema
from dgraph_tpu.store.types import Kind
from dgraph_tpu.store.store import (
    EdgeRel, FacetCol, PredicateData, Store, ValueColumn, build_indexes)
# facet scalars use the WAL's codec so both durability paths (checkpoint
# vs WAL replay) recover identical types
from dgraph_tpu.store.wal import dec_scalar, enc_scalar

FORMAT_VERSION = 3  # v3: per-file crc32 digests (WAL-style integrity)
MIN_FORMAT_VERSION = 1  # v1/v2 checkpoints load (no digests recorded —
#                         integrity checks are skipped for them)


def _slug(pred: str) -> str:
    h = hashlib.sha1(pred.encode()).hexdigest()[:12]
    safe = "".join(c if c.isalnum() or c in "._-" else "_" for c in pred)
    return f"{safe[:40]}.{h}"


def save_uids(uids: np.ndarray, dirname: str, compress: bool) -> int:
    """Write the uid vocabulary block (`compress` delta-varint packs it
    via native/codec.cpp — the role the reference's codec.UidPack plays
    for posting storage). Returns the block's on-disk crc32 (recorded
    as `uids_crc` in the manifest and verified on every load)."""
    if compress:
        from dgraph_tpu import native
        return vault.write_bytes(os.path.join(dirname, "uids.duc"),
                                 native.codec_encode(uids))
    return vault.save_np(os.path.join(dirname, "uids.npy"), uids)


def save_predicate(dirname: str, pred: str, pd) -> dict:
    """Write ONE predicate's tablet segment files; returns its manifest
    meta entry. The loop body of save() and the unit the streaming
    writer (store/stream.py) emits one-at-a-time, so checkpoint/backup/
    export of an out-of-core store never holds more than one tablet
    resident. Byte-identical segments either way."""
    slug = _slug(pred)
    nbytes = sum(r.indptr.nbytes + r.indices.nbytes
                 for r in (pd.fwd, pd.rev) if r is not None)
    nbytes += sum(c.subj.nbytes
                  + (c.vals.nbytes if c.vals.dtype != object
                     else len(c.vals) * 64)
                  for c in pd.vals.values())
    # nbytes: size hint for out-of-core eviction accounting and the
    # tablet-size heartbeat (neither may fault the tablet in)
    meta = {"slug": slug, "langs": sorted(pd.vals), "nbytes": nbytes}
    # per-file crc32 of the on-disk bytes: the tablet's integrity
    # digests, verified on every fault/load/restore of this segment set
    crcs: dict[str, int] = {}
    for side, rel in (("fwd", pd.fwd), ("rev", pd.rev)):
        if rel is not None:
            for part, arr in (("indptr", rel.indptr),
                              ("indices", rel.indices)):
                fname = f"{slug}.{side}.{part}.npy"
                crcs[fname] = vault.save_np(
                    os.path.join(dirname, fname), arr)
            meta[side] = True
    for lang, col in pd.vals.items():
        lslug = lang or "_"
        fname = f"{slug}.val.{lslug}.subj.npy"
        crcs[fname] = vault.save_np(os.path.join(dirname, fname),
                                    col.subj)
        vals = col.vals
        if pd.schema.kind == Kind.VECTOR:
            # vector columns persist as a dense [k, d] f32 stack — the
            # exact bytes the tablet serves, crc-verified like any
            # other segment (the GEO-string precedent, but binary)
            vals = (np.stack([np.asarray(v, np.float32) for v in vals])
                    if len(vals) else np.zeros((0, 0), np.float32))
        elif vals.dtype == object:  # strings: store as fixed-width UTF
            vals = np.array([str(v) for v in vals], dtype=np.str_)
        fname = f"{slug}.val.{lslug}.vals.npy"
        crcs[fname] = vault.save_np(os.path.join(dirname, fname), vals)
    if pd.efacets or pd.vfacets:
        # facets ride in a JSON sidecar (they are sparse; the reference
        # persists them inside each posting — same durability contract)
        fdoc = {
            "efacets": {k: {"pos": col.pos.tolist(),
                            "vals": [enc_scalar(v) for v in col.vals]}
                        for k, col in pd.efacets.items()},
            "vfacets": {k: {str(r): enc_scalar(v)
                            for r, v in m.items()}
                        for k, m in pd.vfacets.items()},
        }
        fname = f"{slug}.facets.json"
        crcs[fname] = vault.write_bytes(os.path.join(dirname, fname),
                                        json.dumps(fdoc).encode())
        meta["facets"] = True
    meta["crc"] = crcs
    return meta


def write_manifest(dirname: str, manifest: dict) -> None:
    """Atomically land the manifest — the commit point of a snapshot.
    The manifest is encrypted too: it carries the schema text and
    predicate names (the reference likewise keeps schema inside the
    encrypted store, exposing only sizes/timestamps in plaintext).
    vault.write_bytes is tmp+fsync+os.replace, so a kill mid-write
    leaves the previous manifest (or none) — never a torn one."""
    vault.write_bytes(os.path.join(dirname, "manifest.json"),
                      json.dumps(manifest, indent=1).encode())


def manifest_doc(n_nodes: int, schema_text: str, preds_meta: dict,
                 base_ts: int, compress: bool,
                 uids_crc: int | None = None) -> dict:
    doc = {
        "format_version": FORMAT_VERSION,
        "base_ts": base_ts,
        "n_nodes": n_nodes,
        "uids_codec": bool(compress),
        "schema": schema_text,
        "predicates": preds_meta,
    }
    if uids_crc is not None:
        doc["uids_crc"] = uids_crc
    return doc


def save(store: Store, dirname: str, base_ts: int = 0,
         compress: bool | None = None) -> None:
    """Write a Store snapshot (reference: export/backup at a timestamp).

    Materialization note: iterating `store.preds.items()` on an
    out-of-core store faults EVERY tablet in — use
    store/stream.py::save_streaming there (same format, same bytes,
    one tablet resident at a time)."""
    from dgraph_tpu import native
    if compress is None:
        compress = native.HAVE_NATIVE
    os.makedirs(dirname, exist_ok=True)
    uids_crc = save_uids(store.uids, dirname, compress)
    preds_meta = {}
    for pred, pd in store.preds.items():
        preds_meta[pred] = save_predicate(dirname, pred, pd)
    write_manifest(dirname, manifest_doc(
        store.n_nodes, store.schema.to_text(), preds_meta, base_ts,
        compress, uids_crc=uids_crc))


def resolve(dirname: str) -> str:
    """Follow a CURRENT pointer (versioned-checkpoint layout written by
    save_versioned) if present; plain snapshot dirs resolve to themselves."""
    cur = os.path.join(dirname, "CURRENT")
    if os.path.exists(cur):
        with open(cur) as f:
            return os.path.join(dirname, f.read().strip())
    return dirname


def exists(dirname: str) -> bool:
    return os.path.exists(os.path.join(resolve(dirname), "manifest.json"))


def begin_versioned(dirname: str, base_ts: int) -> str | None:
    """First half of a crash-safe versioned checkpoint: pick the
    `ckpt-<ts>` subdir name, or None when CURRENT already names a
    fully-written ckpt-<base_ts> — re-saving would scribble over the
    live snapshot in place and a crash mid-save would leave NO intact
    snapshot. The MVCC contract makes base_ts identify the content, so
    the existing snapshot is exactly what we'd write — no-op."""
    os.makedirs(dirname, exist_ok=True)
    sub = f"ckpt-{base_ts:016d}"
    cur = os.path.join(dirname, "CURRENT")
    if os.path.exists(cur):
        with open(cur) as f:
            if (f.read().strip() == sub and os.path.exists(
                    os.path.join(dirname, sub, "manifest.json"))):
                return None
    return sub


def commit_versioned(dirname: str, sub: str, keep=()) -> None:
    """Second half: flip the CURRENT pointer atomically, then delete
    superseded subdirs. `keep` names subdirs that must SURVIVE the
    sweep — an out-of-core MVCC store's older fold points still fault
    tablets from their own ckpt dirs until gc drops them."""
    tmp = os.path.join(dirname, "CURRENT.tmp")
    with open(tmp, "w") as f:
        f.write(sub)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dirname, "CURRENT"))
    for name in os.listdir(dirname):
        if name.startswith("ckpt-") and name != sub and name not in keep:
            import shutil
            shutil.rmtree(os.path.join(dirname, name), ignore_errors=True)


def save_versioned(store: Store, dirname: str, base_ts: int = 0) -> None:
    """Crash-safe checkpoint: write a fresh `ckpt-<ts>` subdir, then flip
    the CURRENT pointer atomically, then delete superseded subdirs. A kill
    at ANY point leaves either the old or the new snapshot fully intact —
    never a half-written mix (the durability role of Badger's MANIFEST)."""
    sub = begin_versioned(dirname, base_ts)
    if sub is None:
        return
    save(store, os.path.join(dirname, sub), base_ts=base_ts)
    commit_versioned(dirname, sub)


def read_manifest(dirname: str) -> tuple[dict, str]:
    """(manifest, resolved dir) with the format gate applied. A
    manifest that won't decode (bit-flip, truncation, tamper) raises a
    typed StorageCorruption naming the file."""
    dirname = resolve(dirname)
    mp = os.path.join(dirname, "manifest.json")
    try:
        manifest = json.loads(vault.read_bytes(mp))
    except (ValueError, vault.VaultError) as e:
        raise vault.corruption(mp, kind="manifest", detail=str(e)) from e
    if not isinstance(manifest, dict) or "format_version" not in manifest:
        raise vault.corruption(mp, kind="manifest",
                               detail="not a manifest document")
    if not (MIN_FORMAT_VERSION <= manifest["format_version"]
            <= FORMAT_VERSION):
        raise ValueError(
            f"checkpoint format {manifest['format_version']} not in "
            f"[{MIN_FORMAT_VERSION}, {FORMAT_VERSION}]")
    return manifest, dirname


def load_uids(dirname: str, manifest: dict) -> np.ndarray:
    crc = manifest.get("uids_crc")
    if manifest.get("uids_codec"):
        from dgraph_tpu import native
        raw = vault.read_bytes(os.path.join(dirname, "uids.duc"),
                               crc=crc, kind="uids")
        try:
            return native.codec_decode(raw, manifest["n_nodes"])
        except Exception as e:  # undecodable varint stream
            raise vault.corruption(os.path.join(dirname, "uids.duc"),
                                   kind="uids", detail=str(e)) from e
    return vault.load_np(os.path.join(dirname, "uids.npy"),
                         crc=crc, kind="uids")


def load_predicate(dirname: str, pred: str, meta: dict,
                   schema) -> PredicateData:
    """Load ONE predicate's tablet from a snapshot dir — the unit the
    out-of-core store faults in on first touch (store/outofcore.py) and
    the loop body of a full load()."""
    slug = meta["slug"]
    crcs = meta.get("crc", {})  # absent on pre-v3 snapshots

    def _load(fname):
        return vault.load_np(os.path.join(dirname, fname),
                             crc=crcs.get(fname), kind="segment")

    pd = PredicateData(schema=schema.get(pred))
    for side in ("fwd", "rev"):
        if meta.get(side):
            indptr = _load(f"{slug}.{side}.indptr.npy")
            indices = _load(f"{slug}.{side}.indices.npy")
            setattr(pd, side, EdgeRel(indptr=indptr, indices=indices))
    for lang in meta["langs"]:
        lslug = lang or "_"
        vals = _load(f"{slug}.val.{lslug}.vals.npy")
        if vals.dtype.kind == "U":  # restore string columns to object
            vals = vals.astype(object)
        ps = schema.get(pred)
        if ps is not None and ps.kind == Kind.GEO and len(vals):
            # geo columns persist as GeoJSON strings; re-wrap
            from dgraph_tpu.store.geo import parse_geo
            out = np.empty(len(vals), dtype=object)
            out[:] = [parse_geo(v) for v in vals]
            vals = out
        elif ps is not None and ps.kind == Kind.VECTOR:
            # dense [k, d] f32 stack → object column of row views
            rows = np.asarray(vals, np.float32)
            vals = np.empty(len(rows), dtype=object)
            vals[:] = [rows[i] for i in range(len(rows))]
        pd.vals[lang] = ValueColumn(
            subj=_load(f"{slug}.val.{lslug}.subj.npy"),
            vals=vals)
    if meta.get("facets"):
        fname = f"{slug}.facets.json"
        try:
            fdoc = json.loads(vault.read_bytes(
                os.path.join(dirname, fname),
                crc=crcs.get(fname), kind="segment"))
        except ValueError as e:
            raise vault.corruption(os.path.join(dirname, fname),
                                   kind="segment", detail=str(e)) from e
        for k, col in fdoc.get("efacets", {}).items():
            vals = np.empty(len(col["vals"]), dtype=object)
            vals[:] = [dec_scalar(v) for v in col["vals"]]
            pd.efacets[k] = FacetCol(
                pos=np.array(col["pos"], np.int64), vals=vals)
        for k, m in fdoc.get("vfacets", {}).items():
            pd.vfacets[k] = {int(r): dec_scalar(v)
                             for r, v in m.items()}
    return pd


def verify_snapshot(dirname: str) -> list[dict]:
    """Offline integrity walk of one snapshot dir: every file with a
    recorded digest is re-read raw and crc-checked WITHOUT decoding
    arrays (cheap — one sequential read per file). Returns a list of
    {"file", "kind", "detail"} problems, empty when clean. A manifest
    that won't decode raises StorageCorruption (there is nothing to
    walk without it). Pre-v3 snapshots (no digests) verify vacuously —
    reported as a single `undigested` advisory entry."""
    manifest, dirname = read_manifest(dirname)
    problems: list[dict] = []

    def check(fname, crc, kind):
        path = os.path.join(dirname, fname)
        if not os.path.exists(path):
            problems.append({"file": path, "kind": kind,
                             "detail": "missing"})
        elif crc is not None and not vault.file_crc_ok(path, crc):
            problems.append({"file": path, "kind": kind,
                             "detail": "crc mismatch"})

    uids_crc = manifest.get("uids_crc")
    uids_file = ("uids.duc" if manifest.get("uids_codec")
                 else "uids.npy")
    check(uids_file, uids_crc, "uids")
    digested = uids_crc is not None
    for _pred, meta in manifest["predicates"].items():
        crcs = meta.get("crc")
        if crcs is None:
            continue
        digested = True
        for fname, crc in crcs.items():
            check(fname, crc, "segment")
    if not digested and manifest["predicates"]:
        problems.append({"file": os.path.join(dirname, "manifest.json"),
                         "kind": "undigested",
                         "detail": "pre-v3 snapshot carries no digests "
                                   "(advisory; re-checkpoint to add)"})
    return problems


def load(dirname: str) -> tuple[Store, int]:
    """Load (store, base_ts). Reference: restore / bulk-load handoff.
    Accepts both plain snapshot dirs and versioned (CURRENT) layouts."""
    manifest, dirname = read_manifest(dirname)
    uids = load_uids(dirname, manifest)
    schema = parse_schema(manifest["schema"])
    preds: dict[str, PredicateData] = {}
    for pred, meta in manifest["predicates"].items():
        preds[pred] = load_predicate(dirname, pred, meta, schema)
    build_indexes(preds)
    return Store(uids=uids, schema=schema, preds=preds), manifest["base_ts"]
