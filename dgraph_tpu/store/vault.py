"""Encryption-at-rest: AES-GCM over checkpoint files and WAL records.

Reference parity: the enterprise encryption-at-rest feature (SURVEY §2.5
`ee/`) — the reference encrypts Badger SSTs and value-log blocks with an
AES key loaded from `--encryption key-file=` at process start. Here the
at-rest units are (a) whole checkpoint files (numpy blocks, facet
sidecars, the manifest) and (b) individual WAL/journal record payloads;
backups inherit both automatically because they are built from the same
two writers.

Design notes:
- One process-global key, loaded once at startup (the reference's model:
  encryption is a property of the deployment, not of a call site).
- AES-256/192/128-GCM via the `cryptography` package; every encryption
  uses a fresh random 96-bit nonce, stored alongside the ciphertext:
  ``MAGIC | nonce(12) | ciphertext+tag``.
- WAL framing CRCs the *ciphertext*, so torn-tail detection and
  truncation (`wal._valid_end`) still work without the key — an operator
  can repair a crashed directory they cannot read, like Badger's
  MANIFEST replay under encryption.
- Plaintext files/records remain readable while a key is set (migration:
  enable the key, next checkpoint rewrites everything encrypted). An
  encrypted file without a key raises `VaultError` with a clear message.
"""

from __future__ import annotations

import io
import os
import struct
import zlib

import numpy as np

MAGIC = b"DTE1"   # single-shot sealed blob (file or WAL payload)
MAGIC_C = b"DTEC"  # chunked sealed blob (large checkpoint files)
MAGIC_P = b"DTEP"  # plaintext-escape: raw bytes that happen to start
#                    with one of our magics (a delta-varint uid stream
#                    can emit any byte sequence) are written behind this
#                    prefix so they are never misread as ciphertext
_NONCE = 12
_KEY_SIZES = (16, 24, 32)
# AESGCM's one-shot API caps plaintext at 2^31-1 bytes; blobs above this
# are sealed as independent 1 GiB chunks, each with its own nonce+tag
_CHUNK = 1 << 30
_LEN = struct.Struct("<Q")

_aead = None    # process-global AESGCM, None = encryption off
_strict = False  # refuse plaintext once migration is done


class VaultError(Exception):
    """Missing/incorrect key or tampered ciphertext."""


class StorageCorruption(Exception):
    """A durable file failed its integrity check (crc mismatch, torn
    content, undecodable manifest). Typed and RETRYABLE: on a clustered
    Alpha the load path first tries to heal the tablet from a replica
    (TabletSnapshot), and a refused load names the exact file so the
    operator can repair or restore it — corruption is never served as
    wrong query results."""

    retryable = True

    def __init__(self, path: str, kind: str = "file", detail: str = ""):
        self.path = path
        self.kind = kind
        msg = f"storage corruption in {kind} {path}"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


def corruption(path: str, kind: str, detail: str = "") -> StorageCorruption:
    """Build (and meter) a StorageCorruption — the single counting site
    so `storage_corruption_total{file_kind=}` covers every detection
    path (checkpoint load, delta replay, restore verify, sidecars)."""
    from dgraph_tpu.utils.metrics import METRICS
    METRICS.inc("storage_corruption_total", file_kind=kind)
    # black-box visibility (lazy import: vault sits below utils'
    # telemetry modules in the import order)
    from dgraph_tpu.utils import flightrec
    flightrec.emit("storage.corruption", file=path, file_kind=kind,
                   detail=detail[:200])
    return StorageCorruption(path, kind=kind, detail=detail)


# ---- disk-fault injection hook (cluster/fault.py FaultSchedule) ----
# One process-global write hook: every durable write (atomic file
# writes below + WAL record appends in store/wal.py) passes its final
# bytes through it. A fuzz/test hook may mutate the bytes (bit-flip),
# shorten them (torn write), or raise OSError (ENOSPC) — recorded
# digests are computed from the INTENDED bytes, so an injected fault is
# exactly what the integrity checks must catch. None = zero overhead.
_io_fault = None


def set_io_fault(cb) -> None:
    """Install (or clear, with None) the write-fault hook:
    ``cb(path, data) -> bytes`` may return mutated/truncated bytes or
    raise OSError. Test/fuzz only — never armed in production."""
    global _io_fault
    _io_fault = cb


def io_faulted(path: str, data: bytes) -> bytes:
    if _io_fault is None:
        return data
    out = _io_fault(path, data)
    return data if out is None else out


def set_key(key: bytes | None, strict: bool = False) -> None:
    """Install (or clear, with None) the process-global at-rest key.
    `strict` additionally REJECTS plaintext blobs on read — the
    post-migration posture in which a keyless writer (or an attacker
    swapping in unauthenticated files) cannot inject data."""
    global _aead, _strict
    if key is None:
        _aead = None
        _strict = False
        return
    if len(key) not in _KEY_SIZES:
        raise VaultError(
            f"encryption key must be {_KEY_SIZES} bytes (AES-128/192/256), "
            f"got {len(key)}")
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    _aead = AESGCM(key)
    _strict = bool(strict)


def load_key_file(path: str, strict: bool = False) -> None:
    """Read the raw AES key from `path` (reference: --encryption
    key-file=). A single trailing newline is tolerated — keys are often
    written by shell redirection."""
    with open(path, "rb") as f:
        key = f.read()
    if len(key) - 1 in _KEY_SIZES and key.endswith(b"\n"):
        key = key[:-1]
    set_key(key, strict=strict)


def active() -> bool:
    return _aead is not None


def encrypt(data: bytes, aad: bytes = b"") -> bytes:
    """Seal `data`. `aad` binds context (e.g. a WAL record's ordinal) so
    a sealed blob cannot be replayed at a different position — GCM
    authenticates it without storing it."""
    if _aead is None:
        return data
    if len(data) <= _CHUNK:
        nonce = os.urandom(_NONCE)
        return MAGIC + nonce + _aead.encrypt(nonce, data, aad or None)
    # chunked: each chunk's AAD carries (index, total) on top of the
    # caller context, so chunk reorder, boundary truncation, and
    # same-key cross-splice of a different-length file all fail the tag
    n_chunks = -(-len(data) // _CHUNK)
    parts = [MAGIC_C]
    for ci, off in enumerate(range(0, len(data), _CHUNK)):
        nonce = os.urandom(_NONCE)
        ct = _aead.encrypt(nonce, data[off:off + _CHUNK],
                           aad + b"|chunk:%d/%d" % (ci, n_chunks))
        parts.append(_LEN.pack(len(ct)) + nonce + ct)
    return b"".join(parts)


def is_encrypted(data: bytes) -> bool:
    return data[:len(MAGIC)] in (MAGIC, MAGIC_C)


def decrypt(data: bytes, aad: bytes = b"") -> bytes:
    """Decrypt an encrypted blob; plaintext blobs pass through unchanged
    (pre-encryption files stay loadable after the key is enabled) unless
    strict mode is on. `aad` must match what encrypt() was given."""
    if not is_encrypted(data):
        if _strict and _aead is not None:
            raise VaultError(
                "plaintext data rejected: encryption is in strict mode")
        return data
    if _aead is None:
        raise VaultError(
            "data is encrypted but no key is loaded "
            "(--encryption_key_file)")
    try:
        if data[:len(MAGIC)] == MAGIC:
            nonce = data[len(MAGIC):len(MAGIC) + _NONCE]
            return _aead.decrypt(nonce, data[len(MAGIC) + _NONCE:],
                                 aad or None)
        # first pass counts chunks (the (index, total) AAD needs the
        # total up front to reject boundary truncation)
        n_chunks, off = 0, len(MAGIC_C)
        while off < len(data):
            (clen,) = _LEN.unpack_from(data, off)
            off += _LEN.size + _NONCE + clen
            n_chunks += 1
        if off != len(data):
            raise VaultError("decryption failed: truncated chunk stream")

        def _chunks(indexed_aad: bool) -> bytes:
            out, off, ci = [], len(MAGIC_C), 0
            while off < len(data):
                (clen,) = _LEN.unpack_from(data, off)
                off += _LEN.size
                nonce = data[off:off + _NONCE]
                off += _NONCE
                ca = (aad + b"|chunk:%d/%d" % (ci, n_chunks)
                      if indexed_aad else (aad or None))
                out.append(_aead.decrypt(nonce, data[off:off + clen], ca))
                off += clen
                ci += 1
            return b"".join(out)

        try:
            return _chunks(True)
        except Exception:
            # chunked blobs sealed before (index, total) binding carried
            # no per-chunk AAD; accept them as a migration path
            return _chunks(False)
    except VaultError:
        raise
    except Exception as e:  # InvalidTag/short read — wrong key/tampering
        raise VaultError(f"decryption failed (wrong key or corrupt "
                         f"data): {e!r}") from e


# ---- file IO helpers (checkpoint blocks, sidecars, manifests) ----

def atomic_write(path: str, file_bytes: bytes) -> int:
    """THE durable-file writer: tmp + flush + fsync + os.replace, so a
    kill at any point leaves either the previous file or the whole new
    one — never a torn mix (graftlint R8 pins every file-writing open
    under store/ to this pattern). Returns crc32 of the INTENDED bytes
    (the integrity digest recorded in manifests); the injected-fault
    hook mutates only what lands on disk, so a fault is exactly what
    the digest check later catches."""
    crc = zlib.crc32(file_bytes)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(io_faulted(path, file_bytes))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return crc


def write_bytes(path: str, data: bytes) -> int:
    """Seal + atomically write `data`; returns the on-disk crc32."""
    # escape regardless of key state: content beginning with any magic
    # must survive the unconditional MAGIC_P strip in read_bytes
    if data[:len(MAGIC)] in (MAGIC, MAGIC_C, MAGIC_P):
        data = MAGIC_P + data
    return atomic_write(path, encrypt(data))


def _verify_crc(path: str, raw: bytes, crc: int | None,
                kind: str) -> None:
    if crc is not None and zlib.crc32(raw) != crc:
        raise corruption(path, kind=kind,
                         detail=f"crc mismatch over {len(raw)} bytes")


def file_crc_ok(path: str, crc: int) -> bool:
    """Digest check of a file's raw on-disk bytes without decoding it
    (backup verify / restore-resume re-verification)."""
    try:
        with open(path, "rb") as f:
            return zlib.crc32(f.read()) == crc
    except OSError:
        return False


def read_bytes(path: str, crc: int | None = None,
               kind: str = "file") -> bytes:
    """Read (+ decrypt) a vault file; `crc` (from the manifest) is
    verified against the RAW on-disk bytes first — a failed check
    raises StorageCorruption naming the file."""
    with open(path, "rb") as f:
        raw = f.read()
    _verify_crc(path, raw, crc, kind)
    data = decrypt(raw)
    if data[:len(MAGIC_P)] == MAGIC_P:
        return data[len(MAGIC_P):]
    return data


def save_np(path: str, arr: np.ndarray) -> int:
    """np.save through the vault (serialize to memory, encrypt, write
    atomically). Returns the on-disk crc32. Plaintext bytes are
    identical to a direct np.save of the same array."""
    buf = io.BytesIO()
    np.save(buf, arr)
    if _aead is None:
        return atomic_write(path, buf.getvalue())
    return write_bytes(path, buf.getvalue())


def load_np(path: str, allow_pickle: bool = False,
            crc: int | None = None,
            kind: str = "segment") -> np.ndarray:
    if crc is None:
        # fast path: no digest recorded (pre-v3 snapshot) — keep the
        # zero-copy np.load for plaintext files
        with open(path, "rb") as f:
            head = f.read(len(MAGIC))
            if not is_encrypted(head):
                if _strict and _aead is not None:
                    raise VaultError(f"plaintext file rejected in strict "
                                     f"encryption mode: {path}")
                return np.load(path, allow_pickle=allow_pickle)
            data = head + f.read()
        return np.load(io.BytesIO(decrypt(data)),
                       allow_pickle=allow_pickle)
    with open(path, "rb") as f:
        raw = f.read()
    _verify_crc(path, raw, crc, kind)
    if not is_encrypted(raw):
        if _strict and _aead is not None:
            raise VaultError(f"plaintext file rejected in strict "
                             f"encryption mode: {path}")
        try:
            return np.load(io.BytesIO(raw), allow_pickle=allow_pickle)
        except ValueError as e:
            # crc passed but the block won't decode — a digest recorded
            # over an already-corrupt write; still a typed refusal
            raise corruption(path, kind=kind, detail=str(e)) from e
    return np.load(io.BytesIO(decrypt(raw)), allow_pickle=allow_pickle)
