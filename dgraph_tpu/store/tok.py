"""Index tokenizers.

Reference parity: `tok/tok.go` — the Tokenizer interface and the built-in
family (exact, hash, term, fulltext, trigram, int/float/datetime buckets).
Tokens key inverted indexes (token → sorted uid-rank posting list) used to
answer root functions (`eq`, `anyofterms`, `alloftext`, `regexp`, ...).

Numeric/datetime *comparisons* (le/ge/lt/gt/between) do NOT use tokens in
this build: the store keeps sorted value columns and answers ranges with
vectorised numpy/searchsorted — strictly better on this architecture than
the reference's ordered token walk.
"""

from __future__ import annotations

import re
import unicodedata

# The snowball/bleve English stopword list (the reference's fulltext
# tokenizer uses bleve's english analyzer; this is its stopword set).
STOPWORDS = frozenset("""
a about above after again against all am an and any are aren't as at be
because been before being below between both but by can't cannot could
couldn't did didn't do does doesn't doing don't down during each few for
from further had hadn't has hasn't have haven't having he he'd he'll he's
her here here's hers herself him himself his how how's i i'd i'll i'm
i've if in into is isn't it it's its itself let's me more most mustn't my
myself no nor not of off on once only or other ought our ours ourselves
out over own same shan't she she'd she'll she's should shouldn't so some
such than that that's the their theirs them themselves then there there's
these they they'd they'll they're they've this those through to too under
until up very was wasn't we we'd we'll we're we've were weren't what
what's when when's where where's which while who who's whom why why's
with won't would wouldn't you you'd you'll you're you've your yours
yourself yourselves
""".split())

_TERM_SPLIT = re.compile(r"[^\w]+", re.UNICODE)
# fulltext keeps intra-word apostrophes through the split so the
# contraction stopwords ("isn't", "you've") can actually match; the
# possessive tail is stripped after filtering ("dog's" → "dog"), the
# bleve analyzer's behavior
_FT_SPLIT = re.compile(r"[^\w']+", re.UNICODE)


def _fold(s: str) -> str:
    """Lowercase + strip diacritics (unicode normalisation)."""
    s = unicodedata.normalize("NFKD", s.lower())
    return "".join(c for c in s if not unicodedata.combining(c))


# -- Porter stemmer ----------------------------------------------------------
# The reference's fulltext analyzer stems with bleve's porter filter;
# this is the classic Porter (1980) algorithm, implemented from the
# published description. Matching symmetry still holds (query and data
# pass through the same function); quality now matches the reference's
# (conflates relational/relate, conditional/condition, etc.).

def _is_cons(w: str, i: int) -> bool:
    c = w[i]
    if c in "aeiou":
        return False
    if c == "y":
        return i == 0 or not _is_cons(w, i - 1)
    return True


def _measure(w: str) -> int:
    """m in [C](VC)^m[V] — the number of vowel→consonant transitions."""
    m, i, n = 0, 0, len(w)
    while i < n and _is_cons(w, i):
        i += 1
    while i < n:
        while i < n and not _is_cons(w, i):
            i += 1
        if i >= n:
            break
        m += 1
        while i < n and _is_cons(w, i):
            i += 1
    return m


def _has_vowel(w: str) -> bool:
    return any(not _is_cons(w, i) for i in range(len(w)))


def _ends_cvc(w: str) -> bool:
    return (len(w) >= 3 and _is_cons(w, len(w) - 3)
            and not _is_cons(w, len(w) - 2) and _is_cons(w, len(w) - 1)
            and w[-1] not in "wxy")


def _ends_double_cons(w: str) -> bool:
    return len(w) >= 2 and w[-1] == w[-2] and _is_cons(w, len(w) - 1)


_STEP2 = (("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
          ("anci", "ance"), ("izer", "ize"), ("abli", "able"),
          ("alli", "al"), ("entli", "ent"), ("eli", "e"),
          ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
          ("ator", "ate"), ("alism", "al"), ("iveness", "ive"),
          ("fulness", "ful"), ("ousness", "ous"), ("aliti", "al"),
          ("iviti", "ive"), ("biliti", "ble"), ("logi", "log"))
_STEP3 = (("icate", "ic"), ("ative", ""), ("alize", "al"),
          ("iciti", "ic"), ("ical", "ic"), ("ful", ""), ("ness", ""))
_STEP4 = ("al", "ance", "ence", "er", "ic", "able", "ible", "ant",
          "ement", "ment", "ent", "ion", "ou", "ism", "ate", "iti",
          "ous", "ive", "ize")


def _stem(w: str) -> str:
    if len(w) <= 2:
        return w
    # step 1a: plurals
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif not w.endswith("ss") and w.endswith("s"):
        w = w[:-1]
    # step 1b: -eed/-ed/-ing
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    else:
        stem = None
        if w.endswith("ed") and _has_vowel(w[:-2]):
            stem = w[:-2]
        elif w.endswith("ing") and _has_vowel(w[:-3]):
            stem = w[:-3]
        if stem is not None:
            w = stem
            if w.endswith(("at", "bl", "iz")):
                w += "e"
            elif _ends_double_cons(w) and w[-1] not in "lsz":
                w = w[:-1]
            elif _measure(w) == 1 and _ends_cvc(w):
                w += "e"
    # step 1c: y → i after a vowel
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"
    # step 2: double suffixes (m > 0)
    for suf, rep in _STEP2:
        if w.endswith(suf):
            if _measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break
    # step 3: -ic-, -full, -ness etc. (m > 0)
    for suf, rep in _STEP3:
        if w.endswith(suf):
            if _measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break
    # step 4: bare suffixes (m > 1)
    for suf in _STEP4:
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if _measure(stem) > 1 and (
                    suf != "ion" or (stem and stem[-1] in "st")):
                w = stem
            break
    # step 5a: trailing e
    if w.endswith("e"):
        m = _measure(w[:-1])
        if m > 1 or (m == 1 and not _ends_cvc(w[:-1])):
            w = w[:-1]
    # step 5b: -ll → -l (m > 1)
    if _measure(w) > 1 and _ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]
    return w


def exact_tokens(value) -> list[str]:
    """`exact` index: the value itself, one token."""
    return [str(value)]


def hash_tokens(value) -> list[str]:
    """`hash` index: same as exact for eq purposes (we key dicts by the
    string itself; a real hash adds nothing host-side)."""
    return [str(value)]


def term_tokens(value) -> list[str]:
    """`term` index: folded alphanumeric words, deduped."""
    return sorted({w for w in _TERM_SPLIT.split(_fold(str(value))) if w})


def fulltext_tokens(value) -> list[str]:
    """`fulltext` index: word tokens (contractions intact) minus the
    snowball stopword list, possessives stripped, Porter-stemmed."""
    out = set()
    for w in _FT_SPLIT.split(_fold(str(value))):
        w = w.strip("'")
        if not w or w in STOPWORDS:
            continue
        if w.endswith("'s"):
            w = w[:-2]
        w = w.replace("'", "")
        if w:
            out.add(_stem(w))
    return sorted(out)


def trigram_tokens(value) -> list[str]:
    """`trigram` index (regexp support): all 3-grams of the raw string."""
    s = str(value)
    return sorted({s[i:i + 3] for i in range(len(s) - 2)}) if len(s) >= 3 else []


def geo_tokens(value) -> list[str]:
    """Geohash cell tokens at every ladder precision (reference: the S2
    cell tokenizer; store/geo.py)."""
    from dgraph_tpu.store.geo import parse_geo, tokens_for_geo
    return tokens_for_geo(parse_geo(value))


TOKENIZERS = {
    "exact": exact_tokens,
    "hash": hash_tokens,
    "term": term_tokens,
    "fulltext": fulltext_tokens,
    "trigram": trigram_tokens,
    # numeric/datetime/bool "indexes" are satisfied by sorted value columns;
    # registered as identity so schema validation accepts them.
    "int": exact_tokens,
    "float": exact_tokens,
    "bool": exact_tokens,
    "datetime": exact_tokens,
    "year": exact_tokens,
    "month": exact_tokens,
    "day": exact_tokens,
    "hour": exact_tokens,
    "geo": geo_tokens,
}


def tokens_for(tokenizer: str, value) -> list[str]:
    try:
        return TOKENIZERS[tokenizer](value)
    except KeyError:
        raise ValueError(f"unknown tokenizer {tokenizer!r}") from None
