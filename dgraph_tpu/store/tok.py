"""Index tokenizers.

Reference parity: `tok/tok.go` — the Tokenizer interface and the built-in
family (exact, hash, term, fulltext, trigram, int/float/datetime buckets).
Tokens key inverted indexes (token → sorted uid-rank posting list) used to
answer root functions (`eq`, `anyofterms`, `alloftext`, `regexp`, ...).

Numeric/datetime *comparisons* (le/ge/lt/gt/between) do NOT use tokens in
this build: the store keeps sorted value columns and answers ranges with
vectorised numpy/searchsorted — strictly better on this architecture than
the reference's ordered token walk.
"""

from __future__ import annotations

import re
import unicodedata

# ~Top English stopwords (the reference's fulltext tokenizer uses bleve's
# english stopword list; this is the standard short list).
STOPWORDS = frozenset(
    """a an and are as at be but by for if in into is it no not of on or such
    that the their then there these they this to was will with""".split()
)

_TERM_SPLIT = re.compile(r"[^\w]+", re.UNICODE)


def _fold(s: str) -> str:
    """Lowercase + strip diacritics (unicode normalisation)."""
    s = unicodedata.normalize("NFKD", s.lower())
    return "".join(c for c in s if not unicodedata.combining(c))


def _stem(w: str) -> str:
    """Tiny English suffix-stripper standing in for the reference's porter
    stemmer — enough for fulltext matching symmetry (query and data pass
    through the same function, so matching is consistent)."""
    for suf in ("ational", "iveness", "fulness", "ousness", "ization",
                "ations", "ingly", "ation", "ness", "ment", "ies", "ing",
                "ed", "es", "ly", "s"):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            return w[: -len(suf)]
    return w


def exact_tokens(value) -> list[str]:
    """`exact` index: the value itself, one token."""
    return [str(value)]


def hash_tokens(value) -> list[str]:
    """`hash` index: same as exact for eq purposes (we key dicts by the
    string itself; a real hash adds nothing host-side)."""
    return [str(value)]


def term_tokens(value) -> list[str]:
    """`term` index: folded alphanumeric words, deduped."""
    return sorted({w for w in _TERM_SPLIT.split(_fold(str(value))) if w})


def fulltext_tokens(value) -> list[str]:
    """`fulltext` index: term tokens minus stopwords, stemmed."""
    return sorted({_stem(w) for w in _TERM_SPLIT.split(_fold(str(value)))
                   if w and w not in STOPWORDS})


def trigram_tokens(value) -> list[str]:
    """`trigram` index (regexp support): all 3-grams of the raw string."""
    s = str(value)
    return sorted({s[i:i + 3] for i in range(len(s) - 2)}) if len(s) >= 3 else []


def geo_tokens(value) -> list[str]:
    """Geohash cell tokens at every ladder precision (reference: the S2
    cell tokenizer; store/geo.py)."""
    from dgraph_tpu.store.geo import parse_geo, tokens_for_geo
    return tokens_for_geo(parse_geo(value))


TOKENIZERS = {
    "exact": exact_tokens,
    "hash": hash_tokens,
    "term": term_tokens,
    "fulltext": fulltext_tokens,
    "trigram": trigram_tokens,
    # numeric/datetime/bool "indexes" are satisfied by sorted value columns;
    # registered as identity so schema validation accepts them.
    "int": exact_tokens,
    "float": exact_tokens,
    "bool": exact_tokens,
    "datetime": exact_tokens,
    "year": exact_tokens,
    "month": exact_tokens,
    "day": exact_tokens,
    "hour": exact_tokens,
    "geo": geo_tokens,
}


def tokens_for(tokenizer: str, value) -> list[str]:
    try:
        return TOKENIZERS[tokenizer](value)
    except KeyError:
        raise ValueError(f"unknown tokenizer {tokenizer!r}") from None
