"""Predicate schema: types, directives, schema-language parser.

Reference parity: `schema/schema.go` (State: per-predicate type +
directives), `schema/parse.go` (the schema mutation language accepted by
Alter), including type definitions used by `dgraph.type` / `expand(_all_)`.

Grammar (the subset the reference's Alter accepts, minus enterprise):

    <pred>: <type> [@index(tok1, tok2)] [@reverse] [@count] [@lang]
            [@upsert] [@unique] .
    type <Name> { <pred1> <pred2> ... }

where <type> is one of uid|int|float|string|bool|datetime|password|geo|default,
optionally wrapped in [] for list-valued predicates.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from dgraph_tpu.store.tok import TOKENIZERS
from dgraph_tpu.store.types import Kind


@dataclass
class PredicateSchema:
    name: str
    kind: Kind = Kind.DEFAULT
    is_list: bool = False
    index_tokenizers: tuple[str, ...] = ()
    reverse: bool = False
    count: bool = False
    lang: bool = False
    upsert: bool = False
    unique: bool = False
    # float32vector only: embedding width. 0 = infer from the first
    # loaded vector; any later mismatch is refused at schema time.
    vector_dim: int = 0

    @property
    def is_uid(self) -> bool:
        return self.kind == Kind.UID

    @property
    def indexed(self) -> bool:
        return bool(self.index_tokenizers)


@dataclass
class TypeDef:
    name: str
    fields: tuple[str, ...] = ()


@dataclass
class Schema:
    """Mutable schema state (reference: schema.State())."""

    predicates: dict[str, PredicateSchema] = field(default_factory=dict)
    types: dict[str, TypeDef] = field(default_factory=dict)

    def get(self, pred: str) -> PredicateSchema:
        """Schema for a predicate; unknown predicates get a mutable default
        entry (the reference auto-creates schema on first mutation)."""
        if pred not in self.predicates:
            self.predicates[pred] = PredicateSchema(name=pred)
        return self.predicates[pred]

    def peek(self, pred: str) -> PredicateSchema | None:
        return self.predicates.get(pred)

    def clone(self) -> "Schema":
        """Deep copy, so a new Store snapshot's schema can evolve without
        mutating the one frozen into the previous snapshot."""
        import copy
        return copy.deepcopy(self)

    def update(self, other: "Schema") -> None:
        """Merge an Alter's schema into the live state (reference:
        Schema.Update — later declarations replace earlier per predicate)."""
        self.predicates.update(other.predicates)
        self.types.update(other.types)

    def to_text(self) -> str:
        out = []
        for p in self.predicates.values():
            t = p.kind.value
            if p.is_list:
                t = f"[{t}]"
            d = ""
            if p.index_tokenizers:
                d += f" @index({', '.join(p.index_tokenizers)})"
            for flag, name in ((p.reverse, "reverse"), (p.count, "count"),
                               (p.lang, "lang"), (p.upsert, "upsert"),
                               (p.unique, "unique")):
                if flag:
                    d += f" @{name}"
            if p.vector_dim:
                d += f" @dim({p.vector_dim})"
            out.append(f"{p.name}: {t}{d} .")
        for t in self.types.values():
            fields = "\n".join(f"  {f}" for f in t.fields)
            out.append(f"type {t.name} {{\n{fields}\n}}")
        return "\n".join(out)


_PRED_RE = re.compile(
    r"^\s*<?([\w.][\w.\-/]*)>?\s*:\s*(\[?)\s*(\w+)\s*(\]?)\s*(.*?)\s*\.\s*$")
_TYPE_RE = re.compile(r"^\s*type\s+<?([\w.]+)>?\s*\{([^}]*)\}", re.S | re.M)
_DIRECTIVE_RE = re.compile(r"@(\w+)(?:\(([^)]*)\))?")


def parse_schema(text: str) -> Schema:
    """Parse schema-language text (reference: schema.ParseBytes)."""
    sch = Schema()
    # strip comments
    text = re.sub(r"#[^\n]*", "", text)
    # type blocks first (they span lines)
    for m in _TYPE_RE.finditer(text):
        name, body = m.group(1), m.group(2)
        fields = tuple(f.strip().strip("<>") for f in body.split() if f.strip())
        sch.types[name] = TypeDef(name=name, fields=fields)
    text = _TYPE_RE.sub("", text)

    for line in text.splitlines():
        if not line.strip():
            continue
        m = _PRED_RE.match(line)
        if not m:
            raise ValueError(f"bad schema line: {line!r}")
        name, lb, typ, rb, rest = m.groups()
        if bool(lb) != bool(rb):
            raise ValueError(f"unbalanced [] in schema line: {line!r}")
        try:
            kind = Kind(typ)
        except ValueError:
            raise ValueError(f"unknown type {typ!r} in schema line: {line!r}")
        if kind == Kind.VECTOR and lb:
            raise ValueError(
                f"float32vector predicates hold one vector per node — "
                f"list form is not supported: {line!r}")
        p = PredicateSchema(name=name, kind=kind, is_list=bool(lb))
        for dm in _DIRECTIVE_RE.finditer(rest):
            d, args = dm.group(1), dm.group(2)
            if d == "index":
                toks = tuple(t.strip() for t in (args or "").split(",") if t.strip())
                if not toks:
                    raise ValueError(f"@index needs tokenizers: {line!r}")
                for t in toks:
                    if t not in TOKENIZERS:
                        raise ValueError(f"unknown tokenizer {t!r}: {line!r}")
                if kind == Kind.UID:
                    raise ValueError(f"@index not allowed on uid predicate: {line!r}")
                p.index_tokenizers = toks
            elif d == "reverse":
                if kind != Kind.UID:
                    raise ValueError(f"@reverse only on uid predicates: {line!r}")
                p.reverse = True
            elif d == "count":
                p.count = True
            elif d == "lang":
                p.lang = True
            elif d == "upsert":
                p.upsert = True
            elif d == "unique":
                p.unique = True
            elif d == "dim":
                if kind != Kind.VECTOR:
                    raise ValueError(
                        f"@dim only on float32vector predicates: {line!r}")
                try:
                    p.vector_dim = int((args or "").strip())
                except ValueError:
                    raise ValueError(f"@dim needs an integer: {line!r}")
                if p.vector_dim <= 0:
                    raise ValueError(f"@dim must be positive: {line!r}")
            elif d == "noconflict":
                pass  # accepted, no-op (as in reference semantics for reads)
            else:
                raise ValueError(f"unknown directive @{d}: {line!r}")
        sch.predicates[name] = p
    return sch
