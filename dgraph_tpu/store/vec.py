"""Vector tablets + brute-force k-NN seed selection (GraphRAG serving).

The ROADMAP's GraphRAG workload ("Democratizing GraphRAG", PAPERS) is
k-NN seed selection feeding `@recurse` expansion under deadlines. This
module is the vector half: per-predicate `[n, d]` float32 embedding
stacks ("vec tablets") built from the columnar value store, plus the
`similar_to(pred, k, <vector|uid>)` top-k scan behind the root func —
FeatGraph's thesis that the same gather/segment machinery generalizes
when nodes carry dense features: the scan is a scored matmul, exactly
the dense-math-per-node shape the device wins biggest on, and on the
mesh an embarrassingly row-shardable one.

Three routes, one contract — bit-identical rank sets:

* **host** — numpy matmul + lexsort((rank, -score)): score descending,
  rank-ascending tie-break. This IS the reference the other routes are
  pinned against.
* **device** — the same trace under jax.jit, launched through the
  memgov OOM lifecycle at site `vec.topk` (alloc failure → evict-retry
  → sticky degrade to the host route).
* **mesh** — row-sharded stacks (the `Store.sharded_rel` discipline:
  per-snapshot residency, placed once), per-device local top-k +
  all_gather merge (the parallel/dsort.py shape).

Selection only compares scores, so the set is identical whenever the
matmul is bit-identical across routes — guaranteed for exactly
representable inputs (the fixtures and bench embeddings use small
integer-valued components); route choice rides the PR-10 costprior
route EMAs (`knn_host`/`knn_device`/`knn_mesh`) the same way
`Executor._mesh_promoted` consults `mesh` vs `numpy`.

Import discipline: jax only inside the device/mesh helpers — the host
route and tablet builders import numpy alone (loaders and the analysis
CLI touch them without a device runtime).
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass

import numpy as np

from dgraph_tpu.store.types import parse_vector
from dgraph_tpu.utils import memgov
from dgraph_tpu.utils.metrics import METRICS

__all__ = ["VecQueryError", "VecTablet", "build_tablet", "host_topk",
           "host_similar", "similar_ranks", "resolve_query"]

EMPTY = np.zeros(0, np.int32)


class VecQueryError(ValueError):
    """Typed user error for malformed `similar_to` arguments — a
    REQUEST refusal, never a route failure: the fused planner's
    `except ValueError` treats it as "serve staged" (non-sticky), the
    staged route raises it to the caller, and a structurally-empty
    seed (uid without an embedding row) is NOT an error at all — it
    returns the empty sorted rank set on every route."""


@dataclass
class VecTablet:
    """One predicate's embedding stack: `vecs[i]` is the vector of rank
    `subj[i]` (sorted unique int32 ranks — first value per subject)."""

    subj: np.ndarray   # int32 [n], sorted unique
    vecs: np.ndarray   # float32 [n, d], row-aligned with subj
    dim: int

    @property
    def rows(self) -> int:
        return int(self.subj.shape[0])

    def vector_of(self, rank: int) -> np.ndarray | None:
        i = int(np.searchsorted(self.subj, rank))
        if i < self.rows and int(self.subj[i]) == rank:
            return self.vecs[i]
        return None


def build_tablet(col, dim_hint: int = 0) -> VecTablet:
    """ValueColumn (object column of 1-D f32 rows) → VecTablet. First
    value per subject wins (the dsort key-column discipline); an empty
    column yields a [0, dim_hint] stack."""
    if col is None or not len(col.subj):
        return VecTablet(subj=EMPTY.copy(),
                         vecs=np.zeros((0, dim_hint), np.float32),
                         dim=dim_hint)
    subj, idx = np.unique(np.asarray(col.subj, np.int32),
                          return_index=True)
    rows = [np.asarray(col.vals[i], np.float32) for i in idx]
    vecs = np.stack(rows).astype(np.float32)
    return VecTablet(subj=subj.astype(np.int32), vecs=vecs,
                     dim=int(vecs.shape[1]))


# ---------------------------------------------------------------------------
# host route: the bit-identity reference

def host_topk(subj: np.ndarray, vecs: np.ndarray, q: np.ndarray,
              k: int) -> np.ndarray:
    """Top-k ranks by dot-product score, ties broken by rank ascending;
    returns the SORTED rank set (root funcs produce sets — ordering and
    pagination compose downstream). k > n clamps to n."""
    if not len(subj) or k <= 0:
        return EMPTY.copy()
    scores = vecs @ np.asarray(q, np.float32)
    # lexsort: primary -scores ascending (= score desc; f32 sign flip
    # is exact), secondary subj ascending — the total order every
    # route reproduces
    order = np.lexsort((subj, -scores))
    return np.sort(subj[order[:k]]).astype(np.int32)


def host_similar(store, f) -> np.ndarray:
    """`eval_func`'s similar_to branch: the pure-numpy reference route
    (no device runtime, no route accounting)."""
    resolved = resolve_query(store, f)
    if resolved is None:
        return EMPTY.copy()
    pred, k, q = resolved
    t = store.vec_tablet(pred)
    return host_topk(t.subj, t.vecs, q, k)


def resolve_query(store, f):
    """FuncNode args → (pred, k, query f32[d]) or None when the seed
    set is structurally empty (no tablet, unknown uid, uid without a
    vector). Malformed args and dimension mismatches raise — the same
    refusal on every route."""
    pred = f.attr
    if len(f.args) != 2:
        raise VecQueryError(
            "similar_to(pred, k, <vector|uid>) takes exactly two "
            "arguments after the predicate")
    k = int(f.args[0])
    if k <= 0:
        raise VecQueryError(f"similar_to k must be positive, got {k}")
    t = store.vec_tablet(pred)
    if t is None or not t.rows:
        return None
    arg = f.args[1]
    if isinstance(arg, (list, tuple, np.ndarray, str)):
        # str: the quoted literal form `"[1, 0, ...]"` from DQL
        try:
            q = parse_vector(arg)
        except ValueError as e:
            raise VecQueryError(str(e)) from e
    elif isinstance(arg, (int, np.integer)):
        rank = int(store.rank_of(np.array([int(arg)], np.int64))[0])
        if rank < 0:
            return None
        q = t.vector_of(rank)
        if q is None:
            return None
    else:
        raise VecQueryError(
            f"similar_to query must be a vector literal or a uid, "
            f"got {arg!r}")
    if len(q) != t.dim:
        raise VecQueryError(
            f"similar_to({pred}): query vector has dim {len(q)}, "
            f"tablet has dim {t.dim}")
    return pred, k, np.asarray(q, np.float32)


# ---------------------------------------------------------------------------
# device route: one jitted kernel, launched through the OOM lifecycle

def _device_topk(store, pred: str, q: np.ndarray, k: int,
                 shape_key) -> np.ndarray:
    """Single-device top-k over the cached HBM stack. Raises
    memgov.OomDegraded for the caller's host fallback."""
    from dgraph_tpu.utils.jitcache import jit_call

    subj_d, vecs_d = store.vec_device(pred)
    n, d = int(vecs_d.shape[0]), int(vecs_d.shape[1])
    key = ("vec.topk", n, d, min(k, n))

    def _launch():
        memgov.check_alloc_fault("vec.topk")
        with jit_call("vec.topk", key):
            out = _topk_kernel(subj_d, vecs_d,
                               np.asarray(q, np.float32), min(k, n))
        return np.asarray(out, np.int32)

    return memgov.oom_retry("vec.topk", shape_key, _launch)


@functools.lru_cache(maxsize=1)
def _topk_jit():
    import jax

    def topk(subj, vecs, q, k):
        import jax.numpy as jnp
        scores = vecs @ q
        order = jnp.lexsort((subj, -scores))
        return jnp.sort(subj[order[:k]])

    return jax.jit(topk, static_argnames=("k",))


def _topk_kernel(subj, vecs, q, k: int):
    return _topk_jit()(subj, vecs, q, k)


# ---------------------------------------------------------------------------
# mesh route: row-sharded scan + local top-k + all_gather merge

def _mesh_topk(store, pred: str, q: np.ndarray, k: int,
               mesh, shape_key) -> np.ndarray:
    from dgraph_tpu.ops.uidalgebra import SENTINEL32

    subj_s, vecs_s, rows = store.vec_sharded(pred, mesh)
    # local cap: a shard contributes at most min(k, rows) candidates
    # (the global top-k is a subset of the per-shard top-k unions);
    # the merge then takes up to k across ALL shards' candidates
    kk = min(k, rows)
    k_out = min(k, kk * int(subj_s.shape[0]))

    def _launch():
        memgov.check_alloc_fault("vec.topk")
        gr = _build_mesh_topk(mesh, rows, int(vecs_s.shape[-1]), kk,
                              k_out)(
            subj_s, vecs_s, np.asarray(q, np.float32))
        return np.asarray(gr, np.int32)

    gr = memgov.oom_retry("vec.topk", shape_key, _launch)
    out = gr[gr != SENTINEL32]
    return np.sort(out[:k]).astype(np.int32)


@functools.lru_cache(maxsize=32)
def _build_mesh_topk(mesh, rows: int, d: int, k: int, k_out: int):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.ops.uidalgebra import valid_mask
    from dgraph_tpu.parallel.mesh import SHARD_AXIS
    from dgraph_tpu.utils.jaxcompat import shard_map

    def per_device(subj_b, vecs_b, q):
        subj, vecs = subj_b[0], vecs_b[0]      # [rows], [rows, d]
        scores = vecs @ q                       # per-row dot products
        # padded rows (sentinel subj) must lose to every real row:
        # +inf key sorts last in the -score-ascending order
        key = jnp.where(valid_mask(subj), -scores, jnp.inf)
        order = jnp.lexsort((subj, key))        # (score desc, rank asc)
        top_r = subj[order[:k]]
        top_v = key[order[:k]]
        gr = lax.all_gather(top_r, SHARD_AXIS).reshape(-1)
        gv = lax.all_gather(top_v, SHARD_AXIS).reshape(-1)
        o2 = jnp.lexsort((gr, gv))              # k-way merge, one sort
        return gr[o2[:k_out]]

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
                   out_specs=P(), check_vma=False)
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# the routed entry point (Executor._leaf_set dispatches here)

def _promoted(route: str, baseline: str) -> bool:
    """Cost-prior promotion below the static threshold: take `route`
    when its measured µs-per-1k-rows EMA beats `baseline` (the
    Executor._mesh_promoted discipline, knn lanes)."""
    from dgraph_tpu.utils import costprior
    if not costprior.enabled():
        return False
    r = costprior.PRIORS.route_cost(route)
    b = costprior.PRIORS.route_cost(baseline)
    return r is not None and b is not None and r < b


def similar_ranks(store, f, mesh=None,
                  device_threshold: int = 512) -> np.ndarray:
    """similar_to with route selection + accounting: mesh when one is
    configured and the tablet clears the threshold (or the knn route
    EMAs promote it), device on a big single-device tablet, host
    otherwise — and host ALWAYS on OOM degradation, bit-identically."""
    resolved = resolve_query(store, f)
    if resolved is None:
        return EMPTY.copy()
    pred, k, q = resolved
    t = store.vec_tablet(pred)
    n = t.rows
    shape_key = (pred, t.dim, k)
    t0 = time.perf_counter()
    route = "host"
    try:
        if mesh is not None and (n >= device_threshold
                                 or _promoted("knn_mesh", "knn_host")):
            route = "mesh"
            out = _mesh_topk(store, pred, q, k, mesh, shape_key)
        elif n >= device_threshold or _promoted("knn_device",
                                                "knn_host"):
            route = "device"
            out = _device_topk(store, pred, q, k, shape_key)
        else:
            out = host_topk(t.subj, t.vecs, q, k)
    except memgov.OomDegraded:
        # allocation failure survived its evict-retry (or the shape is
        # sticky-degraded): the host scan produces the identical set
        route = "host"
        out = host_topk(t.subj, t.vecs, q, k)
    METRICS.inc("knn_route_total", route=route)
    if n:
        from dgraph_tpu.utils import costprior
        costprior.PRIORS.learn_route(
            "knn_" + route,
            (time.perf_counter() - t0) * 1e6 / n * 1000.0)
    return out
