"""Scalar value types and the conversion matrix.

Reference parity: `types/conversion.go`, `types/sort.go` — scalar kinds
(int, float, string, bool, datetime, password/geo out of v1 scope) with a
conversion matrix used by filters, ordering, and schema coercion.

Host-side representation is numpy-columnar (exact dtypes: int64, float64,
object-strings, bool_, datetime64[us]); device-side work (aggregation,
ordering of numerics) down-converts explicitly in the engine.
"""

from __future__ import annotations

import datetime as _dt
from enum import Enum

import numpy as np


class Kind(str, Enum):
    UID = "uid"
    INT = "int"
    FLOAT = "float"
    STRING = "string"
    BOOL = "bool"
    DATETIME = "datetime"
    PASSWORD = "password"
    GEO = "geo"
    VECTOR = "float32vector"  # dense f32 embedding (GraphRAG tablets)
    DEFAULT = "default"  # untyped: stored as string, coerced on use


NUMPY_DTYPE = {
    Kind.INT: np.int64,
    Kind.FLOAT: np.float64,
    Kind.STRING: object,
    Kind.BOOL: np.bool_,
    Kind.DATETIME: "datetime64[us]",
    Kind.PASSWORD: object,
    Kind.GEO: object,
    Kind.VECTOR: object,  # object column of 1-D float32 rows
    Kind.DEFAULT: object,
}


def hash_password(password: str) -> str:
    """Salted scrypt hash, encoded "salt$key" (reference: password scalar
    values store bcrypt hashes, never plaintext). Hashing happens ONCE at
    mutation ingestion so the WAL/broadcast carry the hash and replay is
    deterministic."""
    import base64
    import hashlib
    import os
    salt = os.urandom(16)
    dk = hashlib.scrypt(password.encode(), salt=salt, n=2**14, r=8, p=1)
    return base64.b64encode(salt).decode() + "$" + \
        base64.b64encode(dk).decode()


def check_password(password: str, stored: str) -> bool:
    """Constant-time verification against a hash_password() value."""
    import base64
    import hashlib
    import hmac
    try:
        salt_b64, dk_b64 = stored.split("$", 1)
        salt = base64.b64decode(salt_b64)
        dk = hashlib.scrypt(password.encode(), salt=salt,
                            n=2**14, r=8, p=1)
        return hmac.compare_digest(dk, base64.b64decode(dk_b64))
    except Exception:  # noqa: BLE001 — malformed hash = no access
        return False


def parse_datetime(s: str) -> np.datetime64:
    """RFC3339-ish datetime parsing (reference: types.ParseTime)."""
    s = s.strip()
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    try:
        dt = _dt.datetime.fromisoformat(s)
    except ValueError:
        for fmt in ("%Y", "%Y-%m", "%Y-%m-%d"):
            try:
                dt = _dt.datetime.strptime(s, fmt)
                break
            except ValueError:
                continue
        else:
            raise
    if dt.tzinfo is not None:
        dt = dt.astimezone(_dt.timezone.utc).replace(tzinfo=None)
    return np.datetime64(dt, "us")


def convert(value, kind: Kind):
    """Coerce a raw (string or python) value to `kind`.

    Mirrors the reference conversion matrix: anything → string; string →
    int/float/bool/datetime by parse; int ↔ float; bool → int. Raises
    ValueError on inconvertible pairs (reference returns an error).
    """
    if kind in (Kind.STRING, Kind.DEFAULT, Kind.PASSWORD):
        if isinstance(value, bool):
            return "true" if value else "false"
        return str(value)
    if kind == Kind.INT:
        if isinstance(value, bool):
            return int(value)
        if isinstance(value, (int, np.integer)):
            return int(value)
        if isinstance(value, (float, np.floating)):
            return int(value)
        try:
            return int(str(value), 10)
        except ValueError:
            return int(float(str(value)))  # "3.0" → 3, raises if not numeric
    if kind == Kind.FLOAT:
        if isinstance(value, bool):
            return float(value)
        return float(value) if not isinstance(value, str) else float(value.strip())
    if kind == Kind.BOOL:
        if isinstance(value, bool):
            return value
        if isinstance(value, (int, float, np.number)):
            return bool(value)
        s = str(value).strip().lower()
        if s in ("true", "1"):
            return True
        if s in ("false", "0", ""):
            return False
        raise ValueError(f"cannot convert {value!r} to bool")
    if kind == Kind.DATETIME:
        if isinstance(value, np.datetime64):
            return value
        if isinstance(value, _dt.datetime):
            return np.datetime64(value, "us")
        return parse_datetime(str(value))
    if kind == Kind.GEO:
        from dgraph_tpu.store.geo import parse_geo
        return parse_geo(value)
    if kind == Kind.VECTOR:
        return parse_vector(value)
    raise ValueError(f"cannot convert to {kind}")


def parse_vector(value) -> np.ndarray:
    """Raw value → 1-D float32 vector. Accepts ndarray, list/tuple of
    numbers, or the loader's string literal form `"[0.1, 0.2, ...]"`
    (the `^^<float32vector>` RDF object / JSON string encoding)."""
    if isinstance(value, np.ndarray):
        v = value
    elif isinstance(value, (list, tuple)):
        v = np.asarray(value)
    elif isinstance(value, str):
        s = value.strip()
        if not (s.startswith("[") and s.endswith("]")):
            raise ValueError(f"cannot convert {value!r} to float32vector")
        body = s[1:-1].strip()
        v = np.array([float(p) for p in body.split(",") if p.strip()])
    else:
        raise ValueError(f"cannot convert {value!r} to float32vector")
    v = np.asarray(v, np.float32)
    if v.ndim != 1:
        raise ValueError(
            f"float32vector must be 1-D, got shape {v.shape}")
    return v


def sort_key(value, kind: Kind):
    """Total-order key used by order-by on values (reference: types.Sort)."""
    if kind == Kind.DATETIME:
        return np.datetime64(value, "us").astype("int64")
    return value
